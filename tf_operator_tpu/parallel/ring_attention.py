"""Ring attention: exact attention over a context-parallel (cp) mesh axis.

Long-context support (SURVEY.md §5: absent from the reference — sequence
length was invisible to the operator; here it is a first-class library
capability). The sequence dimension of Q/K/V is sharded over the ``cp``
axis; each device computes flash-style blockwise attention of its local Q
block against the K/V block it currently holds, then rotates K/V around the
ring with ``ppermute`` — after cp_size block-steps every Q block has
attended to every K/V block, with online-softmax accumulators keeping the
result exact. K/V traffic totals cp_size-1 neighbor hops per layer (the
last block needs no onward rotation), the ring-attention recipe (Liu et
al.) mapped onto XLA collectives that ride ICI neighbor links.

Shapes follow [batch, seq, heads, head_dim]. Self-attention only: q and k/v
must share one global sequence length (the causal mask is defined by global
positions within that single sequence).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tf_operator_tpu.ops.flash_attention import NEG_INF, flash_attention_lse
from tf_operator_tpu.parallel.collectives import axis_index, axis_size, ring_shift


def reference_attention(q, k, v, causal: bool = False):
    """Dense softmax attention, the correctness oracle."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / jnp.sqrt(d)
    if causal:
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _ring_attention_local(q, k, v, axis_name: str, causal: bool):
    """Per-device body (runs inside shard_map). q: [b, t_local, h, d];
    k/v: [b, t_local, h_kv, d] with h % h_kv == 0 — GQA-native (r3): the
    score/value einsums carry a (kv_head, group) split of the query heads
    instead of materializing repeated K/V, so the ring rotates the SMALL
    [b, t_local, h_kv, d] blocks — ICI traffic per hop drops by the group
    factor (8x for the llama2-70b 64q/8kv shape), exactly where ring
    attention's cost lives. h_kv == h is the classic path (group 1).
    Returns the local output block [b, t_local, h, d]."""
    n = axis_size(axis_name)
    my_idx = axis_index(axis_name)
    b, t_local, h, d = q.shape
    h_kv = k.shape[2]
    g = h // h_kv
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)

    # [b, t, h, d] -> [b, t, h_kv, g, d]: group dim explicit for the
    # grouped contractions (h label below is the KV head dim).
    qf = q.astype(jnp.float32).reshape(b, t_local, h_kv, g, d)

    def attend_block(o, m, l, k_blk, v_blk, step):
        """Fold one K/V block into the online-softmax accumulators."""
        # The block currently held arrived from device (my_idx - step) mod n.
        src = (my_idx - step) % n
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qf, k_blk.astype(jnp.float32)) * scale
        if causal:
            q_pos = my_idx * t_local + jnp.arange(t_local)
            k_pos = src * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None, None], s, -jnp.inf)
        m_blk = jnp.max(s, axis=-1)  # [b,h_kv,g,q]
        m_new = jnp.maximum(m, m_blk)
        # -inf accumulators need explicit guards: exp(-inf - -inf) is nan.
        alpha = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m - m_new))
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(jnp.isneginf(m_new)[..., None], 0.0, p)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhgqk,bkhd->bhgqd", p, v_blk.astype(jnp.float32)
        )
        return o_new, m_new, l_new

    def scan_body(carry, step):
        o, m, l, k_blk, v_blk = carry
        o, m, l = attend_block(o, m, l, k_blk, v_blk, step)
        # Rotate K/V onward for the next step (the final block, handled
        # outside the scan, needs no rotation).
        k_next = ring_shift(k_blk, axis_name)
        v_next = ring_shift(v_blk, axis_name)
        return (o, m, l, k_next, v_next), None

    o0 = jnp.zeros((b, h_kv, g, t_local, d), jnp.float32)
    m0 = jnp.full((b, h_kv, g, t_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h_kv, g, t_local), jnp.float32)
    (o, m, l, k_last, v_last), _ = jax.lax.scan(
        scan_body, (o0, m0, l0, k, v), jnp.arange(n - 1)
    )
    o, m, l = attend_block(o, m, l, k_last, v_last, n - 1)
    # Rows that attended to nothing keep l=0 (cannot happen for causal self-
    # attention with t_local >= 1, but guard the division anyway).
    o = o / jnp.where(l == 0.0, 1.0, l)[..., None]
    return jnp.einsum("bhgqd->bqhgd", o).reshape(b, t_local, h, d).astype(q.dtype)


def _merge_partials(o, m, d_acc, o_j, lse_j):
    """Fold one normalized partial attention (o_j, lse_j) into the
    running lse-weighted merge. Carry: o = Σ o_i·exp(lse_i − m) (f32),
    d_acc = Σ exp(lse_i − m), m = max lse so far. The standard exact
    softmax decomposition: each block's normalized output re-weighted by
    its share of the global mass. A fully-masked hop folds in with
    weight 0 — masked means lse <= NEG_INF/2, covering BOTH the empty
    carry's true -inf and the kernels' finite NEG_INF sentinel (-1e30;
    r3 advisor: an isneginf-only guard gave a fully-masked partial
    weight 1 against an empty carry, surviving as its uniform-softmax
    artifact)."""
    m_new = jnp.maximum(m, lse_j)
    # exp(-inf - -inf) would be nan: a masked running max (nothing folded
    # yet) or a masked hop must contribute factor 0, not nan.
    alpha = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
    beta = jnp.where(lse_j <= NEG_INF / 2, 0.0, jnp.exp(lse_j - m_new))
    o_new = o * alpha[..., None] + o_j.astype(jnp.float32) * beta[..., None]
    return o_new, m_new, d_acc * alpha + beta


def _ring_attention_local_flash(q, k, v, axis_name: str, causal: bool,
                                interpret: bool):
    """Per-device body, flash-backed (r3): each hop's local attention runs
    through ``flash_attention_lse`` — the Pallas kernel when shapes tile
    (O(t_local·d) HBM per hop), the dense lse fallback otherwise — and
    hops merge EXACTLY via their logsumexp (_merge_partials). Versus the
    einsum body this never materializes the [t_local, t_local] score
    tensor on the kernel path, which is what caps per-device chunk sizes
    at long context (at t_local=8k, b=1, h=12 the per-hop score tensor
    alone is 3 GiB f32 — the kernel path needs none of it). Gradients are
    exact: flash_attention_lse's VJP includes the lse path, and autodiff
    composes it through the merge + scan + ppermute.

    Hop schedule: the diagonal hop (local K/V, causal mask iff causal)
    runs first, outside the scan; the scan then rotates K/V and folds
    each arriving block — under causal masking a block from a LATER
    device contributes nothing and is skipped via lax.cond (its flash
    call never runs; ICI rotation still proceeds)."""
    n = axis_size(axis_name)
    my_idx = axis_index(axis_name)
    b, t_local, h, d = q.shape

    attend = partial(flash_attention_lse, interpret=interpret)

    # Hop 0: the device's own K/V block — the only hop that can need a
    # causal mask (q and k positions share the same global block).
    o0, lse0 = attend(q, k, v, causal=causal)
    o_acc = jnp.zeros((b, t_local, h, d), jnp.float32)
    m0 = jnp.full((b, t_local, h), -jnp.inf, jnp.float32)
    o_acc, m_acc, d_acc = _merge_partials(
        o_acc, m0, jnp.zeros((b, t_local, h), jnp.float32), o0, lse0)

    def scan_body(carry, step):
        o_m_d, k_blk, v_blk = carry
        k_blk = ring_shift(k_blk, axis_name)
        v_blk = ring_shift(v_blk, axis_name)
        src = (my_idx - step) % n  # device whose block just arrived

        def live(_):
            return attend(q, k_blk, v_blk, causal=False)

        def skip(_):
            return (jnp.zeros((b, t_local, h, d), q.dtype),
                    jnp.full((b, t_local, h), -jnp.inf, jnp.float32))

        if causal:
            # src > my_idx ⇒ every key position is in the future of every
            # local query position ⇒ the hop is fully masked.
            o_j, lse_j = jax.lax.cond(src < my_idx, live, skip, None)
        else:
            o_j, lse_j = live(None)
        return ((_merge_partials(*o_m_d, o_j, lse_j), k_blk, v_blk), None)

    ((o_acc, m_acc, d_acc), _, _), _ = jax.lax.scan(
        scan_body, ((o_acc, m_acc, d_acc), k, v), jnp.arange(1, n))
    o = o_acc / jnp.where(d_acc == 0.0, 1.0, d_acc)[..., None]
    return o.astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    mesh,
    axis_name: str = "cp",
    causal: bool = False,
    batch_axes: Optional[tuple] = None,
    impl: Optional[str] = None,
    interpret: bool = False,
):
    """Exact self-attention with sequence sharded over ``axis_name``.

    q/k/v: global arrays [batch, seq, heads, head_dim] sharing one seq
    length divisible by the cp axis size. ``batch_axes``: mesh axes the
    batch dim is sharded over (kept sharded through the computation).

    ``impl``: "flash" (default — per-hop local attention through
    flash_attention_lse, Pallas kernel on TPU when shapes tile, dense
    lse fallback otherwise) or "einsum" (the blockwise online-softmax
    oracle body, materializes per-hop scores). ``interpret`` forces the
    flash path's kernels through the Pallas interpreter (CPU tests).
    """
    from tf_operator_tpu.parallel.collectives import (  # noqa: F401
        shard_map_compat as shard_map,
    )

    cp = mesh.shape[axis_name]
    if q.shape[1] != k.shape[1] or k.shape[1] != v.shape[1]:
        raise ValueError(
            f"ring attention is self-attention: q/k/v seq lengths must match, "
            f"got {q.shape[1]}/{k.shape[1]}/{v.shape[1]}"
        )
    if k.shape[2] != v.shape[2]:
        raise ValueError(f"k/v head mismatch: {k.shape[2]} vs {v.shape[2]}")
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"q heads {q.shape[2]} not a multiple of kv heads {k.shape[2]} "
            "(GQA group must divide evenly)"
        )
    if q.shape[1] % cp:
        raise ValueError(f"seq length {q.shape[1]} must divide by {axis_name}={cp}")
    if impl not in (None, "flash", "einsum"):
        raise ValueError(f"unknown ring attention impl {impl!r}")
    if impl == "einsum":
        body = partial(_ring_attention_local, axis_name=axis_name, causal=causal)
    else:
        body = partial(_ring_attention_local_flash, axis_name=axis_name,
                       causal=causal, interpret=interpret)
    spec = P(batch_axes, axis_name, None, None)
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
