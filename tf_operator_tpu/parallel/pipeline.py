"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pp`` axis.

Each pipeline stage lives on one slice of the ``pp`` mesh axis and holds its
own layer parameters; activations flow stage-to-stage with ``ppermute`` over
neighbor ICI links. The schedule is the classic GPipe fill-drain loop:
with S stages and M microbatches, T = M + S - 1 ticks; at tick t, stage s
computes microbatch (t - s) when 0 <= t - s < M. Bubble fraction
(S-1)/(M+S-1) shrinks as M grows.

The reference has no pipeline support at all (SURVEY.md §2.3); this is new
TPU-native surface.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tf_operator_tpu.parallel.collectives import axis_index, axis_size, ring_shift


def _pipeline_local(stage_params, x_micro, fn: Callable, axis_name: str):
    """Per-device body (inside shard_map).

    stage_params: this stage's params (leading dim of size 1 stripped).
    x_micro: [n_micro, mb, ...] — full microbatched input, replicated.
    Returns [n_micro, mb, ...] outputs (valid on the last stage; psum'ed so
    every stage returns the same array).
    """
    n_stages = axis_size(axis_name)
    stage = axis_index(axis_name)
    n_micro = x_micro.shape[0]
    mb_shape = x_micro.shape[1:]

    total_ticks = n_micro + n_stages - 1

    def tick(carry, t):
        prev_out, y_acc = carry
        # Receive activation from the previous stage (stage 0 receives
        # garbage from the last stage and ignores it).
        recv = ring_shift(prev_out, axis_name)
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        first_in = jax.lax.dynamic_index_in_dim(x_micro, mb_idx, keepdims=False)
        x_in = jnp.where(stage == 0, first_in, recv)
        out = fn(stage_params, x_in)
        # Last stage writes its result for microbatch t-(S-1) when valid.
        out_idx = t - (n_stages - 1)
        valid = (stage == n_stages - 1) & (out_idx >= 0) & (out_idx < n_micro)
        write_idx = jnp.clip(out_idx, 0, n_micro - 1)
        prev_slot = jax.lax.dynamic_index_in_dim(y_acc, write_idx, keepdims=False)
        new_slot = jnp.where(valid, out, prev_slot)
        y_acc = jax.lax.dynamic_update_index_in_dim(y_acc, new_slot, write_idx, 0)
        return (out, y_acc), None

    out0 = jnp.zeros(mb_shape, x_micro.dtype)
    y0 = jnp.zeros((n_micro,) + mb_shape, x_micro.dtype)
    (_, y), _ = jax.lax.scan(tick, (out0, y0), jnp.arange(total_ticks))
    # Broadcast the last stage's result to every stage (replicated output).
    y = jax.lax.psum(
        jnp.where(stage == n_stages - 1, y, jnp.zeros_like(y)), axis_name
    )
    return y


def pipeline_apply(
    stage_params,
    x,
    fn: Callable,
    mesh,
    n_microbatches: int,
    axis_name: str = "pp",
    batch_axes: tuple = ("dp", "fsdp"),
):
    """Run ``fn(stage_params, x_mb)`` as a pipeline over ``axis_name``.

    stage_params: pytree whose leaves have leading dim == pp size (one slice
    per stage). x: [batch, ...] input. fn must map a microbatch through ONE
    stage, preserving shape (classic equal-width pipeline). Returns
    [batch, ...] outputs.

    Composes with data parallelism: the microbatch dim shards over any
    ``batch_axes`` present in the mesh (each dp group runs its own
    pipeline over its batch slice — activations ppermute within the group,
    nothing crosses dp), while stage params shard over ``axis_name`` and
    replicate over the data axes.
    """
    from jax import shard_map

    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} not divisible by {n_microbatches} microbatches")
    mb = batch // n_microbatches
    x_micro = x.reshape((n_microbatches, mb) + x.shape[1:])

    data_axes = tuple(
        a for a in batch_axes
        if a in getattr(mesh, "axis_names", ()) and mesh.shape[a] > 1
    )
    n_data = 1
    for a in data_axes:
        n_data *= mesh.shape[a]
    if mb % n_data:
        raise ValueError(
            f"microbatch size {mb} (batch {batch} / {n_microbatches} "
            f"microbatches) not divisible by data shards {n_data}"
        )
    x_spec = P(None, data_axes or None)  # [n_micro, mb(sharded over dp), ...]
    param_specs = jax.tree_util.tree_map(lambda _: P(axis_name), stage_params)

    def body(params, xm):
        # strip the per-stage leading dim of 1
        local = jax.tree_util.tree_map(lambda a: a[0], params)
        return _pipeline_local(local, xm, fn, axis_name)

    out = shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stage_params, x_micro)
    return out.reshape((batch,) + out.shape[2:])
