"""Pipeline parallelism: microbatch schedules over the ``pp`` axis.

Each pipeline stage lives on one slice of the ``pp`` mesh axis and holds its
own layer parameters; activations flow stage-to-stage with ``ppermute`` over
neighbor ICI links. Two schedules:

- ``"gpipe"``: the classic fill-drain loop; the backward pass is whatever
  JAX autodiff derives from the forward scan. With S stages and M
  microbatches, T = M + S - 1 ticks per phase; bubble (S-1)/(M+S-1).
  Autodiff saves per-TICK residuals — T slots, garbage fill/drain ticks
  included.
- ``"1f1b"`` (r3): an explicit custom-VJP schedule with the 1F1B memory
  discipline — the forward saves ONLY each stage's M microbatch inputs,
  and the backward is a hand-scheduled reverse pipeline that recomputes
  each stage-microbatch forward via jax.vjp at its saved input (the
  standard 1F1B recompute recipe). Per-stage activation memory drops from
  M+S-1 tick-saves to M input-saves, and the backward never replays the
  fill/drain garbage ticks' residuals. Because JAX's grad boundary sits
  at the loss (all output cotangents arrive at once), the fwd and bwd
  phases cannot physically interleave — the schedule realizes 1F1B's
  memory/recompute structure, with the same 2(M+S-1)-tick timeline as
  GPipe at equal M. The practical bubble win is therefore what 1F1B's
  always was: at a FIXED activation budget the schedule affords a larger
  M — e.g. at pp=4 with an 8-slot budget, GPipe fits M=5 (bubble
  (S-1)/(M+S-1) = 37.5%) while 1F1B fits M=8 (27%); see
  ``bubble_fraction``.

- ``"1f1b"`` with ``n_chunks=v > 1`` (r3): the INTERLEAVED virtual-stage
  schedule. The model splits into J = S·v chunks; device d holds chunks
  d, d+S, …, d+(v-1)S, so a microbatch laps the ring v times. Schedule:
  microbatches run in rounds of S; round r's chunk-j execution of its
  m-th member lands at tick r·S·v + m + j on device j mod S. Two
  properties make this a single uniform scan: (a) within a round each
  device's executions occupy DISTINCT ticks (m < S and the device's
  chunks are S apart), and (b) consecutive rounds offset by S·v slot
  into each device's busy window back-to-back — so every activation
  produced at tick t is consumed at tick t+1 one neighbor over
  (chunk j → j+1 is device j%S → (j+1)%S, cyclic: the wrap S-1 → 0 is
  the same ppermute hop), no buffering, no stalls beyond fill/drain.
  Timeline: M·v + S - 1 ticks for M·v chunk-executions per device ⇒
  bubble (S-1)/(M·v + S - 1) — v times smaller than GPipe/plain-1F1B at
  equal M (27% → 16% at pp=4, M=4, v=2 → v=4). Costs: v·M saved stage
  inputs per device (vs M) and v× the ppermute volume — the standard
  interleaved trade. v=1 reduces exactly to the plain 1F1B schedule.

The reference has no pipeline support at all (SURVEY.md §2.3); this is new
TPU-native surface.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tf_operator_tpu.parallel.collectives import axis_index, axis_size, ring_shift


def _pipeline_local(stage_params, x_micro, fn: Callable, axis_name: str,
                    aux_size: int):
    """Per-device body (inside shard_map).

    stage_params: this stage's params (leading dim of size 1 stripped).
    x_micro: [n_micro, mb, ...] — full microbatched input, replicated.
    Returns [n_micro, mb, ...] outputs (valid on the last stage; psum'ed so
    every stage returns the same array).

    fn ALWAYS returns (out, aux[aux_size] f32) — plain stage bodies are
    wrapped by _with_aux at the call sites (a zero dummy row). aux rows
    are summable side losses (MoE router lb/z): each stage accumulates
    its VALID ticks' aux and returns the LOCAL sum (no collective — the
    caller stacks per-shard rows through the shard_map output and reduces
    outside it, where autodiff needs no collective-transpose reasoning).
    Returns (y, aux_local)."""
    n_stages = axis_size(axis_name)
    stage = axis_index(axis_name)
    n_micro = x_micro.shape[0]
    mb_shape = x_micro.shape[1:]

    total_ticks = n_micro + n_stages - 1

    def tick(carry, t):
        prev_out, y_acc, aux_acc = carry
        # Receive activation from the previous stage (stage 0 receives
        # garbage from the last stage and ignores it).
        recv = ring_shift(prev_out, axis_name)
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        first_in = jax.lax.dynamic_index_in_dim(x_micro, mb_idx, keepdims=False)
        x_in = jnp.where(stage == 0, first_in, recv)
        out, aux = fn(stage_params, x_in)
        live = (t - stage >= 0) & (t - stage < n_micro)
        aux_acc = aux_acc + jnp.where(live, aux, jnp.zeros_like(aux))
        # Last stage writes its result for microbatch t-(S-1) when valid.
        out_idx = t - (n_stages - 1)
        valid = (stage == n_stages - 1) & (out_idx >= 0) & (out_idx < n_micro)
        write_idx = jnp.clip(out_idx, 0, n_micro - 1)
        prev_slot = jax.lax.dynamic_index_in_dim(y_acc, write_idx, keepdims=False)
        new_slot = jnp.where(valid, out, prev_slot)
        y_acc = jax.lax.dynamic_update_index_in_dim(y_acc, new_slot, write_idx, 0)
        return (out, y_acc, aux_acc), None

    out0 = jnp.zeros(mb_shape, x_micro.dtype)
    y0 = jnp.zeros((n_micro,) + mb_shape, x_micro.dtype)
    aux0 = jnp.zeros((aux_size,), jnp.float32)
    (_, y, aux_acc), _ = jax.lax.scan(
        tick, (out0, y0, aux0), jnp.arange(total_ticks)
    )
    # Broadcast the last stage's result to every stage (replicated output).
    y = jax.lax.psum(
        jnp.where(stage == n_stages - 1, y, jnp.zeros_like(y)), axis_name
    )
    return y, aux_acc


def bubble_fraction(n_stages: int, n_micro: int, n_chunks: int = 1) -> float:
    """Idle fraction of the fill-drain timeline: (S-1)/(M·v + S-1).
    v = 1: both schedules share it at equal M — plain 1F1B's lever is
    affording a larger M at fixed activation memory. v > 1 (interleaved):
    the same S-1 fill/drain ticks amortize over v times the per-device
    work (module docstring)."""
    return (n_stages - 1) / (n_micro * n_chunks + n_stages - 1)


def _fwd_coords(t, stage, n_stages, n_micro, n_chunks):
    """Decode the interleaved forward schedule: at tick t, the device at
    ``stage`` executes chunk i (its i-th virtual stage, global
    j = stage + i·S) of microbatch m_total — or nothing (valid False).
    Derivation (module docstring): exec tick of round r's m-th member at
    virtual stage j is r·S·v + m + j, so with u = t - stage:
    u = (r·v + i)·S + m."""
    u = t - stage
    q = u // n_stages
    m = u % n_stages
    r = q // n_chunks
    i = q % n_chunks
    m_total = r * n_stages + m
    valid = (u >= 0) & (u < n_micro * n_chunks)
    return valid, jnp.clip(i, 0, n_chunks - 1), jnp.clip(m_total, 0, n_micro - 1)


def _bwd_coords(t, stage, n_stages, n_micro, n_chunks):
    """The backward schedule is the forward's mirror (stage → S-1-stage,
    chunk → v-1-chunk, round → R-1-round, member → S-1-member): cotangents
    enter at the last virtual stage on device S-1 and hop backwards one
    neighbor per tick, with the same contiguous busy windows."""
    ub = t - (n_stages - 1 - stage)
    valid = (ub >= 0) & (ub < n_micro * n_chunks)
    if n_chunks == 1:
        # plain mirror over microbatches — no round structure, so any M
        # (the interleaved decode below needs M % S == 0, enforced by
        # pipeline_apply for v > 1)
        m_total = n_micro - 1 - ub
        return valid, jnp.zeros_like(ub), jnp.clip(m_total, 0, n_micro - 1)
    qb = ub // n_stages
    mb = ub % n_stages
    rb = qb // n_chunks
    ib = qb % n_chunks
    n_rounds = n_micro // n_stages
    i = n_chunks - 1 - ib
    m_total = (n_rounds - 1 - rb) * n_stages + (n_stages - 1 - mb)
    return valid, jnp.clip(i, 0, n_chunks - 1), jnp.clip(m_total, 0, n_micro - 1)


def _fwd_save_ticks(stage_params, x_micro, fn: Callable, axis_name: str,
                    aux_size: int, n_chunks: int = 1):
    """_pipeline_local plus residual capture: returns (y, aux, x_saved)
    where x_saved[i·M + m] is THIS device's chunk-i input for microbatch
    m — the only activation the 1F1B backward needs (it recomputes the
    rest). stage_params carry a leading chunk dim [v, ...] (v = n_chunks;
    1 = plain 1F1B). Same fn contract as _pipeline_local: ALWAYS
    (out, aux) — wrap plain bodies with _with_aux."""
    n_stages = axis_size(axis_name)
    stage = axis_index(axis_name)
    n_micro = x_micro.shape[0]
    mb_shape = x_micro.shape[1:]
    total_ticks = n_micro * n_chunks + n_stages - 1

    def tick(carry, t):
        prev_out, y_acc, aux_acc, x_saved = carry
        recv = ring_shift(prev_out, axis_name)
        valid, ci, m_total = _fwd_coords(t, stage, n_stages, n_micro, n_chunks)
        first_in = jax.lax.dynamic_index_in_dim(x_micro, m_total, keepdims=False)
        # Fresh microbatches enter only at the FIRST virtual stage (device
        # 0, chunk 0); every other execution consumes its neighbor's hop.
        x_in = jnp.where((stage == 0) & (ci == 0), first_in, recv)
        slot = ci * n_micro + m_total
        prev_save = jax.lax.dynamic_index_in_dim(x_saved, slot, keepdims=False)
        x_saved = jax.lax.dynamic_update_index_in_dim(
            x_saved, jnp.where(valid, x_in, prev_save), slot, 0
        )
        params_i = jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, ci, keepdims=False),
            stage_params,
        )
        out, aux = fn(params_i, x_in)
        aux_acc = aux_acc + jnp.where(valid, aux, jnp.zeros_like(aux))
        # The LAST virtual stage (device S-1, chunk v-1) emits results.
        ovalid = valid & (stage == n_stages - 1) & (ci == n_chunks - 1)
        prev_slot = jax.lax.dynamic_index_in_dim(y_acc, m_total, keepdims=False)
        y_acc = jax.lax.dynamic_update_index_in_dim(
            y_acc, jnp.where(ovalid, out, prev_slot), m_total, 0
        )
        return (out, y_acc, aux_acc, x_saved), None

    out0 = jnp.zeros(mb_shape, x_micro.dtype)
    y0 = jnp.zeros((n_micro,) + mb_shape, x_micro.dtype)
    aux0 = jnp.zeros((aux_size,), jnp.float32)
    s0 = jnp.zeros((n_chunks * n_micro,) + mb_shape, x_micro.dtype)
    (_, y, aux_acc, x_saved), _ = jax.lax.scan(
        tick, (out0, y0, aux0, s0), jnp.arange(total_ticks)
    )
    y = jax.lax.psum(
        jnp.where(stage == n_stages - 1, y, jnp.zeros_like(y)), axis_name
    )
    return y, aux_acc, x_saved


def _bwd_ticks(stage_params, x_saved, gy, fn: Callable, axis_name: str, g_aux,
               n_chunks: int = 1):
    """The reverse pipeline: cotangents enter at the LAST virtual stage
    (device S-1, chunk v-1) and ppermute backwards one neighbor per tick
    (_bwd_coords — the forward schedule's mirror); each tick recomputes
    its (chunk, microbatch) forward from the saved input via jax.vjp
    (1F1B recompute) and accumulates that chunk's param grads. Inputs:
    stage_params [v, ...] per-chunk, x_saved [v·M, mb...] as
    _fwd_save_ticks wrote it. Returns (dparams [v, ...], dx) with dx
    valid on stage 0 (psum-broadcast like the forward's y).

    tp-within-stage note: ``fn`` must handle its own tp cotangent algebra
    via the Megatron f/g conjugate pair (collectives.tp_region_enter/
    tp_region_exit, as models/transformer._layer does) — with those in
    place every shard's vjp already yields the full replicated input
    cotangent, so no stage-level reduction is needed here (and a naive
    psum of dx would double-count the residual path)."""
    n_stages = axis_size(axis_name)
    stage = axis_index(axis_name)
    n_micro = x_saved.shape[0] // n_chunks
    mb_shape = x_saved.shape[1:]
    total_ticks = n_micro * n_chunks + n_stages - 1

    dp0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), stage_params
    )

    def tick(carry, t):
        prev_dx, dp_acc, dx_acc = carry
        recv = ring_shift(prev_dx, axis_name, shift=-1)  # from stage s+1
        valid, ci, m_total = _bwd_coords(t, stage, n_stages, n_micro, n_chunks)
        g_in = jnp.where(
            (stage == n_stages - 1) & (ci == n_chunks - 1),
            jax.lax.dynamic_index_in_dim(gy, m_total, keepdims=False),
            recv,
        )
        slot = ci * n_micro + m_total
        x_in = jax.lax.dynamic_index_in_dim(x_saved, slot, keepdims=False)
        params_i = jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, ci, keepdims=False),
            stage_params,
        )
        _, vjp_fn = jax.vjp(fn, params_i, x_in)
        # every valid tick's aux entered the sum with weight 1, so its
        # cotangent is g_aux itself; invalid ticks' pollution of dparams
        # is masked below and their dx never reaches a valid consumer
        # (the reverse schedule masks by the same validity)
        dp, dx = vjp_fn((g_in, g_aux))
        dp_acc = jax.tree_util.tree_map(
            lambda acc, new: jax.lax.dynamic_update_index_in_dim(
                acc,
                jax.lax.dynamic_index_in_dim(acc, ci, keepdims=False)
                + jnp.where(valid, new.astype(jnp.float32),
                            jnp.zeros_like(new, jnp.float32)),
                ci, 0,
            ),
            dp_acc,
            dp,
        )
        w_valid = valid & (stage == 0) & (ci == 0)
        prev_slot = jax.lax.dynamic_index_in_dim(dx_acc, m_total, keepdims=False)
        dx_acc = jax.lax.dynamic_update_index_in_dim(
            dx_acc, jnp.where(w_valid, dx, prev_slot), m_total, 0
        )
        return (dx, dp_acc, dx_acc), None

    dx0 = jnp.zeros(mb_shape, x_saved.dtype)
    dxa0 = jnp.zeros((n_micro,) + mb_shape, x_saved.dtype)
    (_, dparams, dx), _ = jax.lax.scan(
        tick, (dx0, dp0, dxa0), jnp.arange(total_ticks)
    )
    dx = jax.lax.psum(
        jnp.where(stage == 0, dx, jnp.zeros_like(dx)), axis_name
    )
    dparams = jax.tree_util.tree_map(
        lambda g, p: g.astype(p.dtype), dparams, stage_params
    )
    return dparams, dx


def _shard_specs(stage_params, x, mesh, n_microbatches, axis_name, batch_axes,
                 param_specs):
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} not divisible by {n_microbatches} microbatches")
    mb = batch // n_microbatches
    data_axes = tuple(
        a for a in batch_axes
        if a in getattr(mesh, "axis_names", ()) and mesh.shape[a] > 1
    )
    n_data = 1
    for a in data_axes:
        n_data *= mesh.shape[a]
    if mb % n_data:
        raise ValueError(
            f"microbatch size {mb} (batch {batch} / {n_microbatches} "
            f"microbatches) not divisible by data shards {n_data}"
        )
    # STRIDED microbatch layout (r5, VERDICT r4 #3): microbatch i takes
    # rows [i::n_micro], i.e. x_micro[i, j] = x[j*n_micro + i], built as
    # reshape(mb, n_micro)+swapaxes. A microbatch-MAJOR split
    # (x.reshape(n_micro, mb)) can never be computed locally under a
    # batch-dim sharding — microbatch 0 = rows [0, mb) spans several
    # shards' contiguous blocks, so GSPMD falls back to "involuntary full
    # rematerialization" (replicate then re-slice) on every entry to and
    # exit from the pipeline's shard_map. With the strided split, target
    # device g's rows {j*n_micro + i : j in g's mb-block} ARE g's
    # contiguous batch block: the reshape is layout-local. Which rows
    # form a microbatch is internal to the pipeline (the inverse
    # permutation at the exit restores batch order exactly), so the math
    # is unchanged up to microbatch membership — the same freedom any
    # pipeline implementation exercises. The with_sharding_constraint
    # anchors x's batch dim to the data axes so the propagated layout
    # matches the local-reshape contract.
    if data_axes and getattr(mesh, "devices", None) is not None:
        from jax.sharding import NamedSharding

        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(data_axes, *(None,) * (x.ndim - 1)))
        )
    x_micro = jnp.swapaxes(
        x.reshape((mb, n_microbatches) + x.shape[1:]), 0, 1
    )
    x_spec = P(None, data_axes or None)  # [n_micro, mb(sharded over dp), ...]
    if param_specs is None:
        param_specs = jax.tree_util.tree_map(lambda _: P(axis_name), stage_params)
    return x_micro, x_spec, param_specs, data_axes


def pipeline_apply(
    stage_params,
    x,
    fn: Callable,
    mesh,
    n_microbatches: int,
    axis_name: str = "pp",
    batch_axes: tuple = ("dp", "fsdp"),
    schedule: str = "gpipe",
    param_specs=None,
    aux_size: int = 0,
    n_chunks: int = 1,
):
    """Run ``fn(stage_params, x_mb)`` as a pipeline over ``axis_name``.

    stage_params: pytree whose leaves have leading dim == pp size ×
    ``n_chunks`` (one slice per VIRTUAL stage, in model order — chunk j
    runs on device j mod pp). x: [batch, ...] input. fn must map a
    microbatch through ONE virtual stage, preserving shape (classic
    equal-width pipeline). Returns [batch, ...] outputs.

    ``n_chunks``: virtual stages per device (the interleaved 1F1B
    schedule, module docstring) — requires schedule="1f1b" and
    n_microbatches % pp == 0; bubble shrinks to
    (pp-1)/(n_micro·v + pp-1).

    ``aux_size`` > 0: fn instead returns (x_mb_out, aux[aux_size] f32) —
    summable side losses (MoE router lb/z). pipeline_apply then returns
    (y, aux_total) where aux_total sums every (stage, microbatch)
    contribution (psum over pp, mean over the data axes) — the caller
    normalizes by layers x microbatches. Differentiable under both
    schedules (the 1F1B backward feeds each tick's vjp the aux cotangent
    directly).

    ``schedule``: "gpipe" (autodiff backward) or "1f1b" (explicit
    custom-VJP backward with stage-input-only residuals + recompute — the
    1F1B memory discipline; see module docstring).

    ``param_specs``: optional pytree of PartitionSpecs for stage_params
    (leading dim must map to ``axis_name``); default shards ONLY the stage
    dim and replicates the rest. Passing specs with a tensor axis (e.g.
    P("pp", None, "tp")) enables tp-within-stage — ``fn`` then runs on
    tp-local weight shards and must psum its row-parallel outputs over the
    tp axis itself (models/transformer._layer does when given tp_axis).

    Composes with data parallelism: the microbatch dim shards over any
    ``batch_axes`` present in the mesh (each dp group runs its own
    pipeline over its batch slice — activations ppermute within the group,
    nothing crosses dp), while stage params shard over ``axis_name`` (+ tp
    when param_specs say so) and replicate over the data axes.
    """
    from tf_operator_tpu.parallel.collectives import (  # noqa: F401
        shard_map_compat as shard_map,
    )

    batch = x.shape[0]
    if n_chunks > 1:
        if schedule != "1f1b":
            raise ValueError("n_chunks > 1 (interleaved) requires schedule='1f1b'")
        if n_microbatches % mesh.shape[axis_name]:
            raise ValueError(
                f"interleaved schedule needs n_microbatches "
                f"({n_microbatches}) divisible by {axis_name}="
                f"{mesh.shape[axis_name]} (round structure)"
            )
    x_micro, x_spec, param_specs, data_axes = _shard_specs(
        stage_params, x, mesh, n_microbatches, axis_name, batch_axes, param_specs
    )

    if schedule == "1f1b":
        res = _apply_1f1b(
            stage_params, x_micro, fn, mesh, axis_name, x_spec, param_specs,
            data_axes, aux_size, n_chunks,
        )
    elif schedule == "gpipe":
        def body(params, xm):
            # strip the per-stage leading dim of 1
            local = jax.tree_util.tree_map(lambda a: a[0], params)
            y, aux = _pipeline_local(
                local, xm, _with_aux(fn, aux_size), axis_name, max(aux_size, 1)
            )
            return y, aux[None]  # [1, k] row per (stage, data-shard)

        aux_spec = P((axis_name,) + data_axes, None)
        res = shard_map(
            body,
            mesh=mesh,
            in_specs=(param_specs, x_spec),
            out_specs=(x_spec, aux_spec),
        )(stage_params, x_micro)
    else:
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    out, aux_rows = res
    # invert the strided microbatch split: [n_micro, mb, ...] -> [batch]
    # with out[j*n_micro + i] = out_micro[i, j] (see _shard_specs)
    out = jnp.swapaxes(out, 0, 1).reshape((batch,) + out.shape[2:])
    if aux_size:
        return out, _reduce_aux_rows(aux_rows, mesh, axis_name, data_axes, aux_size)
    return out


def _with_aux(fn, aux_size: int):
    """Uniform stage-body contract: fn always returns (out, aux_row). A
    non-aux fn gets a zero dummy row so one code path serves both cases
    (the [1]-vector costs nothing and its cotangent is discarded)."""
    if aux_size:
        return fn
    return lambda p, x: (fn(p, x), jnp.zeros((1,), jnp.float32))


def _reduce_aux_rows(aux_rows, mesh, axis_name, data_axes, aux_size):
    """[S * n_data, k] stacked per-shard aux sums -> [k]: SUM over stages
    (each stage holds distinct layers), MEAN over data shards (each routes
    its own batch slice). Plain jnp outside the shard_map — autodiff
    differentiates it natively, so the cotangent rows arriving back at
    each shard already carry the right scaling."""
    n_data = 1
    for ax in data_axes:
        n_data *= mesh.shape[ax]
    rows = aux_rows.reshape(mesh.shape[axis_name], n_data, aux_size)
    return rows.sum(axis=0).mean(axis=0)


def _apply_1f1b(stage_params, x_micro, fn, mesh, axis_name, x_spec, param_specs,
                data_axes, aux_size: int = 0, n_chunks: int = 1):
    """custom-VJP wrapper: forward ticks save stage inputs; backward runs
    the explicit reverse pipeline (_bwd_ticks). One body serves the aux
    and non-aux cases (_with_aux dummy row): the primal output is always
    (y, aux_rows[S*n_data, k]); the caller reduces the rows outside the
    shard_map (sum over stages, mean over data shards), so aux cotangent
    rows arrive back per shard already correctly scaled and feed straight
    into every valid tick's vjp (a discarded dummy row's cotangent is
    zeros).

    Interleaved (n_chunks = v > 1): the caller's [S·v, ...] virtual-stage
    params reshape to [v, S, ...] OUTSIDE the custom_vjp (chunk j = i·S+d
    lands at [i, d] — device d's i-th chunk; autodiff transposes the
    reshape on the way back), specs shift to P(None, axis_name, …), and
    the local tick bodies see chunk-major [v, ...] params. v = 1 keeps
    the [S, ...] layout where the local [1, ...] block IS chunk-major."""
    from tf_operator_tpu.parallel.collectives import (  # noqa: F401
        shard_map_compat as shard_map,
    )

    fn2 = _with_aux(fn, aux_size)
    k = max(aux_size, 1)
    n_stages = mesh.shape[axis_name]
    # saved stage inputs live stage-major: [S, v*M, mb, ...]
    saved_spec = P(axis_name, *x_spec)
    aux_spec = P((axis_name,) + data_axes, None)

    is_spec = lambda s: isinstance(s, P)
    if n_chunks > 1:
        pspecs = jax.tree_util.tree_map(
            lambda s: P(None, *s), param_specs, is_leaf=is_spec)
        prepare = lambda p: jax.tree_util.tree_map(
            lambda a: a.reshape((n_chunks, n_stages) + a.shape[1:]), p)
        to_local = lambda p: jax.tree_util.tree_map(lambda a: a[:, 0], p)
        from_local = lambda d: jax.tree_util.tree_map(lambda a: a[:, None], d)
    else:
        pspecs = param_specs
        prepare = lambda p: p
        to_local = lambda p: p      # local [1, ...] block is chunk-major
        from_local = lambda d: d

    @jax.custom_vjp
    def run(params, xm):
        out, _ = run_fwd(params, xm)
        return out

    def run_fwd(params, xm):
        def body(p, x):
            y, aux, x_saved = _fwd_save_ticks(
                to_local(p), x, fn2, axis_name, k, n_chunks)
            return y, aux[None], x_saved[None]

        y, aux_rows, x_saved = shard_map(
            body, mesh=mesh,
            in_specs=(pspecs, x_spec),
            out_specs=(x_spec, aux_spec, saved_spec),
        )(params, xm)
        return (y, aux_rows), (params, x_saved)

    def run_bwd(residuals, g):
        params, x_saved = residuals
        gy, gaux_rows = g

        def _spec_axes(s):
            names = set()
            for part in s:
                if part is None:
                    continue
                for a in (part if isinstance(part, (tuple, list)) else (part,)):
                    if a:
                        names.add(a)
            return names

        # Per-leaf data-axis reduction (r4): a param leaf's grad is summed
        # over exactly the data axes the leaf REPLICATES over. An axis
        # the leaf's spec SHARDS (ep on expert-weight leaves under
        # ep-in-stage MoE) must NOT be psum'd — each ep shard's slice is
        # a different parameter block, and summing across it scrambles
        # the expert gradients (caught by the pp x ep oracle).
        # CSV strings because tuples are pytree nodes, not leaves.
        reduce_axes = jax.tree_util.tree_map(
            lambda s: ",".join(ax for ax in data_axes
                               if ax not in _spec_axes(s)),
            pspecs, is_leaf=is_spec,
        )

        def body(p, saved, gy_in, gaux_row):
            dparams, dx = _bwd_ticks(
                to_local(p),
                jax.tree_util.tree_map(lambda a: a[0], saved),
                gy_in, fn2, axis_name,
                gaux_row[0].astype(jnp.float32),
                n_chunks,
            )
            # params replicate over (most of) the data axes, so each data
            # shard holds PARTIAL grads from its batch slice — sum them
            # (the psum autodiff's transpose machinery would have
            # inserted), leaf by leaf per reduce_axes above.
            def reduce_leaf(a, axes_csv):
                for ax in (axes_csv.split(",") if axes_csv else ()):
                    a = jax.lax.psum(a, ax)
                return a

            # reduce_axes shares dparams' tree STRUCTURE (to_local only
            # reshapes leaves), so it zips directly
            dparams = jax.tree_util.tree_map(reduce_leaf, dparams, reduce_axes)
            return from_local(dparams), dx

        dparams, dx = shard_map(
            body, mesh=mesh,
            in_specs=(pspecs, saved_spec, x_spec, aux_spec),
            out_specs=(pspecs, x_spec),
        )(params, x_saved, gy, gaux_rows)
        return dparams, dx

    run.defvjp(run_fwd, run_bwd)
    return run(prepare(stage_params), x_micro)
