"""Pipeline parallelism: microbatch schedules over the ``pp`` axis.

Each pipeline stage lives on one slice of the ``pp`` mesh axis and holds its
own layer parameters; activations flow stage-to-stage with ``ppermute`` over
neighbor ICI links. Two schedules:

- ``"gpipe"``: the classic fill-drain loop; the backward pass is whatever
  JAX autodiff derives from the forward scan. With S stages and M
  microbatches, T = M + S - 1 ticks per phase; bubble (S-1)/(M+S-1).
  Autodiff saves per-TICK residuals — T slots, garbage fill/drain ticks
  included.
- ``"1f1b"`` (r3): an explicit custom-VJP schedule with the 1F1B memory
  discipline — the forward saves ONLY each stage's M microbatch inputs,
  and the backward is a hand-scheduled reverse pipeline that recomputes
  each stage-microbatch forward via jax.vjp at its saved input (the
  standard 1F1B recompute recipe). Per-stage activation memory drops from
  M+S-1 tick-saves to M input-saves, and the backward never replays the
  fill/drain garbage ticks' residuals. Because JAX's grad boundary sits
  at the loss (all output cotangents arrive at once), the fwd and bwd
  phases cannot physically interleave — the schedule realizes 1F1B's
  memory/recompute structure, with the same 2(M+S-1)-tick timeline as
  GPipe at equal M. The practical bubble win is therefore what 1F1B's
  always was: at a FIXED activation budget the schedule affords a larger
  M — e.g. at pp=4 with an 8-slot budget, GPipe fits M=5 (bubble
  (S-1)/(M+S-1) = 37.5%) while 1F1B fits M=8 (27%); see
  ``bubble_fraction``.

Future surface: the interleaved (virtual-stage) schedule — v chunks per
device shrink the bubble to ~(S-1)/(vM+S-1) at the price of v-times the
ppermute volume and activation saves. The tick/table machinery here
extends to it (a statically built [tick, device] -> (chunk, microbatch)
schedule with the same uniform ring shift); not yet implemented.

The reference has no pipeline support at all (SURVEY.md §2.3); this is new
TPU-native surface.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tf_operator_tpu.parallel.collectives import axis_index, axis_size, ring_shift


def _pipeline_local(stage_params, x_micro, fn: Callable, axis_name: str,
                    aux_size: int):
    """Per-device body (inside shard_map).

    stage_params: this stage's params (leading dim of size 1 stripped).
    x_micro: [n_micro, mb, ...] — full microbatched input, replicated.
    Returns [n_micro, mb, ...] outputs (valid on the last stage; psum'ed so
    every stage returns the same array).

    fn ALWAYS returns (out, aux[aux_size] f32) — plain stage bodies are
    wrapped by _with_aux at the call sites (a zero dummy row). aux rows
    are summable side losses (MoE router lb/z): each stage accumulates
    its VALID ticks' aux and returns the LOCAL sum (no collective — the
    caller stacks per-shard rows through the shard_map output and reduces
    outside it, where autodiff needs no collective-transpose reasoning).
    Returns (y, aux_local)."""
    n_stages = axis_size(axis_name)
    stage = axis_index(axis_name)
    n_micro = x_micro.shape[0]
    mb_shape = x_micro.shape[1:]

    total_ticks = n_micro + n_stages - 1

    def tick(carry, t):
        prev_out, y_acc, aux_acc = carry
        # Receive activation from the previous stage (stage 0 receives
        # garbage from the last stage and ignores it).
        recv = ring_shift(prev_out, axis_name)
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        first_in = jax.lax.dynamic_index_in_dim(x_micro, mb_idx, keepdims=False)
        x_in = jnp.where(stage == 0, first_in, recv)
        out, aux = fn(stage_params, x_in)
        live = (t - stage >= 0) & (t - stage < n_micro)
        aux_acc = aux_acc + jnp.where(live, aux, jnp.zeros_like(aux))
        # Last stage writes its result for microbatch t-(S-1) when valid.
        out_idx = t - (n_stages - 1)
        valid = (stage == n_stages - 1) & (out_idx >= 0) & (out_idx < n_micro)
        write_idx = jnp.clip(out_idx, 0, n_micro - 1)
        prev_slot = jax.lax.dynamic_index_in_dim(y_acc, write_idx, keepdims=False)
        new_slot = jnp.where(valid, out, prev_slot)
        y_acc = jax.lax.dynamic_update_index_in_dim(y_acc, new_slot, write_idx, 0)
        return (out, y_acc, aux_acc), None

    out0 = jnp.zeros(mb_shape, x_micro.dtype)
    y0 = jnp.zeros((n_micro,) + mb_shape, x_micro.dtype)
    aux0 = jnp.zeros((aux_size,), jnp.float32)
    (_, y, aux_acc), _ = jax.lax.scan(
        tick, (out0, y0, aux0), jnp.arange(total_ticks)
    )
    # Broadcast the last stage's result to every stage (replicated output).
    y = jax.lax.psum(
        jnp.where(stage == n_stages - 1, y, jnp.zeros_like(y)), axis_name
    )
    return y, aux_acc


def bubble_fraction(n_stages: int, n_micro: int) -> float:
    """Idle fraction of the fill-drain timeline: (S-1)/(M+S-1). Both
    schedules share it at equal M; 1F1B's lever is affording a larger M at
    fixed activation memory (module docstring)."""
    return (n_stages - 1) / (n_micro + n_stages - 1)


def _fwd_save_ticks(stage_params, x_micro, fn: Callable, axis_name: str,
                    aux_size: int):
    """_pipeline_local plus residual capture: returns (y, aux, x_saved)
    where x_saved[m] is THIS stage's input for microbatch m — the only
    activation the 1F1B backward needs (it recomputes the rest). Same fn
    contract as _pipeline_local: ALWAYS (out, aux) — wrap plain bodies
    with _with_aux."""
    n_stages = axis_size(axis_name)
    stage = axis_index(axis_name)
    n_micro = x_micro.shape[0]
    mb_shape = x_micro.shape[1:]
    total_ticks = n_micro + n_stages - 1

    def tick(carry, t):
        prev_out, y_acc, aux_acc, x_saved = carry
        recv = ring_shift(prev_out, axis_name)
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        first_in = jax.lax.dynamic_index_in_dim(x_micro, mb_idx, keepdims=False)
        x_in = jnp.where(stage == 0, first_in, recv)
        # stage s processes microbatch t-s at tick t
        m = t - stage
        valid = (m >= 0) & (m < n_micro)
        slot = jnp.clip(m, 0, n_micro - 1)
        prev_save = jax.lax.dynamic_index_in_dim(x_saved, slot, keepdims=False)
        x_saved = jax.lax.dynamic_update_index_in_dim(
            x_saved, jnp.where(valid, x_in, prev_save), slot, 0
        )
        out, aux = fn(stage_params, x_in)
        aux_acc = aux_acc + jnp.where(valid, aux, jnp.zeros_like(aux))
        out_idx = t - (n_stages - 1)
        ovalid = (stage == n_stages - 1) & (out_idx >= 0) & (out_idx < n_micro)
        write_idx = jnp.clip(out_idx, 0, n_micro - 1)
        prev_slot = jax.lax.dynamic_index_in_dim(y_acc, write_idx, keepdims=False)
        y_acc = jax.lax.dynamic_update_index_in_dim(
            y_acc, jnp.where(ovalid, out, prev_slot), write_idx, 0
        )
        return (out, y_acc, aux_acc, x_saved), None

    out0 = jnp.zeros(mb_shape, x_micro.dtype)
    y0 = jnp.zeros((n_micro,) + mb_shape, x_micro.dtype)
    aux0 = jnp.zeros((aux_size,), jnp.float32)
    s0 = jnp.zeros((n_micro,) + mb_shape, x_micro.dtype)
    (_, y, aux_acc, x_saved), _ = jax.lax.scan(
        tick, (out0, y0, aux0, s0), jnp.arange(total_ticks)
    )
    y = jax.lax.psum(
        jnp.where(stage == n_stages - 1, y, jnp.zeros_like(y)), axis_name
    )
    return y, aux_acc, x_saved


def _bwd_ticks(stage_params, x_saved, gy, fn: Callable, axis_name: str, g_aux):
    """The reverse pipeline: cotangents enter at the LAST stage and
    ppermute backwards; stage s handles microbatch m = t - (S-1-s) at tick
    t, recomputing its forward from the saved input via jax.vjp (1F1B
    recompute) and accumulating param grads. Returns (dparams, dx) with
    dx valid on stage 0 (psum-broadcast like the forward's y).

    tp-within-stage note: ``fn`` must handle its own tp cotangent algebra
    via the Megatron f/g conjugate pair (collectives.tp_region_enter/
    tp_region_exit, as models/transformer._layer does) — with those in
    place every shard's vjp already yields the full replicated input
    cotangent, so no stage-level reduction is needed here (and a naive
    psum of dx would double-count the residual path)."""
    n_stages = axis_size(axis_name)
    stage = axis_index(axis_name)
    n_micro = x_saved.shape[0]
    mb_shape = x_saved.shape[1:]
    total_ticks = n_micro + n_stages - 1

    dp0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), stage_params
    )

    def tick(carry, t):
        prev_dx, dp_acc, dx_acc = carry
        recv = ring_shift(prev_dx, axis_name, shift=-1)  # from stage s+1
        m = t - (n_stages - 1 - stage)
        valid = (m >= 0) & (m < n_micro)
        slot = jnp.clip(m, 0, n_micro - 1)
        g_in = jnp.where(
            stage == n_stages - 1,
            jax.lax.dynamic_index_in_dim(gy, slot, keepdims=False),
            recv,
        )
        x_in = jax.lax.dynamic_index_in_dim(x_saved, slot, keepdims=False)
        _, vjp_fn = jax.vjp(fn, stage_params, x_in)
        # every valid tick's aux entered the sum with weight 1, so its
        # cotangent is g_aux itself; invalid ticks' pollution of dparams
        # is masked below and their dx never reaches a valid consumer
        # (the reverse schedule masks by the same validity)
        dp, dx = vjp_fn((g_in, g_aux))
        dp_acc = jax.tree_util.tree_map(
            lambda acc, new: acc
            + jnp.where(valid, new.astype(jnp.float32), jnp.zeros_like(new, jnp.float32)),
            dp_acc,
            dp,
        )
        w_valid = valid & (stage == 0)
        prev_slot = jax.lax.dynamic_index_in_dim(dx_acc, slot, keepdims=False)
        dx_acc = jax.lax.dynamic_update_index_in_dim(
            dx_acc, jnp.where(w_valid, dx, prev_slot), slot, 0
        )
        return (dx, dp_acc, dx_acc), None

    dx0 = jnp.zeros(mb_shape, x_saved.dtype)
    dxa0 = jnp.zeros((n_micro,) + mb_shape, x_saved.dtype)
    (_, dparams, dx), _ = jax.lax.scan(
        tick, (dx0, dp0, dxa0), jnp.arange(total_ticks)
    )
    dx = jax.lax.psum(
        jnp.where(stage == 0, dx, jnp.zeros_like(dx)), axis_name
    )
    dparams = jax.tree_util.tree_map(
        lambda g, p: g.astype(p.dtype), dparams, stage_params
    )
    return dparams, dx


def _shard_specs(stage_params, x, mesh, n_microbatches, axis_name, batch_axes,
                 param_specs):
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} not divisible by {n_microbatches} microbatches")
    mb = batch // n_microbatches
    x_micro = x.reshape((n_microbatches, mb) + x.shape[1:])
    data_axes = tuple(
        a for a in batch_axes
        if a in getattr(mesh, "axis_names", ()) and mesh.shape[a] > 1
    )
    n_data = 1
    for a in data_axes:
        n_data *= mesh.shape[a]
    if mb % n_data:
        raise ValueError(
            f"microbatch size {mb} (batch {batch} / {n_microbatches} "
            f"microbatches) not divisible by data shards {n_data}"
        )
    x_spec = P(None, data_axes or None)  # [n_micro, mb(sharded over dp), ...]
    if param_specs is None:
        param_specs = jax.tree_util.tree_map(lambda _: P(axis_name), stage_params)
    return x_micro, x_spec, param_specs, data_axes


def pipeline_apply(
    stage_params,
    x,
    fn: Callable,
    mesh,
    n_microbatches: int,
    axis_name: str = "pp",
    batch_axes: tuple = ("dp", "fsdp"),
    schedule: str = "gpipe",
    param_specs=None,
    aux_size: int = 0,
):
    """Run ``fn(stage_params, x_mb)`` as a pipeline over ``axis_name``.

    stage_params: pytree whose leaves have leading dim == pp size (one slice
    per stage). x: [batch, ...] input. fn must map a microbatch through ONE
    stage, preserving shape (classic equal-width pipeline). Returns
    [batch, ...] outputs.

    ``aux_size`` > 0: fn instead returns (x_mb_out, aux[aux_size] f32) —
    summable side losses (MoE router lb/z). pipeline_apply then returns
    (y, aux_total) where aux_total sums every (stage, microbatch)
    contribution (psum over pp, mean over the data axes) — the caller
    normalizes by layers x microbatches. Differentiable under both
    schedules (the 1F1B backward feeds each tick's vjp the aux cotangent
    directly).

    ``schedule``: "gpipe" (autodiff backward) or "1f1b" (explicit
    custom-VJP backward with stage-input-only residuals + recompute — the
    1F1B memory discipline; see module docstring).

    ``param_specs``: optional pytree of PartitionSpecs for stage_params
    (leading dim must map to ``axis_name``); default shards ONLY the stage
    dim and replicates the rest. Passing specs with a tensor axis (e.g.
    P("pp", None, "tp")) enables tp-within-stage — ``fn`` then runs on
    tp-local weight shards and must psum its row-parallel outputs over the
    tp axis itself (models/transformer._layer does when given tp_axis).

    Composes with data parallelism: the microbatch dim shards over any
    ``batch_axes`` present in the mesh (each dp group runs its own
    pipeline over its batch slice — activations ppermute within the group,
    nothing crosses dp), while stage params shard over ``axis_name`` (+ tp
    when param_specs say so) and replicate over the data axes.
    """
    from jax import shard_map

    batch = x.shape[0]
    x_micro, x_spec, param_specs, data_axes = _shard_specs(
        stage_params, x, mesh, n_microbatches, axis_name, batch_axes, param_specs
    )

    if schedule == "1f1b":
        res = _apply_1f1b(
            stage_params, x_micro, fn, mesh, axis_name, x_spec, param_specs,
            data_axes, aux_size,
        )
    elif schedule == "gpipe":
        def body(params, xm):
            # strip the per-stage leading dim of 1
            local = jax.tree_util.tree_map(lambda a: a[0], params)
            y, aux = _pipeline_local(
                local, xm, _with_aux(fn, aux_size), axis_name, max(aux_size, 1)
            )
            return y, aux[None]  # [1, k] row per (stage, data-shard)

        aux_spec = P((axis_name,) + data_axes, None)
        res = shard_map(
            body,
            mesh=mesh,
            in_specs=(param_specs, x_spec),
            out_specs=(x_spec, aux_spec),
            check_vma=False,
        )(stage_params, x_micro)
    else:
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    out, aux_rows = res
    out = out.reshape((batch,) + out.shape[2:])
    if aux_size:
        return out, _reduce_aux_rows(aux_rows, mesh, axis_name, data_axes, aux_size)
    return out


def _with_aux(fn, aux_size: int):
    """Uniform stage-body contract: fn always returns (out, aux_row). A
    non-aux fn gets a zero dummy row so one code path serves both cases
    (the [1]-vector costs nothing and its cotangent is discarded)."""
    if aux_size:
        return fn
    return lambda p, x: (fn(p, x), jnp.zeros((1,), jnp.float32))


def _reduce_aux_rows(aux_rows, mesh, axis_name, data_axes, aux_size):
    """[S * n_data, k] stacked per-shard aux sums -> [k]: SUM over stages
    (each stage holds distinct layers), MEAN over data shards (each routes
    its own batch slice). Plain jnp outside the shard_map — autodiff
    differentiates it natively, so the cotangent rows arriving back at
    each shard already carry the right scaling."""
    n_data = 1
    for ax in data_axes:
        n_data *= mesh.shape[ax]
    rows = aux_rows.reshape(mesh.shape[axis_name], n_data, aux_size)
    return rows.sum(axis=0).mean(axis=0)


def _apply_1f1b(stage_params, x_micro, fn, mesh, axis_name, x_spec, param_specs,
                data_axes, aux_size: int = 0):
    """custom-VJP wrapper: forward ticks save stage inputs; backward runs
    the explicit reverse pipeline (_bwd_ticks). One body serves the aux
    and non-aux cases (_with_aux dummy row): the primal output is always
    (y, aux_rows[S*n_data, k]); the caller reduces the rows outside the
    shard_map (sum over stages, mean over data shards), so aux cotangent
    rows arrive back per shard already correctly scaled and feed straight
    into every valid tick's vjp (a discarded dummy row's cotangent is
    zeros)."""
    from jax import shard_map

    fn2 = _with_aux(fn, aux_size)
    k = max(aux_size, 1)
    # saved stage inputs live stage-major: [S, M, mb, ...]
    saved_spec = P(axis_name, *x_spec)
    aux_spec = P((axis_name,) + data_axes, None)

    def strip(params):
        return jax.tree_util.tree_map(lambda a: a[0], params)

    @jax.custom_vjp
    def run(params, xm):
        out, _ = run_fwd(params, xm)
        return out

    def run_fwd(params, xm):
        def body(p, x):
            y, aux, x_saved = _fwd_save_ticks(strip(p), x, fn2, axis_name, k)
            return y, aux[None], x_saved[None]

        y, aux_rows, x_saved = shard_map(
            body, mesh=mesh,
            in_specs=(param_specs, x_spec),
            out_specs=(x_spec, aux_spec, saved_spec),
            check_vma=False,
        )(params, xm)
        return (y, aux_rows), (params, x_saved)

    def run_bwd(residuals, g):
        params, x_saved = residuals
        gy, gaux_rows = g

        def body(p, saved, gy_in, gaux_row):
            dparams, dx = _bwd_ticks(
                strip(p),
                jax.tree_util.tree_map(lambda a: a[0], saved),
                gy_in, fn2, axis_name,
                gaux_row[0].astype(jnp.float32),
            )
            # params replicate over the data axes, so each data shard holds
            # PARTIAL grads from its batch slice — sum them (the psum
            # autodiff's transpose machinery would have inserted).
            for ax in data_axes:
                dparams = jax.tree_util.tree_map(
                    lambda a, ax=ax: jax.lax.psum(a, ax), dparams
                )
            return jax.tree_util.tree_map(lambda a: a[None], dparams), dx

        dparams, dx = shard_map(
            body, mesh=mesh,
            in_specs=(param_specs, saved_spec, x_spec, aux_spec),
            out_specs=(param_specs, x_spec),
            check_vma=False,
        )(params, x_saved, gy, gaux_rows)
        return dparams, dx

    run.defvjp(run_fwd, run_bwd)
    return run(stage_params, x_micro)
