"""Expert parallelism: top-k MoE with all-to-all dispatch.

k_top=1 is Switch-style routing; k_top=2 is Mixtral-style (each token's
two highest-gated experts, gate weights renormalized over the chosen).

Experts are sharded over the ``ep`` mesh axis; tokens are routed by a gating
network, dispatched to their expert's device with ``all_to_all`` (ragged
traffic rides ICI), processed, and combined back weighted by the gate
probability. Capacity-factor dropping keeps shapes static for XLA; what a
dropped token yields is the caller's choice (``dropped=`` — passthrough
for standalone use, zero when feeding a residual stream).

New TPU-native surface (reference has no MoE support, SURVEY.md §2.3).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tf_operator_tpu.parallel.collectives import axis_size


def expert_capacity(capacity_factor: float, k_top: int, local_tokens: int,
                    n_experts: int) -> int:
    """THE per-expert queue length rule — one definition for every
    routing path (single-device, ep-sharded, and ep-inside-pipeline):
    capacity = cf·k·T_local/E, floored, at least 1. A second copy of
    this formula diverging (different rounding, forgetting k_top) would
    give pp x ep different drop patterns than non-pipelined ep with
    nothing pinning the difference."""
    return max(1, int(capacity_factor * k_top * local_tokens / n_experts))


def _route(x, gate_logits, capacity: int, k_top: int = 1, dropped: str = "passthrough"):
    """Top-k routing bookkeeping shared by the sharded and single-device
    paths. Each token goes to its ``k_top`` highest-gated experts; with
    k_top > 1 the chosen gate probs are renormalized to sum to 1 (the
    Mixtral rule). Queue slots are claimed in token order per expert.

    Partial capacity drops (k_top > 1, some but not all choices
    overflow): in "zero" mode the dropped choice simply contributes 0
    (the Switch training convention — drops are an efficiency artifact,
    not a reweighting); in "passthrough" mode weights renormalize over
    the SURVIVING choices so the output stays a full-strength convex mix
    rather than a silently attenuated one.

    Returns (dispatch_w [T,E,C] — combine weights, keep_any [T] — token
    has >= 1 surviving choice, inbox [E,C,d], stats — router
    observability: expert_load [E] (fraction of token-choices assigned to
    each expert), mean_gate [E] (mean router probability), drop_frac
    (fraction of token-choices that overflowed capacity))."""
    gate_probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    n_experts = gate_logits.shape[-1]
    top_p, top_i = jax.lax.top_k(gate_probs, k_top)  # [T, k]
    if k_top > 1:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

    # assign[t, e] = 1 if e is one of t's choices; w[t, e] = its gate weight
    choice_onehot = jax.nn.one_hot(top_i, n_experts, dtype=jnp.float32)  # [T,k,E]
    assign = jnp.sum(choice_onehot, axis=1)  # [T, E] (0/1: top_k is distinct)
    w = jnp.einsum("tke,tk->te", choice_onehot, top_p)  # [T, E]

    # Position of each (token, choice) within its expert's queue; beyond
    # capacity that choice drops.
    pos = (jnp.cumsum(assign, axis=0) - 1.0) * assign  # [T, E]
    kept = assign * (pos < capacity)  # [T, E]
    if k_top > 1 and dropped == "passthrough":
        surviving = jnp.sum(w * kept, axis=-1, keepdims=True)
        w = jnp.where(surviving > 0, w * kept / jnp.maximum(surviving, 1e-20), w)
    pos_onehot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
    dispatch = kept[:, :, None] * pos_onehot  # [T, E, C] 0/1
    dispatch_w = dispatch * w[:, :, None]  # combine side carries gate weights
    keep_any = jnp.sum(kept, axis=-1) > 0
    # Expert inboxes from local tokens: [E, C, d]
    inbox = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    n_choices = jnp.float32(x.shape[0] * k_top)
    stats = {
        "expert_load": jnp.sum(assign, axis=0) / n_choices,  # [E]
        "mean_gate": jnp.mean(gate_probs, axis=0),  # [E]
        "drop_frac": 1.0 - jnp.sum(kept) / n_choices,
    }
    return dispatch_w, keep_any, inbox, stats


def _route_sparse(x, gate_logits, capacity: int, k_top: int = 1,
                  dropped: str = "passthrough"):
    """Sort-based routing — the same queue semantics as ``_route`` (slots
    claimed in token order per expert, identical drop patterns) at
    O(T·d + T log T) instead of the one-hot einsum's O(T²·d): with
    capacity_factor 2 the dispatch einsum is a [T, 2T] × [T, d] matmul —
    ~4·T²·d FLOPs per layer, measured ~4x the ACTIVE expert FLOPs at
    bench shapes, and the combine einsum pays it again. Here dispatch is
    a scatter-add and combine a gather.

    Returns (slot [T,k] int32 — flat inbox slot e·C + rank (E·C = the
    dump row for capacity-dropped choices), w [T,k] f32 combine weights,
    keep_any [T], inbox [E,C,d] f32, stats) — inbox layout identical to
    _route's, so the ep all_to_all path is impl-agnostic."""
    gate_probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    tokens, d = x.shape
    n_experts = gate_logits.shape[-1]
    top_p, top_i = jax.lax.top_k(gate_probs, k_top)  # [T, k]
    if k_top > 1:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

    flat_e = top_i.reshape(-1).astype(jnp.int32)  # [T*k], t-major: the
    # stable sort below then orders each expert's queue by token index —
    # exactly _route's cumsum-over-tokens position assignment (one token
    # contributes at most one choice per expert, so k-order within a
    # token never ties in a queue)
    order = jnp.argsort(flat_e, stable=True)
    counts = jnp.bincount(flat_e, length=n_experts)  # [E]
    offsets = jnp.cumsum(counts) - counts  # exclusive prefix
    rank_sorted = jnp.arange(flat_e.shape[0]) - offsets[flat_e[order]]
    ranks = jnp.zeros_like(flat_e).at[order].set(rank_sorted.astype(jnp.int32))
    kept = (ranks < capacity).reshape(tokens, k_top)  # [T, k]
    slot = jnp.where(
        kept, (flat_e * capacity + ranks).reshape(tokens, k_top),
        n_experts * capacity,
    ).astype(jnp.int32)

    w = top_p
    if k_top > 1 and dropped == "passthrough":
        surviving = jnp.sum(w * kept, axis=-1, keepdims=True)
        w = jnp.where(surviving > 0, w * kept / jnp.maximum(surviving, 1e-20), w)
    keep_any = jnp.any(kept, axis=-1)

    # inbox by scatter-add: each kept (token, choice) owns a unique slot;
    # dropped choices pile harmlessly into the dump row, sliced off.
    x_rep = jnp.broadcast_to(
        x.astype(jnp.float32)[:, None, :], (tokens, k_top, d)
    ).reshape(tokens * k_top, d)
    inbox = jnp.zeros((n_experts * capacity + 1, d), jnp.float32)
    inbox = inbox.at[slot.reshape(-1)].add(x_rep)
    inbox = inbox[:-1].reshape(n_experts, capacity, d)

    n_choices = jnp.float32(tokens * k_top)
    stats = {
        "expert_load": counts.astype(jnp.float32) / n_choices,
        "mean_gate": jnp.mean(gate_probs, axis=0),
        "drop_frac": 1.0 - jnp.sum(kept) / n_choices,
    }
    return slot, w, keep_any, inbox, stats


def _combine_sparse(outbox, slot, w):
    """Gather each choice's expert output back to its token and weight by
    the gate: out[t] = Σ_k w[t,k] · outbox_flat[slot[t,k]]. The dump row
    is appended as zeros, so dropped choices contribute nothing even in
    "zero" mode where their w is untouched."""
    n_experts, capacity, d = outbox.shape
    flat = jnp.concatenate(
        [outbox.reshape(n_experts * capacity, d), jnp.zeros((1, d), outbox.dtype)]
    )
    gathered = flat[slot]  # [T, k, d]
    return jnp.einsum("tk,tkd->td", w, gathered)


def ragged_swiglu(expert_params, x_sorted, group_sizes):
    """SwiGLU over expert-sorted rows via ``jax.lax.ragged_dot`` — the
    grouped (Megablocks-style) expert matmul. expert_params leaves are
    stacked [E, ...]; x_sorted rows are grouped by expert with
    ``group_sizes`` [E] actual counts (no capacity, no padding rows).
    Measured on v5e: ragged_dot sustains the chip's chained-matmul rate
    exactly (55.2 vs 55.2 TFLOP/s at moe-small shapes, r5), so the cf
    multiplier on expert FLOPs disappears rather than being traded for a
    slower kernel."""
    zg = jax.lax.ragged_dot(x_sorted, expert_params["w_gate"], group_sizes)
    zu = jax.lax.ragged_dot(x_sorted, expert_params["w_up"], group_sizes)
    return jax.lax.ragged_dot(
        jax.nn.silu(zg) * zu, expert_params["w_down"], group_sizes
    )


def _moe_single_ragged(x, gate_logits, expert_params, ragged_expert_fn,
                       k_top: int = 1):
    """Padding-free single-device MoE (r5, VERDICT r4 #2): sort the T·k
    token-choices by expert (a gather, not the scatter-add inbox), run
    the experts as ONE grouped matmul over the actual per-expert counts
    (ragged_swiglu / ragged_dot), and gather-combine. Removes BOTH
    structural terms the r4 decomposition named: the capacity padding
    (cf x the active FLOPs — there is no capacity here) and the
    scatter-add dispatch (the inbox build was ~4x pure-bandwidth; a
    row gather is the cheap direction on TPU). No tokens drop, ever —
    drop_frac is identically 0, which also retires the cf-vs-quality
    trade the capacity path had to make."""
    tokens, d = x.shape
    n_experts = gate_logits.shape[-1]
    gate_probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    top_p, top_i = jax.lax.top_k(gate_probs, k_top)  # [T, k]
    if k_top > 1:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

    flat_e = top_i.reshape(-1).astype(jnp.int32)  # [T*k], t-major
    order = jnp.argsort(flat_e, stable=True)      # sorted-by-expert choice ids
    counts = jnp.bincount(flat_e, length=n_experts).astype(jnp.int32)
    x_sorted = x[(order // k_top)]                # [T*k, d] gather
    h = ragged_expert_fn(expert_params, x_sorted, counts)  # [T*k, d]
    inv = jnp.argsort(order)                      # choice j -> its sorted row
    gathered = h[inv.reshape(tokens, k_top)]      # [T, k, d]
    out = jnp.einsum(
        "tk,tkd->td", top_p, gathered.astype(jnp.float32)
    )
    stats = {
        "expert_load": counts.astype(jnp.float32) / (tokens * k_top),
        "mean_gate": jnp.mean(gate_probs, axis=0),
        "drop_frac": jnp.float32(0.0),
    }
    return out.astype(x.dtype), stats


def _moe_single_gmm(x, gate_logits, expert_params, k_top: int = 1,
                    block_rows: int = 256):
    """Padding-free single-device MoE over the Pallas grouped-matmul
    kernel (ops/grouped_matmul.gmm — the Megablocks-style path, r5):
    sort the T·k token-choices by expert, pad each expert's rows only to
    the ROW-BLOCK granularity (worst case E·B extra rows ≈ 12.5% at
    bench shapes, vs 100% for the cf=2 capacity queues), and steer each
    block's weight-tile load by a scalar-prefetched block→expert map.
    Dispatch is a row GATHER (no scatter-add inbox) and no token ever
    drops. ragged_dot was measured at ~19 TFLOP/s on the same shapes
    (full-height masked-matmul lowering) — the kernel exists because the
    XLA-level formulations all lose; see grouped_matmul.py."""
    tokens, d = x.shape
    n_experts = gate_logits.shape[-1]
    gate_probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    top_p, top_i = jax.lax.top_k(gate_probs, k_top)  # [T, k]
    if k_top > 1:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

    tk = tokens * k_top
    B = block_rows
    nb = -(-tk // B) + n_experts  # static upper bound incl. per-expert pad
    flat_e = top_i.reshape(-1).astype(jnp.int32)  # [T*k], t-major
    order = jnp.argsort(flat_e, stable=True)
    counts = jnp.bincount(flat_e, length=n_experts).astype(jnp.int32)
    offsets = jnp.cumsum(counts) - counts  # unpadded sorted offsets
    rank_sorted = jnp.arange(tk, dtype=jnp.int32) - offsets[flat_e[order]]
    ranks = jnp.zeros((tk,), jnp.int32).at[order].set(rank_sorted)

    # every expert owns >= 1 block even with zero routed tokens: the dw
    # kernel writes an output tile only when a grid step visits it, so a
    # block-less expert would return UNINITIALIZED gradient memory. Its
    # one all-garbage block costs B rows of compute, and its dw is
    # exactly zero — the garbage rows' outputs are never gathered, so
    # their cotangents arrive as zeros (pinned by
    # test_gmm_zero_token_expert_gets_zero_grad).
    blocks_per_e = jnp.maximum((counts + B - 1) // B, 1)
    pad_start = (jnp.cumsum(blocks_per_e) - blocks_per_e) * B  # [E]
    bstart = jnp.arange(nb, dtype=jnp.int32) * B
    block_expert = (
        jnp.searchsorted(pad_start, bstart, side="right").astype(jnp.int32) - 1
    )
    # padded slot s -> source token (garbage slots read row 0; their
    # outputs are never gathered back and their cotangents are zero)
    s = jnp.arange(nb * B, dtype=jnp.int32)
    e_s = block_expert[s // B]
    rank_s = s - pad_start[e_s]
    valid = rank_s < counts[e_s]
    src_choice = order[jnp.clip(offsets[e_s] + rank_s, 0, tk - 1)]
    x_pad = x[jnp.where(valid, src_choice // k_top, 0)]  # [nb*B, d]

    from tf_operator_tpu.ops.grouped_matmul import gmm

    interpret = jax.default_backend() != "tpu"
    run = partial(gmm, block_rows=B, interpret=interpret)
    zg = run(x_pad, expert_params["w_gate"].astype(x.dtype), block_expert)
    zu = run(x_pad, expert_params["w_up"].astype(x.dtype), block_expert)
    # fused combine epilogue (r6): each padded slot's gate weight rides
    # the down-projection kernel as a row scale, so the combine below is
    # a pure gather+sum — the separate f32 [T,k,d] weighted-reduction
    # einsum (and its HBM pass) is gone. Garbage slots scale by 0.
    dst = pad_start[flat_e] + ranks  # [T*k] — every choice's padded slot
    s_pad = jnp.zeros((nb * B,), jnp.float32).at[dst].set(top_p.reshape(-1))
    h = run(jax.nn.silu(zg) * zu,
            expert_params["w_down"].astype(x.dtype), block_expert,
            row_scale=s_pad)

    gathered = h[dst.reshape(tokens, k_top)]  # [T, k, d] — pre-weighted
    out = jnp.sum(gathered.astype(jnp.float32), axis=1)
    stats = {
        "expert_load": counts.astype(jnp.float32) / tk,
        "mean_gate": jnp.mean(gate_probs, axis=0),
        "drop_frac": jnp.float32(0.0),
    }
    return out.astype(x.dtype), stats


def _moe_local_gmm(x, gate_logits, expert_params, axis_name: str,
                   k_top: int = 1, block_rows: int = 256):
    """Padding-free EP-SHARDED MoE over the Pallas grouped-matmul kernel
    (r6 — the tentpole that brings the gmm path to the flagship ep
    layouts; before this, dispatch_impl="gmm" silently degraded to
    capacity queues under an ep axis).

    The obstruction the capacity path existed to solve: ``all_to_all``
    needs static shapes, but per-(source-shard, expert) token counts are
    data-dependent. Resolution:

    1. COUNT EXCHANGE — each shard routes its T·k token-choices, counts
       per global expert, and all_to_alls the [S, E/S] count matrix, so
       every shard knows exactly how many rows it will receive from each
       source for each of its local experts before touching the payload.
    2. BLOCK-QUANTUM BUFFERS — the payload a2a moves one statically
       sized segment per (source, dest) pair: seg_blocks = ceil(T·k/B) +
       E_local row-blocks (the lossless bound — all of a source's
       choices could route to one destination, plus worst-case
       per-expert round-up to the kernel's B-row quantum). Within a
       segment, each expert's rows sit at block-aligned offsets computed
       from the counts, so the RECEIVER can rebuild an exact
       block→expert steering map with pure index arithmetic — no
       capacity queues, no drops, ever.
    3. SENTINEL-SKIPPED COMPUTE — buffer occupancy is data-dependent but
       the kernel grid is static; unoccupied blocks get block_expert=-1
       and the kernel writes zeros without spending MXU work, so expert
       FLOPs scale with ROUTED tokens (+ ≤B-row round-up per
       (source, expert)), not with the worst-case buffer.
    4. FUSED COMBINE — gate weights ride the payload a2a as a [S_cap]
       f32 sidecar and are applied inside the down-projection kernel's
       epilogue (gmm row_scale), so the return-path combine is a pure
       gather+sum at the source.

    The trade receipted in docs/design.md: wire bytes are S× the active
    rows (worst-case-sized segments traverse the a2a even when lightly
    occupied) vs cf× for capacity queues — identical at the flagship
    ep=2/cf=2 point, and the ~2× PADDING FLOPS (the r4 decomposition's
    top structural term) are retired outright. Gradients: garbage rows
    carry zero cotangents by construction (their outputs are never
    gathered and their gate-weight sidecar is hard 0), and the dw kernel
    zero-initializes every expert tile, so zero-token experts get exact
    zero gradients (pinned by the ep-gmm tests)."""
    n_shards = axis_size(axis_name)
    tokens, d = x.shape
    n_experts = gate_logits.shape[-1]
    e_local = n_experts // n_shards
    B = block_rows
    tk = tokens * k_top

    gate_probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    top_p, top_i = jax.lax.top_k(gate_probs, k_top)  # [T, k]
    if k_top > 1:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

    flat_e = top_i.reshape(-1).astype(jnp.int32)  # [T*k], t-major
    order = jnp.argsort(flat_e, stable=True)
    counts = jnp.bincount(flat_e, length=n_experts).astype(jnp.int32)
    offsets = jnp.cumsum(counts) - counts  # unpadded sorted offsets [E]
    rank_sorted = jnp.arange(tk, dtype=jnp.int32) - offsets[flat_e[order]]
    ranks = jnp.zeros((tk,), jnp.int32).at[order].set(rank_sorted)

    # --- send layout: [dest segment | expert region | rank] -------------
    seg_blocks = -(-tk // B) + e_local  # static lossless bound (blocks)
    s_cap = seg_blocks * B              # rows per (src, dest) segment
    pad_rows = (-(-counts // B)) * B    # [E] B-aligned region per expert
    pad_r = pad_rows.reshape(n_shards, e_local)
    bounds_rows = jnp.cumsum(pad_r, axis=1)          # [S, E_l]
    off_in_seg = (bounds_rows - pad_r).reshape(-1)   # [E] flat == expert id

    send_slot = (
        (flat_e // e_local) * s_cap + off_in_seg[flat_e] + ranks
    )  # [T*k] — each choice's row in the send buffer (and, after the
    # return all_to_all, in the received-output buffer: the exchange is
    # symmetric, so the send layout IS the combine layout)

    # fill the send buffer by row GATHER (the cheap direction on TPU —
    # same rationale as _moe_single_gmm's x_pad)
    r = jnp.arange(n_shards * s_cap, dtype=jnp.int32)
    seg, u = r // s_cap, r % s_cap
    le_r = jnp.sum(u[:, None] >= bounds_rows[seg], axis=1).astype(jnp.int32)
    in_region = le_r < e_local
    e_r = seg * e_local + jnp.minimum(le_r, e_local - 1)
    rank_r = u - off_in_seg[e_r]
    valid = in_region & (rank_r < counts[e_r])
    src_choice = order[jnp.clip(offsets[e_r] + rank_r, 0, tk - 1)]
    x_send = x[jnp.where(valid, src_choice // k_top, 0)]  # [S*S_cap, d]
    s_send = jnp.where(
        valid, top_p.reshape(-1)[jnp.clip(src_choice, 0, tk - 1)], 0.0
    )  # gate-weight sidecar; hard 0 on garbage rows kills their outputs
    # AND their backward (ds flows only through the where)

    # --- exchanges ------------------------------------------------------
    a2a = partial(jax.lax.all_to_all, axis_name=axis_name, split_axis=0,
                  concat_axis=0, tiled=False)
    counts_rcv = a2a(counts.reshape(n_shards, e_local))      # [S(src), E_l]
    x_rcv = a2a(x_send.reshape(n_shards, s_cap, d))          # [S(src), S_cap, d]
    s_rcv = a2a(s_send.reshape(n_shards, s_cap))             # [S(src), S_cap]

    # --- dest-side block→expert map from the exchanged counts -----------
    pad_blocks_rcv = -(-counts_rcv // B)                     # [S, E_l]
    bounds_blocks = jnp.cumsum(pad_blocks_rcv, axis=1)       # [S, E_l]
    b = jnp.arange(n_shards * seg_blocks, dtype=jnp.int32)
    seg_b, ub = b // seg_blocks, b % seg_blocks
    le_b = jnp.sum(ub[:, None] >= bounds_blocks[seg_b], axis=1).astype(jnp.int32)
    block_expert = jnp.where(le_b < e_local, le_b, -1).astype(jnp.int32)

    from tf_operator_tpu.ops.grouped_matmul import gmm

    interpret = jax.default_backend() != "tpu"
    run = partial(gmm, block_rows=B, interpret=interpret)
    x_flat = x_rcv.reshape(n_shards * s_cap, d)
    zg = run(x_flat, expert_params["w_gate"].astype(x.dtype), block_expert)
    zu = run(x_flat, expert_params["w_up"].astype(x.dtype), block_expert)
    h = run(jax.nn.silu(zg) * zu,
            expert_params["w_down"].astype(x.dtype), block_expert,
            row_scale=s_rcv.reshape(-1))

    # --- return results to source shards, combine -----------------------
    h_ret = a2a(h.reshape(n_shards, s_cap, -1)).reshape(n_shards * s_cap, -1)
    gathered = h_ret[send_slot.reshape(tokens, k_top)]  # [T, k, d] pre-weighted
    out = jnp.sum(gathered.astype(jnp.float32), axis=1)

    stats = {
        "expert_load": counts.astype(jnp.float32) / tk,
        "mean_gate": jnp.mean(gate_probs, axis=0),
        "drop_frac": jnp.float32(0.0),
    }
    return out.astype(x.dtype), stats


def _dropped_value(x, dropped: str):
    """What capacity-dropped tokens contribute: their input unchanged
    ("passthrough" — moe_apply as a standalone transform) or nothing
    ("zero" — moe_apply as the MLP branch of a residual stream, the
    Switch-Transformer rule: an overflowed token's MLP contributes 0)."""
    if dropped == "passthrough":
        return x.astype(jnp.float32)
    if dropped == "zero":
        return jnp.zeros_like(x, jnp.float32)
    raise ValueError(f"unknown dropped mode {dropped!r}")


def _moe_single(x, gate_logits, expert_params, expert_fn, capacity: int, dropped: str,
                k_top: int = 1, dispatch_impl: str = "sort",
                ragged_expert_fn=None):
    """All experts on one device: same routing math, no collectives — the
    fallback when the mesh has no ep axis (or no mesh at all).

    NOTE on drop patterns: this path runs ONE global per-expert capacity
    queue while the sharded path runs per-(data-shard x ep-shard) queues,
    so WHICH tokens overflow differs between CPU and pod runs of the same
    config — the routing math and aggregate load stats agree, but numeric
    outputs are not bitwise-comparable across mesh layouts whenever any
    tokens drop (drop_frac > 0)."""
    tokens, d = x.shape
    n_experts = gate_logits.shape[-1]
    if dispatch_impl == "gmm":
        import os

        # the gmm path runs the experts as grouped ragged matmuls over
        # the SwiGLU parameter triple directly — a custom expert_fn
        # cannot be honored here, so reject anything but that layout
        # loudly instead of silently computing different math
        if set(expert_params) != {"w_gate", "w_up", "w_down"}:
            raise ValueError(
                "dispatch_impl='gmm' computes a SwiGLU expert from "
                "{w_gate, w_up, w_down} stacked params and ignores "
                f"expert_fn; got param keys {sorted(expert_params)} — use "
                "dispatch_impl='sort' for custom expert bodies"
            )
        return _moe_single_gmm(
            x, gate_logits, expert_params, k_top,
            block_rows=int(os.environ.get("TPUJOB_GMM_BLOCK_ROWS", "256")),
        )
    if dispatch_impl == "ragged":
        if ragged_expert_fn is None:
            raise ValueError(
                "dispatch_impl='ragged' needs a ragged_expert_fn "
                "(e.g. parallel.moe.ragged_swiglu)"
            )
        return _moe_single_ragged(
            x, gate_logits, expert_params, ragged_expert_fn, k_top
        )
    if dispatch_impl == "sort":
        slot, w, keep_any, inbox, stats = _route_sparse(
            x, gate_logits, capacity, k_top, dropped)
    else:
        dispatch_w, keep_any, inbox, stats = _route(
            x, gate_logits, capacity, k_top, dropped)

    # vmap over the stacked expert dim — ONE batched-matmul program for
    # all experts. r4: the previous fori_loop ran E sequential [C,d]
    # matmul chains with a dynamic-slice parameter gather and an
    # acc.at[e].set copy per step; at bench shapes the identical FLOPs
    # measured 15.1 ms looped vs 8.1 ms batched (tools/roofline --mode
    # moe), and the batched form runs at 87% of the chip's chained
    # matmul rate.
    outbox = jax.vmap(
        lambda w_e, t: expert_fn(w_e, t.astype(x.dtype))
    )(expert_params, inbox).astype(jnp.float32)
    if dispatch_impl == "sort":
        combined = _combine_sparse(outbox, slot, w)
    else:
        combined = jnp.einsum("tec,ecd->td", dispatch_w, outbox)
    out = jnp.where(keep_any[:, None], combined, _dropped_value(x, dropped))
    return out.astype(x.dtype), stats


def _moe_local(x, gate_logits, expert_params, expert_fn, axis_name: str, capacity: int,
               dropped: str, k_top: int = 1, stat_axes: tuple = (),
               dispatch_impl: str = "sort", block_rows: int = 256):
    """Per-device body. x: [tokens_local, d]; gate_logits: [tokens_local, E];
    expert_params: this device's experts (leading dim E_local).
    ``stat_axes``: every mesh axis the token dim shards over (data axes +
    ep) — router stats pmean over all of them to give the global view.
    The sort/einsum impls build the same [E, C, d] inbox layout, so the
    capacity all_to_all exchange is impl-agnostic; "gmm" (r6) replaces
    the capacity queues with block-quantum buffers (_moe_local_gmm)."""
    n_shards = axis_size(axis_name)
    tokens, d = x.shape
    n_experts = gate_logits.shape[-1]
    experts_per_shard = n_experts // n_shards

    if dispatch_impl == "gmm":
        if not isinstance(expert_params, dict) or set(expert_params) != {
            "w_gate", "w_up", "w_down"
        }:
            raise ValueError(
                "dispatch_impl='gmm' computes a SwiGLU expert from "
                "{w_gate, w_up, w_down} stacked params and ignores "
                f"expert_fn; got param keys {sorted(expert_params)} — use "
                "dispatch_impl='sort' for custom expert bodies"
            )
        out, stats = _moe_local_gmm(
            x, gate_logits, expert_params, axis_name, k_top, block_rows
        )
        for ax in stat_axes or (axis_name,):
            stats = jax.tree_util.tree_map(
                lambda s: jax.lax.pmean(s, ax), stats)
        return out, stats
    if dispatch_impl == "sort":
        slot, w, keep_any, inbox, stats = _route_sparse(
            x, gate_logits, capacity, k_top, dropped)
    else:
        dispatch_w, keep_any, inbox, stats = _route(
            x, gate_logits, capacity, k_top, dropped)

    # all_to_all: regroup so each shard holds inboxes for ITS experts from
    # every shard: [E, C, d] -> [E_local * n_shards, C, d] where the leading
    # dim interleaves (source_shard, local_expert).
    inbox = inbox.reshape(n_shards, experts_per_shard, capacity, d)
    inbox = jax.lax.all_to_all(inbox, axis_name, split_axis=0, concat_axis=0, tiled=False)
    # Now: [n_shards(source), E_local, C, d] on each device.
    inbox = inbox.reshape(n_shards, experts_per_shard, capacity, d)

    # Run each local expert over its gathered tokens — vmapped over the
    # expert dim into one batched-matmul program (r4, same rationale as
    # _moe_single: the fori_loop form measured 1.87x slower on identical
    # FLOPs).
    def one_expert(params_e, toks):  # toks: [n_shards, C, d]
        out = expert_fn(params_e, toks.reshape(n_shards * capacity, d).astype(x.dtype))
        return out.astype(jnp.float32).reshape(n_shards, capacity, d)

    outbox = jax.vmap(one_expert, in_axes=(0, 1), out_axes=1)(
        expert_params, inbox
    )

    # Return results to source shards.
    outbox = jax.lax.all_to_all(outbox, axis_name, split_axis=0, concat_axis=0, tiled=False)
    outbox = outbox.reshape(n_experts, capacity, d)

    # Combine: weight by gate prob; dropped tokens per the dropped mode.
    if dispatch_impl == "sort":
        combined = _combine_sparse(outbox, slot, w)
    else:
        combined = jnp.einsum("tec,ecd->td", dispatch_w, outbox)
    out = jnp.where(keep_any[:, None], combined, _dropped_value(x, dropped))
    # Aggregate router stats across token shards (every shard routed its
    # own slice; the job-level view is the mean over all of them).
    for ax in stat_axes or (axis_name,):
        stats = jax.tree_util.tree_map(lambda s: jax.lax.pmean(s, ax), stats)
    return out.astype(x.dtype), stats


def moe_apply(
    x,
    gate_logits,
    expert_params,
    expert_fn: Callable,
    mesh,
    axis_name: str = "ep",
    capacity_factor: float = 2.0,
    dropped: str = "passthrough",
    batch_axes: tuple = ("dp", "fsdp"),
    k_top: int = 1,
    return_stats: bool = False,
    dispatch_impl: str = "sort",
    ragged_expert_fn=None,
):
    """Top-k MoE layer with experts sharded over ``axis_name``
    (``k_top=1`` — Switch; ``k_top=2`` — Mixtral-style with renormalized
    gate weights; capacity scales with k_top: total slot demand is
    k_top x tokens).

    x: [tokens, d]; the token dim shards over (batch_axes… , ep) — data
    replicas keep their own token slices (each dp group runs its own
    ep-wide all_to_all; without this, every dp replica would all-gather
    and re-route the full global batch) and within a replica each ep
    shard routes its slice, the all_to_all exchanging (token-shard ×
    expert-shard) traffic so every expert processes distinct tokens from
    every source shard. expert_params: pytree with leading dim n_experts
    (sharded over ep, replicated over the batch axes).
    ``dropped`` picks what capacity-overflowed tokens yield: their input
    ("passthrough", standalone-transform default) or 0 ("zero" — required
    when the caller adds the result to a residual stream, else a dropped
    token gains its own input twice).
    ``return_stats`` also returns router observability (the seam training
    loops and the load-balance tests read): {"expert_load": [E] fraction
    of token-choices per expert, "mean_gate": [E] mean router probability,
    "drop_frac": scalar} — globally averaged over token shards.

    NOTE: drop PATTERNS (which specific tokens overflow) differ between
    the single-device path (one global queue per expert) and the sharded
    path (per-shard queues) — see _moe_single; aggregate stats agree.

    ``dispatch_impl``: "sort" (default, r3 — argsort/scatter/gather
    dispatch, O(T·d)) or "einsum" (the one-hot-matmul formulation,
    O(T²·d) — kept as the parity oracle), or "gmm" (r5/r6 — the Pallas
    grouped-matmul kernel, ops/grouped_matmul.py: no capacity queues,
    no drops, padding only to the kernel's row-block quantum; r6 runs it
    under ep sharding too via count-exchange + block-quantum all_to_all
    buffers, _moe_local_gmm — the flagship layouts no longer degrade to
    capacity queues), or "ragged" (r5 — grouped ragged_dot over actual
    per-expert counts via ``ragged_expert_fn``; single-device/no-ep path
    only: its XLA lowering has no steering map to skip unoccupied
    blocks, so the sharded path falls back to "sort" with a runtime
    warning). Same queue semantics for sort/einsum, same drop patterns,
    same stats (pinned by the impl-parity tests); the end-to-end win is
    recorded in BASELINE.md."""
    from tf_operator_tpu.parallel.collectives import (  # noqa: F401
        shard_map_compat as shard_map,
    )

    if dispatch_impl not in ("sort", "einsum", "ragged", "gmm"):
        raise ValueError(f"unknown dispatch_impl {dispatch_impl!r}")
    n_experts = gate_logits.shape[-1]
    tokens = x.shape[0]
    if mesh is None or axis_name not in getattr(mesh, "axis_names", ()) or (
        mesh.shape[axis_name] == 1
    ):
        capacity = expert_capacity(capacity_factor, k_top, tokens, n_experts)
        out, stats = _moe_single(
            x, gate_logits, expert_params, expert_fn, capacity, dropped, k_top,
            dispatch_impl, ragged_expert_fn,
        )
        return (out, stats) if return_stats else out
    if dispatch_impl == "ragged":
        # ragged_dot has no block steering to skip unoccupied regions of
        # a statically-sized a2a buffer, so under ep it would pay the
        # worst-case FLOPs — the sharded path keeps the sort dispatch.
        # Logged, not just documented: the caller opted into the
        # zero-drop path and is getting capacity drops instead — that
        # change must be visible at runtime. (The gmm impl no longer
        # falls back: r6 runs it ep-sharded via _moe_local_gmm.)
        import logging

        logging.getLogger("tpujob.moe").warning(
            "dispatch_impl='ragged' needs static per-expert shapes under "
            "ep sharding; falling back to 'sort' (capacity queues, drops "
            "possible) — use dispatch_impl='gmm' for the padding-free "
            "ep path",
        )
        dispatch_impl = "sort"
    if dispatch_impl == "gmm" and (
        not isinstance(expert_params, dict)
        or set(expert_params) != {"w_gate", "w_up", "w_down"}
    ):
        raise ValueError(
            "dispatch_impl='gmm' computes a SwiGLU expert from "
            "{w_gate, w_up, w_down} stacked params and ignores expert_fn; "
            f"got param keys {sorted(expert_params)} — use "
            "dispatch_impl='sort' for custom expert bodies"
        )
    ep = mesh.shape[axis_name]
    data_axes = tuple(a for a in batch_axes if a in mesh.axis_names)
    n_data = math.prod(mesh.shape[a] for a in data_axes) if data_axes else 1
    if n_experts % ep:
        raise ValueError(f"{n_experts} experts not divisible by ep={ep}")
    if tokens % (ep * n_data):
        raise ValueError(
            f"{tokens} tokens not divisible by ep={ep} x data={n_data}"
        )
    local_tokens = tokens // (ep * n_data)
    capacity = expert_capacity(capacity_factor, k_top, local_tokens, n_experts)

    token_spec = P((*data_axes, axis_name))
    param_specs = jax.tree_util.tree_map(lambda _: P(axis_name), expert_params)
    stat_specs = {"expert_load": P(), "mean_gate": P(), "drop_frac": P()}
    import os

    fn = shard_map(
        partial(_moe_local, expert_fn=expert_fn, axis_name=axis_name, capacity=capacity,
                dropped=dropped, k_top=k_top, stat_axes=(*data_axes, axis_name),
                dispatch_impl=dispatch_impl,
                block_rows=int(os.environ.get("TPUJOB_GMM_BLOCK_ROWS", "256"))),
        mesh=mesh,
        in_specs=(token_spec, token_spec, param_specs),
        out_specs=(token_spec, stat_specs),
    )
    out, stats = fn(x, gate_logits, expert_params)
    return (out, stats) if return_stats else out
