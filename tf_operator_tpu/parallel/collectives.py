"""Collective helpers for shard_map bodies.

Thin, named wrappers over the XLA collectives (psum / all_gather /
reduce_scatter / ppermute) — the framework NEVER reimplements collectives
(SURVEY.md §2.3: the reference delegated them to TF's runtime; we delegate
to XLA, which maps them onto ICI rings).
"""

from __future__ import annotations

import functools

import jax


def shard_map_compat(f, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` across the jax versions this repo actually meets:
    the public ``jax.shard_map`` (``check_vma=`` kwarg) where it exists,
    else ``jax.experimental.shard_map.shard_map`` (``check_rep=``).
    Replication/VMA checking is disabled either way — the shard bodies
    reduce their own stats with explicit psum/pmean, which the checker
    cannot see through custom_vjp boundaries. Every shard_map in the
    parallelism layer routes through here so a jax upgrade (or the CI
    container's older pin) changes exactly one line, not six call
    sites."""
    try:
        from jax import shard_map as sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm

        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)
    try:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    except TypeError:  # jax versions where the public API still says check_rep
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def all_reduce_mean(x, axis_name: str):
    """Gradient-style mean all-reduce."""
    return jax.lax.pmean(x, axis_name)


def all_reduce_sum(x, axis_name: str):
    return jax.lax.psum(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis`` (FSDP param gather)."""
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter_sum(x, axis_name: str, axis: int = 0, tiled: bool = True):
    """Sum-reduce then scatter along ``axis`` (FSDP grad reduce)."""
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=tiled)


# Megatron's f/g conjugate operator pair for tensor parallelism inside a
# manual (shard_map) region. Plain lax.psum is WRONG for this pattern
# under direct jax.vjp: JAX's psum transpose is psum again (the pmap-era
# convention), which inflates every cotangent behind the reduction by the
# axis size — and the factors compound per layer. The pair pins the
# correct transposes: activations enter the tp region through tp_enter
# (identity fwd / psum bwd: each shard's partial input-cotangent sums to
# the true one) and partial row-parallel products leave through tp_exit
# (psum fwd / identity bwd: the output cotangent is replicated and flows
# to every shard untouched).


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_region_enter(x, axis_name: str):
    """Megatron f: identity forward; backward psums the (shard-partial)
    input cotangent over the tp axis."""
    return x


def _tp_enter_fwd(x, axis_name):
    return x, None


def _tp_enter_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


tp_region_enter.defvjp(_tp_enter_fwd, _tp_enter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_region_exit(x, axis_name: str):
    """Megatron g: psum forward (combine row-parallel partials); backward
    passes the replicated output cotangent through unchanged."""
    return jax.lax.psum(x, axis_name)


def _tp_exit_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _tp_exit_bwd(axis_name, _, g):
    return (g,)


tp_region_exit.defvjp(_tp_exit_fwd, _tp_exit_bwd)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Rotate shards around the ring (ring attention / pipeline transfers)."""
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return jax.lax.axis_index(axis_name)


def axis_size(axis_name: str):
    return jax.lax.psum(1, axis_name)
