"""Collective helpers for shard_map bodies.

Thin, named wrappers over the XLA collectives (psum / all_gather /
reduce_scatter / ppermute) — the framework NEVER reimplements collectives
(SURVEY.md §2.3: the reference delegated them to TF's runtime; we delegate
to XLA, which maps them onto ICI rings).
"""

from __future__ import annotations

import jax


def all_reduce_mean(x, axis_name: str):
    """Gradient-style mean all-reduce."""
    return jax.lax.pmean(x, axis_name)


def all_reduce_sum(x, axis_name: str):
    return jax.lax.psum(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis`` (FSDP param gather)."""
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter_sum(x, axis_name: str, axis: int = 0, tiled: bool = True):
    """Sum-reduce then scatter along ``axis`` (FSDP grad reduce)."""
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=tiled)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Rotate shards around the ring (ring attention / pipeline transfers)."""
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return jax.lax.axis_index(axis_name)


def axis_size(axis_name: str):
    return jax.lax.psum(1, axis_name)
