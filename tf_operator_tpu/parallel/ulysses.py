"""Ulysses-style sequence parallelism: all-to-all head/sequence re-shard.

The second long-context recipe (SURVEY.md §2.3 SP/CP row lists ring,
blockwise, and Ulysses — the reference has none). Where ring attention
keeps heads whole and ROTATES K/V sequence blocks around the cp ring
(cp-1 neighbor hops per layer), Ulysses (DeepSpeed) re-shards ONCE per
attention: an all-to-all turns [seq-sharded, all heads] into
[full seq, head-sharded], each device runs ordinary attention on its
head slice over the FULL sequence, and a second all-to-all restores the
sequence sharding. Two all-to-alls total, each moving t·h·d/cp per
device — cheaper than the ring when cp is large and heads divide evenly,
and the inner attention is just the single-device kernel, so the Pallas
flash path applies untouched (`attn_fn=`).

Trade-off vs ring (why both exist): Ulysses needs n_heads % cp == 0 and
materializes the full-sequence K/V per device (HBM: t·h·d/cp per tensor
— fine until t·d/cp outgrows a head shard); ring keeps per-device memory
at t/cp blocks and has no head-divisibility constraint, at the cost of
cp-1 sequential ppermute steps. The transformer exposes both:
``attn_impl="ring" | "ulysses"``.

GQA (r3): with n_kv % cp == 0, K/V all-to-all on their OWN head dim —
each device then holds h/cp query heads and n_kv/cp kv heads, and
``attn_fn`` MUST accept GQA-shaped inputs (the flash kernel and the
grouped dense reference both do). n_kv % cp != 0 (r4): K/V are
ALL-GATHERED over cp on the sequence dim instead — (cp-1)/cp · t·n_kv·d
moved per device vs the r3 silent repeat's (cp-1)/cp · t·h·d/cp through
the all-to-all, i.e. cp/g the traffic (less whenever cp < g) and no
[t, h, d] repeated tensor is ever materialized. Each shard then takes
exactly the kv heads its contiguous query-head block maps to
(j -> j//g), so the local attention is equal-headed and any MHA
``attn_fn`` works. Per-device K/V HBM is t·(n_kv + h/cp)·d — same
order as the n_kv % cp == 0 path when g >= cp.

Layout contract matches ring_attention: global [batch, seq, heads,
head_dim], sequence sharded over ``axis_name`` on entry and exit.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tf_operator_tpu.parallel.collectives import axis_size
# the GQA-native dense oracle (grouped einsum) — parallel/ring_attention's
# reference is MHA-only and would reject mismatched local head counts
from tf_operator_tpu.ops.flash_attention import reference_attention


def _ulysses_local(q, k, v, axis_name: str, causal: bool,
                   attn_fn: Optional[Callable], gather_kv: bool = False):
    """Per-device body. q/k/v: [b, t_local, h, d] (sequence-sharded).

    all_to_all over the heads dim: [b, t_local, h, d] -> concat over the
    cp group's t blocks with h/cp local heads -> [b, t_global, h_local, d].

    ``gather_kv`` (the n_kv % cp != 0 path): K/V skip the head split —
    they are all-gathered whole over the sequence dim, then each shard
    TAKES the kv head serving each of its h/cp contiguous query heads
    (global query head i·h/cp + j -> kv head (i·h/cp + j)//g), handing
    attn_fn an equal-headed local problem. Exact: same softmax, the
    take only materializes the repeat lazily and only for this shard's
    query block.
    """
    n = axis_size(axis_name)

    def seq_to_heads(x):
        # split heads into n groups, hand group i to shard i, receiving
        # every shard's sequence block for OUR head group
        x = jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )
        return x  # [b, t_global, h/n, d]

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )  # [b, t_local, h, d]

    qg = seq_to_heads(q)
    if gather_kv:
        h, h_kv = q.shape[2], k.shape[2]
        g, h_loc = h // h_kv, h // n
        kg = jax.lax.all_gather(k, axis_name, axis=1, tiled=True)
        vg = jax.lax.all_gather(v, axis_name, axis=1, tiled=True)
        i = jax.lax.axis_index(axis_name)
        head_map = (i * h_loc + jnp.arange(h_loc)) // g
        kg = jnp.take(kg, head_map, axis=2)
        vg = jnp.take(vg, head_map, axis=2)
    else:
        kg, vg = seq_to_heads(k), seq_to_heads(v)
    if attn_fn is None:
        out = reference_attention(qg, kg, vg, causal=causal)
    else:
        out = attn_fn(qg, kg, vg)
    return heads_to_seq(out.astype(q.dtype))


def ulysses_attention(
    q,
    k,
    v,
    mesh,
    axis_name: str = "cp",
    causal: bool = False,
    batch_axes: Optional[tuple] = None,
    attn_fn: Optional[Callable] = None,
):
    """Exact self-attention with sequence sharded over ``axis_name`` via
    head/sequence all-to-all re-sharding (DeepSpeed-Ulysses recipe).

    q/k/v: global [batch, seq, heads, head_dim] (k/v may carry
    n_kv < heads GQA heads); seq % cp == 0 and heads % cp == 0 required.
    ``attn_fn(q, k, v)`` runs the per-device full-sequence attention and
    must handle GQA-shaped k/v when n_kv % cp == 0 (its local inputs are
    then h/cp query vs n_kv/cp kv heads — the flash kernel and the
    grouped dense default both do; an MHA-only attn_fn is safe only for
    equal-head models)."""
    from tf_operator_tpu.parallel.collectives import (  # noqa: F401
        shard_map_compat as shard_map,
    )

    cp = mesh.shape[axis_name]
    b, t, h, d = q.shape
    h_kv = k.shape[2]
    if t % cp:
        raise ValueError(f"seq length {t} must divide by {axis_name}={cp}")
    if h % cp:
        raise ValueError(
            f"ulysses needs heads % cp == 0 (got {h} heads, cp={cp}) — "
            "use attn_impl='ring' for head counts the cp axis cannot split"
        )
    if k.shape[2] != v.shape[2]:
        raise ValueError(f"k/v head mismatch: {k.shape[2]} vs {v.shape[2]}")
    if h % h_kv:
        raise ValueError(
            f"q heads {h} not a multiple of kv heads {h_kv}"
        )
    # GQA (r3): when the kv heads divide cp, K/V all-to-all on their OWN
    # (smaller) head dim — each shard gets n_kv/cp kv heads + full seq,
    # moving group-times less data per all-to-all, and the local
    # attention runs GQA-native (contiguous head blocks keep query head
    # j -> kv head j//group aligned per shard since h/cp = g * n_kv/cp).
    # Indivisible kv counts (r4): all-gather the small K/V whole and map
    # heads per shard inside the body — no silent repeat (the r3
    # fallback restored exactly the K/V traffic GQA removes).
    gather_kv = bool(h_kv != h and h_kv % cp)
    spec = P(batch_axes, axis_name, None, None)
    fn = shard_map(
        partial(_ulysses_local, axis_name=axis_name, causal=causal,
                attn_fn=attn_fn, gather_kv=gather_kv),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
