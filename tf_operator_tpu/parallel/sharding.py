"""Sharding rules: logical array axes -> mesh axes -> NamedShardings.

The pattern (flax ``logical_axis_rules`` reimagined without the flax
dependency): models annotate arrays with *logical* axis names ("batch",
"embed", "mlp", "heads", "kv", "seq", "layers", "expert"...), and a
``ShardingRules`` table maps logical names to mesh axes. Changing the
parallelism strategy = changing the table, not the model.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple, Union

from tf_operator_tpu.parallel.mesh import (
    AXIS_CONTEXT,
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPELINE,
    AXIS_TENSOR,
)

MeshAxes = Union[str, Tuple[str, ...], None]


@dataclass(frozen=True)
class ShardingRules:
    """Logical-name -> mesh-axis mapping. None = replicate."""

    rules: Dict[str, MeshAxes] = field(default_factory=dict)

    def mesh_axes_for(self, logical: Optional[str]) -> MeshAxes:
        if logical is None:
            return None
        return self.rules.get(logical)

    def sharding(self, mesh, logical_axes: Sequence[Optional[str]]):
        from jax.sharding import NamedSharding

        # Drop references to axes the mesh doesn't have (e.g. rules mention
        # "tp" but this job runs pure DP): treat them as replicated.
        spec_parts = []
        for ax in logical_axes:
            m = self.mesh_axes_for(ax)
            if isinstance(m, str) and m not in mesh.axis_names:
                m = None
            elif isinstance(m, tuple):
                m = tuple(a for a in m if a in mesh.axis_names) or None
            spec_parts.append(m)
        from jax.sharding import PartitionSpec

        return NamedSharding(mesh, PartitionSpec(*spec_parts))


# The standard rule set for transformer-family models (scaling-book layout):
# batch over dp+fsdp, params sharded over fsdp (all-gathered per layer) and
# tp (stay sharded), sequence over cp, experts over ep.
DEFAULT_RULES = ShardingRules(
    rules={
        "batch": (AXIS_DATA, AXIS_FSDP),
        "seq": AXIS_CONTEXT,
        "embed": AXIS_FSDP,
        "heads": AXIS_TENSOR,
        "kv_heads": AXIS_TENSOR,
        "mlp": AXIS_TENSOR,
        "vocab": AXIS_TENSOR,
        "expert": AXIS_EXPERT,
        # Layer-stacked params shard their [n_layers] dim over pp: stage s
        # holds the contiguous layer group it pipelines (pipeline_apply
        # reshapes [L] -> [S, L/S]; PartitionSpec blocks are contiguous, so
        # the resident shard IS the stage's group — no resharding).
        "layers": AXIS_PIPELINE,
        "head_dim": None,
        "kv": None,
    }
)


def logical_to_sharding(mesh, logical_axes, rules: ShardingRules = DEFAULT_RULES):
    return rules.sharding(mesh, logical_axes)


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh, rules: ShardingRules = DEFAULT_RULES):
    """Sharding for a [batch, ...] data array."""
    return rules.sharding(mesh, ["batch"])
