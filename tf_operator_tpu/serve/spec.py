"""Serve TPUJob construction — the seam shared by ``tpujob submit
--workload serve``, tools/servebench.py's operator probe, and
tools/trace_smoke.py's smoke serve job. One builder so the workload-key
vocabulary (kv_page_size, kv_pool_pages, requests, ...) has exactly one
authoritative spelling."""

from __future__ import annotations

from typing import Any, Dict, Optional

from tf_operator_tpu.api.types import (
    JOB_CLASS_SERVING,
    ObjectMeta,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    SchedulingSpec,
    TPUJob,
    TPUJobSpec,
)

SERVE_ENTRYPOINT = "tf_operator_tpu.workloads.serve:main"

# The workload-config vocabulary workloads/serve.py reads (defaults sized
# for the CPU-fallback smoke path; a real deployment overrides).
SERVE_WORKLOAD_DEFAULTS: Dict[str, Any] = {
    "preset": "tiny",
    "requests": 8,          # number of synthetic requests to serve
    "prompt_len": 8,        # mean synthetic prompt length (tokens)
    "max_new_tokens": 16,   # generation budget per request
    "arrival_rate": 20.0,   # Poisson arrivals per second (0 ⇒ all at t=0)
    "seed": 0,              # arrival schedule + prompt RNG
    "kv_page_size": 16,
    "kv_pool_pages": 64,
    "max_slots": 4,
    "prefill_chunk": 16,
    "report_every": 4,      # engine steps between live status reports
}


def build_serve_job(
    name: str,
    namespace: str = "default",
    cpu_env: bool = True,
    queue: str = "",
    priority: str = "",
    chips: int = 0,
    workload: Optional[Dict[str, Any]] = None,
) -> TPUJob:
    """One-worker serve job: the engine is a single-process decode loop
    (multi-host serving is roadmap, not r10). job_class="serving" rides
    along so the fleet scheduler treats it as latency-sensitive."""
    env: Dict[str, str] = {}
    if cpu_env:
        env = {
            "JAX_PLATFORMS": "cpu",
            "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "",
        }
    wl = dict(SERVE_WORKLOAD_DEFAULTS)
    wl.update(workload or {})
    spec = TPUJobSpec(
        replica_specs={
            ReplicaType.WORKER: ReplicaSpec(
                replicas=1,
                template=ProcessTemplate(
                    entrypoint=SERVE_ENTRYPOINT, env=env,
                    chips_per_process=chips,
                ),
            )
        },
        workload=wl,
        scheduling=SchedulingSpec(
            queue=queue, priority_class=priority, job_class=JOB_CLASS_SERVING
        ),
    )
    return TPUJob(metadata=ObjectMeta(name=name, namespace=namespace), spec=spec)
