"""Serving subsystem (r10): continuous-batching LM decode under the
operator. ``kvcache`` — the paged KV pool + free-list allocator;
``engine`` — the iteration-level (continuous-batching) scheduler loop;
``spec`` — serve TPUJob construction (the CLI/servebench seam)."""
