"""Paged KV cache: fixed-size pages in a preallocated device pool.

The vLLM (SOSP '23) memory model in jax_graft form: decode K/V state
lives in PAGES of ``page_size`` token slots, preallocated as one device
pool per layer side — shape [n_layers, num_pages + 1, page_size,
n_kv_heads, head_dim]. A sequence owns an ordered page table (host-side
int32 row); growing by one token touches exactly one page row, and
completion returns the pages to a free list with NO copying — the next
sequence overwrites them in place (pages carry no ownership state on
device; the page table is the only source of truth).

Page index ``num_pages`` (the +1) is the TRASH page: masked writes from
inactive batch slots and prefill padding are steered there instead of
predicating the scatter — its contents are never read (no page table
ever names it inside a live prefix).

The allocator is deliberately host-side and trivial: a LIFO free list.
LIFO maximizes page reuse locality (a just-freed page is hot in whatever
cache hierarchy applies) and makes the leak check exact —
``free_count`` must return to ``num_pages`` when the engine drains,
which the serve-bench CI stage asserts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List


class PoolExhausted(Exception):
    """Raised when an allocation cannot be satisfied — admission control
    must catch this and hold the request, never the decode step."""


def pages_needed(tokens: int, page_size: int) -> int:
    """Pages required to hold ``tokens`` K/V positions (ceil)."""
    return max(1, -(-int(tokens) // int(page_size)))


def pool_bytes(
    n_layers: int,
    num_pages: int,
    page_size: int,
    n_kv_heads: int,
    head_dim: int,
    dtype_bytes: int = 4,
) -> int:
    """Device bytes of the K+V pools (including the trash page) — the
    number tools/memplan.py budgets for a serve job."""
    per_side = (
        n_layers * (num_pages + 1) * page_size * n_kv_heads * head_dim
    )
    return 2 * per_side * dtype_bytes


@dataclass
class PagePool:
    """Free-list page allocator over a pool of ``num_pages`` pages.

    Pure host-side bookkeeping: the device pool itself is allocated by
    the engine (it owns dtype/layout); this class only decides which
    page ids are live. ``free_count`` is the leak probe — after every
    sequence is finished and freed it must equal ``num_pages``."""

    num_pages: int
    _free: List[int] = field(default_factory=list)

    def __post_init__(self) -> None:
        if self.num_pages < 1:
            raise ValueError(f"page pool needs >= 1 page, got {self.num_pages}")
        # LIFO: pop from the tail, so page 0 is handed out first.
        self._free = list(range(self.num_pages - 1, -1, -1))

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def trash_page(self) -> int:
        """The masked-write sink: one past the allocatable range."""
        return self.num_pages

    def alloc(self, n: int) -> List[int]:
        """Allocate ``n`` pages or raise PoolExhausted (all-or-nothing:
        a partial grant would leak on the caller's error path)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} pages, {len(self._free)}/{self.num_pages} free"
            )
        got = [self._free.pop() for _ in range(n)]
        return got

    def free(self, pages: List[int]) -> None:
        """Return pages to the free list. Copy-free reuse: the device
        pages are NOT cleared — the next owner overwrites them and its
        page table masks anything it hasn't written yet."""
        for p in pages:
            if not 0 <= p < self.num_pages:
                raise ValueError(f"free of page {p} outside pool")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
        self._free.extend(pages)


@dataclass
class SequencePages:
    """One sequence's page table: the ordered page ids backing positions
    [0, len). Grown on demand by the engine as the sequence crosses page
    boundaries; freed wholesale at completion."""

    page_size: int
    pages: List[int] = field(default_factory=list)

    @property
    def capacity(self) -> int:
        return len(self.pages) * self.page_size

    def ensure(self, length: int, pool: PagePool) -> None:
        """Grow to cover ``length`` positions (PoolExhausted propagates —
        the engine's admission policy reserves worst-case up front by
        default, so on-demand growth only fires under the optimistic
        knob)."""
        need = pages_needed(length, self.page_size) - len(self.pages)
        if need > 0:
            self.pages.extend(pool.alloc(need))

    def release(self, pool: PagePool) -> None:
        pool.free(self.pages)
        self.pages = []
