"""Continuous-batching decode engine (Orca OSDI '22 iteration-level
scheduling + vLLM SOSP '23 paged KV) over the models/transformer.py LM.

The unit of scheduling is ONE engine step, not one request: at every
step boundary the engine admits newly-arrived requests into free batch
slots, pushes one prefill chunk for each still-prefilling slot, runs one
batched decode step for every decoding slot, and evicts finished
sequences immediately (pages back to the free list the same step — the
next admission reuses them copy-free). There is no drain-the-batch
barrier anywhere; ``mode="static"`` deliberately reintroduces one (admit
only into an EMPTY batch, hold every slot until the whole batch
finishes) as the baseline tools/servebench.py compares against.

Two compiled functions, both fixed-shape:

- the DECODE step: every slot advances one token. Each layer computes
  single-position q/k/v, rotates at the token's absolute position
  (rope_at_positions), scatters k/v into the slot's current page row,
  and attends through the page table (ops.flash_attention_decode —
  kernel on TPU, gather reference off-TPU). Inactive slots steer their
  writes to the pool's trash page and mask attention with seq_len 0.

- the PREFILL chunk: ``prefill_chunk`` prompt tokens of ONE sequence.
  The chunk's C positions are treated as C pseudo-sequences sharing the
  sequence's page table row with per-position lengths pos+1 — k/v are
  written first, then the SAME paged decode attention runs, which makes
  the chunk causal by construction and keeps prefill on the decode
  path instead of a second attention implementation. The last chunk's
  final logits yield the request's first generated token (the TTFT
  boundary).

Greedy argmax sampling, f32 compute throughout: serving determinism is
what the correctness oracle (tests/test_serve.py) and the seeded bench
artifact pin against.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from tf_operator_tpu.serve.kvcache import (
    PagePool,
    PoolExhausted,
    SequencePages,
    pages_needed,
)


@dataclass
class ServeConfig:
    """Engine policy knobs (workload keys carry the same names with a
    ``kv_``/serve prefix — see workloads/serve.py)."""

    page_size: int = 16
    pool_pages: int = 64
    max_slots: int = 4
    prefill_chunk: int = 16
    # admission policy: reserve the worst case (prompt + max_new) pages
    # at admission so a running sequence can never hit PoolExhausted
    # mid-decode; False allocates prompt-only and grows on demand (a
    # growth failure is a hard error — the knob exists to measure the
    # reservation's utilization cost, not for production).
    reserve_full: bool = True
    # at most this many admissions per step boundary (0 = unlimited):
    # bounds per-step prefill work so decode latency stays smooth under
    # an arrival burst.
    max_admit_per_step: int = 0
    mode: str = "continuous"  # "continuous" | "static" (drain baseline)


@dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new: int
    arrival: float = 0.0  # seconds offset from run start

    # filled in by the engine
    tokens: List[int] = field(default_factory=list)
    admitted: float = -1.0
    first_token: float = -1.0
    finished: float = -1.0
    token_times: List[float] = field(default_factory=list)


@dataclass
class RunResult:
    requests: List[Request]
    steps: int
    wall_s: float
    generated_tokens: int
    free_pages_start: int
    free_pages_end: int

    @property
    def completed(self) -> int:
        return sum(1 for r in self.requests if r.finished >= 0)

    @property
    def tokens_per_s(self) -> float:
        return self.generated_tokens / self.wall_s if self.wall_s > 0 else 0.0

    def ttfts(self) -> List[float]:
        return [r.first_token - r.arrival for r in self.requests
                if r.first_token >= 0]

    def token_latencies(self) -> List[float]:
        """Inter-token gaps per request (the per-token latency the bench
        quotes p50/p99 of; TTFT is excluded — it has its own metric)."""
        out: List[float] = []
        for r in self.requests:
            ts = r.token_times
            out.extend(b - a for a, b in zip(ts, ts[1:]))
        return out


class _Slot:
    __slots__ = ("req", "pages", "seq_len", "prefill_pos", "cur_tok", "generated")

    def __init__(self, req: Request, pages: SequencePages):
        self.req = req
        self.pages = pages
        self.seq_len = 0        # K/V positions written
        self.prefill_pos = 0    # prompt tokens consumed
        self.cur_tok = -1       # pending input token once decoding
        self.generated = 0


class ServeEngine:
    def __init__(self, cfg, params, scfg: ServeConfig):
        import jax

        if cfg.n_experts:
            raise ValueError("serve engine: MoE presets not supported")
        if getattr(cfg, "pp_stages", 0):
            raise ValueError("serve engine: pipeline presets not supported")
        if scfg.page_size < 1:
            raise ValueError(f"kv_page_size must be >= 1, got {scfg.page_size}")
        if scfg.pool_pages < 1:
            raise ValueError(f"kv_pool_pages must be >= 1, got {scfg.pool_pages}")
        self.cfg = cfg
        self.scfg = scfg
        # f32 master weights: serving determinism + the logits-parity
        # oracle; pools match.
        import jax.numpy as jnp

        self.params = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a, jnp.float32), params
        )
        self.max_pages_per_seq = pages_needed(cfg.max_seq, scfg.page_size)
        self._jit_build()

    # -- compiled step functions -----------------------------------------

    def _jit_build(self) -> None:
        import jax
        import jax.numpy as jnp

        from tf_operator_tpu.models.transformer import (
            _rms_norm,
            rope_at_positions,
        )
        from tf_operator_tpu.ops.flash_attention import flash_attention_decode

        cfg = self.cfg
        ps = self.scfg.page_size
        trash = self.scfg.pool_pages  # PagePool.trash_page
        hd = cfg.head_dim
        L = cfg.n_layers

        def _body(params, kp, vp, x, pos, table, lens, write_pid, write_row):
            """Shared per-layer body: x [n, d] at absolute positions pos
            [n]; writes each row's k/v to (write_pid[i], write_row[i])
            then attends through ``table`` with per-row lengths ``lens``.
            Returns (kp, vp, final hidden [n, d])."""
            n = x.shape[0]
            lp = params["layers"]
            for l in range(L):
                h = _rms_norm(x, lp["attn_norm"][l], cfg.norm_eps)
                q = (h @ lp["wq"][l]).reshape(n, -1, hd)
                k = (h @ lp["wk"][l]).reshape(n, -1, hd)
                v = (h @ lp["wv"][l]).reshape(n, -1, hd)
                q = rope_at_positions(q[:, None], pos[:, None], cfg.rope_theta)[:, 0]
                k = rope_at_positions(k[:, None], pos[:, None], cfg.rope_theta)[:, 0]
                kp = kp.at[l, write_pid, write_row].set(k)
                vp = vp.at[l, write_pid, write_row].set(v)
                attn = flash_attention_decode(q, kp[l], vp[l], table, lens)
                x = x + attn.reshape(n, -1) @ lp["wo"][l]
                h2 = _rms_norm(x, lp["mlp_norm"][l], cfg.norm_eps)
                x = x + (
                    jax.nn.silu(h2 @ lp["w_gate"][l]) * (h2 @ lp["w_up"][l])
                ) @ lp["w_down"][l]
            return kp, vp, x

        def decode_step(params, kp, vp, table, seq_lens, tokens, active):
            """One token for every slot. tokens[i] sits at position
            seq_lens[i]; returns next greedy token per slot."""
            s = tokens.shape[0]
            x = params["embed"][tokens]
            pos = seq_lens
            pid = table[jnp.arange(s), pos // ps]
            pid = jnp.where(active, pid, trash)
            kp, vp, x = _body(
                params, kp, vp, x, pos, table,
                jnp.where(active, pos + 1, 0), pid, pos % ps,
            )
            logits = _rms_norm(x, params["final_norm"], cfg.norm_eps) @ params["embed"].T
            return kp, vp, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def prefill_chunk(params, kp, vp, table_row, start, tokens_c, n_valid):
            """One chunk of one sequence's prompt: the C positions run as
            C pseudo-sequences over the shared page-table row (lengths
            pos+1 ⇒ causal), reusing the paged decode attention."""
            c = tokens_c.shape[0]
            idx = jnp.arange(c)
            pos = start + idx
            valid = idx < n_valid
            x = params["embed"][tokens_c]
            pid = jnp.where(valid, table_row[pos // ps], trash)
            table_c = jnp.broadcast_to(table_row, (c, table_row.shape[0]))
            kp, vp, x = _body(
                params, kp, vp, x, pos, table_c,
                jnp.where(valid, pos + 1, 0), pid, pos % ps,
            )
            last = _rms_norm(x[n_valid - 1], params["final_norm"], cfg.norm_eps)
            logits = last @ params["embed"].T
            return kp, vp, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        self._decode = jax.jit(decode_step, donate_argnums=(1, 2))
        self._prefill = jax.jit(prefill_chunk, donate_argnums=(1, 2))

    def _fresh_pools(self):
        import jax.numpy as jnp

        cfg, scfg = self.cfg, self.scfg
        shape = (
            cfg.n_layers, scfg.pool_pages + 1, scfg.page_size,
            cfg.n_kv_heads, cfg.head_dim,
        )
        return jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32)

    # -- the scheduler loop ----------------------------------------------

    def run(
        self,
        requests: List[Request],
        mode: Optional[str] = None,
        clock: Callable[[], float] = time.perf_counter,
        on_event: Optional[Callable[[str, Any], None]] = None,
    ) -> RunResult:
        """Serve ``requests`` (arrival offsets in seconds from run start)
        to completion. ``on_event(kind, payload)`` fires with kinds
        "admitted"/"first_token"/"finished" (payload: the Request) and
        "step" (payload: dict with step/active/waiting/completed) — the
        workload's span + live-count seam."""
        import jax.numpy as jnp

        mode = mode or self.scfg.mode
        if mode not in ("continuous", "static"):
            raise ValueError(f"mode {mode!r}")
        scfg = self.scfg
        for r in requests:
            if not r.prompt:
                raise ValueError(f"request {r.rid}: empty prompt")
            if len(r.prompt) + r.max_new > self.cfg.max_seq:
                raise ValueError(
                    f"request {r.rid}: prompt {len(r.prompt)} + max_new "
                    f"{r.max_new} exceeds max_seq {self.cfg.max_seq}"
                )
            if pages_needed(len(r.prompt) + r.max_new, scfg.page_size) > scfg.pool_pages:
                raise ValueError(
                    f"request {r.rid} alone needs "
                    f"{pages_needed(len(r.prompt) + r.max_new, scfg.page_size)} "
                    f"pages but the pool holds {scfg.pool_pages} — it could "
                    f"never be admitted"
                )
        pool = PagePool(scfg.pool_pages)
        free_start = pool.free_count
        kp, vp = self._fresh_pools()
        s_n = scfg.max_slots
        table = np.full((s_n, self.max_pages_per_seq), pool.trash_page - 1,
                        np.int32)
        slots: List[Optional[_Slot]] = [None] * s_n

        pending = deque(sorted(requests, key=lambda r: (r.arrival, r.rid)))
        waiting: deque = deque()
        emit = on_event or (lambda kind, payload: None)
        t0 = clock()
        step = 0
        completed = 0
        generated = 0

        def _admit_ok() -> bool:
            if mode == "static":
                # drain-the-batch baseline: the batch forms only when
                # EMPTY — late arrivals wait out the whole generation.
                return all(sl is None for sl in slots)
            return True

        def _try_admit(now: float) -> int:
            n = 0
            while waiting and _admit_ok():
                if scfg.max_admit_per_step and n >= scfg.max_admit_per_step:
                    break
                free = [i for i, sl in enumerate(slots) if sl is None]
                if not free:
                    break
                req = waiting[0]
                want = len(req.prompt) + (req.max_new if scfg.reserve_full else 0)
                sp = SequencePages(scfg.page_size)
                try:
                    sp.ensure(want, pool)
                except PoolExhausted:
                    break  # head-of-line blocks: FIFO admission, no bypass
                waiting.popleft()
                i = free[0]
                slots[i] = _Slot(req, sp)
                table[i, : len(sp.pages)] = sp.pages
                req.admitted = now
                emit("admitted", req)
                n += 1
                if mode == "static" and n >= s_n:
                    break
            return n

        def _finish(i: int, now: float) -> None:
            """Mark slot i's request complete. Continuous mode releases
            the slot and its pages IMMEDIATELY (reusable this very step);
            static mode holds everything until the whole batch drains —
            the barrier being measured."""
            nonlocal completed
            sl = slots[i]
            sl.req.finished = now
            completed += 1
            emit("finished", sl.req)
            if mode == "continuous":
                sl.pages.release(pool)
                table[i, :] = pool.trash_page - 1
                slots[i] = None

        def _drain_static(now: float) -> None:
            if mode != "static":
                return
            live = [sl for sl in slots if sl is not None]
            if live and all(sl.generated >= sl.req.max_new for sl in live):
                for j, sl in enumerate(slots):
                    if sl is not None:
                        sl.pages.release(pool)
                        table[j, :] = pool.trash_page - 1
                        slots[j] = None

        while completed < len(requests):
            now = clock() - t0
            while pending and pending[0].arrival <= now:
                waiting.append(pending.popleft())
            _try_admit(now)
            busy = [sl for sl in slots if sl is not None]
            if not busy:
                if pending:
                    # idle until the next arrival — a serving engine,
                    # not a busy loop.
                    time.sleep(
                        max(0.0, min(0.01, pending[0].arrival - (clock() - t0)))
                    )
                continue

            # ---- prefill: one chunk per still-prefilling slot ----------
            for i, sl in enumerate(slots):
                if sl is None or sl.prefill_pos >= len(sl.req.prompt):
                    continue
                prompt = sl.req.prompt
                c = self.scfg.prefill_chunk
                chunk = prompt[sl.prefill_pos : sl.prefill_pos + c]
                n_valid = len(chunk)
                buf = np.zeros(c, np.int32)
                buf[:n_valid] = chunk
                if not scfg.reserve_full:
                    sl.pages.ensure(sl.prefill_pos + n_valid, pool)
                    table[i, : len(sl.pages.pages)] = sl.pages.pages
                kp, vp, tok = self._prefill(
                    self.params, kp, vp, jnp.asarray(table[i]),
                    jnp.int32(sl.prefill_pos), jnp.asarray(buf),
                    jnp.int32(n_valid),
                )
                sl.prefill_pos += n_valid
                sl.seq_len = sl.prefill_pos
                if sl.prefill_pos >= len(prompt):
                    # last chunk's logits ARE the first generated token
                    t_tok = clock() - t0
                    first = int(tok)
                    sl.req.tokens.append(first)
                    sl.req.token_times.append(t_tok)
                    sl.req.first_token = t_tok
                    sl.generated = 1
                    sl.cur_tok = first
                    generated += 1
                    emit("first_token", sl.req)
                    if sl.generated >= sl.req.max_new:
                        _finish(i, t_tok)

            # ---- decode: one batched step over decoding slots ----------
            dec = [
                (i, sl) for i, sl in enumerate(slots)
                if sl is not None
                and sl.prefill_pos >= len(sl.req.prompt)
                and sl.generated < sl.req.max_new
            ]
            if dec:
                active = np.zeros(s_n, bool)
                toks = np.zeros(s_n, np.int32)
                lens = np.zeros(s_n, np.int32)
                for i, sl in dec:
                    if not scfg.reserve_full:
                        sl.pages.ensure(sl.seq_len + 1, pool)
                        table[i, : len(sl.pages.pages)] = sl.pages.pages
                    active[i] = True
                    toks[i] = sl.cur_tok
                    lens[i] = sl.seq_len
                kp, vp, nxt = self._decode(
                    self.params, kp, vp, jnp.asarray(table), jnp.asarray(lens),
                    jnp.asarray(toks), jnp.asarray(active),
                )
                nxt = np.asarray(nxt)
                t_tok = clock() - t0
                for i, sl in dec:
                    sl.seq_len += 1
                    sl.generated += 1
                    sl.cur_tok = int(nxt[i])
                    sl.req.tokens.append(sl.cur_tok)
                    sl.req.token_times.append(t_tok)
                    generated += 1
                    if sl.generated >= sl.req.max_new:
                        _finish(i, t_tok)
            _drain_static(clock() - t0)
            step += 1
            emit("step", {
                "step": step,
                "active": sum(1 for sl in slots if sl is not None),
                "waiting": len(waiting) + len(pending),
                "completed": completed,
                "generated": generated,
                "free_pages": pool.free_count,
            })

        wall = clock() - t0
        return RunResult(
            requests=list(requests), steps=step, wall_s=wall,
            generated_tokens=generated, free_pages_start=free_start,
            free_pages_end=pool.free_count,
        )
