"""The operator daemon.

Reference parity: cmd/tf-operator.v2/app/server.go — flag parsing, client
wiring, informers, leader election, controller Run. One process hosts the
store (apiserver analogue), the reconciling controller, the local process
backend, and the REST dashboard.

Beyond the reference: ``--chaos-level`` is actually implemented (the
reference shipped it as an explicit placeholder,
cmd/tf-operator/app/options/options.go:40-41): at level L, roughly every
``--chaos-interval`` seconds each running process is SIGKILLed with
probability L/10 — exercising the retryable-failure/gang-restart path
continuously.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import random
import signal
import sys
import threading

log = logging.getLogger("tpujob.operator")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpujob-operator", description="TPUJob operator daemon"
    )
    # reference: options.go (v1alpha1:23-47, v2:22-48)
    from tf_operator_tpu.utils.version import add_version_flag

    add_version_flag(p)
    p.add_argument("--threadiness", type=int, default=2,
                   help="controller worker threads (reference default 2)")
    p.add_argument("--resync-period", type=float, default=15.0,
                   help="reconciler sync loop period seconds (reference 15s)")
    p.add_argument("--reconcile-shards", type=int, default=1,
                   help="partition the reconcile workqueue into N namespace-"
                        "hashed shards (clamped to --threadiness); >1 keeps "
                        "one tenant's submit burst from head-of-line "
                        "blocking other tenants behind a single queue mutex")
    p.add_argument("--port", type=int, default=8080, help="dashboard/API port")
    p.add_argument("--host", default="127.0.0.1", help="dashboard/API bind host")
    p.add_argument("--api-workers", type=int, default=64,
                   help="max concurrently-served API connections (bounded "
                        "handler threads; watch streams hold a slot each — "
                        "size above the agent count)")
    p.add_argument("--json-log-format", action="store_true",
                   help="structured JSON logs (reference: logrus JSON for Stackdriver)")
    p.add_argument("--log-dir", default=os.path.join(os.getcwd(), "tpujob-logs"),
                   help="directory for per-process logs")
    p.add_argument("--enable-leader-elect", action="store_true",
                   help="leader election (reference: EndpointsLock): a store "
                        "Lease when --store-server is set (cluster-wide "
                        "RunOrDie), else a file lease (one machine)")
    p.add_argument("--lease-file", default="/tmp/tpujob-operator.lease")
    p.add_argument("--store-server", default=None,
                   help="connect to a remote store at URL instead of hosting "
                        "one — HA mode: several operators on different "
                        "machines share one store, leader-elect through it, "
                        "and exactly one reconciles")
    p.add_argument("--data-dir", default=None,
                   help="durable store state under this directory (WAL + "
                        "compacted snapshots, runtime/persist.py): a "
                        "restarted operator recovers the identical object "
                        "set and resource_version and re-adopts its "
                        "children instead of double-creating them. Unset = "
                        "classic in-memory store (state dies with the "
                        "process). Conflicts with --store-server (the "
                        "remote store owns durability there).")
    p.add_argument("--snapshot-every", type=int, default=1000,
                   help="mutations between WAL compactions (snapshot + "
                        "segment rotation) when --data-dir is set")
    p.add_argument("--persist-telemetry", action="store_true",
                   help="also WAL-log Telemetry ring-slot writes under "
                        "--data-dir. Default off: telemetry is overwrite "
                        "churn (the WAL would grow with step count, not "
                        "object count) and rings refill from live "
                        "reporters after a restart.")
    p.add_argument("--ledger-dir", default=None,
                   help="fleet ledger directory (obs/ledger.py): one "
                        "compact record per terminal job, durable across "
                        "operator death and job GC — feeds GET "
                        "/api/fleet/*, `tpujob fleet`, autopilot MTBF "
                        "priors, and host reputation. Defaults to "
                        "<data-dir>/ledger when --data-dir is set.")
    p.add_argument("--wal-fsync", action="store_true",
                   help="fsync the WAL per mutation (and snapshots): "
                        "survives machine/power loss, not just operator "
                        "crashes, at a large per-write cost. Default off: "
                        "per-record flush() already survives any operator "
                        "process death.")
    p.add_argument("--store-only", action="store_true",
                   help="host only the store + dashboard/API (the apiserver "
                        "analogue) with no controller — the shared substrate "
                        "for --store-server HA operators")
    p.add_argument("--chaos-level", type=int, default=0, choices=range(0, 11),
                   help="0-10: probability/10 of killing each running process "
                        "per chaos interval (reference flag was unimplemented)")
    p.add_argument("--chaos-interval", type=float, default=10.0)
    p.add_argument("--controller-config-file", default=None,
                   help="admin ControllerConfig (JSON/YAML) mapping chip kinds "
                        "to env/library injection (reference: "
                        "--controller-config-file, server.go:138-156)")
    p.add_argument("--local-agents", type=int, default=0,
                   help="start N in-process host agents (multi-host mode on "
                        "one machine: gang scheduler + per-host launch; 0 = "
                        "classic single-host mode)")
    p.add_argument("--agent-chips", type=int, default=8,
                   help="chip capacity each local agent advertises")
    p.add_argument("--agent-slice-type", default="",
                   help="slice type local agents advertise (e.g. v5e-8)")
    p.add_argument("--compile-cache", action="store_true",
                   help="host the fleet compile-cache service (cachesvc/): "
                        "created gang members get its URL as "
                        "TPUJOB_COMPILE_CACHE and compile_cache.enable() "
                        "becomes a two-tier read-through; the reconciler "
                        "kicks AOT compiles at admission so compilation "
                        "overlaps the scheduling wait")
    p.add_argument("--compile-cache-bytes", type=int, default=4 << 30,
                   help="compile-cache service byte cap (oldest-touched "
                        "entries are evicted past it)")
    p.add_argument("--aot-workers", type=int, default=2,
                   help="admission-time AOT compiler threads (with "
                        "--compile-cache)")
    p.add_argument("--warm-pool", type=int, default=0, metavar="N",
                   help="each local agent keeps N pre-initialized harness "
                        "runtimes (runtime/warmpool.py); gang members "
                        "launch into a warm slot instead of a cold fork")
    p.add_argument("--warm-import-jax", action="store_true",
                   help="warm slots also pre-initialize the jax runtime")
    p.add_argument("--backend", choices=("native", "local"), default="native",
                   help="process runtime: 'native' = C++ supervisor "
                        "(group kills, normalized exit codes; built on demand), "
                        "'local' = pure-Python subprocess fallback")
    p.add_argument("--auth-token-file", default=None,
                   help="file holding the cluster's shared API secret "
                        "(utils.auth): this daemon requires it as a bearer "
                        "token on mutating/API routes it serves, and presents "
                        "it to --store-server. Defaults to $TPUJOB_AUTH_TOKEN "
                        "/ $TPUJOB_AUTH_TOKEN_FILE; unset = open server "
                        "(reference parity note: k8sutil.go:53-77 rode "
                        "kubeconfig auth instead)")
    p.add_argument("--auth-reads", action="store_true",
                   help="extend the bearer check to every READ route except "
                        "/healthz (job reads, events, logs, /metrics, UI) — "
                        "full reference parity, where Kubernetes auth covers "
                        "all API access. Requires --auth-token-file.")
    return p


class _JsonFormatter(logging.Formatter):
    def format(self, record):
        return json.dumps(
            {
                "severity": record.levelname,
                "message": record.getMessage(),
                "logger": record.name,
                "time": self.formatTime(record),
                "filename": f"{record.filename}:{record.lineno}",
            }
        )


def setup_logging(json_format: bool) -> None:
    handler = logging.StreamHandler(sys.stderr)
    if json_format:
        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(name)s [%(levelname)s] %(filename)s:%(lineno)d %(message)s")
        )
    logging.basicConfig(level=logging.INFO, handlers=[handler])


class ChaosMonkey:
    """Implemented --chaos-level (SURVEY.md §5: placeholder in reference)."""

    def __init__(self, store, level: int, interval: float) -> None:
        self.store = store
        self.level = level
        self.interval = interval
        self._stop = threading.Event()
        self._thread = None

    def start(self) -> None:
        if self.level <= 0:
            return
        self._thread = threading.Thread(target=self._loop, name="chaos", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        from tf_operator_tpu.runtime.objects import ProcessPhase

        while not self._stop.wait(self.interval):
            for proc in self.store.list("Process"):
                if proc.status.phase is ProcessPhase.RUNNING and proc.status.pid:
                    if random.random() < self.level / 10.0:
                        log.warning("chaos: killing %s (pid %s)", proc.key(), proc.status.pid)
                        try:
                            os.kill(proc.status.pid, signal.SIGKILL)
                        except OSError:
                            pass

    def stop(self) -> None:
        self._stop.set()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    setup_logging(args.json_log_format)

    from tf_operator_tpu.controller import TPUJobController
    from tf_operator_tpu.controller.leader import FileLease, LeaderElector, StoreLease
    from tf_operator_tpu.dashboard import DashboardServer
    from tf_operator_tpu.runtime import LocalProcessControl, NativeProcessControl, Store

    from tf_operator_tpu.utils.auth import resolve_token

    auth_token = resolve_token(token_file=args.auth_token_file)
    if args.auth_reads and not auth_token:
        # a tokenless "authed-reads" server would silently serve open —
        # the exact hole the flag exists to close
        sys.exit("--auth-reads requires an auth token "
                 "(--auth-token-file / $TPUJOB_AUTH_TOKEN)")
    if auth_token:
        log.info("API auth enabled (bearer token)")
        # Export to our own env: launched child processes inherit it, so
        # workload write-backs (evaluator -> ENV_API_SERVER) authenticate
        # without the secret ever entering job specs or the store.
        from tf_operator_tpu.utils.auth import ENV_AUTH_TOKEN

        os.environ[ENV_AUTH_TOKEN] = auth_token

    recovery = None
    if args.store_server:
        if args.data_dir:
            sys.exit("--data-dir conflicts with --store-server: durability "
                     "belongs to the process hosting the store")
        from tf_operator_tpu.runtime.remote_store import RemoteStore

        store = RemoteStore(args.store_server, token=auth_token)
    elif args.data_dir:
        from tf_operator_tpu.runtime.persist import open_store

        store, recovery = open_store(
            args.data_dir,
            snapshot_every=args.snapshot_every,
            fsync=args.wal_fsync,
            persist_telemetry=args.persist_telemetry,
        )
        if recovery.recovered:
            log.warning(
                "recovered durable store from %s: %d objects at rv %d "
                "(snapshot rv %d + %d WAL records%s)",
                args.data_dir, recovery.objects, recovery.resource_version,
                recovery.snapshot_rv, recovery.replayed,
                ", torn tail truncated" if recovery.truncated_tail else "",
            )
    else:
        store = Store()

    if args.store_only:
        # apiserver analogue: store + API only; HA operators connect via
        # --store-server and leader-elect through a Lease in this store.
        if args.store_server:
            sys.exit("--store-only hosts the store; it conflicts with --store-server")
        dashboard = DashboardServer(
            store, host=args.host, port=args.port, auth_token=auth_token,
            auth_reads=args.auth_reads, max_workers=args.api_workers,
        )
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        dashboard.start()
        log.info("store-only mode: API listening on %s", dashboard.url)
        stop.wait()
        dashboard.stop()
        return 0
    if args.backend == "native":
        from tf_operator_tpu.runtime.native import NativeBuildError

        try:
            backend = NativeProcessControl(store, log_dir=args.log_dir)
        except (NativeBuildError, OSError) as exc:
            # Toolchain missing/broken: degrade, don't die. Anything else
            # (a bug in the binding) must surface, not silently lose the
            # native guarantees (group kills, normalized exit codes).
            log.warning("native supervisor unavailable (%s); using local backend", exc)
            backend = LocalProcessControl(store, log_dir=args.log_dir)
    else:
        backend = LocalProcessControl(store, log_dir=args.log_dir)
    controller_config = None
    if args.controller_config_file:
        from tf_operator_tpu.api.helpers import ControllerConfig

        controller_config = ControllerConfig.load(args.controller_config_file)
        log.info("loaded controller config from %s", args.controller_config_file)
    controller = TPUJobController(
        store, backend, resync_period=args.resync_period,
        controller_config=controller_config,
    )
    # Fleet ledger (r18): the cross-job memory. attach_ledger sweeps any
    # terminal jobs a previous incarnation died before folding, then
    # seeds host reputation into the scheduler's deprioritized set.
    ledger = None
    ledger_dir = args.ledger_dir or (
        os.path.join(args.data_dir, "ledger") if args.data_dir else None
    )
    if ledger_dir:
        from tf_operator_tpu.obs.ledger import FleetLedger

        ledger = FleetLedger(ledger_dir, fsync=args.wal_fsync)
        controller.attach_ledger(ledger)
        log.info("fleet ledger at %s (%d job records)", ledger_dir, len(ledger))
    warm_pool = None
    if args.warm_pool > 0 and args.local_agents == 0:
        # Single-host mode: the operator's own backend launches the gang,
        # so the warm pool attaches here (multi-host: each agent's).
        from tf_operator_tpu.runtime.warmpool import WarmPool

        warm_pool = WarmPool(args.warm_pool, import_jax=args.warm_import_jax)
        backend.warm_pool = warm_pool
        controller.metrics.gauge_providers["tpujob_warmpool_warm_idle"] = (
            warm_pool.warm_idle
        )
        controller.metrics.gauge_help["tpujob_warmpool_warm_idle"] = (
            "Idle pre-warmed worker slots ready for handoff."
        )
        log.info("warm pool: %d pre-initialized runtimes", args.warm_pool)
    cachesvc = None
    aot = None
    if args.compile_cache:
        from tf_operator_tpu.cachesvc import CompileCacheService
        from tf_operator_tpu.cachesvc.aot import AOTCompiler

        cachesvc = CompileCacheService(
            host=args.host, max_bytes=args.compile_cache_bytes
        )
        aot = AOTCompiler(
            cachesvc.url, workers=args.aot_workers,
            on_done=controller._aot_span,
        )
        controller.compile_cache_url = cachesvc.url
        controller.aot = aot
        controller.metrics.gauge_providers["tpujob_cachesvc_entries"] = (
            lambda: cachesvc.snapshot()["entries"]
        )
        controller.metrics.gauge_help["tpujob_cachesvc_entries"] = (
            "Entries resident in the fleet compile-cache service."
        )
        if ledger is not None:
            # The per-fleet compile-cache miss rate rides the ledger
            # rollup (summary()["compile_cache"]) for capacity sizing.
            ledger.cachesvc_stats = cachesvc.snapshot
        log.info("compile-cache service on %s (cap %d bytes, %d AOT workers)",
                 cachesvc.url, args.compile_cache_bytes, args.aot_workers)
    # In --store-server HA mode the primary API/UI lives on the store
    # server, but each operator still serves its own endpoint: /metrics
    # (workqueue depth, reconcile counters) exists only in the controller
    # process, and the UI/API routes proxy reads through the RemoteStore.
    # --port 0 picks an ephemeral port for candidates sharing a machine.
    dashboard = DashboardServer(
        store, host=args.host, port=args.port, metrics=controller.metrics,
        auth_token=auth_token, auth_reads=args.auth_reads,
        max_workers=args.api_workers, ledger=ledger,
    )
    chaos = ChaosMonkey(store, args.chaos_level, args.chaos_interval)

    # Multi-host mode on one machine: per-host agents launch their bound
    # processes; the controller only writes bindings (kubelet split).
    agents = []
    if args.local_agents > 0:
        from tf_operator_tpu.runtime.agent import HostAgent

        for i in range(args.local_agents):
            agents.append(
                HostAgent(
                    store,
                    f"host-{i}",
                    total_chips=args.agent_chips,
                    slice_type=args.agent_slice_type,
                    backend=type(backend)(store, log_dir=args.log_dir),
                    warm_pool=args.warm_pool,
                    warm_import_jax=args.warm_import_jax,
                )
            )
        for a in agents:
            a.start()
        log.info("started %d local host agents", len(agents))

    stop = threading.Event()

    def shutdown(*_):
        log.info("shutting down")
        stop.set()

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)

    dashboard.start()
    log.info("dashboard/API listening on %s", dashboard.url)
    # Children report results (eval scores) back through the API; in HA
    # mode that is the shared store server, locally our own dashboard.
    controller.api_url = args.store_server or dashboard.url

    def start_controller():
        controller.run(workers=args.threadiness, shards=args.reconcile_shards)
        if recovery is not None and recovery.recovered:
            # Restart re-adoption: claim recovered children, stamp a
            # controller-restart span/event into every live job's trace,
            # and enqueue them — expectations are empty post-restart, so
            # the first syncs trust the recovered cache and must find the
            # existing gang members instead of double-creating them.
            n = controller.record_recovery(recovery)
            log.info("controller restart recovery: re-adopted %d live jobs", n)
        chaos.start()
        log.info("controller running (%d workers)", args.threadiness)

    rc = {"code": 0}

    def lost_leadership():
        # RunOrDie semantics: a dead leader must exit NONZERO so a
        # restart-on-failure supervisor brings a candidate back up.
        log.error("lost leadership; exiting")
        rc["code"] = 1
        stop.set()

    if args.enable_leader_elect:
        if args.store_server:
            lease = StoreLease(store)
            where = f"store {args.store_server}"
        else:
            lease = FileLease(args.lease_file)
            where = f"file {args.lease_file}"
        elector = LeaderElector(
            lease,
            on_started_leading=start_controller,
            on_stopped_leading=lost_leadership,
            stop_event=stop,
        )
        elector.run_in_background()
        log.info("waiting for leadership (lease in %s)", where)
    else:
        start_controller()

    stop.wait()
    chaos.stop()
    if aot is not None:
        aot.stop()
    controller.stop()
    for a in agents:
        a.stop()
    if warm_pool is not None:
        warm_pool.stop()
    backend.shutdown()
    if cachesvc is not None:
        cachesvc.stop()
    dashboard.stop()
    if ledger is not None:
        ledger.close()
    return rc["code"]


if __name__ == "__main__":
    sys.exit(main())
