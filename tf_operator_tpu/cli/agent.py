"""Standalone host-agent daemon: run one per machine against a remote
operator.

The multi-machine deployment shape (docs/design.md §8): the operator
(controller + store + REST API) runs on one host; each TPU host runs

    python -m tf_operator_tpu.cli.agent --server http://operator:8080 \
        --name host-3 --address 10.0.0.3 --chips 4 [--slice-type v5e-8]

The agent registers its Host object through the generic object API,
heartbeats it, watches for Process bindings to its name, and launches
them with the local or native backend — the kubelet half of the
controller/kubelet split, over the wire.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading
import time

from tf_operator_tpu.runtime.agent import HostAgent
from tf_operator_tpu.runtime.remote_store import RemoteStore

log = logging.getLogger("tpujob.agent-daemon")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpujob-agent", description="per-host launcher daemon"
    )
    from tf_operator_tpu.utils.version import add_version_flag

    add_version_flag(p)
    p.add_argument("--server", required=True,
                   help="operator base URL, e.g. http://10.0.0.1:8080")
    p.add_argument("--name", required=True, help="unique host name")
    p.add_argument("--address", default="127.0.0.1",
                   help="this host's address reachable by gang peers")
    p.add_argument("--chips", type=int, default=0, help="TPU chips on this host")
    p.add_argument("--slice-type", default="", help="slice family, e.g. v5e-8")
    p.add_argument("--max-processes", type=int, default=0)
    p.add_argument("--heartbeat-interval", type=float, default=3.0)
    p.add_argument("--drain-grace", type=float, default=0.0,
                   help="seconds to drain on SIGTERM before stopping: the "
                        "agent marks its Host DRAINING (preemption notice) "
                        "so the controller checkpoint-restarts gangs off "
                        "this host, and waits until its children are gone "
                        "or the grace expires. 0 = stop immediately "
                        "(SIGINT always stops immediately)")
    p.add_argument("--backend", choices=("native", "local"), default="native")
    p.add_argument("--warm-pool", type=int, default=0, metavar="N",
                   help="keep N pre-initialized harness runtimes per host "
                        "(runtime/warmpool.py); gang members launch into a "
                        "warm slot instead of a cold fork. 0 = disabled")
    p.add_argument("--warm-import-jax", action="store_true",
                   help="warm slots also pre-initialize the jax runtime/"
                        "backend (the expensive part on TPU hosts)")
    p.add_argument("--log-dir", default=None,
                   help="capture launched processes' stdout/stderr here")
    p.add_argument("--json-log-format", action="store_true")
    p.add_argument("--auth-token-file", default=None,
                   help="file with the cluster API secret; defaults to "
                        "$TPUJOB_AUTH_TOKEN / $TPUJOB_AUTH_TOKEN_FILE")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format=(
            '{"ts":"%(asctime)s","level":"%(levelname)s","msg":"%(message)s"}'
            if args.json_log_format
            else "%(asctime)s %(name)s [%(levelname)s] %(message)s"
        ),
    )
    from tf_operator_tpu.utils.auth import ENV_AUTH_TOKEN, resolve_token

    token = resolve_token(token_file=args.auth_token_file)
    if token:
        import os

        # children this agent launches inherit the credential (evaluator
        # write-back); mirrors the operator daemon's export
        os.environ[ENV_AUTH_TOKEN] = token
    store = RemoteStore(args.server, token=token)
    if args.backend == "native":
        from tf_operator_tpu.runtime.native import NativeBuildError
        from tf_operator_tpu.runtime.process_backend import (
            LocalProcessControl,
            NativeProcessControl,
        )

        try:
            backend = NativeProcessControl(store, log_dir=args.log_dir)
        except (NativeBuildError, OSError) as exc:
            log.warning("native supervisor unavailable (%s); using local", exc)
            backend = LocalProcessControl(store, log_dir=args.log_dir)
    else:
        from tf_operator_tpu.runtime.process_backend import LocalProcessControl

        backend = LocalProcessControl(store, log_dir=args.log_dir)

    agent = HostAgent(
        store,
        args.name,
        address=args.address,
        total_chips=args.chips,
        slice_type=args.slice_type,
        max_processes=args.max_processes,
        backend=backend,
        heartbeat_interval=args.heartbeat_interval,
        warm_pool=args.warm_pool,
        warm_import_jax=args.warm_import_jax,
    )
    stop = threading.Event()
    drain = threading.Event()

    def shutdown(*_):
        stop.set()

    def sigterm(*_):
        # Cloud preemption delivers SIGTERM with a grace window: drain
        # first (the controller checkpoint-restarts gangs off this host),
        # stop when children are gone or the grace expires.
        if args.drain_grace > 0:
            drain.set()
        else:
            stop.set()

    signal.signal(signal.SIGTERM, sigterm)
    signal.signal(signal.SIGINT, shutdown)
    agent.start()
    log.info(
        "agent %s up: server=%s chips=%d backend=%s",
        args.name, args.server, args.chips, type(backend).__name__,
    )
    # Wake periodically to notice a fatal agent (permanent auth failure):
    # a daemon that kept running with a dead watch thread would look alive
    # while every binding to it sat Pending.
    deadline = None
    while not stop.wait(0.5):
        if agent.fatal:
            log.critical("agent %s fatal: %s", args.name, agent.fatal)
            agent.stop()
            return 1
        if drain.is_set() and not agent.draining:
            agent.notify_preemption("SIGTERM: host preempted, draining")
            deadline = time.monotonic() + args.drain_grace
        if deadline is not None:
            drained = not agent.backend.tracked_keys()
            if drained or time.monotonic() >= deadline:
                log.info("agent %s drain %s; stopping", args.name,
                         "complete" if drained else "grace expired")
                break
    log.info("agent %s stopping", args.name)
    agent.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
