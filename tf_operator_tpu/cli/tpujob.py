"""Client CLI: the kubectl-for-TPUJobs.

Reference parity: the kubectl workflows the reference documents
(`kubectl create -f examples/tf_job.yaml`, `kubectl get tfjobs`, pod logs)
plus py/tf_job_client.py's wait_for_job, against the daemon's REST API.

    python -m tf_operator_tpu.cli.tpujob submit examples/smoke.json
    python -m tf_operator_tpu.cli.tpujob list
    python -m tf_operator_tpu.cli.tpujob get default smoke
    python -m tf_operator_tpu.cli.tpujob wait default smoke
    python -m tf_operator_tpu.cli.tpujob logs default smoke-worker-0
    python -m tf_operator_tpu.cli.tpujob delete default smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tf_operator_tpu.api.validation import ValidationError

DEFAULT_SERVER = os.environ.get("TPUJOB_SERVER", "http://127.0.0.1:8080")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpujob", description="TPUJob client")
    from tf_operator_tpu.utils.version import add_version_flag

    add_version_flag(p)
    p.add_argument("--server", default=DEFAULT_SERVER, help="operator API URL")
    p.add_argument("--auth-token-file", default=None,
                   help="file with the cluster API secret for an "
                        "auth-enabled operator; defaults to "
                        "$TPUJOB_AUTH_TOKEN / $TPUJOB_AUTH_TOKEN_FILE")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("submit", help="create a job from a JSON spec file")
    s.add_argument("file", nargs="?", default=None,
                   help="JSON spec file (omit with --workload)")
    s.add_argument("--workload", choices=["serve"], default=None,
                   help="build a canned workload job instead of reading a "
                        "spec file (r10: serve)")
    s.add_argument("--name", default=None,
                   help="job name for --workload (default: <workload>)")
    s.add_argument("--namespace", default="default")
    s.add_argument("--queue", default="",
                   help="Queue for --workload jobs")
    s.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                   dest="overrides",
                   help="workload config override for --workload "
                        "(repeatable), e.g. --set kv_page_size=8")
    sub.add_parser("list", help="list jobs").add_argument(
        "--namespace", default=None
    )
    for name in ("get", "delete", "wait"):
        sp = sub.add_parser(name)
        sp.add_argument("namespace")
        sp.add_argument("name")
        if name == "wait":
            sp.add_argument("--timeout", type=float, default=600.0)
    lp = sub.add_parser("logs", help="fetch a process's logs")
    lp.add_argument("namespace")
    lp.add_argument("process_name")
    tp = sub.add_parser(
        "trace",
        help="export a job's lifecycle trace as Chrome trace-event JSON "
             "(load it in Perfetto / chrome://tracing)",
    )
    tp.add_argument("namespace_or_name",
                    help="namespace (with NAME following) or, alone, a "
                         "job name in the default namespace")
    tp.add_argument("name", nargs="?", default=None)
    op = sub.add_parser(
        "top",
        help="live per-job telemetry: tokens/s, MFU, per-rank step-time "
             "spread, goodput decomposition",
    )
    op.add_argument("namespace_or_name",
                    help="namespace (with NAME following) or, alone, a "
                         "job name in the default namespace")
    op.add_argument("name", nargs="?", default=None)
    op.add_argument("--json", action="store_true", dest="as_json",
                    help="dump the raw telemetry payload instead of the "
                         "rendered table")
    pp = sub.add_parser(
        "profile",
        help="capture an on-demand profile: the chief wraps the next N "
             "steps in a profiler trace and reports the xplane path as a "
             "profile-capture span",
    )
    pp.add_argument("namespace_or_name",
                    help="namespace (with NAME following) or, alone, a "
                         "job name in the default namespace")
    pp.add_argument("name", nargs="?", default=None)
    pp.add_argument("--steps", type=int, default=5,
                    help="number of steps to capture (default 5)")
    pp.add_argument("--dir", default="", dest="profile_dir",
                    help="capture directory on the chief's host "
                         "(default: <checkpoint_dir>/profile)")
    dp = sub.add_parser(
        "debug",
        help="assemble a job's frozen postmortem (hang/failure bundle + "
             "per-rank stack dumps) into a single tar; fails LOUDLY when "
             "no postmortem exists or the job was GC'd — never an empty "
             "tar",
    )
    dp.add_argument("namespace_or_name",
                    help="namespace (with NAME following) or, alone, a "
                         "job name in the default namespace")
    dp.add_argument("name", nargs="?", default=None)
    dp.add_argument("-o", "--output", default=None,
                    help="tar path (default <name>-postmortem.tar.gz)")
    ep = sub.add_parser("events")
    ep.add_argument("--namespace", default=None)
    ap = sub.add_parser(
        "apply",
        help="create a non-job object (Queue, PriorityClass, Host, ...) "
             "from a JSON doc with a top-level \"kind\"",
    )
    ap.add_argument("file")
    qp = sub.add_parser("queues", help="list Queues with quota usage")
    qp.add_argument("--namespace", default=None)
    fp = sub.add_parser(
        "fleet",
        help="fleet ledger rollup: cross-job MTBF, per-cause downtime "
             "percentiles, goodput histogram, per-host incident counts — "
             "the durable record that survives job GC and operator "
             "restarts",
    )
    fp.add_argument("--json", action="store_true", dest="as_json",
                    help="dump the raw summary+hosts payloads instead of "
                         "the rendered table")
    return p


def _parse_override(kv: str):
    """KEY=VALUE → (key, typed value): ints/floats/bools coerce, else str."""
    if "=" not in kv:
        raise ValueError(f"--set expects KEY=VALUE, got {kv!r}")
    key, _, raw = kv.partition("=")
    if raw.lower() in ("true", "false"):
        return key, raw.lower() == "true"
    for cast in (int, float):
        try:
            return key, cast(raw)
        except ValueError:
            continue
    return key, raw


def _build_workload_job(args):
    """submit --workload NAME: build the canned job locally so it still
    passes through the server's validation/defaulting like any other."""
    from tf_operator_tpu.serve.spec import build_serve_job

    workload = dict(_parse_override(kv) for kv in args.overrides)
    return build_serve_job(
        name=args.name or args.workload,
        namespace=args.namespace,
        queue=args.queue,
        workload=workload,
    )


def _default_ns(args):
    """`VERB <job>` assumes the default namespace; `VERB <ns> <job>` is
    explicit — same convention as `tpujob trace`."""
    if args.name is None:
        return "default", args.namespace_or_name
    return args.namespace_or_name, args.name


def assemble_debug_tar(payload: dict, out_path: str) -> list:
    """Write a /postmortem payload as one tar.gz: the bundle JSON, each
    rank's stack dump as its own file, and a README naming the scene.
    Returns the member names written (separated from main() so tests can
    exercise it without a live server)."""
    import tarfile
    import time as _time
    from io import BytesIO

    def add(tf, name, text):
        data = text.encode()
        info = tarfile.TarInfo(name)
        info.size = len(data)
        info.mtime = int(payload.get("frozen_at") or _time.time())
        tf.addfile(info, BytesIO(data))
        return name

    members = []
    with tarfile.open(out_path, "w:gz") as tf:
        members.append(add(tf, "bundle.json",
                           json.dumps(payload.get("bundle") or {}, indent=2)))
        for d in payload.get("stackdumps") or []:
            members.append(add(
                tf,
                f"stackdumps/rank-{d.get('rank')}-e{d.get('epoch')}.stack",
                d.get("text", ""),
            ))
        members.append(add(
            tf, "README.txt",
            f"postmortem for tpujob {payload.get('job', '?')}\n"
            f"reason: {payload.get('reason', '?')}\n"
            f"frozen_at: {payload.get('frozen_at')}\n"
            f"stack dumps: {len(payload.get('stackdumps') or [])}\n"
            "bundle.json: status history, events, spans (open spans "
            "included), last telemetry window per rank, hang verdict.\n",
        ))
    return members


def render_top(payload: dict, job: dict = None, now: float = None) -> str:
    """Render a /telemetry payload as the `tpujob top` table (separated
    from main() so tests can golden-check it without a live server).
    ``job`` is the /api/tpujob job payload, used to surface a declared
    hang: a HUNG job shows the stuck step and seconds-since-progress
    instead of leaving stale tokens/s as the headline."""
    import time as _time

    summary = payload.get("summary") or {}
    goodput = payload.get("goodput") or {}
    lines = [f"JOB        {payload.get('job', '-')}"]
    hang = ((job or {}).get("status") or {}).get("hang_state") or {}
    if hang:
        since = float(hang.get("since", 0.0) or 0.0)
        stalled = max(0.0, (_time.time() if now is None else now) - since)
        ranks = hang.get("last_moving_ranks") or []
        lines.append(
            f"HUNG       stuck at step {hang.get('stuck_step', '?')} — no "
            f"progress for {stalled:.0f}s (last moving ranks {ranks})"
        )
        ns_name = (payload.get("job") or "/").split("/")
        lines.append(
            f"POSTMORTEM tpujob debug {' '.join(ns_name)}  "
            "(stack dumps + frozen scene)"
        )
    if not summary.get("ranks"):
        lines.append("no telemetry batches yet")
    else:
        lines.append(f"RANKS      {summary['ranks']}")
        lines.append(f"LAST-STEP  {summary.get('last_step', 0)}")
        lines.append(f"TOKENS/S   {summary.get('tokens_per_s', 0.0):,.1f}")
        lines.append(f"MFU        {summary.get('mfu', 0.0):.3f}")
        step_times = summary.get("step_time_s") or {}
        spread = summary.get("spread", 0.0)
        per_rank = "  ".join(
            f"r{r}={step_times[r]:.3f}s"
            for r in sorted(step_times, key=lambda k: int(k))
        )
        lines.append(f"STEP-TIME  {per_rank}  (spread {spread:.2f}x)")
        if summary.get("degraded"):
            lines.append("DEGRADED   some ranks report local-only telemetry")
    ratio = goodput.get("goodput_ratio")
    if ratio is not None:
        lines.append(f"GOODPUT    {ratio:.3f} over {goodput.get('wall_s', 0.0):.1f}s wall")
        lost = goodput.get("lost_s") or {}
        for cause in sorted(lost):
            if lost[cause] > 0:
                lines.append(f"  lost[{cause}]  {lost[cause]:.1f}s")
    # Goodput autopilot (r16): the active checkpoint cadence and the last
    # executed decision, from the job's status mirror — the quick answer
    # to "is the autopilot driving, and what did it just do".
    status = (job or {}).get("status") or {}
    ap = status.get("autopilot") or {}
    if ap:
        every = ap.get("active_checkpoint_every", 0)
        lines.append(
            f"AUTOPILOT  {ap.get('decisions_total', 0)} decisions, "
            f"checkpoint every {every} steps"
        )
        last = ap.get("last_decision") or {}
        if last:
            lines.append(
                f"  last[{last.get('kind', '?')}]  {last.get('action', '?')}"
            )
    return "\n".join(lines)


def render_fleet(summary: dict, hosts: dict) -> str:
    """Render /api/fleet/summary + /api/fleet/hosts as the `tpujob
    fleet` report (separated from main() so tests can golden-check it
    without a live server)."""
    lines = [f"FLEET      {summary.get('jobs', 0)} jobs recorded"]
    phases = summary.get("phases") or {}
    if phases:
        lines.append(
            "PHASES     "
            + "  ".join(f"{k}={phases[k]}" for k in sorted(phases))
        )
    mtbf = summary.get("mtbf_s")
    lines.append(
        f"MTBF       {mtbf:.1f}s over {summary.get('failures', 0)} failures"
        if mtbf is not None
        else f"MTBF       - ({summary.get('failures', 0)} failures)"
    )
    if summary.get("goodput_mean") is not None:
        lines.append(f"GOODPUT    mean {summary['goodput_mean']:.3f}")
        hist = summary.get("goodput_hist") or {}
        if any(hist.values()):
            lines.append(
                "  hist     "
                + "  ".join(f"[{b}]={hist[b]}" for b in sorted(hist))
            )
    queues = summary.get("queues") or {}
    for qname in sorted(queues):
        q = queues[qname]
        qm = q.get("mtbf_s")
        lines.append(
            f"  queue[{qname or '-'}]  jobs={q.get('jobs', 0)} "
            f"failures={q.get('failures', 0)} "
            f"mtbf={f'{qm:.1f}s' if qm is not None else '-'} "
            f"goodput={q.get('goodput_mean', 0.0):.3f} "
            f"save_stall={q.get('save_stall_s', 0.0):.3f}s"
        )
    causes = summary.get("causes") or {}
    for cause in sorted(causes):
        c = causes[cause]
        lines.append(
            f"  lost[{cause}]  {c.get('incidents', 0)} incidents, "
            f"{c.get('lost_s', 0.0):.1f}s total "
            f"(p50 {c.get('lost_p50_s', 0.0):.1f}s / "
            f"p90 {c.get('lost_p90_s', 0.0):.1f}s / "
            f"p99 {c.get('lost_p99_s', 0.0):.1f}s)"
        )
    cc = summary.get("compile_cache")
    if cc:
        rate = cc.get("miss_rate")
        lines.append(
            f"CACHE      hits={cc.get('hits', 0)} misses={cc.get('misses', 0)} "
            f"evictions={cc.get('evictions', 0)} "
            + (f"miss_rate={rate:.3f}" if rate is not None else "miss_rate=-")
        )
    hmap = (hosts or {}).get("hosts") or {}
    if hmap:
        lines.append(
            f"{'HOST':<20} {'JOBS':<5} {'INCIDENT-JOBS':<13} {'FAILURES':<8}"
        )
        for h in sorted(hmap):
            v = hmap[h]
            lines.append(
                f"{h:<20} {v.get('jobs', 0):<5} "
                f"{v.get('incident_jobs', 0):<13} {v.get('failures', 0):<8}"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from tf_operator_tpu.api.types import TPUJob
    from tf_operator_tpu.dashboard.client import TPUJobApiError, TPUJobClient

    from tf_operator_tpu.utils.auth import resolve_token

    client = TPUJobClient(
        args.server, token=resolve_token(token_file=args.auth_token_file)
    )
    try:
        if args.cmd == "submit":
            if args.workload:
                job = _build_workload_job(args)
            elif args.file:
                from tf_operator_tpu.api.v1alpha1 import parse_job

                with open(args.file) as f:
                    job = parse_job(json.load(f))  # accepts both API generations
            else:
                print("error: submit needs a spec file or --workload",
                      file=sys.stderr)
                return 1
            created = client.create(job)
            print(f"tpujob {created.key()} created (uid {created.metadata.uid})")
        elif args.cmd == "list":
            jobs = client.list(args.namespace)
            print(
                f"{'NAMESPACE':<12} {'NAME':<24} {'PHASE':<10} "
                f"{'QUEUE':<12} {'PRIORITY':<10} {'RESTARTS':<8} "
                f"{'PREEMPTED':<9} {'WORLD':<6} {'RESIZES':<7}"
            )
            for j in jobs:
                # world_size 0 = never resized: the spec-derived size applies
                world = j.status.world_size or "-"
                # RESIZES = the bounded history plus everything folded out
                # of it (r19): the lifetime total survives the 32-entry cap.
                resizes = j.status.resize_history_folded + len(
                    j.status.resize_history or []
                )
                print(
                    f"{j.metadata.namespace:<12} {j.metadata.name:<24} "
                    f"{j.status.phase().value or '-':<10} "
                    f"{j.spec.scheduling.queue or '-':<12} "
                    f"{j.spec.scheduling.priority_class or '-':<10} "
                    f"{j.status.restart_count:<8} {j.status.preemption_count:<9} "
                    f"{world:<6} {resizes:<7}"
                )
        elif args.cmd == "get":
            print(json.dumps(client.get(args.namespace, args.name), indent=2))
        elif args.cmd == "delete":
            client.delete(args.namespace, args.name)
            print(f"tpujob {args.namespace}/{args.name} deleted")
        elif args.cmd == "wait":
            job = client.wait_for_job(args.namespace, args.name, timeout=args.timeout)
            phase = job.status.phase().value
            print(f"tpujob {args.namespace}/{args.name}: {phase}")
            return 0 if phase == "Done" else 3
        elif args.cmd == "logs":
            sys.stdout.write(client.logs(args.namespace, args.process_name))
        elif args.cmd == "trace":
            ns, name = _default_ns(args)
            print(json.dumps(client.trace(ns, name), indent=2))
        elif args.cmd == "top":
            ns, name = _default_ns(args)
            payload = client.telemetry(ns, name)
            if args.as_json:
                print(json.dumps(payload, indent=2))
            else:
                try:
                    jobd = client.get(ns, name).get("job")
                except TPUJobApiError:
                    jobd = None  # telemetry may outlive the job object
                print(render_top(payload, job=jobd))
        elif args.cmd == "debug":
            ns, name = _default_ns(args)
            # 404 (never frozen, or GC'd with the job) raises and exits
            # loudly below — a missing postmortem must never produce an
            # empty-but-plausible tar.
            payload = client.postmortem(ns, name)
            out = args.output or f"{name}-postmortem.tar.gz"
            members = assemble_debug_tar(payload, out)
            print(
                f"postmortem for {ns}/{name} (reason={payload.get('reason')}, "
                f"{len(payload.get('stackdumps') or [])} rank stacks) -> "
                f"{out} ({len(members)} files)"
            )
        elif args.cmd == "profile":
            ns, name = _default_ns(args)
            out = client.profile(ns, name, args.steps, args.profile_dir)
            d = out.get("profile_directive", {})
            print(
                f"profile directive epoch {d.get('epoch')} published for "
                f"{ns}/{name}: {d.get('steps')} steps"
                + (f" -> {d['dir']}" if d.get("dir") else "")
            )
            print("watch: tpujob trace "
                  f"{ns} {name}  (profile-capture span carries the xplane path)")
        elif args.cmd == "events":
            for e in client.events(args.namespace):
                print(f"{e['type']:<8} {e['reason']:<28} x{e['count']:<4} {e['message']}")
        elif args.cmd == "apply":
            from tf_operator_tpu.runtime.serialize import from_doc

            with open(args.file) as f:
                doc = json.load(f)
            kind = doc.get("kind")
            if not kind:
                print("error: document needs a top-level \"kind\"", file=sys.stderr)
                return 1
            obj = from_doc(kind, doc)
            client.create_object(obj)
            print(f"{kind} {obj.metadata.namespace}/{obj.metadata.name} created")
        elif args.cmd == "queues":
            from tf_operator_tpu.api.types import KIND_QUEUE
            from tf_operator_tpu.sched.objects import job_demand

            queues = client.list_objects(KIND_QUEUE, args.namespace)
            jobs = client.list(args.namespace)
            used: dict = {}
            for j in jobs:
                qname = j.spec.scheduling.queue
                phase = j.status.phase().value
                if qname and phase not in ("Done", "Failed", "Queued"):
                    k = (j.metadata.namespace, qname)
                    c, n = used.get(k, (0, 0))
                    used[k] = (c + job_demand(j), n + 1)
            print(
                f"{'NAMESPACE':<12} {'NAME':<16} {'QUOTA-CHIPS':<12} "
                f"{'USED-CHIPS':<11} {'JOBS':<5} {'MAX-JOBS':<8}"
            )
            for qobj in queues:
                k = (qobj.metadata.namespace, qobj.metadata.name)
                c, n = used.get(k, (0, 0))
                print(
                    f"{qobj.metadata.namespace:<12} {qobj.metadata.name:<16} "
                    f"{qobj.spec.quota_chips or '-':<12} {c:<11} {n:<5} "
                    f"{qobj.spec.max_running_jobs or '-':<8}"
                )
        elif args.cmd == "fleet":
            summary = client.fleet_summary()
            hosts = client.fleet_hosts()
            if args.as_json:
                print(json.dumps({"summary": summary, **hosts}, indent=2))
            else:
                print(render_fleet(summary, hosts))
    except TPUJobApiError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    except (FileNotFoundError, json.JSONDecodeError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    except ValidationError as exc:  # e.g. v1alpha1 PS rejection
        print(f"invalid job: {exc}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
