"""Process entrypoints (reference: cmd/tf-operator{,.v2} + kubectl usage).

- ``python -m tf_operator_tpu.cli.operator`` — the operator daemon: store +
  controller + process backend + REST dashboard + optional leader election
  and chaos injection.
- ``python -m tf_operator_tpu.cli.tpujob``  — the client CLI (kubectl
  analogue): submit/list/get/delete/wait/logs/events against a daemon.
"""
