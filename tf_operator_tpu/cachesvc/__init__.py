"""Fleet-wide compile-cache service (the TTFS attack, ROADMAP item 4).

Three cooperating pieces:

- :mod:`service` — the operator-hosted HTTP store of compiled
  executables (sha256-verified, byte-bounded, key-sanitized), plus
  compile *intents* for fleet-wide single-flight compilation.
- :mod:`client` — the best-effort worker/controller client; every
  failure degrades to the PR 10 local-only path, never to a job failure.
- :mod:`aot` — AOT-at-admission: compiles a workload's step function
  while the job is still scheduling/queued and publishes the executable,
  so the gang's processes find a warm cache the moment they reach
  ``compile_cache.enable()``.
"""

from tf_operator_tpu.cachesvc.client import CacheClient
from tf_operator_tpu.cachesvc.service import CompileCacheService

__all__ = ["CacheClient", "CompileCacheService"]
