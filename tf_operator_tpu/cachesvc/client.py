"""Worker/controller-side client for the compile-cache service.

Every method is best-effort and returns None/False on any transport or
integrity failure — the remote tier is a latency lever, and a dead or
lying cachesvc must degrade the caller to the PR 10 local-only path
(recompile), never fail a job. The ``dead`` flag records that a
transport failure was seen; ``train/compile_cache.py`` surfaces it as a
span attribute so the degradation is observable in the job trace
instead of silent.
"""

from __future__ import annotations

import hashlib
import logging
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Optional

log = logging.getLogger("tpujob.cachesvc")


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class CacheClient:
    def __init__(self, url: str, timeout: float = 5.0) -> None:
        self.url = url.rstrip("/")
        self.timeout = timeout
        # Sticky: one observed transport failure marks the service dead
        # for span-attribute purposes (the caller's degradation receipt).
        # Later calls still try — the service may come back.
        self.dead = False

    def _entry_url(self, key: str) -> str:
        return f"{self.url}/cachesvc/v1/entry?{urllib.parse.urlencode({'key': key})}"

    def alive(self) -> bool:
        try:
            with urllib.request.urlopen(  # noqa: S310 — operator-stamped URL
                f"{self.url}/healthz", timeout=self.timeout
            ) as resp:
                return resp.status == 200
        except (OSError, urllib.error.URLError, ValueError):
            self.dead = True
            return False

    def fetch(self, key: str, wait_s: float = 0.0) -> Optional[bytes]:
        """Fetch one verified entry. ``wait_s`` > 0 honors the service's
        202/Retry-After while an admission-time compile intent is live —
        the single-flight wait that turns AOT-at-admission overlap into a
        hit instead of a duplicated compile. Returns None on miss, digest
        mismatch, or any transport failure."""
        deadline = time.monotonic() + max(0.0, wait_s)
        while True:
            try:
                with urllib.request.urlopen(  # noqa: S310
                    self._entry_url(key), timeout=self.timeout
                ) as resp:
                    if resp.status == 200:
                        data = resp.read()
                        want = resp.headers.get("X-Entry-SHA256", "")
                        if want and _sha256(data) != want:
                            log.warning(
                                "cachesvc entry %s failed transfer "
                                "verification; treating as a miss", key,
                            )
                            return None
                        return data
                    retry_after = float(resp.headers.get("Retry-After", "1") or 1)
            except urllib.error.HTTPError as exc:
                if exc.code == 202:
                    retry_after = float(exc.headers.get("Retry-After", "1") or 1)
                elif exc.code == 404:
                    return None
                else:
                    self.dead = True
                    return None
            except (OSError, urllib.error.URLError, ValueError):
                self.dead = True
                return None
            # 202: a compile intent is live. Wait out the retry hint while
            # budget remains; otherwise report a miss (the caller compiles
            # locally — correct, just not deduplicated). The 100 ms cap
            # bounds how long a published entry sits unnoticed — this poll
            # latency lands directly on TTFS when AOT-at-admission is
            # racing the gang to first step.
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            time.sleep(min(retry_after, remaining, 0.1))

    def publish(self, key: str, data: bytes) -> bool:
        try:
            req = urllib.request.Request(
                self._entry_url(key), data=data, method="PUT",
                headers={"X-Entry-SHA256": _sha256(data)},
            )
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:  # noqa: S310
                return resp.status == 200
        except urllib.error.HTTPError as exc:
            if exc.code == 409:
                return True  # first-writer-wins: the entry already exists
            log.debug("cachesvc rejected publish of %s: HTTP %d", key, exc.code)
            return False  # e.g. 413 over-cap: a policy reject, not a death
        except (OSError, urllib.error.URLError, ValueError) as exc:
            self.dead = True
            log.debug("cachesvc publish of %s failed: %s", key, exc)
            return False

    def announce(self, key: str) -> bool:
        """Register a compile intent (AOT-at-admission calls this the
        moment the scheduler decides, before compiling)."""
        try:
            req = urllib.request.Request(
                f"{self.url}/cachesvc/v1/intent?"
                f"{urllib.parse.urlencode({'key': key})}",
                data=b"", method="POST",
            )
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:  # noqa: S310
                return resp.status == 200
        except (OSError, urllib.error.URLError, ValueError):
            self.dead = True
            return False

    def stats(self) -> Optional[Dict[str, int]]:
        try:
            import json

            with urllib.request.urlopen(  # noqa: S310
                f"{self.url}/cachesvc/v1/stats", timeout=self.timeout
            ) as resp:
                return json.loads(resp.read().decode())
        except (OSError, urllib.error.URLError, ValueError):
            self.dead = True
            return None
