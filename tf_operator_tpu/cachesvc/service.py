"""Fleet-wide compile-cache service: the shared remote tier behind
``train/compile_cache.py``.

TTFS (submit→first-step) is the north-star latency metric and on TPU it
is dominated by XLA compilation — a per-host persistent cache (PR 10)
only amortizes it per machine, so the first job on every host of a fleet
still pays the full compile. This service makes any host's first compile
of a config the FLEET's last: executables keyed exactly the way jax's
persistent cache keys them ((HLO fingerprint, compile options, backend)
— the key string IS jax's cache key) are published here once and fetched
everywhere else.

Same construction discipline as the PR 8 shard depots
(rendezvous/statechannel.py), because the threat model is identical —
an unauthenticated loopback/pod-network HTTP service moving opaque
binary blobs that will be handed to native code:

- every transfer carries a sha256 (``X-Entry-SHA256``) verified on BOTH
  ends; a mismatch is a miss, never bytes-to-XLA,
- keys are validated against a filesystem-safe charset before they touch
  a path (the relpath-sanitization lesson: an unauthenticated peer's
  string must never steer a filesystem write),
- held bytes are bounded with oldest-touched eviction — an evicted entry
  degrades the fleet to a local recompile, never to failure,
- puts are staged (temp file) and committed with one ``os.replace``; a
  service killed mid-put never serves a torn entry.

One extra verb the depots don't need: **compile intents**. AOT-at-
admission (cachesvc/aot.py) announces "this key is being compiled" when
the scheduler admits or parks a job; a worker that reaches its cache
miss while the intent is live gets 202 + Retry-After instead of 404 and
briefly waits for the admission-time compile instead of duplicating it —
single-flight compilation, fleet-wide.

Wire protocol (stdlib HTTP, no new deps):

- ``GET  /cachesvc/v1/entry?key=``  → raw bytes + ``X-Entry-SHA256``;
  404 miss; 202 + ``Retry-After`` while a compile intent is live
- ``PUT  /cachesvc/v1/entry?key=``  → stage+verify+commit (409 on digest
  mismatch, 413 over the entry bound)
- ``POST /cachesvc/v1/intent?key=`` → register an in-flight compile
  (TTL-bounded; cleared by the entry's PUT)
- ``GET  /cachesvc/v1/stats``       → JSON counters
- ``GET  /healthz``                 → liveness
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import shutil
import tempfile
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

log = logging.getLogger("tpujob.cachesvc")

# jax persistent-cache keys are "jit_<name>-<hex digest>"; allow that plus
# the digest-only keys cached_compile() derives. Anything else — path
# separators, dots that could spell "..", unicode — is rejected before it
# can steer a filesystem operation.
_KEY_RE = re.compile(r"^[A-Za-z0-9_=-]{1,200}$")

_MAX_ENTRY_BYTES = 1 << 31  # sanity bound on a single executable
DEFAULT_MAX_BYTES = 4 << 30  # total held bytes before eviction
DEFAULT_INTENT_TTL = 120.0  # an AOT compile slower than this lost its slot

_LOOPBACK_HOSTS = ("127.0.0.1", "localhost", "::1")


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def valid_key(key: str) -> bool:
    return bool(_KEY_RE.match(key or ""))


class CompileCacheService:
    """Disk-backed, byte-bounded compile-executable store over HTTP.

    One per operator (cli/operator.py hosts it next to the dashboard and
    the controller stamps its URL into every gang member's env as
    ``TPUJOB_COMPILE_CACHE``). Entries live under ``root`` as
    ``<key>.bin`` with the digest in the in-memory index — the service is
    a cache, not a system of record: losing it degrades every host to
    the PR 10 local-only path, never to failure.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        root: Optional[str] = None,
        max_bytes: int = DEFAULT_MAX_BYTES,
        intent_ttl: float = DEFAULT_INTENT_TTL,
    ) -> None:
        self.max_bytes = int(max_bytes)
        self.intent_ttl = float(intent_ttl)
        self._own_root = root is None
        self.root = root or tempfile.mkdtemp(prefix="tpujob-cachesvc-")
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        # key -> (size, sha256hex); the committed, servable index.
        self._entries: Dict[str, tuple] = {}
        self._bytes = 0
        # key -> last-use sequence number: the eviction order.
        self._seq = 0
        self._touch: Dict[str, int] = {}
        # key -> intent deadline (monotonic): in-flight compiles.
        self._intents: Dict[str, float] = {}
        self.stats = {
            "hits": 0, "misses": 0, "waits": 0, "puts": 0,
            "put_rejects": 0, "evictions": 0, "intents": 0,
        }
        svc = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: D102 — silence stdlib
                log.debug("cachesvc %s " + fmt, self.client_address[0], *args)

            def _q(self):
                parsed = urllib.parse.urlparse(self.path)
                return parsed.path, dict(urllib.parse.parse_qsl(parsed.query))

            def _reply(self, code: int, body: bytes = b"", headers=()):
                self.send_response(code)
                for k, v in headers:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def do_GET(self):
                path, q = self._q()
                if path == "/healthz":
                    self._reply(200, b"ok")
                    return
                if path in ("/cachesvc/v1/stats", "/stats"):
                    self._reply(200, json.dumps(svc.snapshot()).encode(),
                                [("Content-Type", "application/json")])
                    return
                if path != "/cachesvc/v1/entry":
                    self._reply(404)
                    return
                key = q.get("key", "")
                if not valid_key(key):
                    self._reply(400)
                    return
                data = svc.get(key)
                if data is not None:
                    self._reply(200, data, [
                        ("Content-Type", "application/octet-stream"),
                        ("X-Entry-SHA256", _sha256(data)),
                    ])
                elif svc.intent_live(key):
                    # An admission-time AOT compile of this key is in
                    # flight: tell the worker to wait briefly instead of
                    # duplicating the compile.
                    self._reply(202, b"", [("Retry-After", "1")])
                else:
                    self._reply(404)

            def do_PUT(self):
                path, q = self._q()
                if path != "/cachesvc/v1/entry":
                    self._reply(404)
                    return
                key = q.get("key", "")
                n = int(self.headers.get("Content-Length", "0"))
                if not valid_key(key):
                    self._reply(400)
                    return
                if n < 0 or n > _MAX_ENTRY_BYTES:
                    self._reply(413)
                    return
                data = self.rfile.read(n)
                want = self.headers.get("X-Entry-SHA256", "")
                code = svc.put(key, data, want)
                self._reply(code)

            def do_POST(self):
                path, q = self._q()
                if path != "/cachesvc/v1/intent":
                    self._reply(404)
                    return
                key = q.get("key", "")
                if not valid_key(key):
                    self._reply(400)
                    return
                svc.announce(key)
                self._reply(200)

        if host not in _LOOPBACK_HOSTS:
            # Same caveat as the shard depots: the protocol carries no
            # authentication, and what it serves is EXECUTABLE code.
            log.warning(
                "compile-cache service binding non-loopback %s: the "
                "protocol is unauthenticated — restrict access at the "
                "network layer", host,
            )
        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"cachesvc-{self.port}",
        )
        self._thread.start()

    # -- service-side operations (also callable in-process) ---------------

    def _path(self, key: str) -> str:
        # valid_key() already forbids separators/dots; belt-and-suspenders
        # against any future key-charset loosening.
        full = os.path.abspath(os.path.join(self.root, f"{key}.bin"))
        if os.path.dirname(full) != os.path.abspath(self.root):
            raise ValueError(f"unsafe cache key: {key!r}")
        return full

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.stats["misses"] += 1
                return None
            self._seq += 1
            self._touch[key] = self._seq
            size, want = entry
        try:
            with open(self._path(key), "rb") as f:
                data = f.read()
        except OSError:
            data = b""
        if _sha256(data) != want:
            # Disk rot / torn external write: drop the entry — this
            # service must NEVER serve bytes that don't match its index.
            log.warning("cachesvc entry %s failed integrity check; dropping", key)
            self.drop(key)
            with self._lock:
                self.stats["misses"] += 1
            return None
        with self._lock:
            self.stats["hits"] += 1
        return data

    def put(self, key: str, data: bytes, want_digest: str = "") -> int:
        """Stage+verify+commit one entry; returns an HTTP status code.
        First writer wins — a key already committed is left untouched
        (200): executables for one key are interchangeable by keying."""
        digest = _sha256(data)
        if want_digest and digest != want_digest:
            with self._lock:
                self.stats["put_rejects"] += 1
            log.warning("cachesvc put of %s rejected: digest mismatch "
                        "(transfer corruption)", key)
            return 409
        if len(data) > self.max_bytes:
            with self._lock:
                self.stats["put_rejects"] += 1
            return 413
        with self._lock:
            if key in self._entries:
                self._intents.pop(key, None)
                return 200
            # Make room BEFORE committing: evict oldest-touched until the
            # new entry fits (never the entry being inserted).
            while self._bytes + len(data) > self.max_bytes and self._entries:
                victim = min(self._entries, key=lambda k: self._touch.get(k, 0))
                self._evict_locked(victim)
            tmp = self._path(key) + f".tmp{os.getpid()}-{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(key))
        except OSError as exc:
            log.warning("cachesvc put of %s failed: %s", key, exc)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return 500
        with self._lock:
            if key not in self._entries:
                self._entries[key] = (len(data), digest)
                self._bytes += len(data)
                self._seq += 1
                self._touch[key] = self._seq
                self.stats["puts"] += 1
            self._intents.pop(key, None)  # the compile landed
        return 200

    def _evict_locked(self, key: str) -> None:
        size, _ = self._entries.pop(key)
        self._touch.pop(key, None)
        self._bytes -= size
        self.stats["evictions"] += 1
        log.info("cachesvc evicting %s (%d bytes) under the byte cap", key, size)
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    def drop(self, key: str) -> None:
        with self._lock:
            entry = self._entries.pop(key, None)
            self._touch.pop(key, None)
            if entry is not None:
                self._bytes -= entry[0]
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    def announce(self, key: str) -> None:
        """Register an in-flight compile intent for ``key`` (TTL-bounded:
        a compiler that died keeps nobody waiting past the TTL)."""
        with self._lock:
            if key not in self._entries:
                self._intents[key] = time.monotonic() + self.intent_ttl
                self.stats["intents"] += 1

    def intent_live(self, key: str) -> bool:
        with self._lock:
            deadline = self._intents.get(key)
            if deadline is None:
                return False
            if time.monotonic() > deadline:
                self._intents.pop(key, None)
                return False
            self.stats["waits"] += 1
            return True

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                **self.stats,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "intents_live": len(self._intents),
            }

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
        if self._own_root:
            shutil.rmtree(self.root, ignore_errors=True)
