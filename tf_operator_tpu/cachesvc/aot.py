"""AOT-at-admission: overlap compilation with the scheduling wait.

The second leg of the r11 TTFS attack. The moment the fleet scheduler
decides a job's fate — admitted (gang about to be created) or parked
(QUEUED behind quota/capacity) — the reconciler hands the job to this
compiler. A worker thread registers a compile *intent* with the
compile-cache service (fleet-wide single-flight: any gang member that
races ahead gets 202/Retry-After instead of duplicating the compile),
compiles the workload's step function, and publishes the executable.
By the time the gang finishes placement + spawn + rendezvous and
reaches ``compile_cache.enable()``, the cache is warm — the compile
cost paid during a wait that was happening anyway.

Workload contract (``spec.workload`` JSON, all optional):

- ``{"aot": {"key": "<key material>", "compile_ms": 1500}}`` — modeled
  mode: the executable is a deterministic artifact derived from the key
  material, produced after a modeled ``compile_ms`` delay. The workload
  side retrieves it with ``compile_cache.cached_compile(key_material,
  fn)`` — same key derivation (sha256 of the material), so the
  admission-time publish is a remote hit at enable() time. This is the
  bench/CI mode: real intents, transport, and integrity machinery;
  modeled compile cost (no chips in CI — the r8 ``--disk-restore-delay``
  precedent).
- ``{"aot": {"topology": "v5e:2x4"}}`` — topology mode: spawn
  ``tools/hloprobe.py``'s AOT machinery in a subprocess with
  ``JAX_COMPILATION_CACHE_DIR`` pointed at a scratch dir, then publish
  every ``*-cache`` entry that landed, under jax's own keys. Requires
  the TPU compiler (libtpu); degrades to a logged skip without it —
  never a job failure.

Dedup: one kick per (job uid, key). A re-sync of a parked job does not
re-compile; a gang restart of the same job finds the entry already
published (the service is first-writer-wins).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, Optional

from tf_operator_tpu.cachesvc.client import CacheClient

log = logging.getLogger("tpujob.cachesvc.aot")

# Modeled-mode executables are this many bytes: big enough that a
# corrupted transfer cannot accidentally verify, small enough to be free.
_MODELED_PAYLOAD_BYTES = 4096


def modeled_payload(key_material: str, size: int = _MODELED_PAYLOAD_BYTES) -> bytes:
    """The deterministic modeled 'executable' for a key: both the
    admission-time compiler and the workload's local fallback produce
    byte-identical artifacts, so integrity verification is end-to-end
    real even though the compile itself is modeled."""
    seed = hashlib.sha256(key_material.encode()).digest()
    out = bytearray()
    block = seed
    while len(out) < size:
        out.extend(block)
        block = hashlib.sha256(block).digest()
    return bytes(out[:size])


def aot_spec_of(workload) -> Optional[Dict]:
    """Extract the ``aot`` section from a job's spec.workload (the dict
    itself, or its ENV_WORKLOAD JSON form); None when absent/unparseable
    (most jobs: nothing to pre-compile)."""
    if not workload:
        return None
    if isinstance(workload, str):
        try:
            spec = json.loads(workload)
        except ValueError:
            return None
    else:
        spec = workload
    aot = spec.get("aot") if isinstance(spec, dict) else None
    return aot if isinstance(aot, dict) and ("key" in aot or "topology" in aot) else None


class AOTCompiler:
    """Admission-time compiler pool. ``kick()`` is called from the
    reconciler's sync path and must be O(µs): it only enqueues; worker
    threads do the announce/compile/publish. Every failure is a logged
    degradation (the gang compiles at first step, exactly the pre-r11
    behavior), never an error surfaced to the job.
    """

    def __init__(
        self,
        cache_url: str,
        workers: int = 2,
        on_done: Optional[Callable[..., None]] = None,
    ) -> None:
        """``on_done(namespace, job_name, trace_id, key, mode, start, end,
        ok)`` — the reconciler wires this to its span recorder so the
        aot-compile span lands in the job timeline."""
        self.client = CacheClient(cache_url)
        self.on_done = on_done
        self._kicked: set = set()  # (job_uid, key) — one compile per pair
        self._lock = threading.Lock()
        self._queue: list = []
        self._wake = threading.Condition(self._lock)
        self._stopping = False
        self.stats = {"kicked": 0, "published": 0, "skipped": 0, "failed": 0}
        self._threads = [
            threading.Thread(target=self._worker, daemon=True, name=f"aot-{i}")
            for i in range(max(1, workers))
        ]
        for t in self._threads:
            t.start()

    # -- reconciler-facing -------------------------------------------------

    def kick(self, namespace: str, job_name: str, job_uid: str,
             workload) -> bool:
        """Queue an admission-time compile for the job's workload. Returns
        True when a new compile was scheduled (False: nothing declared, or
        already kicked for this job)."""
        aot = aot_spec_of(workload)
        if aot is None:
            return False
        key = self._cache_key(aot)
        with self._lock:
            if self._stopping or (job_uid, key) in self._kicked:
                return False
            self._kicked.add((job_uid, key))
            self.stats["kicked"] += 1
            self._queue.append((namespace, job_name, job_uid, aot))
            self._wake.notify()
        return True

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            self._wake.notify_all()

    # -- workers -----------------------------------------------------------

    @staticmethod
    def _cache_key(aot: Dict) -> str:
        if "key" in aot:
            return hashlib.sha256(str(aot["key"]).encode()).hexdigest()
        return f"topology:{aot.get('topology', '')}"

    def _worker(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._stopping:
                    self._wake.wait()
                if self._stopping and not self._queue:
                    return
                namespace, job_name, job_uid, aot = self._queue.pop(0)
            start = time.time()
            mode = "modeled" if "key" in aot else "topology"
            ok = False
            key = self._cache_key(aot)
            try:
                if mode == "modeled":
                    ok = self._compile_modeled(aot)
                else:
                    ok = self._compile_topology(aot)
            except Exception:  # noqa: BLE001 — degradation, never job failure
                log.exception("aot compile for %s/%s failed", namespace, job_name)
            self.stats["published" if ok else "failed"] += 1
            if self.on_done is not None:
                try:
                    self.on_done(namespace, job_name, job_uid, key, mode,
                                 start, time.time(), ok)
                except Exception:  # noqa: BLE001
                    log.exception("aot on_done callback failed")

    def _compile_modeled(self, aot: Dict) -> bool:
        key_material = str(aot["key"])
        key = hashlib.sha256(key_material.encode()).hexdigest()
        # Repeat submission of an already-compiled workload: the entry is
        # there, the cache is warm — nothing to do (and no modeled cost
        # to pay). fetch(wait_s=0) is a cheap existence probe.
        if self.client.fetch(key) is not None:
            return True
        # Single-flight: the intent makes racing gang members wait the
        # few hundred ms for this publish instead of recompiling.
        self.client.announce(key)
        delay = max(0.0, float(aot.get("compile_ms", 0)) / 1000.0)
        if delay:
            time.sleep(delay)  # the modeled XLA compile cost
        return self.client.publish(key, modeled_payload(key_material))

    def _compile_topology(self, aot: Dict) -> bool:
        """Real AOT against a virtual TPU topology (no chips needed, but
        the TPU *compiler* — libtpu — must be importable). Runs hloprobe
        in a subprocess with the persistent compilation cache pointed at
        a scratch dir, then publishes every executable that landed under
        jax's own cache keys."""
        topology = str(aot.get("topology", ""))
        self.client.announce(self._cache_key(aot))
        scratch = tempfile.mkdtemp(prefix="tpujob-aot-")
        try:
            env = dict(os.environ)
            env["JAX_COMPILATION_CACHE_DIR"] = scratch
            env.setdefault("JAX_PLATFORMS", "cpu")
            proc = subprocess.run(
                [sys.executable, "-m", "tools.hloprobe",
                 "--topology", topology],
                env=env, capture_output=True, timeout=float(
                    aot.get("timeout_s", 600)),
                check=False,
            )
            if proc.returncode != 0:
                log.info("aot topology compile for %s skipped (hloprobe rc=%d)",
                         topology, proc.returncode)
                self.stats["skipped"] += 1
                return False
            published = 0
            for fname in os.listdir(scratch):
                if not fname.endswith("-cache"):
                    continue
                with open(os.path.join(scratch, fname), "rb") as f:
                    data = f.read()
                if self.client.publish(fname[: -len("-cache")], data):
                    published += 1
            return published > 0
        finally:
            import shutil

            shutil.rmtree(scratch, ignore_errors=True)
