"""ResNet family (v1.5 bottleneck): the framework's headline bench model.

Reference parity: the reference's ResNet-50 benchmark config
(BASELINE.json: "ResNet-50 ImageNet ... -> TPUStrategy"). TPU-first:

- NHWC layout (XLA-TPU's native conv layout; C lands on the 128-lane axis);
- bfloat16 activations and conv inputs, f32 batch-norm statistics;
- functional params + logical axes ("batch" on data only — convs are small
  enough to replicate; DP/FSDP shards the batch);
- BatchNorm in training mode computes batch statistics inline (the bench
  measures training throughput); running stats are carried in a separate
  `state` pytree updated with momentum for eval.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

BN_MOMENTUM = 0.9
BN_EPS = 1e-5


@dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    widths: Tuple[int, ...] = (64, 128, 256, 512)
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    # "s2d": space-to-depth stem — the 7x7/s2 conv on 3 channels packs only
    # 3 of the MXU's 128 input lanes; rearranging 2x2 pixel blocks into
    # channels (4x4/s1 conv on [112,112,12]) computes the same receptive
    # field at 4x the lane utilization (standard TPU ResNet reformulation).
    # "conv7": the literal 7x7 stride-2 stem.
    stem: str = "s2d"
    # Apply BN normalization in the activation dtype (stats always f32):
    # halves elementwise HBM traffic vs normalizing in f32.
    bn_in_activation_dtype: bool = True
    # Train-mode statistics as E[x]/E[x²] accumulated in ONE fused pass over
    # the bf16 activation, instead of mean-then-var (two passes: jnp.var
    # re-reads (x-mean)²). Cuts a full HBM read of every BN input from both
    # fwd and bwd: measured ~9% faster ResNet-50 train step on v5e. The
    # cancellation risk of E[x²]-E[x]² is negligible for BN inputs (conv
    # outputs are near-centered) and accumulation stays f32.
    bn_fused_stats: bool = True
    # Stop the gradient through BN batch statistics: removes the backward's
    # stats-reduction terms (measured −6.9 ms / +5.1 MFU pts on the v5e
    # b=128 train step). Values: False (exact) | True (stop both — the
    # synthetic bench DIVERGES at lr=0.1; keep opt-in) | "var" (stop only
    # the variance gradient, keeping the centering stabilizer — measured
    # the SAME full speedup, 37.4% vs 32% MFU).
    # DEFAULT "var" since r3: accuracy-validated on REAL data through the
    # idx/augmentation pipeline — 3-seed test accuracy 0.9764 vs exact's
    # 0.9787 on real scanned digits, overlapping seed ranges (BASELINE.md
    # "BN decomposition"); BENCH_BN_STATS_GRAD=exact restores exact BN.
    bn_stats_stop_gradient: Any = "var"
    # Ghost batch statistics: train-mode normalization uses the PREVIOUS
    # step's batch stats (carried in state) while this step's stats are
    # computed only to ship forward — the normalize affine becomes a step
    # constant that fuses into the conv epilogue and the stats reduction
    # leaves the critical path (the 10.8 ms barrier, BASELINE.md).
    # DOCUMENTED NEGATIVE RESULT (r3): stale-stats normalization composed
    # through depth is a divergent fixed-point iteration — even at FIXED
    # params and input, layer k's stats describe the previous pass's
    # (different) input distribution, the scale mismatch multiplies
    # through layers/residuals, and activations blow up within ~3 steps
    # (tests/test_models.py::test_bn_ghost_stats_is_divergent_documented;
    # a variance floor does not save it). Kept for the receipt; do not
    # enable for training.
    bn_ghost_stats: bool = False
    # Run the bottleneck 1x1 convolutions (conv1/conv3/proj — ~83% of the
    # BN'd activations) through the Pallas fused matmul+stats kernel
    # (ops/fused_linear_stats): BN batch statistics accumulate in the
    # matmul epilogue while the output block is in VMEM, and the previous
    # BN's normalize+ReLU folds into the next kernel's load prologue — the
    # batch-stats HBM barrier (measured 10.8 ms of a 51.4 ms v5e train
    # step) never exists for those layers. Train-mode only; eval uses the
    # folded-affine path either way.
    fused_1x1: bool = False

    @staticmethod
    def resnet50(num_classes: int = 1000) -> "ResNetConfig":
        return ResNetConfig((3, 4, 6, 3), (64, 128, 256, 512), num_classes)

    @staticmethod
    def resnet18(num_classes: int = 1000) -> "ResNetConfig":
        # basic-block resnets are modeled as bottlenecks-of-1 for simplicity;
        # resnet50 is the bench target.
        return ResNetConfig((2, 2, 2, 2), (64, 128, 256, 512), num_classes)

    @staticmethod
    def tiny(num_classes: int = 10) -> "ResNetConfig":
        """Test-scale variant (~width/4, one block per stage): the same
        stem/BN/residual machinery at ~1/30 the FLOPs, so CPU-mesh e2e
        tests can train the REAL-image pipeline to an accuracy gate in
        minutes (the digits fixtures), the way `tiny` serves the
        transformer family."""
        return ResNetConfig((1, 1, 1, 1), (16, 32, 64, 128), num_classes)

    def flops_per_image(self, image_size: int = 224) -> float:
        """Approximate forward FLOPs per image (2*MACs). ResNet-50@224 ≈ 8.2e9."""
        # computed empirically below via jax cost analysis when available;
        # fallback literature value scaled by depth relative to resnet50
        base = 8.2e9
        depth_ratio = sum(self.stage_sizes) / 16.0
        return base * depth_ratio * (image_size / 224.0) ** 2


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * (2.0 / fan_in) ** 0.5


def _bn_params(c):
    return {"scale": jnp.ones((c,), jnp.float32), "bias": jnp.zeros((c,), jnp.float32)}


def _bn_state(c, ghost: bool = False):
    s = {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)}
    if ghost:
        # last BATCH's stats (not the running average) — what ghost-stats
        # normalization reads next step; init = identity-ish normalize.
        s["bmean"] = jnp.zeros((c,), jnp.float32)
        s["bvar"] = jnp.ones((c,), jnp.float32)
    return s


def init_resnet(key, cfg: ResNetConfig) -> Tuple[Dict, Dict]:
    """Returns (params, state) — state carries BN running statistics."""
    keys = iter(jax.random.split(key, 256))
    ghost = cfg.bn_ghost_stats
    params: Dict[str, Any] = {
        "stem": {"conv": _conv_init(next(keys), 7, 7, 3, 64), "bn": _bn_params(64)}
    }
    state: Dict[str, Any] = {"stem": _bn_state(64, ghost)}
    cin = 64
    for si, (n_blocks, width) in enumerate(zip(cfg.stage_sizes, cfg.widths)):
        stage_p: List[Dict] = []
        stage_s: List[Dict] = []
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            cout = width * 4
            bp = {
                "conv1": _conv_init(next(keys), 1, 1, cin, width),
                "bn1": _bn_params(width),
                "conv2": _conv_init(next(keys), 3, 3, width, width),
                "bn2": _bn_params(width),
                "conv3": _conv_init(next(keys), 1, 1, width, cout),
                "bn3": _bn_params(cout),
            }
            bs = {
                "bn1": _bn_state(width, ghost),
                "bn2": _bn_state(width, ghost),
                "bn3": _bn_state(cout, ghost),
            }
            if stride != 1 or cin != cout:
                bp["proj"] = _conv_init(next(keys), 1, 1, cin, cout)
                bp["proj_bn"] = _bn_params(cout)
                bs["proj_bn"] = _bn_state(cout, ghost)
            stage_p.append(bp)
            stage_s.append(bs)
            cin = cout
        params[f"stage{si}"] = stage_p
        state[f"stage{si}"] = stage_s
    params["head"] = {
        "w": jax.random.normal(next(keys), (cin, cfg.num_classes), jnp.float32) * 0.01,
        "b": jnp.zeros((cfg.num_classes,), jnp.float32),
    }
    return params, state


def resnet_logical_axes(params) -> Dict:
    """Conv/BN params are replicated (None axes); only the data batch is
    sharded. FSDP of convnets buys little — weights are ~100MB."""
    return jax.tree_util.tree_map(lambda a: tuple(None for _ in a.shape), params)


def _batch_norm(x, p, s, train: bool, in_act_dtype: bool = True, fused_stats: bool = True,
                stats_stop_gradient: bool = False, ghost: bool = False):
    """x: [b,h,w,c] activations (any float dtype). Stats in f32.
    Returns (y, new_state).

    With ``in_act_dtype`` the per-channel affine (a = scale/sqrt(var+eps),
    b = bias - mean*a) is folded in f32 and applied in the activation dtype
    — one bf16 fma per element instead of f32 widen/normalize/narrow.

    With ``fused_stats`` (cfg.bn_fused_stats) train-mode mean/var come from
    E[x] and E[x²] computed in one fused read of x (f32 accumulation);
    autodiff of this form also yields the minimal backward (sum(dy),
    sum(dy·x) reductions + one elementwise pass) — the structure a
    hand-written BN VJP would produce.

    With ``ghost`` (cfg.bn_ghost_stats) train-mode NORMALIZES with the
    PREVIOUS batch's statistics (s["bmean"]/s["bvar"], carried state) while
    computing this batch's stats only to ship forward. That breaks the
    reduce→normalize serialization on the conv output — the affine's
    (a, b) are step constants, so XLA can fuse the normalize into the conv
    epilogue, and the stats reduction becomes an independent consumer off
    the critical path (the 10.8 ms v5e barrier, BASELINE.md). Semantics:
    one-step-stale statistics, no gradient through them (they're state) —
    accuracy must be validated per recipe (the real-data e2e path)."""
    if train and ghost:
        if fused_stats:
            bmean = jnp.mean(x, axis=(0, 1, 2), dtype=jnp.float32)
            m2 = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=(0, 1, 2))
            bvar = jnp.maximum(m2 - jnp.square(bmean), 0.0)
        else:
            xf = x.astype(jnp.float32)
            bmean = jnp.mean(xf, axis=(0, 1, 2))
            bvar = jnp.var(xf, axis=(0, 1, 2))
        new_s = {
            "mean": BN_MOMENTUM * s["mean"] + (1 - BN_MOMENTUM) * bmean,
            "var": BN_MOMENTUM * s["var"] + (1 - BN_MOMENTUM) * bvar,
            "bmean": bmean,
            "bvar": bvar,
        }
        mean, var = s["bmean"], s["bvar"]  # previous step's batch stats
        a = jax.lax.rsqrt(var + BN_EPS) * p["scale"]
        b = p["bias"] - mean * a
        if in_act_dtype:
            return x * a.astype(x.dtype) + b.astype(x.dtype), new_s
        return (x.astype(jnp.float32) * a + b).astype(x.dtype), new_s
    if train:
        if fused_stats:
            mean = jnp.mean(x, axis=(0, 1, 2), dtype=jnp.float32)
            m2 = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=(0, 1, 2))
            var = jnp.maximum(m2 - jnp.square(mean), 0.0)
        else:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=(0, 1, 2))
            var = jnp.var(xf, axis=(0, 1, 2))
        new_s = {
            "mean": BN_MOMENTUM * s["mean"] + (1 - BN_MOMENTUM) * mean,
            "var": BN_MOMENTUM * s["var"] + (1 - BN_MOMENTUM) * var,
        }
        if stats_stop_gradient:
            # cfg.bn_stats_stop_gradient: drop the backward's stats terms
            # (faster, different optimization dynamics — see config note).
            # "var" keeps the mean (centering) gradient and still gets the
            # FULL speedup — the var path's sum(dy·x) re-read is the cost.
            if stats_stop_gradient != "var":
                mean = jax.lax.stop_gradient(mean)
            var = jax.lax.stop_gradient(var)
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    a = jax.lax.rsqrt(var + BN_EPS) * p["scale"]
    b = p["bias"] - mean * a
    if in_act_dtype:
        return x * a.astype(x.dtype) + b.astype(x.dtype), new_s
    return (x.astype(jnp.float32) * a + b).astype(x.dtype), new_s


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x,
        w.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _space_to_depth(x, block: int = 2):
    """[b,h,w,c] -> [b,h/2,w/2,4c]: 2x2 pixel blocks become channels."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // block, w // block, block * block * c)


def _stem_s2d(x, w7):
    """Exact reformulation of SAME 7x7/s2 conv as a 4x4/s1 conv on
    space-to-depth(2) input: the 7x7 kernel is zero-padded to 8x8 and its
    2x2 phase structure folded into input channels. Output position i reads
    original rows 2i-2..2i+4, identical to SAME padding (2,3)."""
    xs = _space_to_depth(x, 2)
    k8 = jnp.pad(w7, ((0, 1), (0, 1), (0, 0), (0, 0)))
    cin, cout = w7.shape[2], w7.shape[3]
    k = (
        k8.reshape(4, 2, 4, 2, cin, cout)
        .transpose(0, 2, 1, 3, 4, 5)
        .reshape(4, 4, 4 * cin, cout)
    )
    return jax.lax.conv_general_dilated(
        xs,
        k.astype(xs.dtype),
        window_strides=(1, 1),
        padding=((1, 2), (1, 2)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _bottleneck(x, bp, bs, stride, train, bn_act, bn_fused, bn_sg=False, bn_ghost=False):
    y, s1 = _batch_norm(_conv(x, bp["conv1"]), bp["bn1"], bs["bn1"], train, bn_act, bn_fused, bn_sg, bn_ghost)
    y = jax.nn.relu(y)
    y, s2 = _batch_norm(
        _conv(y, bp["conv2"], stride), bp["bn2"], bs["bn2"], train, bn_act, bn_fused, bn_sg, bn_ghost
    )
    y = jax.nn.relu(y)
    y, s3 = _batch_norm(_conv(y, bp["conv3"]), bp["bn3"], bs["bn3"], train, bn_act, bn_fused, bn_sg, bn_ghost)
    new_bs = {"bn1": s1, "bn2": s2, "bn3": s3}
    if "proj" in bp:
        shortcut, sp = _batch_norm(
            _conv(x, bp["proj"], stride), bp["proj_bn"], bs["proj_bn"], train, bn_act, bn_fused, bn_sg, bn_ghost
        )
        new_bs["proj_bn"] = sp
    else:
        shortcut = x
    return jax.nn.relu(y + shortcut), new_bs


def _bn_affine(p, mean, var):
    """Folded BN affine from given statistics: y*a + b == normalize."""
    a = jax.lax.rsqrt(var + BN_EPS) * p["scale"]
    b = p["bias"] - mean * a
    return a, b


def _bn_update(s, mean, var):
    return {
        "mean": BN_MOMENTUM * s["mean"] + (1 - BN_MOMENTUM) * mean,
        "var": BN_MOMENTUM * s["var"] + (1 - BN_MOMENTUM) * var,
    }


def _bottleneck_fused(x, bp, bs, stride, bn_act, bn_fused=True, bn_sg=False):
    """Train-mode bottleneck with the 1x1 convs through the Pallas fused
    matmul+stats kernel (see ResNetConfig.fused_1x1). Same math as
    _bottleneck with bn_fused_stats (E[x]/E[x²] in f32 — the kernel's
    epilogue computes exactly that form, so ``bn_fused`` only steers the
    XLA-path BN2): parity is pinned by tests/test_fused_linear_stats.py.
    Only the 3x3 conv and its BN stay on the XLA path (17% of the
    activations). ``bn_sg`` (cfg.bn_stats_stop_gradient) applies to the
    kernel-derived statistics too."""
    from tf_operator_tpu.ops.fused_linear_stats import fused_linear_stats

    b, h, w, cin = x.shape
    flat = x.reshape(b * h * w, cin)

    def stats(s, q, rows):
        mean = s / rows
        var = jnp.maximum(q / rows - jnp.square(mean), 0.0)
        if bn_sg:
            # same semantics as _batch_norm: "var" keeps the centering
            # (mean) gradient and stops only the variance path
            if bn_sg != "var":
                mean = jax.lax.stop_gradient(mean)
            var = jax.lax.stop_gradient(var)
        return mean, var

    # conv1 (1x1): stats in the matmul epilogue
    y1, s1, q1 = fused_linear_stats(flat, bp["conv1"][0, 0].astype(x.dtype))
    mean1, var1 = stats(s1, q1, float(flat.shape[0]))
    a1, b1 = _bn_affine(bp["bn1"], mean1, var1)

    # conv2 (3x3, XLA): the previous normalize+relu is ONE elementwise op
    # that XLA fuses into the conv input; BN2 takes the existing path.
    y1n = jax.nn.relu(
        y1.reshape(b, h, w, -1) * a1.astype(x.dtype) + b1.astype(x.dtype)
        if bn_act
        else (y1.reshape(b, h, w, -1).astype(jnp.float32) * a1 + b1).astype(x.dtype)
    )
    y2 = _conv(y1n, bp["conv2"], stride)
    y2n, s2 = _batch_norm(y2, bp["bn2"], bs["bn2"], True, bn_act, bn_fused, bn_sg)
    y2n = jax.nn.relu(y2n)

    # conv3 (1x1): plain input (y2n already normalized by XLA BN2)
    oh, ow = y2n.shape[1], y2n.shape[2]
    y3, s3, q3 = fused_linear_stats(
        y2n.reshape(b * oh * ow, -1), bp["conv3"][0, 0].astype(x.dtype)
    )
    mean3, var3 = stats(s3, q3, float(b * oh * ow))
    a3, b3 = _bn_affine(bp["bn3"], mean3, var3)
    y3 = y3.reshape(b, oh, ow, -1)

    new_bs = {
        "bn1": _bn_update(bs["bn1"], mean1, var1),
        "bn2": s2,
        "bn3": _bn_update(bs["bn3"], mean3, var3),
    }

    if "proj" in bp:
        xs = x[:, ::stride, ::stride, :] if stride != 1 else x
        yp, sp, qp = fused_linear_stats(
            xs.reshape(b * oh * ow, cin), bp["proj"][0, 0].astype(x.dtype)
        )
        meanp, varp = stats(sp, qp, float(b * oh * ow))
        ap, bpb = _bn_affine(bp["proj_bn"], meanp, varp)
        yp = yp.reshape(b, oh, ow, -1)
        shortcut = (
            yp * ap.astype(x.dtype) + bpb.astype(x.dtype)
            if bn_act
            else (yp.astype(jnp.float32) * ap + bpb).astype(x.dtype)
        )
        new_bs["proj_bn"] = _bn_update(bs["proj_bn"], meanp, varp)
    else:
        shortcut = x
    y3n = (
        y3 * a3.astype(x.dtype) + b3.astype(x.dtype)
        if bn_act
        else (y3.astype(jnp.float32) * a3 + b3).astype(x.dtype)
    )
    return jax.nn.relu(y3n + shortcut), new_bs


def resnet_forward(params, state, images, cfg: ResNetConfig, train: bool = True):
    """images: [b, h, w, 3] -> (logits [b, classes] f32, new_state)."""
    bn_act = cfg.bn_in_activation_dtype
    bn_fused = cfg.bn_fused_stats
    x = images.astype(cfg.dtype)
    # s2d needs even spatial dims (2x2 blocks); odd sizes take the literal
    # 7x7/s2 path, which SAME-pads any size.
    if cfg.stem == "s2d" and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
        x = _stem_s2d(x, params["stem"]["conv"])
    else:
        x = _conv(x, params["stem"]["conv"], stride=2)
    bn_sg = cfg.bn_stats_stop_gradient
    bn_ghost = cfg.bn_ghost_stats
    if bn_ghost and cfg.fused_1x1:
        raise ValueError("bn_ghost_stats does not compose with fused_1x1")
    x, stem_s = _batch_norm(
        x, params["stem"]["bn"], state["stem"], train, bn_act, bn_fused, bn_sg,
        bn_ghost,
    )
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    new_state: Dict[str, Any] = {"stem": stem_s}
    fused_1x1 = cfg.fused_1x1 and train  # eval folds running stats anyway
    for si, n_blocks in enumerate(cfg.stage_sizes):
        stage_s = []
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            if fused_1x1:
                x, bs = _bottleneck_fused(
                    x, params[f"stage{si}"][bi], state[f"stage{si}"][bi],
                    stride, bn_act, bn_fused, bn_sg,
                )
            else:
                x, bs = _bottleneck(
                    x, params[f"stage{si}"][bi], state[f"stage{si}"][bi], stride,
                    train, bn_act, bn_fused, bn_sg, bn_ghost,
                )
            stage_s.append(bs)
        new_state[f"stage{si}"] = stage_s
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    logits = x @ params["head"]["w"] + params["head"]["b"]
    return logits, new_state
