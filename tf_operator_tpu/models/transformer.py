"""Decoder/encoder transformer family: GPT, Llama-2, BERT-class.

TPU-first design choices:

- **Stacked layers + scan**: all layer params carry a leading [n_layers]
  dim and the forward pass is one ``lax.scan`` — compile time stays flat in
  depth and XLA pipelines the layer loop cleanly.
- **Logical axes on every param** (transformer_logical_axes) so
  parallel.sharding.ShardingRules decides DP/FSDP/TP placement; the model
  never mentions mesh axes.
- **bf16 activations, f32 params**: matmuls hit the MXU in bfloat16; the
  loss/softmax runs in f32.
- **Ring attention** over a cp axis is a drop-in (attn_impl="ring") for
  long-context jobs; default is dense attention, which XLA fuses well.
- **Remat**: optional jax.checkpoint per layer to trade FLOPs for HBM.

Architecture follows the Llama-2 recipe (RMSNorm, rotary embeddings, GQA,
SwiGLU) with ``causal=False`` turning the same core into a BERT-class
bidirectional encoder (MLM head = the same tied vocab projection).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    d_ff: int = 11008
    max_seq: int = 4096
    causal: bool = True
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # True/"full": save only layer inputs, recompute everything (min HBM,
    # +2ND FLOPs). "dots": selective checkpointing — save matmul outputs,
    # recompute just the elementwise chain (near-6ND at moderate HBM).
    # False/"none": no remat (max HBM).
    remat: Any = True
    # "dense" | "flash" (Pallas kernel) | "ring" (cp ppermute ring) |
    # "ulysses" (cp all-to-all head/seq re-shard; needs heads % cp == 0)
    attn_impl: str = "dense"
    cp_axis: str = "cp"
    # Blockwise fused loss (ops/fused_cross_entropy): logits never hit HBM
    # as a [b,t,vocab] f32 array. Same math as the unfused path.
    fused_xent: bool = True
    # Mixture-of-experts MLP (parallel.moe): 0 = dense. moe_top_k=1 is
    # Switch-style; 2 is Mixtral-style (renormalized gate weights).
    # Experts shard over the ep mesh axis (all-to-all dispatch); without an
    # ep axis all experts run on every device (the routing math is
    # identical, so one config tests on CPU and scales on a pod).
    n_experts: int = 0
    moe_top_k: int = 1
    capacity_factor: float = 2.0
    ep_axis: str = "ep"
    # Expert dispatch: "sort" (capacity queues + scatter/gather, the ep
    # all_to_all layout), "einsum" (one-hot oracle), "ragged" (r5 —
    # lax.ragged_dot over actual per-expert counts; measured SLOWER than
    # the padded vmap on v5e — kept as the negative-result receipt), or
    # "gmm" (r5/r6 — the Pallas grouped-matmul kernel: block-granular
    # padding only, no drops; ops/grouped_matmul.py). r6: gmm runs under
    # ep sharding too (count-exchange + block-quantum all_to_all
    # buffers, parallel.moe._moe_local_gmm) including ep-inside-pipeline;
    # only "ragged" still falls back to sort under ep.
    moe_dispatch: str = "sort"
    # Router auxiliary losses — without them top-k routing collapses onto a
    # few experts under real training. moe_aux_weight scales the Switch
    # load-balance loss  E * Σ_e f_e·P_e  (f_e = fraction of token-choices
    # assigned to expert e — non-differentiable, acts as the coefficient;
    # P_e = mean router probability — carries the gradient; uniform routing
    # gives exactly 1.0). moe_zloss_weight scales the ST-MoE router z-loss
    # mean(logsumexp(router_logits)²), which keeps router logits from
    # drifting to magnitudes where softmax saturates and bf16 rounds.
    # Both default ON for MoE configs (0.0 disables — the ablation knob).
    moe_aux_weight: float = 0.01
    moe_zloss_weight: float = 1e-3
    # Pipeline parallelism (parallel.pipeline): with a pp axis in the mesh
    # and pp_microbatches > 0, the layer stack is stage-partitioned into
    # mesh.shape["pp"] groups of n_layers/pp contiguous layers and run as a
    # fill-drain pipeline (activations ppermute stage-to-stage);
    # embed/norm/head stay replicated. Composes with dp (each dp group
    # pipelines its own batch slice) and, r3, with tp (stage weights shard
    # over the tp axis; _layer psums its row-parallel matmuls). 0 = no
    # pipeline. pp_schedule: "1f1b" (explicit backward, stage-input-only
    # residuals — the memory-disciplined default) | "gpipe" (autodiff).
    # pp_chunks (r3): virtual stages per device — the INTERLEAVED 1F1B
    # schedule. n_layers splits into pp*pp_chunks chunks (chunk j on
    # device j mod pp, model order); bubble shrinks from
    # (pp-1)/(M+pp-1) to (pp-1)/(M*v+pp-1). Requires pp_schedule="1f1b"
    # and pp_microbatches % pp == 0.
    pp_microbatches: int = 0
    pp_axis: str = "pp"
    pp_schedule: str = "1f1b"
    pp_chunks: int = 1

    def __post_init__(self):
        if self.n_experts and not (1 <= self.moe_top_k <= self.n_experts):
            raise ValueError(
                f"moe_top_k={self.moe_top_k} must be in [1, n_experts="
                f"{self.n_experts}] (it silently corrupts FLOP accounting "
                "and fails inside lax.top_k otherwise)"
            )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def n_params(self) -> int:
        """Parameter count (for MFU accounting)."""
        d, f, v, L = self.d_model, self.d_ff, self.vocab, self.n_layers
        kv = self.n_kv_heads * self.head_dim
        mlp = 3 * d * f
        if self.n_experts:
            mlp = self.n_experts * mlp + d * self.n_experts  # experts + router
        per_layer = d * d + 2 * d * kv + d * d + mlp + 2 * d  # qkv+o+mlp+norms
        return v * d + L * per_layer + d  # embed + layers + final norm

    def n_active_params(self) -> int:
        """Params touched per token (= n_params for dense; top-k MoE
        activates k experts) — the right N for 6ND FLOP accounting."""
        if not self.n_experts:
            return self.n_params()
        d, f, L = self.d_model, self.d_ff, self.n_layers
        inactive = (self.n_experts - self.moe_top_k) * 3 * d * f
        return self.n_params() - L * inactive


PRESETS: Dict[str, TransformerConfig] = {
    # test-scale
    "tiny": TransformerConfig(
        vocab=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
        max_seq=128, remat=False,
    ),
    "tiny-moe": TransformerConfig(
        vocab=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
        max_seq=128, remat=False, n_experts=4,
    ),
    "gpt-small": TransformerConfig(
        vocab=50257, d_model=768, n_layers=12, n_heads=12, n_kv_heads=12, d_ff=3072,
        max_seq=1024,
    ),
    # Mixtral-class sparse config (8 experts, top-1 routing): total params
    # ~8x the dense MLP stack, active params per token ~ the dense model.
    # r6: the grouped-matmul dispatch is the default (it beat the r4
    # capacity path at zero drops in the r5 capture; BENCH_MOE_DISPATCH
    # still overrides for A/Bs against sort/ragged).
    "moe-small": TransformerConfig(
        vocab=32000, d_model=768, n_layers=12, n_heads=12, n_kv_heads=12, d_ff=3072,
        max_seq=1024, n_experts=8, moe_dispatch="gmm",
    ),
    # BERT-base as bidirectional encoder (MLM-style head)
    "bert-base": TransformerConfig(
        vocab=30522, d_model=768, n_layers=12, n_heads=12, n_kv_heads=12, d_ff=3072,
        max_seq=512, causal=False,
    ),
    # North-star-shape single-chip config (r4): the largest GQA model
    # whose adamw state fits one 16 GB chip, at the d>=2048 shapes the
    # 50%-MFU target presumes — measured 56% exact MFU / 49.7% 6ND vs
    # gpt-small's 38% at d=768 (BASELINE.md; the gap is model-level
    # per-op overhead at small d, not a matmul-rate wall — the chip's
    # chained-matmul rate is ~flat across these shapes under the r4
    # corrected protocol). ~795M params — sized against the MEASURED
    # adamw residency of ~18 bytes/param at grad_accum=1 (p+m+v+grads f32
    # + the bf16 compute cast; accum>1 adds a second f32 grad buffer and
    # pushed the L=14 variant to 19.9G on a 15.75G chip). The
    # [b·t,2048]x[2048,8192] MLP matmuls dominate the FLOPs.
    "gqa-2048": TransformerConfig(
        vocab=32000, d_model=2048, n_layers=12, n_heads=16, n_kv_heads=4,
        d_ff=8192, max_seq=4096,
    ),
    "llama2-7b": TransformerConfig(
        vocab=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=32, d_ff=11008,
        max_seq=4096,
    ),
    "llama2-13b": TransformerConfig(
        vocab=32000, d_model=5120, n_layers=40, n_heads=40, n_kv_heads=40, d_ff=13824,
        max_seq=4096,
    ),
    # The GQA member of the family (8 kv heads vs 64 query heads): the
    # config that actually exercises grouped-query attention at scale.
    # Memory plan validated by tests/test_tools.py::TestMemPlan (fits a
    # v5p-256-shaped fsdp=32 x tp=8 mesh).
    "llama2-70b": TransformerConfig(
        vocab=32000, d_model=8192, n_layers=80, n_heads=64, n_kv_heads=8, d_ff=28672,
        max_seq=4096,
    ),
    # Flagship-scale sparse config (r4, VERDICT r3 #5): Mixtral-8x7B
    # shapes — 8 experts top-2, GQA 32q/8kv, ~46.5B total / ~12.7B
    # active params. Its legal mesh is dp x fsdp x ep: experts shard
    # over ep on their expert dim AND over fsdp on their embed dim
    # (DEFAULT_RULES "expert"/"embed"), so expert weights no longer
    # replicate per dp replica — the memplan-closing layout for a
    # v5p-256 pod (examples/mixtral_8x7b_v5p256.json). r6: the default
    # dispatch is the padding-free grouped-matmul kernel — it now runs
    # UNDER the ep axis (count-exchange + block-quantum a2a buffers), so
    # the flagship no longer pays cf× padding FLOPs or drops tokens.
    "mixtral-8x7b": TransformerConfig(
        vocab=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq=4096, n_experts=8, moe_top_k=2,
        moe_dispatch="gmm",
    ),
}


# ---------------------------------------------------------------------------
# Params
# ---------------------------------------------------------------------------


def init_transformer(key, cfg: TransformerConfig) -> Dict[str, Any]:
    """Initialize params (f32). Layer params are stacked on a leading
    [n_layers] axis for the scan."""
    d, f = cfg.d_model, cfg.d_ff
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    L = cfg.n_layers
    k_embed, k_layers = jax.random.split(key)

    def norm_init(k, *shape):
        del k
        return jnp.ones(shape, jnp.float32)

    def dense_init(k, fan_in, *shape):
        return jax.random.normal(k, shape, jnp.float32) * (fan_in**-0.5)

    ks = jax.random.split(k_layers, 8)
    layers = {
        "attn_norm": jnp.ones((L, d), jnp.float32),
        "wq": dense_init(ks[0], d, L, d, nh * hd),
        "wk": dense_init(ks[1], d, L, d, nkv * hd),
        "wv": dense_init(ks[2], d, L, d, nkv * hd),
        "wo": dense_init(ks[3], nh * hd, L, nh * hd, d),
        "mlp_norm": jnp.ones((L, d), jnp.float32),
    }
    if cfg.n_experts:
        E = cfg.n_experts
        layers.update(
            {
                "w_router": dense_init(ks[7], d, L, d, E),
                "w_gate": dense_init(ks[4], d, L, E, d, f),
                "w_up": dense_init(ks[5], d, L, E, d, f),
                "w_down": dense_init(ks[6], f, L, E, f, d),
            }
        )
    else:
        layers.update(
            {
                "w_gate": dense_init(ks[4], d, L, d, f),
                "w_up": dense_init(ks[5], d, L, d, f),
                "w_down": dense_init(ks[6], f, L, f, d),
            }
        )
    params = {
        "embed": jax.random.normal(k_embed, (cfg.vocab, d), jnp.float32) * 0.02,
        "final_norm": jnp.ones((d,), jnp.float32),
        "layers": layers,
    }
    return params


def transformer_logical_axes(cfg: TransformerConfig) -> Dict[str, Any]:
    """Logical axis names per param leaf (same tree structure as params)."""
    layers = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", "embed"),
    }
    if cfg.n_experts:
        layers.update(
            {
                "w_router": ("layers", "embed", "expert"),
                "w_gate": ("layers", "expert", "embed", "mlp"),
                "w_up": ("layers", "expert", "embed", "mlp"),
                "w_down": ("layers", "expert", "mlp", "embed"),
            }
        )
    else:
        layers.update(
            {
                "w_gate": ("layers", "embed", "mlp"),
                "w_up": ("layers", "embed", "mlp"),
                "w_down": ("layers", "mlp", "embed"),
            }
        )
    return {
        "embed": ("vocab", "embed"),
        "final_norm": ("embed",),
        "layers": layers,
    }


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _rms_norm(x, gamma, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * gamma.astype(
        x.dtype
    )


def _rope(x, theta: float):
    """Rotary position embedding. x: [b, t, h, d_head]."""
    b, t, h, d = x.shape
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = jnp.arange(t, dtype=jnp.float32)[:, None] * freqs[None, :]  # [t, half]
    cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def rope_at_positions(x, positions, theta: float):
    """_rope at explicit ABSOLUTE positions. x: [b, t, h, d_head];
    positions: [b, t] int. ``_rope(x, theta)`` is exactly this with
    positions = arange(t) — the incremental decode path (serve/engine.py)
    needs the general form because a decode step's single token sits at
    position seq_len, not 0, and a prefill chunk starts mid-sequence;
    rotating at the wrong absolute position is the classic silent KV-cache
    bug (every token attends as if it were the first)."""
    half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [b, t, half]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def _attention(q, k, v, cfg: TransformerConfig, mesh):
    """q: [b,t,nh,hd]; k/v: [b,t,nkv,hd].

    GQA (nkv < nh) runs NATIVE on the dense, flash AND ring paths: no
    [b,t,nh,hd] K/V tensor ever exists — the flash kernel grids over K/V
    heads with the group folded into its q tile ([g·block_q, hd] rows
    per K/V block load, so in-kernel K/V HBM traffic scales with nkv),
    the dense path groups the einsum
    (ops/flash_attention.py), and ring attention rotates the SMALL
    [*, nkv, hd] blocks around the cp ring (g-times less ICI traffic per
    hop — parallel/ring_attention.py), keeping K/V traffic at the nkv
    rate that is GQA's whole point at t>=4096. Ulysses is GQA-native when
    n_kv % cp == 0 (K/V all-to-all on their own smaller head dim); with
    indivisible kv counts it all-gathers the small K/V over cp and
    head-maps per shard (r4 — no repeated [t, h, hd] tensor either way),
    both handled inside parallel/ulysses.py."""
    if cfg.attn_impl == "ring" and mesh is not None and cfg.cp_axis in mesh.axis_names:
        from tf_operator_tpu.parallel.ring_attention import ring_attention

        batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names) or None
        return ring_attention(
            q, k, v, mesh, axis_name=cfg.cp_axis, causal=cfg.causal, batch_axes=batch_axes
        )
    if cfg.attn_impl == "ulysses" and mesh is not None and cfg.cp_axis in mesh.axis_names:
        # All-to-all SP (DeepSpeed-Ulysses): re-shard seq->heads once, run
        # ordinary full-sequence attention per head shard (the flash kernel
        # applies untouched on TPU; dense fallback elsewhere), re-shard back.
        from tf_operator_tpu.ops.flash_attention import flash_attention
        from tf_operator_tpu.parallel.ulysses import ulysses_attention

        batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names) or None
        return ulysses_attention(
            q, k, v, mesh, axis_name=cfg.cp_axis, causal=cfg.causal,
            batch_axes=batch_axes,
            attn_fn=lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=cfg.causal),
        )
    if cfg.attn_impl == "flash":
        from tf_operator_tpu.ops.flash_attention import flash_attention

        # Pallas online-softmax kernel on TPU; identical-math jnp fallback
        # elsewhere, so one config runs on the CPU test mesh too. Under a
        # mesh the pallas_call has no GSPMD partitioning rule, so wrap in
        # shard_map — attention is independent per (batch, head), so batch
        # shards over dp/fsdp and heads over tp with no collectives. A
        # sequence-sharded (cp) mesh needs ring attention instead.
        if mesh is not None and mesh.devices.size > 1:
            from tf_operator_tpu.parallel.collectives import (
                shard_map_compat as shard_map,
            )
            from jax.sharding import PartitionSpec as P

            batch = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names) or None
            heads = "tp" if "tp" in mesh.axis_names else None
            tp = mesh.shape["tp"] if heads else 1
            if k.shape[2] % tp:
                # kv heads don't divide tp (tiny test configs): materialize
                # the repeat so head sharding stays legal. When nkv % tp
                # == 0 (llama2-70b: 8 kv / tp=8) GQA stays native: the
                # per-shard contiguous head blocks keep hi//g mapping to
                # the right local kv head (g_local == g).
                grp = q.shape[2] // k.shape[2]
                k = jnp.repeat(k, grp, axis=2)
                v = jnp.repeat(v, grp, axis=2)
            spec = P(batch, None, heads, None)
            fn = shard_map(
                lambda q, k, v: flash_attention(q, k, v, causal=cfg.causal),
                mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
            )
            return fn(q, k, v)
        return flash_attention(q, k, v, causal=cfg.causal)
    # dense path: the GQA-native grouped einsum with f32 MXU accumulation
    # (ops/flash_attention.reference_attention — also the flash oracle, so
    # dense and flash configs are pinned to the same math by its tests)
    from tf_operator_tpu.ops.flash_attention import reference_attention

    return reference_attention(q, k, v, causal=cfg.causal)


def _anchored_gamma(gamma, cfg: TransformerConfig, mesh):
    """Read an rms-norm gamma through a replicated constraint on MoE
    multi-axis meshes. ZeRO shards even the [d] norm scales over fsdp —
    on the dp×fsdp×ep mesh that is a TRANSPOSED tile assignment, and the
    broadcast multiply pulls the (batch-anchored) layer-scan carry and
    its backward cotangent toward that d-over-fsdp layout; GSPMD can
    only reconcile differently ORDERED assignments with an involuntary
    full rematerialization of the carry, once per layer per step. A [d]
    all-gather is noise; the carry remat is not. No-op for dense configs
    and single-axis meshes (propagation is already consistent there),
    and for pipeline/shard_map callers (mesh is None inside the stage
    body — manual axes can't take auto sharding constraints anyway)."""
    if not (cfg.n_experts and mesh is not None
            and getattr(mesh, "devices", None) is not None
            and cfg.ep_axis in getattr(mesh, "axis_names", ())):
        return gamma
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.lax.with_sharding_constraint(
        gamma, NamedSharding(mesh, P(*(None,) * gamma.ndim))
    )


def _layer(x, layer_params, cfg: TransformerConfig, mesh, tp_axis=None,
           tp_manual_vjp=True, local_ep_axis: Optional[str] = None):
    """One decoder layer. ``tp_axis`` (pipeline tp-within-stage, r3):
    weights arrive as tp-LOCAL shards (wq/wk/wv/w_gate/w_up
    column-parallel, wo/w_down row-parallel — the Megatron split).

    The tp collective convention depends on WHO differentiates
    (``tp_manual_vjp``): under direct jax.vjp inside the 1F1B backward,
    plain psum is silently wrong (its transpose-is-psum convention
    inflates every cotangent behind it by tp, compounding per layer), so
    activations route through the Megatron f/g conjugate pair
    (collectives.tp_region_enter/exit). Under shard_map AUTODIFF (the
    GPipe schedule), the framework hands each tp shard gy/tp for a
    replicated output — there raw psum's transpose restores exactly the
    full cotangent and the f/g pair would HALVE row-parallel weight
    grads. Both pinned by test_pipeline_tp_grads_match_single_device.
    Head counts derive from the local weight shapes, so the same body
    serves both layouts."""
    if tp_axis is not None:
        from tf_operator_tpu.parallel.collectives import (
            tp_region_enter,
            tp_region_exit,
        )

        if tp_manual_vjp:
            enter = lambda a: tp_region_enter(a, tp_axis)  # noqa: E731
            leave = lambda a: tp_region_exit(a, tp_axis)  # noqa: E731
        else:
            enter = lambda a: a  # noqa: E731
            leave = lambda a: jax.lax.psum(a, tp_axis)  # noqa: E731
    b, t, d = x.shape
    hd = cfg.head_dim
    wq = layer_params["wq"].astype(x.dtype)
    wk = layer_params["wk"].astype(x.dtype)
    wv = layer_params["wv"].astype(x.dtype)
    gamma_attn = _anchored_gamma(layer_params["attn_norm"], cfg, mesh)
    gamma_mlp = _anchored_gamma(layer_params["mlp_norm"], cfg, mesh)

    def anchor_tokens(a):
        # companion to _anchored_gamma (same scope): keeps the normed
        # activations — and, through the constraint's transpose, their
        # COTANGENTS arriving from the ZeRO-sharded qkv/router matmul
        # transposes — in the batch layout the layer-scan carry is
        # pinned to, so no d-over-fsdp pressure reaches the while
        # boundary
        if not (cfg.n_experts and mesh is not None
                and getattr(mesh, "devices", None) is not None
                and cfg.ep_axis in getattr(mesh, "axis_names", ())):
            return a
        data_axes = tuple(ax for ax in ("dp", "fsdp") if ax in mesh.axis_names)
        if not data_axes:
            return a
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.lax.with_sharding_constraint(
            a, NamedSharding(mesh, P(data_axes, *(None,) * (a.ndim - 1)))
        )

    h = anchor_tokens(_rms_norm(x, gamma_attn, cfg.norm_eps))
    if tp_axis is not None:
        h = enter(h)
    q = (h @ wq).reshape(b, t, wq.shape[-1] // hd, hd)
    k = (h @ wk).reshape(b, t, wk.shape[-1] // hd, hd)
    v = (h @ wv).reshape(b, t, wv.shape[-1] // hd, hd)
    q, k = _rope(q, cfg.rope_theta), _rope(k, cfg.rope_theta)
    attn = _attention(q, k, v, cfg, mesh).reshape(b, t, wq.shape[-1])
    proj = attn @ layer_params["wo"].astype(x.dtype)
    if tp_axis is not None:
        proj = leave(proj)
    # Selective-remat tag: saving the post-attention residual stream lets
    # the MLP recompute chain start HERE instead of replaying qkv →
    # attention → wo to rebuild it (see _remat_wrap).
    x = checkpoint_name(x + proj, "resid_mid")

    h = anchor_tokens(_rms_norm(x, gamma_mlp, cfg.norm_eps))
    if cfg.n_experts:
        moe_out, aux = _moe_mlp(h, layer_params, cfg, mesh,
                                local_ep_axis=local_ep_axis)
        return x + moe_out, aux
    if tp_axis is not None:
        h = enter(h)
    # PRE-activation tags: the silu backward needs the pre-activation
    # value (silu'(z) is a function of z, not of silu(z)), so saving z
    # rather than silu(z) is what actually retires the gate/up matmul
    # recompute — the elementwise silu/mul replay from z is free.
    z_gate = checkpoint_name(h @ layer_params["w_gate"].astype(x.dtype), "mlp_gate")
    up = checkpoint_name(h @ layer_params["w_up"].astype(x.dtype), "mlp_up")
    down = (jax.nn.silu(z_gate) * up) @ layer_params["w_down"].astype(x.dtype)
    if tp_axis is not None:
        down = leave(down)
    return x + down, None


def _moe_mlp(h, layer_params, cfg: TransformerConfig, mesh,
             local_ep_axis: Optional[str] = None):
    """Top-k expert MLP (k = cfg.moe_top_k: 1 Switch / 2 Mixtral-style):
    router -> all-to-all dispatch over the ep axis (parallel.moe) ->
    per-expert SwiGLU -> gate-weighted combine.

    ``local_ep_axis`` (r4, ep-inside-pipeline): the caller already runs
    inside a shard_map that maps the ep axis (pipeline_apply binds every
    mesh axis), so moe_apply's own shard_map would nest — instead the
    per-device body (parallel.moe._moe_local) runs directly against the
    bound axis name: h is this shard's token slice, layer_params carry
    this shard's E/ep experts.

    Returns (out, aux) — aux carries the router losses (UNWEIGHTED; the
    loss head applies cfg.moe_aux_weight / cfg.moe_zloss_weight) plus
    observability stats: {"lb_loss", "z_loss", "expert_load" [E],
    "drop_frac"}."""
    from tf_operator_tpu.parallel.moe import (
        _moe_local,
        expert_capacity,
        moe_apply,
    )

    b, t, d = h.shape
    flat = h.reshape(b * t, d)
    gate_logits = flat @ layer_params["w_router"].astype(h.dtype)

    def expert_fn(wp, toks):
        gate = jax.nn.silu(toks @ wp["w_gate"].astype(toks.dtype))
        up = toks @ wp["w_up"].astype(toks.dtype)
        return (gate * up) @ wp["w_down"].astype(toks.dtype)

    expert_params = {
        "w_gate": layer_params["w_gate"],
        "w_up": layer_params["w_up"],
        "w_down": layer_params["w_down"],
    }
    if local_ep_axis is not None:
        # same capacity rule as moe_apply's sharded branch: flat is
        # already the per-shard token slice. dispatch follows
        # cfg.moe_dispatch with moe_apply's ladder semantics: gmm runs
        # padding-free in-stage (r6); ragged/einsum degrade to sort (the
        # einsum inbox layout is identical, sort is the cheap form).
        import os

        local_impl = "gmm" if cfg.moe_dispatch == "gmm" else "sort"
        capacity = expert_capacity(
            cfg.capacity_factor, cfg.moe_top_k, flat.shape[0], cfg.n_experts
        )
        out, stats = _moe_local(
            flat, gate_logits, expert_params, expert_fn,
            axis_name=local_ep_axis, capacity=capacity, dropped="zero",
            k_top=cfg.moe_top_k, stat_axes=(local_ep_axis,),
            dispatch_impl=local_impl,
            block_rows=int(os.environ.get("TPUJOB_GMM_BLOCK_ROWS", "256")),
        )
    else:
        from tf_operator_tpu.parallel.moe import ragged_swiglu

        out, stats = moe_apply(
            flat,
            gate_logits,
            expert_params,
            expert_fn,
            mesh,
            axis_name=cfg.ep_axis,
            capacity_factor=cfg.capacity_factor,
            # the result feeds a residual add: a capacity-dropped token's
            # MLP must contribute 0, not its own input again
            dropped="zero",
            k_top=cfg.moe_top_k,
            return_stats=True,
            dispatch_impl=cfg.moe_dispatch,
            ragged_expert_fn=ragged_swiglu,
        )
    # Switch load-balance loss: E * Σ_e f_e·P_e. f_e (expert_load) comes
    # out of the discrete top-k assignment, so it carries no gradient and
    # acts as a per-expert coefficient on the differentiable mean gate
    # probability — overloaded experts get their router prob pushed down.
    lb_loss = cfg.n_experts * jnp.sum(
        stats["expert_load"] * stats["mean_gate"]
    )
    # ST-MoE router z-loss: keeps router logits near the softmax's
    # well-conditioned range.
    z = jax.scipy.special.logsumexp(gate_logits.astype(jnp.float32), axis=-1)
    z_loss = jnp.mean(jnp.square(z))
    aux = {
        "lb_loss": lb_loss,
        "z_loss": z_loss,
        "expert_load": stats["expert_load"],
        "drop_frac": stats["drop_frac"],
    }
    out = out.reshape(b, t, d)
    if local_ep_axis is None and mesh is not None and getattr(
        mesh, "devices", None
    ) is not None and cfg.ep_axis in getattr(mesh, "axis_names", ()):
        # Re-anchor the layer output to the model's canonical activation
        # layout (batch over the data axes, ep REPLICATED). moe_apply's
        # shard_map constrains its flat tokens to P((dp, fsdp, ep)) —
        # correct inside the ep exchange, but without this anchor that
        # 8-way token sharding propagates OUT into the layer-scan carry
        # while the rest of the loop body (attention, residual adds)
        # settles on the (dp, fsdp)-only layout, and GSPMD reconciles
        # the conflicting while-carry specs with an "involuntary full
        # rematerialization" (replicate + re-slice of the carry AND the
        # downstream fused-CE block walk) on every layer iteration of
        # the ep×fsdp×dp flagship pass. Same anchoring rule as the
        # pipeline's microbatch split (parallel/pipeline.py).
        from jax.sharding import NamedSharding, PartitionSpec as P

        data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
        if data_axes:
            out = jax.lax.with_sharding_constraint(
                out, NamedSharding(mesh, P(data_axes, None, None))
            )
    return out, aux


# Selective-remat policy ladder (r5, VERDICT r4 #1): named-activation sets
# between the two extremes full remat (save layer inputs only, fits, but
# replays qkv+attn+wo+gate+up in the backward) and "dots" (save every
# matmul output, OOMs at north-star shapes). Ordered by per-layer HBM cost
# at gqa-2048 b=6 t=2048 (bf16): flash_q 50.3 MB + flash_k/v 12.6 each;
# resid_mid 50.3; mlp_up/mlp_gate 201 each. The recompute each tier
# retires (in btd² matmul units of the 23 the full-remat backward replays
# — the down projection is never replayed, its output is dead in the
# backward): qkv 3, +wo 2, +up 8, +gate 8. The attention forward replay
# (~2 units) is the structural floor of every tier: the flash custom-vjp
# rebuilds its (o, lse) residuals in the backward regardless (see
# ops/flash_attention.py FLASH_SAVE_NAMES — the boundary is opaque to
# name policies on the output side).
_REMAT_SAVE_SETS: Dict[str, tuple] = {
    # the r5 north-star winner: +50 MB/layer at gqa-2048 b=6 retires the
    # wo replay AND severs the recompute chain at the residual stream —
    # measured 57.3% exact / 50.9% 6ND vs full remat's 55.9/49.6 (the
    # only policy that beats full remat at the max-fit batch; BASELINE.md
    # selective-remat table)
    "save_mid": ("resid_mid",),
    "save_qkv": ("flash_q", "flash_k", "flash_v"),
    "save_qkv_mid": ("flash_q", "flash_k", "flash_v", "resid_mid"),
    "save_qkv_mid_up": (
        "flash_q", "flash_k", "flash_v", "resid_mid", "mlp_up",
    ),
    "save_qkv_mid_mlp": (
        "flash_q", "flash_k", "flash_v", "resid_mid", "mlp_up", "mlp_gate",
    ),
    "save_mlp_mid": ("resid_mid", "mlp_gate", "mlp_up"),
}


# Every checkpoint_name tag the model actually emits (flash q/k/v from
# ops/flash_attention.FLASH_SAVE_NAMES + the layer-body tags above) —
# the validation domain for user "save:" policies.
KNOWN_SAVE_NAMES = frozenset(
    {"flash_q", "flash_k", "flash_v", "resid_mid", "mlp_gate", "mlp_up"}
)


def remat_save_names(remat) -> Optional[tuple]:
    """The activation names a remat mode saves (None for non-name modes).
    Accepts the _REMAT_SAVE_SETS aliases or ``"save:name1,name2"``.
    Unknown names in a ``save:`` policy are rejected: a typo
    (save:resid_mld) would otherwise save NOTHING and silently degrade
    to full remat — the opposite of what the user asked for."""
    if isinstance(remat, str):
        if remat in _REMAT_SAVE_SETS:
            return _REMAT_SAVE_SETS[remat]
        if remat.startswith("save:"):
            names = tuple(n.strip() for n in remat[5:].split(",") if n.strip())
            unknown = sorted(set(names) - KNOWN_SAVE_NAMES)
            if unknown:
                raise ValueError(
                    f"remat policy {remat!r}: unknown activation name(s) "
                    f"{unknown} — no such checkpoint_name tag exists, so "
                    "they would save nothing (silent full remat); known "
                    f"names: {sorted(KNOWN_SAVE_NAMES)}"
                )
            return names
    return None


def checkpoint_name(x, name: str):
    """jax.ad_checkpoint.checkpoint_name on every array leaf — identity
    outside remat; under a save_only_these_names policy the tagged value
    is stored instead of recomputed."""
    from jax.ad_checkpoint import checkpoint_name as cn

    return jax.tree_util.tree_map(lambda a: cn(a, name), x)


def _remat_wrap(layer_fn, cfg: TransformerConfig):
    if cfg.remat in (True, "full"):
        return jax.checkpoint(layer_fn)
    if cfg.remat == "dots":
        return jax.checkpoint(
            layer_fn, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
    names = remat_save_names(cfg.remat)
    if names is not None:
        return jax.checkpoint(
            layer_fn, policy=jax.checkpoint_policies.save_only_these_names(*names)
        )
    if cfg.remat not in (False, None, "none"):
        raise ValueError(f"unknown remat mode {cfg.remat!r}")
    return layer_fn


def _use_pipeline(cfg: TransformerConfig, mesh) -> bool:
    return bool(
        cfg.pp_microbatches
        and mesh is not None
        and cfg.pp_axis in getattr(mesh, "axis_names", ())
        and mesh.shape[cfg.pp_axis] > 1
    )


def _pp_param_specs(cfg: TransformerConfig, tp_axis: Optional[str]):
    """PartitionSpecs for the stage-major [S, per_stage, ...] layer params:
    stage dim over pp; with tp, the Megatron split — wq/wk/wv/w_gate/w_up
    column-parallel (last dim over tp), wo/w_down row-parallel (first
    weight dim over tp), norms replicated."""
    from jax.sharding import PartitionSpec as P

    pp = cfg.pp_axis
    col = P(pp, None, None, tp_axis)
    row = P(pp, None, tp_axis, None)
    return {
        "attn_norm": P(pp, None, None),
        "wq": col, "wk": col, "wv": col, "wo": row,
        "mlp_norm": P(pp, None, None),
        "w_gate": col, "w_up": col, "w_down": row,
    }


def _pp_param_specs_moe(cfg: TransformerConfig):
    """PartitionSpecs for MoE stage params under ep-in-stage (r4): stage
    dim over pp everywhere; the expert leaves additionally shard their
    expert dim (index 2 of [S, per_stage, E, ...]) over ep, so each
    device holds its stage's layers x its E/ep experts."""
    from jax.sharding import PartitionSpec as P

    pp, ep = cfg.pp_axis, cfg.ep_axis
    exp = P(pp, None, ep)
    return {
        "attn_norm": P(pp), "wq": P(pp), "wk": P(pp), "wv": P(pp),
        "wo": P(pp), "mlp_norm": P(pp), "w_router": P(pp),
        "w_gate": exp, "w_up": exp, "w_down": exp,
    }


def transformer_hidden_pp(params, tokens, cfg: TransformerConfig, mesh):
    """Pipeline-parallel layer stack: n_layers/pp contiguous layers per
    stage through parallel.pipeline.pipeline_apply (fill-drain pipeline —
    "1f1b" explicit-backward schedule by default, cfg.pp_schedule —
    activations over ppermute). The per-stage body is itself a lax.scan
    over the stage's layers — the same stacked-params execution the
    single-device path uses, so the oracle comparison is exact math.

    Composes with dp (each dp group pipelines its batch slice) and, r3,
    with tp-WITHIN-STAGE: with a tp axis in the mesh, stage weights shard
    Megatron-style (_pp_param_specs) and _layer psums its row-parallel
    matmuls over tp.

    MoE + pipeline: experts REPLICATE within each stage by default (the
    moe_apply no-ep routing path — identical math to the ep-sharded
    dispatch); with an ep axis in the mesh (r4 — the VERDICT r3 #5
    stretch), experts SHARD over ep inside each stage: pipeline_apply's
    one shard_map binds every mesh axis, so the stage body runs
    parallel.moe._moe_local directly against the bound "ep" name (no
    nesting) — tokens shard over (dp, fsdp, ep) as additional pipeline
    data axes, expert weights shard over (pp on the stage dim, ep on the
    expert dim), and the all-to-all dispatch runs per (stage,
    microbatch). The router aux losses ride the pipeline's aux channel
    (pipeline_apply aux_size=2: summed lb/z per (stage-layer,
    microbatch), normalized back to means here) so MoE trains at quality
    under pp — with the caveat that load-balance fractions are computed
    per MICROBATCH rather than per batch. Per-layer router telemetry
    (expert_load/drop_frac) is not carried through the pipeline;
    lm_loss_and_metrics reports the scalar losses only for pp+MoE.
    MoE + tp-within-stage is rejected (the expert MLP has no tp
    split)."""
    from tf_operator_tpu.parallel.pipeline import pipeline_apply

    if cfg.n_experts and "tp" in mesh.axis_names and mesh.shape["tp"] > 1:
        raise NotImplementedError(
            "MoE + tp-within-stage is not supported (the expert MLP has "
            "no tensor-parallel split); use pp x ep x dp for MoE pipelines"
        )
    ep_in_stage = bool(
        cfg.n_experts
        and cfg.ep_axis in mesh.axis_names
        and mesh.shape[cfg.ep_axis] > 1
    )
    if ep_in_stage and cfg.n_experts % mesh.shape[cfg.ep_axis]:
        raise ValueError(
            f"{cfg.n_experts} experts not divisible by "
            f"{cfg.ep_axis}={mesh.shape[cfg.ep_axis]}"
        )
    n_stages = mesh.shape[cfg.pp_axis]
    n_virtual = n_stages * cfg.pp_chunks
    if cfg.n_layers % n_virtual:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pp*pp_chunks="
            f"{n_virtual}"
        )
    tp_axis = None
    if "tp" in mesh.axis_names and mesh.shape["tp"] > 1:
        tp = mesh.shape["tp"]
        for nm, val in (("n_heads", cfg.n_heads), ("n_kv_heads", cfg.n_kv_heads),
                        ("d_ff", cfg.d_ff)):
            if val % tp:
                raise ValueError(f"{nm}={val} not divisible by tp={tp}")
        tp_axis = "tp"
    x = params["embed"].astype(cfg.dtype)[tokens]
    layer_fn = _remat_wrap(
        partial(_layer, cfg=cfg, mesh=None, tp_axis=tp_axis,
                tp_manual_vjp=(cfg.pp_schedule == "1f1b"),
                local_ep_axis=(cfg.ep_axis if ep_in_stage else None)),
        cfg,
    )
    moe = bool(cfg.n_experts)

    if moe:
        def stage_fn(stage_layers, xb):
            def body(carry, lp):
                h, acc = carry
                out, aux = layer_fn(h, lp)
                acc = acc + jnp.stack(
                    [aux["lb_loss"], aux["z_loss"]]
                ).astype(jnp.float32)
                return (out, acc), None

            (out, acc), _ = jax.lax.scan(
                body, (xb, jnp.zeros((2,), jnp.float32)), stage_layers
            )
            return out, acc
    else:
        def stage_fn(stage_layers, xb):
            def body(h, lp):
                out, _ = layer_fn(h, lp)
                return out, None

            out, _ = jax.lax.scan(body, xb, stage_layers)
            return out

    per_stage = cfg.n_layers // n_virtual
    stage_params = jax.tree_util.tree_map(
        lambda a: a.reshape((n_virtual, per_stage) + a.shape[1:]),
        params["layers"],
    )
    if tp_axis:
        param_specs = _pp_param_specs(cfg, tp_axis)
    elif ep_in_stage:
        param_specs = _pp_param_specs_moe(cfg)
    else:
        param_specs = None
    res = pipeline_apply(
        stage_params, x, stage_fn, mesh, cfg.pp_microbatches, cfg.pp_axis,
        schedule=cfg.pp_schedule,
        # with ep-in-stage the ep axis is a pipeline DATA axis too: each
        # (dp, ep) coordinate pipelines its own token slice, and the MoE
        # layers all-to-all those slices to the expert owners over ep
        batch_axes=(("dp", "fsdp", cfg.ep_axis) if ep_in_stage
                    else ("dp", "fsdp")),
        param_specs=param_specs,
        aux_size=2 if moe else 0,
        n_chunks=cfg.pp_chunks,
    )
    if moe:
        h, aux_sums = res
        # sums over (layers x microbatches) -> the means the loss head
        # expects (matching the non-pp per-layer-mean semantics up to
        # microbatched load-balance fractions)
        denom = cfg.n_layers * cfg.pp_microbatches
        aux = {
            "lb_loss": aux_sums[0] / denom,
            "z_loss": aux_sums[1] / denom,
            "expert_load": None,  # per-layer telemetry not carried via pp
            "drop_frac": None,
        }
        return _rms_norm(h, params["final_norm"], cfg.norm_eps), aux
    return _rms_norm(res, params["final_norm"], cfg.norm_eps), None


def transformer_hidden(params, tokens, cfg: TransformerConfig, mesh=None,
                       with_aux: bool = False):
    """tokens: [b, t] int32 -> final-norm hidden states [b, t, d] (cfg.dtype).

    ``with_aux`` also returns the MoE router aux dict (None for dense):
    {"lb_loss", "z_loss" — mean over layers, unweighted;
    "expert_load" [L, E], "drop_frac" [L] — per layer, for telemetry}.

    With cfg.pp_microbatches set and a pp axis in the mesh, the layer
    stack runs as a GPipe pipeline (transformer_hidden_pp)."""
    if _use_pipeline(cfg, mesh):
        h, aux = transformer_hidden_pp(params, tokens, cfg, mesh)
        return (h, aux) if with_aux else h
    # Pin the layer-scan carry to the canonical activation layout (batch
    # over the data axes) for MoE configs. A while-loop carry must keep
    # ONE sharding across init/body-input/body-output; the MoE body
    # contains moe_apply's shard_map, whose in/out specs constrain the
    # flat token slab to P((dp, fsdp, ep)) — that 8-way sharding
    # propagates through the entry/exit reshapes onto the carry, while
    # the embedding gather hands the INIT a d-over-fsdp layout (the ZeRO
    # table sharding) and the rest of the body settles on (dp, fsdp)
    # batch sharding. GSPMD reconciles the disagreeing carry specs with
    # an "involuntary full rematerialization" (replicate + re-slice) of
    # the carry every iteration — the moe-fsdp warning pair the r5
    # verdict pinned. Two anchors fix the disagreement at its sources:
    # the embedding TABLE is read through a replicated constraint (the
    # all-gather ZeRO pays at first use anyway, made explicit so the
    # gather's output is batch-sharded like the loop), and the body
    # output re-anchors after the MoE layer (see _moe_mlp's matching
    # anchor). Dense configs are unaffected.
    carry_anchor = None
    if mesh is not None and getattr(mesh, "devices", None) is not None:
        data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
        # scoped to MoE-on-ep-mesh: only moe_apply's shard_map injects
        # the competing token spec; elsewhere propagation is already
        # consistent and anchors would just constrain it for nothing
        if (cfg.n_experts and data_axes
                and cfg.ep_axis in mesh.axis_names):
            from jax.sharding import NamedSharding, PartitionSpec as P

            carry_anchor = NamedSharding(mesh, P(data_axes, None, None))
    et = params["embed"].astype(cfg.dtype)
    if carry_anchor is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        et = jax.lax.with_sharding_constraint(
            et, NamedSharding(mesh, P(None, None))
        )
    x = et[tokens]
    if carry_anchor is not None:
        # The token-embedding-gradient scatter-add (this gather's
        # transpose) accumulates into the table's layout; handing it the
        # batch-sharded backward cotangent makes GSPMD replicate +
        # re-slice it INVOLUNTARILY (the last remat warning of the
        # moe-fsdp pass). The movement is unavoidable — the cotangent
        # genuinely changes layout axes — so do the same replicate
        # explicitly in the backward only: identity forward, cotangent
        # constrained replicated. Same bytes on the wire, zero warnings,
        # and the forward pays nothing.
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(mesh, P(*(None,) * x.ndim))

        @jax.custom_vjp
        def _bwd_replicate(a):
            return a

        def _br_fwd(a):
            return a, None

        def _br_bwd(_, g):
            return (jax.lax.with_sharding_constraint(g, rep),)

        _bwd_replicate.defvjp(_br_fwd, _br_bwd)
        x = _bwd_replicate(x)

    layer_fn = _remat_wrap(partial(_layer, cfg=cfg, mesh=mesh), cfg)

    def scan_body(x, layer_params):
        if carry_anchor is not None:
            # input-side: without this, the moe shard_map's 8-way token
            # spec back-propagates through rms_norm/reshape onto the
            # while-body PARAMETER and outvotes the output-side anchor
            x = jax.lax.with_sharding_constraint(x, carry_anchor)
        new_x, aux = layer_fn(x, layer_params)  # (new_x, per-layer aux or None)
        if carry_anchor is not None:
            new_x = jax.lax.with_sharding_constraint(new_x, carry_anchor)
        return new_x, aux

    x, aux_stack = jax.lax.scan(scan_body, x, params["layers"])
    if carry_anchor is not None:
        # exit anchor: pins the BACKWARD scan's carry init too — the
        # transpose of this constraint re-anchors the loss head's
        # incoming cotangent before it becomes the reverse while carry,
        # so the fused-CE block walk and the backward loop agree on the
        # batch layout instead of full-rematerializing per layer
        x = jax.lax.with_sharding_constraint(x, carry_anchor)
    h = _rms_norm(x, _anchored_gamma(params["final_norm"], cfg, mesh),
                  cfg.norm_eps)
    if not with_aux:
        return h
    if aux_stack is None:
        return h, None
    aux = {
        "lb_loss": jnp.mean(aux_stack["lb_loss"]),
        "z_loss": jnp.mean(aux_stack["z_loss"]),
        "expert_load": aux_stack["expert_load"],  # [L, E]
        "drop_frac": aux_stack["drop_frac"],  # [L]
    }
    return h, aux


def transformer_forward(params, tokens, cfg: TransformerConfig, mesh=None):
    """tokens: [b, t] int32 -> logits [b, t, vocab] (f32)."""
    x = transformer_hidden(params, tokens, cfg, mesh)
    # tied output head: embed^T
    return (x @ params["embed"].astype(cfg.dtype).T).astype(jnp.float32)


MASK_TOKEN = 0


def lm_loss_and_metrics(params, tokens, cfg: TransformerConfig, mesh=None, key=None,
                        mask_rate=0.15):
    """Causal: next-token cross entropy. Bidirectional (BERT-class): masked
    language modeling — ``mask_rate`` of positions are replaced with
    MASK_TOKEN and only those positions contribute to the loss (training on
    unmasked inputs would be degenerate identity reconstruction).

    Returns (total_loss, metrics). For MoE configs the total includes the
    weighted router losses and metrics carries the router telemetry:
    ce_loss, moe_lb_loss, moe_z_loss (unweighted), moe_expert_entropy
    (mean over layers, nats — uniform routing = ln(E)), moe_drop_frac."""
    def _hidden(inp):
        return transformer_hidden(params, inp, cfg, mesh, with_aux=True)

    def _ce_operands(flat_h, embed):
        # MoE on a multi-axis mesh (r6): pin the fused-CE block walk to
        # the batch-sharded layout with the EMBED all-gathered. Left to
        # propagation, the ZeRO-sharded embed (d over fsdp, a TRANSPOSED
        # device order on the dp×fsdp×ep mesh) pulls the CE loop's xs/dx
        # carries toward d-over-fsdp while the anchored hidden states
        # arrive batch-sharded — and converting between differently
        # ORDERED tile assignments is exactly what GSPMD can only do by
        # involuntary full rematerialization, once per block per layer.
        # On the single-axis fsdp mesh propagation picks one consistent
        # d-sharded assignment and none of this is needed (no warnings
        # there at the seed); the anchor is scoped to ep meshes. The
        # all-gathered embed transient is vocab·d·dtype — at mixtral
        # shapes ~256 MB bf16, far below the [b·t, vocab] psum the
        # d-sharded assignment pays instead.
        if not (cfg.n_experts and mesh is not None
                and getattr(mesh, "devices", None) is not None
                and cfg.ep_axis in getattr(mesh, "axis_names", ())):
            return flat_h, embed
        data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
        if not data_axes:
            return flat_h, embed
        from jax.sharding import NamedSharding, PartitionSpec as P

        flat_h = jax.lax.with_sharding_constraint(
            flat_h, NamedSharding(mesh, P(data_axes, None)))
        embed = jax.lax.with_sharding_constraint(
            embed, NamedSharding(mesh, P(None, None)))
        return flat_h, embed

    if cfg.causal:
        if cfg.fused_xent:
            from tf_operator_tpu.ops.fused_cross_entropy import fused_cross_entropy

            h, aux = _hidden(tokens)
            h = h[:, :-1]
            b, t, d = h.shape
            ce = fused_cross_entropy(
                *_ce_operands(h.reshape(b * t, d), params["embed"]),
                tokens[:, 1:].reshape(b * t),
            )
        else:
            h, aux = _hidden(tokens)
            logits = (h @ params["embed"].astype(cfg.dtype).T).astype(jnp.float32)
            targets = tokens[:, 1:]
            logits = logits[:, :-1]
            logp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
            ce = -jnp.mean(ll)
    else:
        if key is None:
            key = jax.random.PRNGKey(0)
        mask = jax.random.bernoulli(key, mask_rate, tokens.shape)
        inputs = jnp.where(mask, MASK_TOKEN, tokens)
        h, aux = _hidden(inputs)
        if cfg.fused_xent:
            from tf_operator_tpu.ops.fused_cross_entropy import fused_cross_entropy

            b, t, d = h.shape
            ce = fused_cross_entropy(
                *_ce_operands(h.reshape(b * t, d), params["embed"]),
                tokens.reshape(b * t),
                weights=mask.reshape(b * t),
            )
        else:
            logits = (h @ params["embed"].astype(cfg.dtype).T).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(logp, tokens[..., None], axis=-1)[..., 0]
            denom = jnp.maximum(jnp.sum(mask), 1)
            ce = -jnp.sum(ll * mask) / denom

    metrics = {"ce_loss": ce}
    total = ce
    if aux is not None:
        total = (
            ce
            + cfg.moe_aux_weight * aux["lb_loss"]
            + cfg.moe_zloss_weight * aux["z_loss"]
        )
        metrics.update(moe_lb_loss=aux["lb_loss"], moe_z_loss=aux["z_loss"])
        if aux.get("expert_load") is not None:
            # per-layer router telemetry (absent under pipeline parallelism
            # — only the scalar losses ride the pp aux channel)
            load = aux["expert_load"]  # [L, E]
            p = load / jnp.maximum(jnp.sum(load, axis=-1, keepdims=True), 1e-9)
            entropy = -jnp.sum(p * jnp.log(jnp.maximum(p, 1e-9)), axis=-1)  # [L]
            metrics.update(
                moe_expert_entropy=jnp.mean(entropy),
                moe_drop_frac=jnp.mean(aux["drop_frac"]),
            )
    return total, metrics


def lm_loss(params, tokens, cfg: TransformerConfig, mesh=None, key=None, mask_rate=0.15):
    """Scalar training loss (lm_loss_and_metrics without the telemetry);
    includes the weighted MoE router losses for MoE configs."""
    total, _ = lm_loss_and_metrics(params, tokens, cfg, mesh, key, mask_rate)
    return total


def preset(name: str, **overrides) -> TransformerConfig:
    return replace(PRESETS[name], **overrides)


# Workload-dict keys accepted as TransformerConfig overrides. ONE set for
# every role reading the shared spec.workload (trainer lm.py, evaluator
# eval.py) — duplicated sets would let the roles build different configs
# from the same dict and fail at checkpoint restore.
CONFIG_OVERRIDE_FIELDS = frozenset(
    {
        "vocab", "d_model", "n_layers", "n_heads", "n_kv_heads", "d_ff",
        "max_seq", "causal", "remat", "fused_xent", "n_experts",
        "moe_top_k", "capacity_factor", "moe_aux_weight", "moe_zloss_weight",
        "moe_dispatch", "pp_microbatches", "pp_schedule",
    }
)


def preset_from_workload(workload: Dict[str, Any]) -> TransformerConfig:
    """TransformerConfig from a TPUJob workload dict: ``preset`` plus any
    CONFIG_OVERRIDE_FIELDS, with ``attn`` mapping to ``attn_impl``."""
    overrides = {k: workload[k] for k in CONFIG_OVERRIDE_FIELDS if k in workload}
    if workload.get("attn") in ("ring", "ulysses", "flash", "dense"):
        overrides["attn_impl"] = workload["attn"]
    return preset(workload.get("preset", "tiny"), **overrides)
