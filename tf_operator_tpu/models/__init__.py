"""Model families: transformer (GPT/Llama/BERT-class) and ResNet.

These correspond to the reference's benchmark workload families
(BASELINE.json configs: MNIST-DP, ResNet-50 ImageNet, BERT-base, Llama-2-7B)
— the reference itself contains no model code (its workloads live in user
containers); here they are first-class library code, TPU-first:

- pure functional param pytrees (no framework state), so pjit/shard_map
  compose directly;
- every parameter carries *logical axis names* consumed by
  parallel.sharding.ShardingRules — switching DP/FSDP/TP/CP is a rules
  change, not a model change;
- layers stored stacked [n_layers, ...] and applied with lax.scan for
  O(1)-in-depth compile time, with optional jax.checkpoint rematerialization;
- bfloat16 activations / float32 params+optimizer by default (MXU-friendly).
"""

from tf_operator_tpu.models.transformer import (  # noqa: F401
    TransformerConfig,
    transformer_forward,
    init_transformer,
    transformer_logical_axes,
    lm_loss,
    PRESETS,
)
from tf_operator_tpu.models.resnet import (  # noqa: F401
    ResNetConfig,
    init_resnet,
    resnet_forward,
    resnet_logical_axes,
)
