"""Version/build-identity print (reference: pkg/version — the operator
binaries print version + git SHA at startup; same contract here)."""

from __future__ import annotations

import os
import subprocess

from tf_operator_tpu import __version__


def git_sha(length: int = 0, honor_env: bool = True) -> str:
    """Best-effort build SHA — THE one implementation (release/artifact
    tooling imports this; keep copies from diverging): env override
    (TPUJOB_GIT_SHA — release artifacts bake it in) then git, but only
    when the package actually lives in a source checkout (a pip-installed
    copy inside someone else's repo must not report THAT repo's HEAD).
    Empty when neither applies. ``length`` truncates (0 = full);
    ``honor_env=False`` forces the real checkout HEAD — release tooling
    must record the commit it actually archives, never a baked-in
    override."""
    sha = os.environ.get("TPUJOB_GIT_SHA", "") if honor_env else ""
    if not sha:
        root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        if not os.path.exists(os.path.join(root, ".git")):
            return ""
        try:
            sha = subprocess.run(
                ["git", "rev-parse", "HEAD"],
                capture_output=True, text=True, timeout=5, cwd=root,
            ).stdout.strip()
        except Exception:  # noqa: BLE001
            return ""
    return sha[:length] if length else sha


def version_string() -> str:
    sha = git_sha(length=7)
    return f"tf-operator-tpu {__version__}" + (f" ({sha})" if sha else "")


def add_version_flag(parser) -> None:
    """--version on a CLI parser, LAZILY: the git subprocess only runs when
    the flag is actually passed (eager evaluation would tax every daemon
    start and every test building a parser)."""
    import argparse

    class _Version(argparse.Action):
        def __init__(self, option_strings, dest, **kw):
            super().__init__(option_strings, dest, nargs=0, **kw)

        def __call__(self, parser, namespace, values, option_string=None):
            print(version_string())
            parser.exit()

    parser.add_argument("--version", action=_Version,
                        help="print version + build sha and exit")
