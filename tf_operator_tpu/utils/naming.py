"""Name generation for managed objects.

Reference parity: pkg/util/util.go:30-75 (RandString over a DNS-safe
alphabet) and pkg/trainer/replicas.go:520-526 (genName
⟨job⟩-⟨type⟩-⟨runtimeid⟩-⟨index⟩ with the job name truncated to 40 chars).
"""

from __future__ import annotations

import random
import string

# DNS-1035-safe: lowercase alphanumerics (names may be used as hostnames).
_ALPHABET = string.ascii_lowercase + string.digits
_MAX_JOB_NAME = 40


def rand_string(n: int, rng: random.Random | None = None) -> str:
    r = rng or random
    return "".join(r.choice(_ALPHABET) for _ in range(n))


def gen_runtime_id(rng: random.Random | None = None) -> str:
    """4-char run id, regenerated per job incarnation (training.go:214-248)."""
    return rand_string(4, rng)


def gen_name(job_name: str, replica_type: str, runtime_id: str, index: int) -> str:
    return f"{job_name[:_MAX_JOB_NAME]}-{replica_type.lower()}-{runtime_id}-{index}"
