"""Utilities: exit-code taxonomy, naming, logging.

Reference parity: pkg/util (util.go, train/train_util.go, k8sutil).
"""

from tf_operator_tpu.utils.exit_codes import (  # noqa: F401
    ExitClass,
    classify_exit_code,
    is_permanent,
    is_preemption,
    is_retryable,
)
from tf_operator_tpu.utils.naming import gen_name, gen_runtime_id, rand_string  # noqa: F401
