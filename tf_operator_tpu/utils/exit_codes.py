"""Exit-code taxonomy driving restart decisions.

Reference parity: pkg/util/train/train_util.go:18-53 — permanent failures
{1, 2, 126, 127, 128, 139}, retryable {130, 137, 143} (SIGINT/SIGKILL/SIGTERM
— infrastructure evictions), and 138 (128+SIGUSR1) reserved as the
user-defined "please retry me" code. OOM is always permanent
(pkg/trainer/training.go:193-206): retrying an OOM on identical hardware
just OOMs again.

TPU-native addition: exit codes raised by TPU runtime preemption/maintenance
events are retryable — on Cloud TPU a preemption is the moral equivalent of
the reference's pod eviction.
"""

from __future__ import annotations

import enum


class ExitClass(enum.Enum):
    SUCCEEDED = "Succeeded"
    RETRYABLE = "Retryable"
    PERMANENT = "Permanent"


# Semantics preserved from train_util.go:18-53. Retryable codes are
# 128+signal for external kill/eviction signals INT, KILL, TERM.
PERMANENT_CODES = frozenset({1, 2, 126, 127, 128, 139})
RETRYABLE_CODES = frozenset(128 + sig for sig in (2, 9, 15))  # {130, 137, 143}
USER_RETRYABLE_CODE = 138  # 128 + SIGUSR1: workload asks to be restarted


def classify_exit_code(code: int, oom_killed: bool = False) -> ExitClass:
    """Classify a process exit code.

    ``oom_killed`` mirrors the reference's OOMKilled-reason override
    (training.go:193-206): permanent regardless of code.
    """
    if oom_killed:
        return ExitClass.PERMANENT
    if code == 0:
        return ExitClass.SUCCEEDED
    if code < 0:  # Python subprocess convention: -N means killed by signal N
        code = 128 + (-code)
    if code == USER_RETRYABLE_CODE:
        return ExitClass.RETRYABLE
    if code in RETRYABLE_CODES:
        return ExitClass.RETRYABLE
    if code in PERMANENT_CODES:
        return ExitClass.PERMANENT
    # Unknown nonzero codes: the reference treats unrecognized codes as
    # permanent by falling through its whitelist; keep that conservatism.
    return ExitClass.PERMANENT


def is_retryable(code: int, oom_killed: bool = False) -> bool:
    return classify_exit_code(code, oom_killed) is ExitClass.RETRYABLE


def is_permanent(code: int, oom_killed: bool = False) -> bool:
    return classify_exit_code(code, oom_killed) is ExitClass.PERMANENT
