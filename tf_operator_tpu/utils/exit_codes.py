"""Exit-code taxonomy driving restart decisions.

Reference parity: pkg/util/train/train_util.go:18-53 — permanent failures
{1, 2, 126, 127, 128, 139}, retryable {130, 137, 143} (SIGINT/SIGKILL/SIGTERM
— infrastructure evictions), and 138 (128+SIGUSR1) reserved as the
user-defined "please retry me" code. OOM is always permanent
(pkg/trainer/training.go:193-206): retrying an OOM on identical hardware
just OOMs again.

TPU-native addition: exit codes raised by TPU runtime preemption/maintenance
events are retryable — on Cloud TPU a preemption is the moral equivalent of
the reference's pod eviction.
"""

from __future__ import annotations

import enum


class ExitClass(enum.Enum):
    SUCCEEDED = "Succeeded"
    RETRYABLE = "Retryable"
    # Preemption-retryable: the process was evicted by infrastructure
    # (SIGTERM during a host drain, SIGINT eviction). Restarted like
    # RETRYABLE, but the restart is a *preemption* restart — it carries a
    # distinct cause in status and does not count against backoff_limit
    # (crash-looping workloads consume backoff; being evicted must not).
    PREEMPTED = "Preempted"
    PERMANENT = "Permanent"


# Semantics preserved from train_util.go:18-53. Retryable codes are
# 128+signal for external kill/eviction signals INT, KILL, TERM; the
# graceful-eviction pair (INT, TERM) classifies as PREEMPTED — a drained
# host SIGTERMs its children (exit 143) and that is infrastructure's
# doing, not the workload's.
PERMANENT_CODES = frozenset({1, 2, 126, 127, 128, 139})
PREEMPTION_CODES = frozenset(128 + sig for sig in (2, 15))  # {130, 143}
RETRYABLE_CODES = frozenset({128 + 9})  # {137}: SIGKILL-class infra loss
USER_RETRYABLE_CODE = 138  # 128 + SIGUSR1: workload asks to be restarted


def classify_exit_code(code: int, oom_killed: bool = False) -> ExitClass:
    """Classify a process exit code.

    ``oom_killed`` mirrors the reference's OOMKilled-reason override
    (training.go:193-206): permanent regardless of code.
    """
    if oom_killed:
        return ExitClass.PERMANENT
    if code == 0:
        return ExitClass.SUCCEEDED
    if code < 0:  # Python subprocess convention: -N means killed by signal N
        code = 128 + (-code)
    if code == USER_RETRYABLE_CODE:
        return ExitClass.RETRYABLE
    if code in PREEMPTION_CODES:
        return ExitClass.PREEMPTED
    if code in RETRYABLE_CODES:
        return ExitClass.RETRYABLE
    if code in PERMANENT_CODES:
        return ExitClass.PERMANENT
    # Unknown nonzero codes: the reference treats unrecognized codes as
    # permanent by falling through its whitelist; keep that conservatism.
    return ExitClass.PERMANENT


def is_retryable(code: int, oom_killed: bool = False) -> bool:
    """True for any restartable failure — plain retryable OR preemption."""
    return classify_exit_code(code, oom_killed) in (
        ExitClass.RETRYABLE,
        ExitClass.PREEMPTED,
    )


def is_preemption(code: int, oom_killed: bool = False) -> bool:
    return classify_exit_code(code, oom_killed) is ExitClass.PREEMPTED


def is_permanent(code: int, oom_killed: bool = False) -> bool:
    return classify_exit_code(code, oom_killed) is ExitClass.PERMANENT
