"""Exit-code taxonomy driving restart decisions.

Reference parity: pkg/util/train/train_util.go:18-53 — permanent failures
{1, 2, 126, 127, 128, 139}, retryable {130, 137, 143} (SIGINT/SIGKILL/SIGTERM
— infrastructure evictions), and 138 (128+SIGUSR1) reserved as the
user-defined "please retry me" code. OOM is always permanent
(pkg/trainer/training.go:193-206): retrying an OOM on identical hardware
just OOMs again.

TPU-native addition: exit codes raised by TPU runtime preemption/maintenance
events are retryable — on Cloud TPU a preemption is the moral equivalent of
the reference's pod eviction.
"""

from __future__ import annotations

import enum
import os


class ExitClass(enum.Enum):
    SUCCEEDED = "Succeeded"
    RETRYABLE = "Retryable"
    # Preemption-retryable: the process was evicted by infrastructure
    # (SIGTERM during a host drain, SIGINT eviction). Restarted like
    # RETRYABLE, but the restart is a *preemption* restart — it carries a
    # distinct cause in status and does not count against backoff_limit
    # (crash-looping workloads consume backoff; being evicted must not).
    PREEMPTED = "Preempted"
    # Killed by the kernel OOM killer. Permanent under EXIT_CODE policy
    # (retrying on identical hardware just OOMs again, training.go:193-206)
    # but a distinct class: OOM presents as SIGKILL, exactly like
    # infrastructure loss, and conflating the two would let a memory-leaking
    # workload masquerade as preemption churn in every restart metric.
    OOM = "OOMKilled"
    PERMANENT = "Permanent"
    # Declared hung by the gang-progress watchdog (obs/watchdog.py): no
    # rank advanced a step for run_policy.hang_timeout_seconds while
    # heartbeats stayed live. Never produced by classify_exit_code — a
    # hang by definition has NO exit; the reconciler assigns this class
    # out-of-band when it shoots a wedged gang, so the resulting
    # controller-driven SIGKILLs are attributed to cause "hang" rather
    # than misread as infrastructure loss. Retryable under
    # ON_FAILURE/ALWAYS/EXIT_CODE and charged against backoff_limit.
    HUNG = "Hung"


# Semantics preserved from train_util.go:18-53. Retryable codes are
# 128+signal for external kill/eviction signals INT, KILL, TERM; the
# graceful-eviction pair (INT, TERM) classifies as PREEMPTED — a drained
# host SIGTERMs its children (exit 143) and that is infrastructure's
# doing, not the workload's.
PERMANENT_CODES = frozenset({1, 2, 126, 127, 128, 139})
PREEMPTION_CODES = frozenset(128 + sig for sig in (2, 15))  # {130, 143}
RETRYABLE_CODES = frozenset({128 + 9})  # {137}: SIGKILL-class infra loss
USER_RETRYABLE_CODE = 138  # 128 + SIGUSR1: workload asks to be restarted


def classify_exit_code(code: int, oom_killed: bool = False) -> ExitClass:
    """Classify a process exit code.

    ``oom_killed`` mirrors the reference's OOMKilled-reason override
    (training.go:193-206): OOM regardless of code — permanent for restart
    decisions (is_permanent is True), distinct for cause accounting.
    """
    if oom_killed:
        return ExitClass.OOM
    if code == 0:
        return ExitClass.SUCCEEDED
    if code < 0:  # Python subprocess convention: -N means killed by signal N
        code = 128 + (-code)
    if code == USER_RETRYABLE_CODE:
        return ExitClass.RETRYABLE
    if code in PREEMPTION_CODES:
        return ExitClass.PREEMPTED
    if code in RETRYABLE_CODES:
        return ExitClass.RETRYABLE
    if code in PERMANENT_CODES:
        return ExitClass.PERMANENT
    # Unknown nonzero codes: the reference treats unrecognized codes as
    # permanent by falling through its whitelist; keep that conservatism.
    return ExitClass.PERMANENT


def is_retryable(code: int, oom_killed: bool = False) -> bool:
    """True for any restartable failure — plain retryable OR preemption."""
    return classify_exit_code(code, oom_killed) in (
        ExitClass.RETRYABLE,
        ExitClass.PREEMPTED,
    )


def is_preemption(code: int, oom_killed: bool = False) -> bool:
    return classify_exit_code(code, oom_killed) is ExitClass.PREEMPTED


def is_permanent(code: int, oom_killed: bool = False) -> bool:
    return classify_exit_code(code, oom_killed) in (
        ExitClass.PERMANENT,
        ExitClass.OOM,
    )


# ---- OOM detection -------------------------------------------------------
# The kernel's OOM killer delivers SIGKILL, so an OOM exit is
# indistinguishable from infrastructure loss (exit 137) by code alone. The
# reference reads the container runtime's OOMKilled reason; a bare host's
# nearest oracle is the supervising cgroup's memory.events counter — the
# backend snapshots it around each child's lifetime and promotes
# SIGKILL-shaped exits to OOM only when the counter advanced.

def read_cgroup_oom_kills() -> "int | None":
    """Cumulative ``oom_kill`` count of this process's cgroup (v2 unified
    hierarchy), or None when no oracle is available (cgroup v1, non-Linux,
    masked /sys). Children spawned without cgroup delegation share the
    parent's cgroup, so a delta across a child's lifetime implicates it —
    best-effort (a sibling's OOM in the same cgroup also advances it), but
    strictly better than the code-only guess."""
    try:
        with open("/proc/self/cgroup") as f:
            path = ""
            for line in f:
                # v2 unified entry: "0::/<path>"
                if line.startswith("0::"):
                    path = line.split("::", 1)[1].strip()
                    break
        events = os.path.join("/sys/fs/cgroup", path.lstrip("/"), "memory.events")
        with open(events) as f:
            for line in f:
                if line.startswith("oom_kill "):
                    return int(line.split()[1])
    except (OSError, ValueError, IndexError):
        return None
    return None


def was_oom_killed(
    code: int,
    oom_kills_before: "int | None" = None,
    oom_kills_after: "int | None" = None,
) -> bool:
    """The SIGKILL→OOM promotion, in the taxonomy proper: an exit counts
    as OOM-killed iff it is SIGKILL-shaped (the only signal the OOM killer
    sends) AND the supervising cgroup's oom_kill counter advanced across
    the child's lifetime. Without an oracle (either count None) it stays
    conservative: False — a bare SIGKILL remains retryable infrastructure
    loss, never a guessed OOM."""
    if code not in (137, -9):
        return False
    if oom_kills_before is None or oom_kills_after is None:
        return False
    return oom_kills_after > oom_kills_before
