"""Shared-secret bearer-token auth for the API surface.

The reference's control plane rode Kubernetes auth: every client goes
through kubeconfig/in-cluster credentials
(/root/reference/pkg/util/k8sutil/k8sutil.go:53-77) and the dashboard
talks to the authenticated apiserver
(/root/reference/dashboard/backend/client/manager.go:13-45). This repo's
substrate has no apiserver to lean on, so the store/dashboard server owes
its own check — especially since --store-only / --store-server made an
exposed store the advertised HA topology (VERDICT r2 missing #1).

Model: ONE shared secret per cluster, provisioned by file or env.
When the server is started with a token, it requires
``Authorization: Bearer <token>`` on

- every mutating route (POST/PUT/DELETE — job submit, object writes), and
- the whole generic object API (/api/v1/**, including the watch stream) —
  that surface is the machine seam (agents, HA operators, informers,
  evaluator write-back), all of which can carry credentials.

Read-only human routes (/ui, job list/detail, events, logs, /metrics,
/healthz) stay open, matching the reference dashboard's in-cluster
read-through. Missing/wrong token -> 401 with no detail.

Provisioning order (first hit wins): explicit value, explicit file,
$TPUJOB_AUTH_TOKEN, file named by $TPUJOB_AUTH_TOKEN_FILE. The
controller injects the token into child-process env so workloads
(evaluator status write-back) inherit it without touching job specs.
"""

from __future__ import annotations

import hmac
import os
from typing import Optional

ENV_AUTH_TOKEN = "TPUJOB_AUTH_TOKEN"
ENV_AUTH_TOKEN_FILE = "TPUJOB_AUTH_TOKEN_FILE"


def resolve_token(
    token: Optional[str] = None, token_file: Optional[str] = None
) -> Optional[str]:
    """Resolve the shared secret (None = auth disabled / anonymous client).
    Surrounding whitespace/newlines are stripped (token files end in \\n)."""
    if token:
        return token.strip() or None
    if token_file:
        with open(token_file) as f:
            return f.read().strip() or None
    env = os.environ.get(ENV_AUTH_TOKEN, "")
    if env.strip():
        return env.strip()
    env_file = os.environ.get(ENV_AUTH_TOKEN_FILE, "")
    if env_file:
        with open(env_file) as f:
            return f.read().strip() or None
    return None


def check_bearer(header_value: Optional[str], expected: str) -> bool:
    """Constant-time check of an ``Authorization`` header against the
    expected token."""
    if not header_value or not header_value.startswith("Bearer "):
        return False
    presented = header_value[len("Bearer "):].strip()
    return hmac.compare_digest(presented.encode(), expected.encode())


def bearer_headers(token: Optional[str]) -> dict:
    """Client-side header dict ({} when anonymous)."""
    return {"Authorization": f"Bearer {token}"} if token else {}
