"""Built-in workloads: the framework's example/test data-plane programs.

Reference parity: examples/tf_sample/tf_sample/tf_smoke.py (every-device op
check) and test/e2e/dist-mnist/dist_mnist.py (real distributed training run
used by CI). These are SPMD JAX programs launched by the harness; each
receives a JobContext and drives the whole device mesh collectively.
"""
