"""Chaos-soak workload: control-plane-faithful, data-plane-minimal.

The chaos soak (chaos/soak.py) exercises crash / preemption / drain /
warm-restart mechanics in the CONTROL plane; the data plane only needs to
make progress observable and resumable. This workload does exactly that
with no cross-process collectives (CI containers without a gloo-capable
jax cannot run multi-process SPMD — the real-collectives soak uses the lm
workload instead, selectable via ``chaos.soak --data-plane lm``):

- every gang member paces ``steps`` wall-clock steps of ``step_sleep_s``
  (long enough for faults to land mid-run);
- the chief (worker 0 / coordinator) drives the real checkpoint
  subsystem — ``train.checkpoint.CheckpointManager`` saves every
  ``checkpoint_every`` steps into ``checkpoint_dir`` and a resumed
  incarnation continues from ``latest_step()`` instead of step 0,
  logging the same "resumed from checkpoint at step N" line the
  restart-recovery e2e pins.

The warm-restart env contract is asserted here, not just logged: the
controller's declared ``TPUJOB_RESUME_STEP`` must never exceed what is
actually on disk (it may lag it — a checkpoint can land between creation
and restore, and the controller fences nothing on it)."""

from __future__ import annotations

import logging
import time

from tf_operator_tpu.rendezvous.context import JobContext

log = logging.getLogger("tpujob.soakwl")


def main(ctx: JobContext) -> None:
    wl = ctx.workload
    steps = int(wl.get("steps", 8))
    sleep_s = float(wl.get("step_sleep_s", 0.25))
    is_chief = ctx.replica_type == "Coordinator" or (
        ctx.replica_type == "Worker" and ctx.replica_index == 0
    )

    if not (is_chief and wl.get("checkpoint_dir")):
        # Non-chief members just pace the same wall clock; gang restart /
        # drain semantics act on them via signals, not their own logic.
        for i in range(steps):
            time.sleep(sleep_s)
            if i == 0:
                ctx.mark_first_step(1)
        return

    import numpy as np

    from tf_operator_tpu.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(
        wl["checkpoint_dir"], keep=int(wl.get("checkpoint_keep", 3))
    )
    every = int(wl.get("checkpoint_every", 2))
    start = mgr.latest_step() or 0
    if start:
        log.info("resumed from checkpoint at step %d", start)
    if ctx.resume_step > start:
        raise AssertionError(
            f"controller declared resume step {ctx.resume_step} but disk "
            f"has only {start} — the warm-restart env over-promised"
        )
    state = {"step": np.asarray(start)}
    for s in range(start + 1, steps + 1):
        time.sleep(sleep_s)
        state = {"step": np.asarray(s)}
        if s == start + 1:
            ctx.mark_first_step(s)
        if every and s % every == 0:
            t_save = time.time()
            mgr.save(s, state)
            ctx.record_span(
                "checkpoint-save", t_save, time.time(),
                attrs={"step": str(s), "track": "checkpoint"},
            )
    mgr.save(steps, state, wait=True)  # final save (no-op if step exists)
    mgr.close()
    log.info("soak workload done: steps=%d (resumed from %d)", steps, start)
