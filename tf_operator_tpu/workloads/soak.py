"""Chaos-soak workload: control-plane-faithful, data-plane-minimal.

The chaos soak (chaos/soak.py) exercises crash / preemption / drain /
warm-restart mechanics in the CONTROL plane; the data plane only needs to
make progress observable and resumable. This workload does exactly that
with no cross-process collectives (CI containers without a gloo-capable
jax cannot run multi-process SPMD — the real-collectives soak uses the lm
workload instead, selectable via ``chaos.soak --data-plane lm``):

- every gang member paces ``steps`` wall-clock steps of ``step_sleep_s``
  (long enough for faults to land mid-run);
- the chief (worker 0 / coordinator) drives the real checkpoint
  subsystem — ``train.checkpoint.CheckpointManager`` via
  ``WorkloadCheckpointer`` saves every ``checkpoint_every`` steps into
  ``checkpoint_dir``, pushes each COMMITTED step to the host shard depot
  (``TPUJOB_PEER_DEPOT``), and a resumed incarnation pulls warm state
  from a surviving peer's depot (``TPUJOB_RESTORE_PEERS``) before
  falling back to disk — logging the same "resumed from checkpoint at
  step N" line the restart-recovery e2e pins, plus the restore-source
  span the p2p soak invariant reads.

``disk_restore_delay_s`` models the flagship-scale disk fetch (the
multi-minute object-store read a real multi-TB restore pays): a resumed
chief sleeps that long when — and only when — its restore source is
disk. The peer path skips it, which is exactly the downtime the p2p
protocol exists to cut; the soak's compare mode measures that cut.

The warm-restart env contract is asserted here, not just logged: the
controller's declared ``TPUJOB_RESUME_STEP`` must never exceed what is
actually restorable (it may lag — a checkpoint can land between creation
and restore, and the controller fences nothing on it)."""

from __future__ import annotations

import logging
import os
import time

from tf_operator_tpu.chaos.faults import WEDGE_MARKER
from tf_operator_tpu.rendezvous.context import JobContext

log = logging.getLogger("tpujob.soakwl")


def _wedge_marker(ctx: JobContext, wl: dict) -> str:
    """Path this member polls for the chaos HANG wedge, or "" when the
    wedge cannot apply. Warm incarnations (resume_step > 0) never wedge:
    the marker is left on disk after the fault, and obeying it again
    would hang the recovery the soak is trying to prove."""
    if ctx.resume_step or not wl.get("checkpoint_dir"):
        return ""
    return os.path.join(str(wl["checkpoint_dir"]), WEDGE_MARKER)


def _fake_collective_all_reduce(ctx: JobContext, step: int) -> None:
    """The wedge: block forever, exactly like an all-reduce whose peer
    never arrives. Deliberately a NAMED function — the hang soak greps
    every rank's SIGUSR2 stack dump for this frame, proving the
    faulthandler hook captures *where* each rank is stuck, not just that
    it is. The process stays alive and signal-handling (PEP 475 retries
    the sleep after SIGUSR2), so heartbeats keep flowing while step
    progress is dead — the watchdog's exact target."""
    log.warning(
        "chaos wedge: rank %d entering fake collective at step %d "
        "(will never return)", ctx.process_id, step,
    )
    while True:
        time.sleep(1.0)


def main(ctx: JobContext) -> None:
    wl = ctx.workload
    steps = int(wl.get("steps", 8))
    sleep_s = float(wl.get("step_sleep_s", 0.25))
    is_chief = ctx.replica_type == "Coordinator" or (
        ctx.replica_type == "Worker" and ctx.replica_index == 0
    )

    # Step telemetry (r13): every member reports step batches through the
    # ring; `slow_ranks` + `slow_extra_s` let the telemetry bench model a
    # deliberately slow host, `data_wait_s` injects input-pipeline stall
    # that goodput accounting must attribute to cause data-wait.
    data_wait_s = float(wl.get("data_wait_s", 0.0))
    extra_s = (
        float(wl.get("slow_extra_s", 0.0))
        if ctx.process_id in [int(r) for r in wl.get("slow_ranks", [])]
        else 0.0
    )
    rep = ctx.telemetry(
        flush_every=int(wl.get("telemetry_every", 2)),
        tokens_per_step=float(wl.get("tokens_per_step", 0.0)),
        flops_per_step=float(wl.get("flops_per_step", 0.0)),
    )

    wedge = _wedge_marker(ctx, wl)

    if not (is_chief and wl.get("checkpoint_dir")):
        # Non-chief members just pace the same wall clock; gang restart /
        # drain semantics act on them via signals, not their own logic.
        for i in range(steps):
            if wedge and os.path.exists(wedge):
                _fake_collective_all_reduce(ctx, i + 1)
            t0 = time.time()
            time.sleep(sleep_s + data_wait_s + extra_s)
            if i == 0:
                ctx.mark_first_step(1)
            if rep:
                rep.step(time.time() - t0, data_wait_s=data_wait_s)
        ctx.close_telemetry(rep)
        return

    import numpy as np

    from tf_operator_tpu.train.checkpoint import WorkloadCheckpointer

    ckpt = WorkloadCheckpointer(wl, ctx=ctx)
    mgr = ckpt.manager

    # Warm restore: peer depots first (materializes the committed step
    # locally), then disk — the same decision order run_loop follows.
    t0 = time.time()
    source = ckpt.prefetch_from_peers()
    start = mgr.latest_step() or 0
    state = {"step": np.asarray(start)}
    if start:
        if source == "disk":
            # Model the flagship disk fetch: a real multi-TB restore pays
            # minutes of object-store reads the peer path skips entirely.
            time.sleep(float(wl.get("disk_restore_delay_s", 0.0)))
        state = mgr.restore(state)
        ckpt.restore_source = source
        log.info(
            "resumed from checkpoint at step %d (source=%s)", start, source
        )
        ctx.record_restore(source, start, t0, time.time())
    if ctx.resume_step > start:
        raise AssertionError(
            f"controller declared resume step {ctx.resume_step} but disk "
            f"has only {start} — the warm-restart env over-promised"
        )
    for s in range(start + 1, steps + 1):
        if wedge and os.path.exists(wedge):
            _fake_collective_all_reduce(ctx, s)
        # Step-boundary cadence poll (r16): the autopilot's
        # checkpoint_cadence_directive retunes ckpt.every live; re-read
        # it every step so the retuned interval governs THIS step's save.
        ckpt.poll_cadence_directive(step=s - 1)
        every = ckpt.every
        t0 = time.time()
        time.sleep(sleep_s + data_wait_s + extra_s)
        state = {"step": np.asarray(s)}
        if s == start + 1:
            ctx.mark_first_step(s)
        stall = 0.0
        if every and s % every == 0:
            if mgr.save(s, state):
                # `save_stall_extra_s` models the flagship-scale blocking
                # write (the multi-second device-sync + serialize a real
                # multi-TB save pays before the async drain takes over) —
                # the per-save cost the autopilot's Young/Daly retune
                # exists to amortize, exactly as disk_restore_delay_s
                # models the slow restore read.
                extra = float(wl.get("save_stall_extra_s", 0.0))
                if extra:
                    time.sleep(extra)
                now = time.time()
                stall = mgr.last_save_stall_s + extra
                ctx.record_save_stall(s, now - stall, now)
        if rep:
            rep.step(
                time.time() - t0, data_wait_s=data_wait_s,
                ckpt_stall_s=max(0.0, stall),
            )
    ctx.close_telemetry(rep)
    mgr.save(steps, state, wait=True)  # final save (no-op if step exists)
    mgr.close()
    log.info("soak workload done: steps=%d (resumed from %d)", steps, start)
