"""Elastic-soak workload: lock-step data consumption that survives
shrink/re-grow without losing or duplicating a single token.

Control-plane-faithful, data-plane-minimal (same constraint as
workloads/soak.py: CI containers cannot run multi-process SPMD), but
unlike the plain soak workload this one exercises the ELASTIC data
contract end to end:

- The corpus is ``total_windows`` abstract windows consumed in the
  canonical seeded order G (``train.data.elastic_global_order``) — the
  world-size-independent sequence every incarnation derives identically.
- Each epoch's active members own a round-robin deal of the REMAINING
  (not-yet-recorded) positions: rank r of n gets ``remaining[r::n]``.
  Consumption is durable-record-defined: a member consumes position p by
  appending ``{"p", "w", "t", "m", "e"}`` to its own
  ``consumed-<member>.jsonl`` in the shared workdir; a member killed
  before the append never consumed it, so its orphans fall back into
  ``remaining`` at the next re-carve with no bookkeeping of the corpse.
- Members poll the job's resize directive every step
  (``JobContext.poll_resize_directive``). On a new epoch, survivors ack
  (``ack-<member>-<epoch>``) and stop; the chief waits for every
  surviving ack, recomputes ``remaining`` from ALL recorded
  consumptions, deals it to the directive's member list, writes
  ``epoch-<E>.json`` atomically, and publishes barrier fields into the
  directive (``publish_resize_barrier``). Everyone then consumes from
  the new deal — the re-carve boundary the reconciler's directive
  promised.
- A re-grown member (created with ``TPUJOB_RESIZE_EPOCH`` > 0) waits for
  the directive to reach its epoch, pulls the latest committed
  checkpoint from a surviving peer's shard depot
  (``WorkloadCheckpointer.prefetch_from_peers`` + ``record_restore``)
  before touching disk, then joins the epoch's deal.
- When every position is recorded, the chief merges all records, asserts
  exactly-once coverage of [0, total_windows), and writes the eval
  digest (sha256 over the position-ordered (p, G[p]) stream) to
  ``workdir/eval_digest.txt`` + ``done.json``. A faulted run is
  bit-identical to an uninterrupted run at the same token count iff the
  digests match — the elastic soak's hard gate.
- ``device_state: true`` (r19) additionally carries a real (small)
  param/opt pytree on device through every resize: each member holds the
  full ``(total, PARAM_DIM)`` params + per-row momentum as jax arrays,
  applies a one-touch jitted row update per consumed position, and
  rebuilds the arrays at every re-carve boundary through
  ``train.reshard.rebuild_state`` — own device copy re-laid-out via
  pjit, rows other members advanced re-fetched from the shared row
  store, a re-grown member's warm base restored through the peer shard
  depot first. The update is computed from the deterministic init base
  (not the current row), so replaying a consume whose record was torn
  is idempotent. The chief's ``done.json`` then carries
  ``params_digest`` — sha256 over the final float32 params — and the
  soak gate becomes bit-identical params + eval digest vs the
  uninterrupted run.

Requires a workers-only gang (chief = worker 0), like the light soak
data plane.
"""

from __future__ import annotations

import glob
import hashlib
import json
import logging
import os
import time
from typing import Dict, List, Optional

from tf_operator_tpu.rendezvous.context import JobContext

log = logging.getLogger("tpujob.elasticwl")

_POLL_S = 0.05


def _member_name(ctx: JobContext) -> str:
    return f"{ctx.job_name}-{ctx.replica_type.lower()}-{ctx.replica_index}"


def _record_path(workdir: str, member: str) -> str:
    return os.path.join(workdir, f"consumed-{member}.jsonl")


def _read_records(workdir: str) -> List[dict]:
    """All durable consumption records; a torn final line (member killed
    mid-append) parses as nothing — that position was never consumed."""
    out = []
    for path in sorted(glob.glob(os.path.join(workdir, "consumed-*.jsonl"))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
    return out


def _epoch_path(workdir: str, epoch: int) -> str:
    return os.path.join(workdir, f"epoch-{epoch}.json")


def _latest_epoch_file(workdir: str, at_least: int) -> Optional[dict]:
    """The highest epoch-<E>.json with E >= at_least, if any."""
    best, best_e = None, -1
    for path in glob.glob(os.path.join(workdir, "epoch-*.json")):
        try:
            e = int(os.path.basename(path)[len("epoch-"):-len(".json")])
        except ValueError:
            continue
        if e >= at_least and e > best_e:
            best, best_e = path, e
    if best is None:
        return None
    try:
        with open(best) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _write_json_atomic(path: str, obj: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _deal(remaining: List[int], members: List[str]) -> Dict[str, List[int]]:
    """Round-robin the remaining positions over the members in rank
    order — the rank::n stride applied to whatever is left, so orphaned
    positions interleave with the untouched tail."""
    n = len(members)
    return {m: remaining[r::n] for r, m in enumerate(members)}


def _digest(records: List[dict], total: int) -> str:
    """Sha256 over the position-ordered consumed stream, duplicates
    included — a drop, a duplicate, or a different window at a position
    all change the digest."""
    h = hashlib.sha256()
    for rec in sorted(records, key=lambda r: (int(r["p"]), int(r["w"]))):
        h.update(f"{rec['p']}:{rec['w']};".encode())
    h.update(str(total).encode())
    return h.hexdigest()


def main(ctx: JobContext) -> None:
    import numpy as np

    from tf_operator_tpu.train.checkpoint import WorkloadCheckpointer
    from tf_operator_tpu.train.data import elastic_global_order

    wl = ctx.workload
    workdir = wl["workdir"]
    total = int(wl.get("total_windows", 48))
    sleep_s = float(wl.get("step_sleep_s", 0.15))
    order = elastic_global_order(total, seed=int(wl.get("data_seed", 0)))
    me = _member_name(ctx)
    is_chief = ctx.replica_type == "Worker" and ctx.replica_index == 0
    os.makedirs(workdir, exist_ok=True)

    ckpt = WorkloadCheckpointer(wl, ctx=ctx)
    mgr = ckpt.manager

    # -- device-state mode (r19) -----------------------------------------
    device_state = bool(wl.get("device_state"))
    R = None
    dev_params = dev_mom = None
    fresh: set = set()
    dim = int(wl.get("param_dim", 0))
    seed = int(wl.get("data_seed", 0))
    if device_state:
        import jax.numpy as jnp

        from tf_operator_tpu.train import reshard as _reshard
        R = _reshard
        dim = dim or R.PARAM_DIM
        sdir = R.state_dir(workdir)
        sharding = R.replicated_sharding(R.local_mesh())
        row_update = R.make_row_update()
        zero_mom = jnp.zeros((), jnp.float32)
        plan_total = R.ReshardPlan()

    # -- join ------------------------------------------------------------
    my_epoch = 0
    if ctx.resize_epoch > 0:
        # Re-grown member: the controller stamped the grow epoch at
        # creation. Do not touch the deal until the directive catches up
        # (it is published in the same sync, after our create).
        my_epoch = ctx.resize_epoch
        while True:
            d = ctx.poll_resize_directive()
            if d and int(d.get("epoch", 0)) >= my_epoch:
                my_epoch = int(d["epoch"])
                break
            time.sleep(_POLL_S)
        # Peer warm restore: pull the latest committed step from a
        # surviving host's shard depot before touching disk. Retried
        # briefly — a commit can be mid-push to the depot when we land.
        if mgr is not None:
            t0 = time.time()
            source = ckpt.prefetch_from_peers()
            deadline = time.time() + 3.0
            while source != "peer" and time.time() < deadline:
                time.sleep(0.2)
                source = ckpt.prefetch_from_peers()
            start = mgr.latest_step() or 0
            if start:
                tmpl = {"step": np.asarray(start)}
                if device_state:
                    # Warm base for the rebuild below: the chief's last
                    # committed params/momentum, sourced peer-depot-first.
                    # The row store overlays anything newer row by row.
                    tmpl["params"] = np.zeros((total, dim), np.float32)
                    tmpl["mom"] = np.zeros((total,), np.float32)
                mgr.restore(tmpl)
                ckpt.restore_source = source
                ctx.record_restore(source, start, t0, time.time())
                log.info("re-grown member restored step %d (source=%s)",
                         start, source)
    elif is_chief:
        # Epoch 0: the full gang in worker-index rank order, dealt the
        # whole corpus. A full restart at epoch 0 must NOT re-deal —
        # the surviving records already cover part of the corpus and
        # the assignment filter below skips them against the old doc.
        if not os.path.exists(_epoch_path(workdir, 0)):
            members = [f"{ctx.job_name}-worker-{i}"
                       for i in range(ctx.num_processes)]
            _write_json_atomic(_epoch_path(workdir, 0), {
                "epoch": 0, "direction": "start", "members": members,
                "positions": _deal(list(range(total)), members),
            })

    if is_chief and ctx.resize_epoch > 0:
        # Full gang restart mid-resize: the controller stamps EVERY
        # member (chief included) with the open resize epoch, so the
        # chief lands in the join path too. If the pre-restart chief
        # never wrote this epoch's deal, nobody else ever will — the
        # whole gang would wait forever on a doc only we can write.
        # Re-carve it here WITHOUT an ack barrier: a full restart means
        # no member is still consuming an older deal, and the durable
        # records are the complete consumption history. An existing doc
        # (restart landed after the re-carve) is reused as-is; the
        # assignment filter below drops recorded positions either way.
        live = ctx.poll_resize_directive()
        e = max(int(live.get("epoch", 0)) if live else 0, my_epoch)
        if e > 0 and _latest_epoch_file(workdir, e) is None:
            members = list(live.get("members", [])) if live else []
            if me in members:
                records = _read_records(workdir)
                seen = {int(r["p"]) for r in records}
                remaining = [p for p in range(total) if p not in seen]
                _write_json_atomic(_epoch_path(workdir, e), {
                    "epoch": e,
                    "direction": str(live.get("direction", "")),
                    "members": members,
                    "positions": _deal(remaining, members),
                    "reclaim": bool(live.get("reclaim", False)),
                })
                ctx.publish_resize_barrier(e, {
                    "completed": total - len(remaining),
                    "boundary_remaining": len(remaining),
                })
                log.info("%s re-carved epoch %d after full restart: %d "
                         "remaining", me, e, len(remaining))

    epoch_doc = None
    acked: set = set()
    while epoch_doc is None:
        epoch_doc = _latest_epoch_file(workdir, my_epoch)
        if epoch_doc is None:
            if ctx.resize_epoch > 0 and not is_chief:
                # Anyone in the join path (a re-grown member, or a
                # restarted survivor after a full mid-resize restart)
                # is by definition not consuming, so it can ack ANY
                # live barrier the moment it sees it. Without this, a
                # kill landing while we wait here deadlocks: the chief
                # counts us among the barrier's survivors while we wait
                # for the epoch doc it will only write after our ack.
                live = ctx.poll_resize_directive()
                e = int(live.get("epoch", 0)) if live else 0
                if e >= ctx.resize_epoch and e not in acked and \
                        me in live.get("members", []):
                    with open(os.path.join(workdir, f"ack-{me}-{e}"),
                              "w"):
                        pass
                    acked.add(e)
            time.sleep(_POLL_S)
    my_epoch = int(epoch_doc["epoch"])
    assignment = list(epoch_doc["positions"].get(me, []))
    if assignment:
        # A reused deal (full restart, or a joiner adopting a doc cut
        # before it landed) may contain positions whose records are
        # already durable — never consume a position twice.
        seen = {int(r["p"]) for r in _read_records(workdir)}
        assignment = [p for p in assignment if p not in seen]
    idx = 0
    consumed = 0
    rec_f = open(_record_path(workdir, me), "a")

    if device_state:
        # Initial rebuild: re-fetch every already-published row from the
        # shared store (covers re-grown joins and full restarts alike),
        # deterministic init for the untouched rest. The one-touch update
        # makes every fetched row final, so it stays authoritative across
        # all later re-carves.
        dev_params, dev_mom, plan = R.rebuild_state(
            total, dim, seed, sdir, None, None, set(), sharding,
            epoch=my_epoch)
        fresh = set(plan.authoritative)
        plan_total.merge(plan)

    def handle_resize(directive: dict) -> None:
        """Act on a directive whose epoch is ahead of ours."""
        nonlocal my_epoch, assignment, idx, epoch_doc
        nonlocal dev_params, dev_mom, fresh
        t0 = time.time()
        epoch = int(directive["epoch"])
        direction = str(directive.get("direction", ""))
        members = list(directive.get("members", []))
        if me not in members:
            # Shrunk out while still alive — not expected (the reconciler
            # only drops dead members), but exit cleanly rather than
            # consume positions nobody dealt us.
            log.warning("%s not in epoch %d members; exiting", me, epoch)
            rec_f.close()
            raise SystemExit(0)
        if is_chief:
            # Wait for every SURVIVING member of the current epoch to ack
            # (stop consuming) before recomputing the deal; dead members
            # are exactly those missing from the new member list.
            need = [m for m in members
                    if m != me and m in epoch_doc.get("members", [])]
            deadline = time.time() + 60.0
            while True:
                live = ctx.poll_resize_directive()
                if live and int(live.get("epoch", 0)) > epoch:
                    # Superseded mid-barrier; restart at the newer epoch.
                    handle_resize(live)
                    return
                missing = [m for m in need if not os.path.exists(
                    os.path.join(workdir, f"ack-{m}-{epoch}"))]
                if not missing:
                    break
                if time.time() > deadline:
                    raise AssertionError(
                        f"resize barrier {epoch}: no ack from {missing}")
                time.sleep(_POLL_S)
            rec_f.flush()
            records = _read_records(workdir)
            seen = {int(r["p"]) for r in records}
            remaining = [p for p in range(total) if p not in seen]
            _write_json_atomic(_epoch_path(workdir, epoch), {
                "epoch": epoch, "direction": direction, "members": members,
                "positions": _deal(remaining, members),
                # Over-spec reclaim shrinks back to the SPEC mesh — the
                # full mesh eval runs on — so the done gate must not hold
                # the final digest waiting for a re-grow nobody owes.
                "reclaim": bool(directive.get("reclaim", False)),
            })
            ctx.publish_resize_barrier(epoch, {
                "completed": total - len(remaining),
                "boundary_remaining": len(remaining),
            })
        else:
            # Ack, then wait for the chief's re-carve for this (or a
            # newer, superseding) epoch.
            with open(os.path.join(workdir, f"ack-{me}-{epoch}"), "w"):
                pass
            while _latest_epoch_file(workdir, epoch) is None:
                live = ctx.poll_resize_directive()
                if live and int(live.get("epoch", 0)) > epoch:
                    handle_resize(live)
                    return
                time.sleep(_POLL_S)
        epoch_doc = _latest_epoch_file(workdir, epoch)
        my_epoch = int(epoch_doc["epoch"])
        assignment = list(epoch_doc["positions"].get(me, []))
        idx = 0
        if device_state:
            # Re-shard for the new world: rows this member is still
            # authoritative for re-layout device-to-device, everything
            # another member advanced since the last barrier re-fetches
            # from the row store.
            dev_params, dev_mom, plan = R.rebuild_state(
                total, dim, seed, sdir, dev_params, dev_mom, fresh,
                sharding, epoch=my_epoch)
            fresh = set(plan.authoritative)
            plan_total.merge(plan)
        ctx.record_resize(direction, my_epoch, t0, time.time())
        log.info("%s re-carved at epoch %d (%s): %d positions",
                 me, my_epoch, direction, len(assignment))

    # -- consume ---------------------------------------------------------
    done_path = os.path.join(workdir, "done.json")
    while True:
        d = ctx.poll_resize_directive()
        if d and int(d.get("epoch", 0)) > my_epoch:
            handle_resize(d)
            continue
        if idx >= len(assignment):
            if os.path.exists(done_path):
                break
            if is_chief and (epoch_doc.get("direction") != "shrink"
                             or epoch_doc.get("reclaim")):
                # Eval runs on the full mesh: while the gang is shrunk a
                # re-grow is still owed, so hold the final digest until
                # the grow directive lands (the loop keeps polling).
                records = _read_records(workdir)
                if len({int(r["p"]) for r in records}) >= total:
                    positions = sorted(int(r["p"]) for r in records)
                    if positions != list(range(total)):
                        raise AssertionError(
                            f"elastic coverage broken: {len(positions)} "
                            f"records over {len(set(positions))} distinct "
                            f"positions, want {total} exactly once")
                    digest = _digest(records, total)
                    with open(os.path.join(workdir, "eval_digest.txt"),
                              "w") as f:
                        f.write(digest + "\n")
                    done = {
                        "digest": digest, "total": total,
                        "records": len(records),
                    }
                    if device_state:
                        final = R.assemble_final(total, dim, seed, sdir)
                        pdigest = R.params_digest(final)
                        with open(os.path.join(
                                workdir, "params_digest.txt"), "w") as f:
                            f.write(pdigest + "\n")
                        done["params_digest"] = pdigest
                        done["reshard"] = {
                            "relaid": plan_total.relaid,
                            "refetched": plan_total.refetched,
                            "inited": plan_total.inited,
                            "epochs": plan_total.epochs,
                        }
                    _write_json_atomic(done_path, done)
                    log.info("elastic run complete: %d windows, digest %s",
                             total, digest[:12])
                    break
            time.sleep(_POLL_S)
            continue
        p = assignment[idx]
        time.sleep(sleep_s)
        if device_state:
            # One-touch update computed from the deterministic init base,
            # NOT the current device row: a member killed after the row
            # write but before the record append leaves p in `remaining`,
            # and the re-consumer must recompute the identical bits.
            # Row published durably BEFORE the record — a durable record
            # implies a durable row, so the re-carve can trust the store.
            row, mom = row_update(
                jnp.asarray(R.init_row(seed, p, dim)), zero_mom,
                jnp.asarray(float(int(order[p])), jnp.float32))
            dev_params = dev_params.at[p].set(row)
            dev_mom = dev_mom.at[p].set(mom)
            R.write_row(sdir, p, np.asarray(row), float(np.asarray(mom)))
            fresh.add(p)
        rec_f.write(json.dumps({
            "p": int(p), "w": int(order[p]), "t": time.time(),
            "m": me, "e": my_epoch,
        }) + "\n")
        rec_f.flush()
        idx += 1
        consumed += 1
        if consumed == 1:
            ctx.mark_first_step(1)
        if is_chief and mgr is not None and ckpt.every and \
                consumed % ckpt.every == 0:
            state = {"step": np.asarray(consumed)}
            if device_state:
                # Committed params travel with the step so the depot
                # push is world-size-tagged alongside it — a re-grown
                # member's warm restore base.
                state["params"] = np.asarray(dev_params)
                state["mom"] = np.asarray(dev_mom)
            mgr.save(consumed, state)

    if is_chief and mgr is not None:
        state = {"step": np.asarray(consumed)}
        if device_state:
            state["params"] = np.asarray(dev_params)
            state["mom"] = np.asarray(dev_mom)
        mgr.save(max(consumed, 1), state, wait=True)
        mgr.close()
    rec_f.close()
    log.info("%s done: consumed %d positions (final epoch %d)",
             me, consumed, my_epoch)
