"""Distributed MNIST: the framework's dist_mnist analogue.

Reference parity: test/e2e/dist-mnist/dist_mnist.py — a real training run
(PS-strategy MNIST with optional SyncReplicasOptimizer) used by CI to prove
end-to-end training works. The TPU-native version is pure data-parallel
SPMD: an MLP trained under jit over the mesh's first axis, synthetic data
generated on-device, loss verified to decrease. No parameter servers — the
gradient all-reduce is inserted by XLA from the sharding annotations.

All global arrays (params, optimizer state, batches) are produced inside
jit with ``out_shardings``, the multi-controller-safe creation pattern.
"""

from __future__ import annotations

import logging
from functools import partial

from tf_operator_tpu.rendezvous.context import JobContext

log = logging.getLogger("tpujob.mnist")


def init_params(key, sizes):
    import jax
    import jax.numpy as jnp

    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for k, (n_in, n_out) in zip(keys, zip(sizes[:-1], sizes[1:])):
        w = jax.random.normal(k, (n_in, n_out), jnp.float32) * (2.0 / n_in) ** 0.5
        b = jnp.zeros((n_out,), jnp.float32)
        params.append((w, b))
    return params


def forward(params, x):
    import jax

    h = x
    for w, b in params[:-1]:
        h = jax.nn.relu(h @ w + b)
    w, b = params[-1]
    return h @ w + b


def loss_fn(params, x, y):
    import jax.numpy as jnp
    import optax

    logits = forward(params, x)
    return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(logits, y))


def main(ctx: JobContext) -> None:
    ctx.initialize_distributed()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = ctx.build_mesh()
    axis = mesh.axis_names[0]

    # At least 2 steps: the final loss-decrease check needs a before/after.
    steps = max(2, int(ctx.workload.get("steps", 30)))
    global_batch = int(ctx.workload.get("batch_size", 256))
    lr = float(ctx.workload.get("lr", 0.1))
    hidden = int(ctx.workload.get("hidden", 128))

    repl = NamedSharding(mesh, P())
    data_sharding = NamedSharding(mesh, P(axis))
    tx = optax.sgd(lr, momentum=0.9)

    @partial(jax.jit, out_shardings=repl)
    def init_fn():
        params = init_params(jax.random.PRNGKey(0), [784, hidden, 10])
        return params, tx.init(params)

    @partial(jax.jit, out_shardings=data_sharding)
    def make_batch(step):
        dkey = jax.random.PRNGKey(42)
        centroids = jax.random.normal(dkey, (10, 784)) * 2.0
        skey = jax.random.fold_in(dkey, step)
        y = jax.random.randint(skey, (global_batch,), 0, 10)
        x = centroids[y] + 0.1 * jax.random.normal(
            jax.random.fold_in(skey, 1), (global_batch, 784)
        )
        return x, y

    @jax.jit
    def train_step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    params, opt_state = init_fn()
    losses = []
    for step in range(steps):
        x, y = make_batch(np.int32(step))
        params, opt_state, loss = train_step(params, opt_state, x, y)
        losses.append(float(loss))
        if step % 10 == 0:
            log.info("step %d loss %.4f", step, losses[-1])

    first, last = losses[0], losses[-1]
    log.info("mnist done: loss %.4f -> %.4f over %d steps", first, last, steps)
    if not last < first:
        raise AssertionError(f"loss did not decrease: {first} -> {last}")
