"""Distributed MNIST: the framework's dist_mnist analogue.

Reference parity: test/e2e/dist-mnist/dist_mnist.py — a real training run
(PS-strategy MNIST with optional SyncReplicasOptimizer, real
read_data_sets download at :214-215) used by CI to prove end-to-end
training works. The TPU-native version is pure data-parallel SPMD: an MLP
trained under jit over the mesh's first axis. No parameter servers — the
gradient all-reduce is inserted by XLA from the sharding annotations.

Two data modes:

- ``data_dir`` set: REAL data from standard MNIST idx files
  (train-images-idx3-ubyte etc., .gz accepted) through the prefetching
  DeviceLoader, each process reading a disjoint shard; evaluates on the
  test split, reports accuracy into TPUJobStatus.eval_metrics, and fails
  the job if ``target_accuracy`` isn't reached. Drop the real MNIST
  distribution files in data_dir and this trains actual MNIST; the e2e
  fixtures feed it real scanned-digit images (sklearn's UCI digits) in
  the same wire format because this environment has no network egress to
  download MNIST itself.
- no ``data_dir``: explicitly-labeled SYNTHETIC mode (gaussian class
  blobs) for smoke/bench runs that only need the distributed-training
  machinery, not a dataset.

workload keys: data_dir, steps (synthetic) / epochs (real), batch_size,
lr, hidden, target_accuracy, eval_batch_size.
"""

from __future__ import annotations

import logging
from functools import partial

from tf_operator_tpu.rendezvous.context import JobContext

log = logging.getLogger("tpujob.mnist")


def init_params(key, sizes):
    import jax
    import jax.numpy as jnp

    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for k, (n_in, n_out) in zip(keys, zip(sizes[:-1], sizes[1:])):
        w = jax.random.normal(k, (n_in, n_out), jnp.float32) * (2.0 / n_in) ** 0.5
        b = jnp.zeros((n_out,), jnp.float32)
        params.append((w, b))
    return params


def forward(params, x):
    import jax

    h = x
    for w, b in params[:-1]:
        h = jax.nn.relu(h @ w + b)
    w, b = params[-1]
    return h @ w + b


def loss_fn(params, x, y):
    import jax.numpy as jnp
    import optax

    logits = forward(params, x)
    return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(logits, y))


def _np_accuracy(params, images, labels) -> float:
    """Host-side accuracy: params are replicated, the test set is small —
    a numpy forward avoids any cross-process collective in eval."""
    import numpy as np

    h = images.reshape(images.shape[0], -1)
    mats = [(np.asarray(w), np.asarray(b)) for w, b in params]
    for w, b in mats[:-1]:
        h = np.maximum(h @ w + b, 0.0)
    w, b = mats[-1]
    pred = np.argmax(h @ w + b, axis=-1)
    return float((pred == labels).mean())


def main(ctx: JobContext) -> None:
    ctx.initialize_distributed()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = ctx.build_mesh()
    axis = mesh.axis_names[0]
    wl = ctx.workload

    global_batch = int(wl.get("batch_size", 256))
    lr = float(wl.get("lr", 0.1))
    hidden = int(wl.get("hidden", 128))
    data_dir = wl.get("data_dir")

    repl = NamedSharding(mesh, P())
    data_sharding = NamedSharding(mesh, P(axis))
    tx = optax.sgd(lr, momentum=0.9)

    @jax.jit
    def train_step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if data_dir:
        _train_real(ctx, mesh, data_sharding, repl, tx, train_step,
                    data_dir, global_batch, hidden, wl)
        return

    # ---- synthetic mode (smoke/bench: machinery, not a dataset) ---------
    log.info("no data_dir: training on SYNTHETIC gaussian class blobs")
    steps = max(2, int(wl.get("steps", 30)))

    @partial(jax.jit, out_shardings=repl)
    def init_fn():
        params = init_params(jax.random.PRNGKey(0), [784, hidden, 10])
        return params, tx.init(params)

    @partial(jax.jit, out_shardings=data_sharding)
    def make_batch(step):
        dkey = jax.random.PRNGKey(42)
        centroids = jax.random.normal(dkey, (10, 784)) * 2.0
        skey = jax.random.fold_in(dkey, step)
        y = jax.random.randint(skey, (global_batch,), 0, 10)
        x = centroids[y] + 0.1 * jax.random.normal(
            jax.random.fold_in(skey, 1), (global_batch, 784)
        )
        return x, y

    params, opt_state = init_fn()
    losses = []
    for step in range(steps):
        x, y = make_batch(np.int32(step))
        params, opt_state, loss = train_step(params, opt_state, x, y)
        losses.append(float(loss))
        if step % 10 == 0:
            log.info("step %d loss %.4f", step, losses[-1])

    first, last = losses[0], losses[-1]
    log.info("mnist done (synthetic): loss %.4f -> %.4f over %d steps",
             first, last, steps)
    if not last < first:
        raise AssertionError(f"loss did not decrease: {first} -> {last}")


def _train_real(ctx, mesh, data_sharding, repl, tx, train_step,
                data_dir, global_batch, hidden, wl) -> None:
    """Real-data path: idx files -> DeviceLoader -> SPMD train -> test-set
    accuracy -> TPUJobStatus.eval_metrics (+ hard gate)."""
    import jax
    import numpy as np
    from functools import partial

    from tf_operator_tpu.train.data import DeviceLoader, MnistIdxDataset

    epochs = max(1, int(wl.get("epochs", 10)))
    target = float(wl.get("target_accuracy", 0.0))
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(f"batch_size {global_batch} % {n_proc} processes != 0")

    ds = MnistIdxDataset(
        data_dir, global_batch // n_proc, split="train",
        seed=jax.process_index(),
    )
    sample = next(ds.epoch(0))
    in_dim = int(np.prod(sample["image"].shape[1:]))

    @partial(jax.jit, out_shardings=repl)
    def init_fn():
        params = init_params(jax.random.PRNGKey(0), [in_dim, hidden, 10])
        return params, tx.init(params)

    params, opt_state = init_fn()
    loader = DeviceLoader(ds, data_sharding)
    # Derived from the GLOBAL example count so every rank runs the same
    # number of SPMD steps (local shard sizes differ by one when nprocs
    # doesn't divide n; the repeating dataset wraps epochs as needed).
    steps_per_epoch = max(1, ds.global_n // global_batch)
    total = epochs * steps_per_epoch
    losses = []
    try:
        for step in range(total):
            batch = next(loader)
            x = batch["image"].reshape(batch["image"].shape[0], -1)
            params, opt_state, loss = train_step(params, opt_state, x, batch["label"])
            if step % max(1, total // 10) == 0:
                losses.append(float(loss))
                log.info("step %d/%d loss %.4f", step, total, losses[-1])
    finally:
        loader.close()

    # Test-split accuracy from the replicated params (host-side numpy:
    # the test set is small and this avoids eval collectives). Reuses the
    # dataset reader so every filename variant it accepts works here too.
    test_ds = MnistIdxDataset(
        data_dir, batch_size=1, split="test", shuffle=False, process_shard=False
    )
    host_params = jax.tree_util.tree_map(np.asarray, params)
    acc = _np_accuracy(
        host_params, test_ds.arrays["image"],
        test_ds.arrays["label"].astype(np.int64),
    )
    log.info("mnist done (real data): test accuracy %.4f over %d examples "
             "(%d epochs, final loss %.4f)",
             acc, test_ds.n, epochs, float(loss))
    if ctx.process_id == 0:
        ctx.report_eval_metrics(total, {"accuracy": acc})
    if target and acc < target:
        raise AssertionError(
            f"test accuracy {acc:.4f} below target {target} — real-data "
            "training regressed"
        )
