"""Modeled-compile workload: the TTFS-bench payload (r11, no JAX import).

Exercises the full compile-cache pipeline with a MODELED compile cost —
the r8 ``--disk-restore-delay`` precedent for honest mechanism receipts
in a chipless container: the cache key derivation, two-tier lookup,
compile intents, sha256-verified transfer, and local landing are all
real (``compile_cache.cached_compile``); only the XLA compile itself is
replaced by a sleep of ``compile_ms``. A cache hit (local or remote —
including one published by AOT-at-admission while this job sat in the
scheduler) skips the modeled cost exactly as a real hit skips XLA.

workload config keys:

- ``aot``: ``{"key": <key material>, "compile_ms": <int>}`` — the same
  section the reconciler's AOT kick reads, so admission-time compilation
  and this workload derive the SAME cache key.
- ``sleep_s`` / ``exit_code``: as in the noop workload.

The first-step mark lands AFTER the compile resolves — TTFS includes
the (modeled) compile exactly as it includes real XLA time — and its
span carries the hit/miss counters and warm-slot flag the reconciler
splits the cold/warm TTFS histograms on.
"""

from __future__ import annotations

import sys
import time

from tf_operator_tpu.rendezvous.context import JobContext
from tf_operator_tpu.train.compile_cache import cached_compile


def main(ctx: JobContext) -> None:
    aot = ctx.workload.get("aot") or {}
    key_material = str(aot.get("key", f"{ctx.namespace}/{ctx.job_name}"))
    compile_ms = float(aot.get("compile_ms", 0))

    def compile_fn() -> bytes:
        # The modeled XLA compile: identical artifact derivation to the
        # admission-time compiler, so integrity checks are end-to-end.
        from tf_operator_tpu.cachesvc.aot import modeled_payload

        if compile_ms:
            time.sleep(compile_ms / 1000.0)
        return modeled_payload(key_material)

    t0 = time.time()
    data, source = cached_compile(key_material, compile_fn)
    ctx.record_span(
        "compile", t0, time.time(),
        attrs={"source": source, "bytes": str(len(data)), "track": "compile"},
    )
    ctx.mark_first_step(0)
    sleep_s = float(ctx.workload.get("sleep_s", 0))
    if sleep_s:
        time.sleep(sleep_s)
    code = int(ctx.workload.get("exit_code", 0))
    if code:
        sys.exit(code)
