"""ResNet training workload (operator-launchable).

The BASELINE.json "ResNet-50 ImageNet → TPUStrategy" config as a TPUJob
entrypoint: joins the gang, builds the mesh, trains ResNet with the
sharded Trainer, logs step time and MFU.

Data modes (workload key ``data``):

- ``"idx"`` + ``data_dir``: REAL images from standard idx files (the
  MNIST wire format the reference's dist_mnist consumes,
  /root/reference/test/e2e/dist-mnist/dist_mnist.py:214-215), prepared to
  the convnet contract (3-channel, optional integer upsample to
  ``image_size``), with random-crop(+flip) augmentation
  (train.data.augment_images) ahead of the prefetching DeviceLoader.
  Trains by ``epochs``, evaluates the test split, reports accuracy into
  TPUJobStatus.eval_metrics, and fails below ``target_accuracy``.
- ``"stream"``: SYNTHETIC host batches through the DeviceLoader (the
  input-pipeline-overlap proof, not a dataset).
- ``"fixed"`` (default): one resident SYNTHETIC device batch — the
  benchmarking shape.

workload config keys: steps (synthetic) / epochs (idx), batch_size,
image_size, num_classes, lr, variant ("resnet50"|"resnet18"),
checkpoint_dir, checkpoint_every, data, data_dir, augment (default true),
crop_padding (default 4), flip (default false — digit-class fixtures are
orientation-sensitive; set true for natural images), target_accuracy,
eval_batch_size, profile_dir (XLA trace), device_loop (K steps per
compiled call — lax.scan device loop).
"""

from __future__ import annotations

import logging

from tf_operator_tpu.rendezvous.context import JobContext
from tf_operator_tpu.train.profile import profile_ctx

log = logging.getLogger("tpujob.resnet")


def resnet_config_from_workload(wl):
    """ResNetConfig from the shared workload dict — ONE builder for every
    role reading spec.workload (trainer here, evaluator in eval.py), so
    the roles cannot drift apart and fail at checkpoint restore."""
    from tf_operator_tpu.models.resnet import ResNetConfig

    classes = int(wl.get("num_classes", 1000))
    variant = wl.get("variant", "resnet50")
    return {
        "resnet50": ResNetConfig.resnet50,
        "resnet18": ResNetConfig.resnet18,
        "tiny": ResNetConfig.tiny,
    }[variant](classes)


def make_test_accuracy(cfg, batch_sharding=None):
    """Build a reusable eval-mode accuracy scorer: the jitted forward is
    created ONCE and shared across calls — the Evaluator role scores many
    checkpoints, and a per-call @jax.jit closure would recompile the full
    eval ResNet every time (identity-keyed jit cache).

    ``batch_sharding`` (r6, VERDICT r5 weak #4): a NamedSharding for the
    [eval_b, ...] image batch — each batch is placed with its batch dim
    sharded over the caller's dp mesh before the forward, so an
    ImageNet-class eval runs data-parallel instead of serial on one
    chip. The eval forward has no cross-batch collectives (per-example
    argmax; BN in eval mode reads running stats), so sharding the input
    is the whole parallelization. None keeps the single-device
    behavior."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models.resnet import resnet_forward

    @jax.jit
    def eval_logits(params, bn_state, x):
        logits, _ = resnet_forward(params, bn_state, x, cfg, train=False)
        return jnp.argmax(logits, axis=-1)

    def score(params, bn_state, images, labels, eval_b: int = 64) -> float:
        correct = 0
        for i in range(0, len(labels), eval_b):
            x = images[i : i + eval_b]
            y = labels[i : i + eval_b]
            if x.shape[0] < eval_b:  # pad to the static shape, mask the tail
                padding = eval_b - x.shape[0]
                x = np.concatenate(
                    [x, np.zeros((padding,) + x.shape[1:], x.dtype)]
                )
            if batch_sharding is not None:
                x = jax.device_put(x, batch_sharding)
            pred = np.asarray(eval_logits(params, bn_state, x))[: len(y)]
            correct += int((pred == y).sum())
        return correct / len(labels)

    return score


def test_accuracy(params, bn_state, cfg, images, labels, eval_b: int = 64) -> float:
    """Eval-mode (running BN stats) top-1 accuracy — one-shot convenience
    over make_test_accuracy (the trainer's end-of-run gate; repeat
    callers like the Evaluator should hold the factory's scorer)."""
    return make_test_accuracy(cfg)(params, bn_state, images, labels, eval_b)


def main(ctx: JobContext) -> None:
    ctx.initialize_distributed()

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.resnet import ResNetConfig, init_resnet, resnet_forward
    from tf_operator_tpu.train.metrics import mfu, resnet_train_flops
    from tf_operator_tpu.train.trainer import Trainer, TrainerConfig

    wl = ctx.workload
    steps = max(2, int(wl.get("steps", 20)))
    batch = int(wl.get("batch_size", 128))
    image_size = int(wl.get("image_size", 224))
    classes = int(wl.get("num_classes", 1000))

    cfg = resnet_config_from_workload(wl)
    mesh = ctx.build_mesh()

    def loss_fn(params, data, state):
        images, labels = data
        logits, new_state = resnet_forward(params, state, images, cfg, train=True)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1)), new_state

    trainer = Trainer(
        mesh,
        loss_fn=loss_fn,
        init_fn=lambda k: init_resnet(k, cfg),
        config=TrainerConfig(
            optimizer="sgd", learning_rate=float(wl.get("lr", 0.1)), grad_clip=None,
            # submit-latency path: rbg init sheds the threefry subgraphs
            # (opt-in since r5 — library default stays deterministic)
            fast_init_rng=bool(wl.get("fast_init_rng", True)),
        ),
    )
    from tf_operator_tpu.train.checkpoint import WorkloadCheckpointer

    ckpt = WorkloadCheckpointer(wl)
    if ckpt.is_complete(steps):
        log.info("already complete (budget %d); nothing to do", steps)
        return
    if wl.get("data") == "idx":
        _train_real(ctx, mesh, trainer, cfg, wl)
        return
    loader = None
    if wl.get("data", "fixed") == "stream":
        from tf_operator_tpu.train.data import SyntheticImages, local_loader

        # batch_size is GLOBAL; local_loader splits it across processes
        # with rank-distinct data and prefetches onto the mesh. skip= keeps
        # a resumed incarnation from replaying batches steps 0..k consumed.
        loader = local_loader(
            SyntheticImages, batch, trainer.batch_sharding,
            min_examples=64, image_size=image_size, num_classes=classes,
            skip=ckpt.resume_step(),
        )
        data = ((b["image"], b["label"]) for b in loader)
    else:
        images = jax.device_put(
            jax.random.normal(jax.random.PRNGKey(1), (batch, image_size, image_size, 3)),
            trainer.batch_sharding,
        )
        labels = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, classes),
            trainer.batch_sharding,
        )
        data = (images, labels)
    try:
        with profile_ctx(wl.get("profile_dir")):
            state, loss, timed, step_s = ckpt.run_loop(
                trainer, jax.random.PRNGKey(0), data, steps,
                device_loop=int(wl.get("device_loop", 1)),
            )
    finally:
        if loader is not None:
            loader.close()
    if step_s is not None:
        n_chips = mesh.devices.size
        flops = resnet_train_flops(cfg.flops_per_image(image_size), batch)
        log.info(
            "resnet done: loss=%.4f step=%.2fms imgs/s=%.0f mfu=%.3f (%d chips)",
            loss, step_s * 1e3, batch / step_s, mfu(flops, step_s, n_chips), n_chips,
        )
    else:
        log.info("resnet done: loss=%.4f (no timed steps remained)", loss)


def _train_real(ctx, mesh, trainer, cfg, wl) -> None:
    """Real-image path: idx files -> prepare (3ch/upsample) -> augment ->
    DeviceLoader -> sharded Trainer -> eval-mode test accuracy ->
    TPUJobStatus.eval_metrics (+ hard gate). The ResNet counterpart of
    the dist_mnist real-data proof (workloads/mnist._train_real)."""
    import math

    import jax

    from tf_operator_tpu.train.data import (
        AugmentedImages,
        DeviceLoader,
        MnistIdxDataset,
        prepare_classification_images,
    )

    global_batch = int(wl.get("batch_size", 128))
    image_size = int(wl.get("image_size", 32))
    epochs = max(1, int(wl.get("epochs", 5)))
    target = float(wl.get("target_accuracy", 0.0))
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(f"batch_size {global_batch} % {n_proc} processes != 0")

    ds = MnistIdxDataset(
        wl["data_dir"], global_batch // n_proc, split="train",
        seed=jax.process_index(),
    )
    ds.arrays["image"] = prepare_classification_images(
        ds.arrays["image"], image_size
    )
    source = ds
    if wl.get("augment", True):
        source = AugmentedImages(
            ds,
            pad=int(wl.get("crop_padding", 4)),
            # digits/text are orientation-sensitive; natural-image recipes
            # opt in with flip: true
            flip=bool(wl.get("flip", False)),
            seed=jax.process_index(),
        )
    state = trainer.init(jax.random.PRNGKey(0))
    loader = DeviceLoader(source, trainer.batch_sharding)
    # Periodic checkpoints (r4): the Evaluator role scores them as they
    # land (workloads/eval.py model="resnet") — params + BN stats both,
    # restore_subtrees.
    from tf_operator_tpu.train.checkpoint import CheckpointManager

    ckpt_dir = wl.get("checkpoint_dir")
    every = int(wl.get("checkpoint_every", 0))
    mgr = CheckpointManager(ckpt_dir) if ckpt_dir else None
    # GLOBAL example count -> identical SPMD step count on every rank
    # (a rank-local count would deadlock the gang; see MnistIdxDataset).
    steps_per_epoch = max(1, ds.global_n // global_batch)
    total = epochs * steps_per_epoch
    loss = float("nan")
    def checkpoint(step, state, m, wait=False):
        # EVERY rank calls save (orbax save is a collective — a rank-0
        # gate would deadlock multi-host gangs; same convention as
        # WorkloadCheckpointer.advance), and a non-finite state is
        # refused: persisting a diverged state would hand the Evaluator
        # a poisoned latest checkpoint.
        cur = float(m["loss"])
        if not math.isfinite(cur):
            log.warning("skipping checkpoint at step %d: loss %r", step, cur)
            # fence in-flight async saves (r5, ADVICE r4): the caller is
            # about to raise and exit — without the fence the last
            # periodic save could still be writing and land torn
            mgr.wait_until_finished()
            return
        mgr.save(step, state, wait=wait)

    try:
        for step in range(total):
            batch = next(loader)
            state, m = trainer.step(state, (batch["image"], batch["label"]))
            if mgr and every and (step + 1) % every == 0:
                checkpoint(step + 1, state, m)
            if step % max(1, total // 10) == 0:
                loss = float(m["loss"])
                log.info("step %d/%d loss %.4f", step, total, loss)
        loss = float(m["loss"])
        if mgr:
            checkpoint(total, state, m, wait=True)
    finally:
        loader.close()
    if not math.isfinite(loss):
        raise AssertionError(f"non-finite training loss {loss}")

    # Eval-mode (running BN stats) accuracy on the test split. Params are
    # replicated, and eval batches are fed REPLICATED so every rank runs
    # the identical program — no collectives, no gang divergence. Padded
    # to a static batch so jit compiles once.
    test = MnistIdxDataset(
        wl["data_dir"], batch_size=1, split="test", shuffle=False,
        process_shard=False,
    )
    images = prepare_classification_images(test.arrays["image"], image_size)
    labels = test.arrays["label"]
    acc = test_accuracy(
        state.params, state.extra, cfg, images, labels,
        eval_b=int(wl.get("eval_batch_size", 64)),
    )
    log.info(
        "resnet done (real data): test accuracy %.4f over %d examples "
        "(%d epochs, final loss %.4f)", acc, len(labels), epochs, loss,
    )
    if ctx.process_id == 0:
        ctx.report_eval_metrics(total, {"accuracy": acc})
    if target and acc < target:
        raise AssertionError(
            f"test accuracy {acc:.4f} below target {target} — real-image "
            "training regressed"
        )
