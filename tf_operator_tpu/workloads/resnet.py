"""ResNet training workload (operator-launchable).

The BASELINE.json "ResNet-50 ImageNet → TPUStrategy" config as a TPUJob
entrypoint: joins the gang, builds the mesh, trains ResNet on synthetic
ImageNet-shaped data with the sharded Trainer, logs step time and MFU.

workload config keys: steps, batch_size, image_size, num_classes, lr,
variant ("resnet50"|"resnet18"), checkpoint_dir, checkpoint_every,
data ("fixed": one resident device batch, the benchmarking shape;
"stream": host batches through the prefetching DeviceLoader — the
production input-pipeline shape), profile_dir (capture an XLA trace),
device_loop (K steps per compiled call — lax.scan device loop).
"""

from __future__ import annotations

import logging

from tf_operator_tpu.rendezvous.context import JobContext
from tf_operator_tpu.train.profile import profile_ctx

log = logging.getLogger("tpujob.resnet")


def main(ctx: JobContext) -> None:
    ctx.initialize_distributed()

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.resnet import ResNetConfig, init_resnet, resnet_forward
    from tf_operator_tpu.train.metrics import mfu, resnet_train_flops
    from tf_operator_tpu.train.trainer import Trainer, TrainerConfig

    wl = ctx.workload
    steps = max(2, int(wl.get("steps", 20)))
    batch = int(wl.get("batch_size", 128))
    image_size = int(wl.get("image_size", 224))
    classes = int(wl.get("num_classes", 1000))
    variant = wl.get("variant", "resnet50")

    cfg = (
        ResNetConfig.resnet50(classes) if variant == "resnet50" else ResNetConfig.resnet18(classes)
    )
    mesh = ctx.build_mesh()

    def loss_fn(params, data, state):
        images, labels = data
        logits, new_state = resnet_forward(params, state, images, cfg, train=True)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1)), new_state

    trainer = Trainer(
        mesh,
        loss_fn=loss_fn,
        init_fn=lambda k: init_resnet(k, cfg),
        config=TrainerConfig(
            optimizer="sgd", learning_rate=float(wl.get("lr", 0.1)), grad_clip=None
        ),
    )
    from tf_operator_tpu.train.checkpoint import WorkloadCheckpointer

    ckpt = WorkloadCheckpointer(wl)
    if ckpt.is_complete(steps):
        log.info("already complete (budget %d); nothing to do", steps)
        return
    loader = None
    if wl.get("data", "fixed") == "stream":
        from tf_operator_tpu.train.data import SyntheticImages, local_loader

        # batch_size is GLOBAL; local_loader splits it across processes
        # with rank-distinct data and prefetches onto the mesh. skip= keeps
        # a resumed incarnation from replaying batches steps 0..k consumed.
        loader = local_loader(
            SyntheticImages, batch, trainer.batch_sharding,
            min_examples=64, image_size=image_size, num_classes=classes,
            skip=ckpt.resume_step(),
        )
        data = ((b["image"], b["label"]) for b in loader)
    else:
        images = jax.device_put(
            jax.random.normal(jax.random.PRNGKey(1), (batch, image_size, image_size, 3)),
            trainer.batch_sharding,
        )
        labels = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, classes),
            trainer.batch_sharding,
        )
        data = (images, labels)
    try:
        with profile_ctx(wl.get("profile_dir")):
            state, loss, timed, step_s = ckpt.run_loop(
                trainer, jax.random.PRNGKey(0), data, steps,
                device_loop=int(wl.get("device_loop", 1)),
            )
    finally:
        if loader is not None:
            loader.close()
    if step_s is not None:
        n_chips = mesh.devices.size
        flops = resnet_train_flops(cfg.flops_per_image(image_size), batch)
        log.info(
            "resnet done: loss=%.4f step=%.2fms imgs/s=%.0f mfu=%.3f (%d chips)",
            loss, step_s * 1e3, batch / step_s, mfu(flops, step_s, n_chips), n_chips,
        )
    else:
        log.info("resnet done: loss=%.4f (no timed steps remained)", loss)
