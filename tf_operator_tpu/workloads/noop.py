"""No-op workload: exits 0 immediately, no JAX import.

The control-plane load-test payload (tools/genjob.py --wait): measures the
operator's reconcile throughput at the reference's O(100)-concurrent-jobs
design scale (tf_job_design_doc.md:24-26) without paying 2xN JAX process
startups — the data plane is exercised by the smoke/mnist/lm workloads.

workload config keys: sleep_s (hold the gang alive), exit_code (fault
injection: nonzero exercises the restart/backoff machinery at scale).
"""

from __future__ import annotations

import sys
import time

from tf_operator_tpu.rendezvous.context import JobContext


def main(ctx: JobContext) -> None:
    # TTFS boundary for the control-plane bench: a no-op payload's "first
    # step" is workload code running at all — submit -> here is exactly
    # the control-plane share of time-to-first-step.
    ctx.mark_first_step(0)
    # Emit one telemetry batch so even the cheapest payload exercises the
    # ring end to end (trace-smoke golden-checks /telemetry on noop jobs).
    rep = ctx.telemetry(flush_every=1)
    sleep_s = float(ctx.workload.get("sleep_s", 0))
    t0 = time.time()
    if sleep_s:
        time.sleep(sleep_s)
    if rep:
        rep.step(max(time.time() - t0, 1e-6))
    ctx.close_telemetry(rep)
    code = int(ctx.workload.get("exit_code", 0))
    if code:
        sys.exit(code)
