"""Smoke workload: prove every device in the gang computes and communicates.

Reference parity: tf_smoke.py, where the master assigns a matmul to every
task and verifies the results (examples/tf_sample/tf_sample/tf_smoke.py:
34-75). The SPMD equivalent: every process joins the gang, a sharded matmul
runs across the full mesh, and the globally-reduced checksum must equal the
analytic value — if any device or link is broken, the collective hangs or
the value is wrong.

All global arrays are created *inside* jit with ``out_shardings`` — the
multi-controller-safe pattern (no host array ever needs cross-process
placement).
"""

from __future__ import annotations

import logging
from functools import partial

from tf_operator_tpu.rendezvous.context import JobContext

log = logging.getLogger("tpujob.smoke")


def main(ctx: JobContext) -> None:
    ctx.initialize_distributed()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = ctx.build_mesh()
    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    dim = int(ctx.workload.get("dim", 256))

    log.info("mesh=%s devices=%d", dict(zip(mesh.axis_names, mesh.devices.shape)), n_dev)

    sharded = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())

    @partial(jax.jit, out_shardings=sharded)
    def make_ones():
        return jnp.ones((n_dev, dim, dim), jnp.float32)

    @partial(jax.jit, out_shardings=replicated)
    def checksum(a, b):
        return jnp.sum(jnp.einsum("bij,bjk->bik", a, b))

    import math

    sleep_s = float(ctx.workload.get("sleep_s", 0))
    if sleep_s:
        # Fault-injection hook: keep the gang alive so tests can kill a
        # host/process mid-run (chaos + node-lost scenarios).
        import time

        time.sleep(sleep_s)

    total = float(checksum(make_ones(), make_ones()))
    # First real device work done: the TTFS boundary (obs/) — covers
    # rendezvous + mesh bring-up + the first compiled computation.
    ctx.mark_first_step(0)
    expected = float(n_dev) * dim**3
    # fp32 accumulation is inexact for large dims; a relative tolerance
    # still catches any dead device or broken link (whole blocks missing).
    if not math.isclose(total, expected, rel_tol=1e-5):
        raise AssertionError(f"smoke mismatch: got {total}, expected {expected}")
    log.info("smoke ok: %d devices, checksum %.0f", n_dev, total)
