"""Evaluator workload: score checkpoints as training produces them.

The reference defines the Evaluator replica role but gives it no behavior
— it is just a pod excluded from the cluster spec
(/root/reference/pkg/apis/tensorflow/v1alpha2/types.go:105-112,
controller_tensorflow.go:91-95); what an evaluator *does* lives in user
code. Here it is library code: run as the Evaluator replica of an LM
TPUJob (or as a standalone job) pointed at the trainer's
``checkpoint_dir``; it polls for new checkpoints, restores the params onto
its own mesh, and logs eval loss per checkpoint step. The evaluator is
excluded from the training gang, so it needs no rendezvous with the
trainers — the checkpoint directory IS the interface, exactly the
coupling the reference's design doc prescribes for the data plane.

workload config keys: preset (+ TransformerConfig overrides, as lm.py),
checkpoint_dir (required), eval_batch_size, eval_seq_len, eval_batches,
poll_interval_s, train_steps (stop once a checkpoint >= this step is
scored; otherwise score the first checkpoint seen and every newer one
until then), max_wait_s (give up if nothing new appears), eval_report
(path: per-checkpoint losses written as JSON — the scored artifact other
tooling and the e2e oracle read).
"""

from __future__ import annotations

import json
import logging
import os
import time

from tf_operator_tpu.rendezvous.context import JobContext

log = logging.getLogger("tpujob.eval")


def main(ctx: JobContext) -> None:
    # Evaluators are outside the gang: single-process jax, no rendezvous.
    import jax

    from tf_operator_tpu.models.transformer import (
        init_transformer,
        lm_loss_and_metrics,
        preset_from_workload,
        transformer_logical_axes,
    )
    from tf_operator_tpu.parallel import build_mesh
    from tf_operator_tpu.train.checkpoint import CheckpointManager
    from tf_operator_tpu.train.trainer import Trainer, TrainerConfig

    wl = ctx.workload
    ckpt_dir = wl.get("checkpoint_dir")
    if not ckpt_dir:
        raise ValueError("eval workload requires workload.checkpoint_dir")
    cfg = preset_from_workload(wl)
    batch = int(wl.get("eval_batch_size", 8))
    seq = int(wl.get("eval_seq_len", min(cfg.max_seq, 512)))
    n_batches = max(1, int(wl.get("eval_batches", 4)))
    poll_s = float(wl.get("poll_interval_s", 2.0))
    train_steps = int(wl.get("train_steps", 0))
    max_wait_s = float(wl.get("max_wait_s", 600.0))

    # dp must divide the eval batch; gcd keeps any batch size valid on any
    # device count (spare devices idle — eval is cheap and off the gang).
    import math

    dp = math.gcd(batch, jax.device_count())
    mesh = build_mesh({"dp": dp}, devices=jax.devices()[:dp])
    trainer = Trainer(
        mesh,
        # this Trainer only templates state for restore — eval never steps
        loss_fn=lambda p, tok, extra: lm_loss_and_metrics(p, tok, cfg, mesh=mesh)[0],
        init_fn=lambda k: init_transformer(k, cfg),
        logical_axes=transformer_logical_axes(cfg),
        config=TrainerConfig(),
    )
    # readonly: never sweep a live trainer's tmp dirs, never save.
    manager = CheckpointManager(ckpt_dir, readonly=True)
    report_path = wl.get("eval_report")

    # Held-out batches: a seed stream disjoint from the trainers' (they
    # seed data by process rank; 10_000+ is reserved for eval).
    eval_batches = [
        jax.device_put(
            jax.random.randint(
                jax.random.PRNGKey(10_000 + i), (batch, seq), 0, cfg.vocab
            ),
            trainer.batch_sharding,
        )
        for i in range(n_batches)
    ]

    # Score CROSS-ENTROPY, not the training objective: for MoE configs
    # lm_loss includes the weighted router aux losses, which would skew
    # eval comparisons against dense baselines or no-aux ablations.
    eval_fn = jax.jit(
        lambda params, tok: lm_loss_and_metrics(params, tok, cfg, mesh=mesh)[1][
            "ce_loss"
        ]
    )

    def write_report(scored):
        if not report_path:
            return
        tmp = report_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({str(k): v for k, v in sorted(scored.items())}, f)
        os.replace(tmp, report_path)  # atomic: readers never see a partial file

    scored: dict = {}
    pruned: set = set()  # steps that vanished mid-scan (keep-N retention)
    deadline = time.time() + max_wait_s
    done = False
    while not done:
        # The orbax manager caches its step list at construction; reload()
        # re-scans so the trainers' new saves become visible.
        manager.reload()
        # Score EVERY unscored checkpoint, oldest first — when the trainer
        # saves faster than eval scores, scoring only latest_step() would
        # silently skip intermediates and leave gaps in eval_report.
        # One-shot mode keeps its contract: score the latest and exit.
        steps = manager.all_steps()
        if not train_steps:
            steps = steps[-1:]
        for step in steps:
            if step in scored or step in pruned:
                continue
            try:
                params = manager.restore_params(
                    trainer.state_template().params, step=step
                )
            except Exception as exc:  # noqa: BLE001
                # Keep-N retention can prune an older step between our
                # directory scan and the restore (the exact races-with-a-
                # live-trainer scenario this loop exists for): a vanished
                # checkpoint is a skip, not an evaluator death. The next
                # reload() drops it from all_steps().
                log.warning("checkpoint step=%d vanished mid-scan (%s); skipping",
                            step, exc)
                pruned.add(step)
                continue
            losses = [float(eval_fn(params, tok)) for tok in eval_batches]
            scored[step] = sum(losses) / len(losses)
            log.info(
                "eval: checkpoint step=%d loss=%.4f (%d batches of %dx%d)",
                step, scored[step], n_batches, batch, seq,
            )
            write_report(scored)
            # Surface the score where it is queryable: tpujob get / the
            # dashboard read TPUJobStatus.eval_metrics (best-effort —
            # standalone runs without an operator just skip it).
            ctx.report_eval_metrics(step, {"loss": scored[step]})
            deadline = time.time() + max_wait_s  # progress resets the clock
            if train_steps and step >= train_steps:
                done = True
                break
            if not train_steps:
                done = True  # one-shot mode: score the latest and exit
        if not done and time.time() > deadline:
            raise TimeoutError(
                f"no new checkpoint under {ckpt_dir} within {max_wait_s}s "
                f"(scored: {sorted(scored)})"
            )
        if not done:
            time.sleep(poll_s)

    best = min(scored.values())
    log.info("eval done: %d checkpoints scored, best loss %.4f", len(scored), best)
