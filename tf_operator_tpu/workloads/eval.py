"""Evaluator workload: score checkpoints as training produces them.

The reference defines the Evaluator replica role but gives it no behavior
— it is just a pod excluded from the cluster spec
(/root/reference/pkg/apis/tensorflow/v1alpha2/types.go:105-112,
controller_tensorflow.go:91-95); what an evaluator *does* lives in user
code. Here it is library code: run as the Evaluator replica of an LM
TPUJob (or as a standalone job) pointed at the trainer's
``checkpoint_dir``; it polls for new checkpoints, restores the params onto
its own mesh, and logs eval loss per checkpoint step. The evaluator is
excluded from the training gang, so it needs no rendezvous with the
trainers — the checkpoint directory IS the interface, exactly the
coupling the reference's design doc prescribes for the data plane.

workload config keys: model ("lm" default | "resnet" — r4, VERDICT r3
#7b: the scorer follows the model family), preset (+ TransformerConfig
overrides, as lm.py; LM only), variant/num_classes/image_size/data_dir
(resnet only — scores test-split accuracy from idx files, restoring
params AND BN running stats via restore_subtrees), checkpoint_dir
(required), eval_batch_size, eval_seq_len, eval_batches, poll_interval_s,
train_steps (stop once a checkpoint >= this step is scored; otherwise
score the first checkpoint seen and every newer one until then),
max_wait_s (give up if nothing new appears), eval_report (path:
per-checkpoint scores written as JSON — the scored artifact other
tooling and the e2e oracle read).
"""

from __future__ import annotations

import json
import logging
import os
import time

from tf_operator_tpu.rendezvous.context import JobContext

log = logging.getLogger("tpujob.eval")


def _lm_scorer(wl):
    """LM scorer: held-out token batches, mean cross-entropy per
    checkpoint (lower is better). Returns (templates, score_fn, best_fn)
    — the model-agnostic polling loop's contract."""
    import jax

    from tf_operator_tpu.models.transformer import (
        init_transformer,
        lm_loss_and_metrics,
        preset_from_workload,
        transformer_logical_axes,
    )
    from tf_operator_tpu.parallel import build_mesh
    from tf_operator_tpu.train.trainer import Trainer, TrainerConfig

    cfg = preset_from_workload(wl)
    batch = int(wl.get("eval_batch_size", 8))
    seq = int(wl.get("eval_seq_len", min(cfg.max_seq, 512)))
    n_batches = max(1, int(wl.get("eval_batches", 4)))

    # dp must divide the eval batch; gcd keeps any batch size valid on any
    # device count (spare devices idle — eval is cheap and off the gang).
    import math

    dp = math.gcd(batch, jax.device_count())
    mesh = build_mesh({"dp": dp}, devices=jax.devices()[:dp])
    trainer = Trainer(
        mesh,
        # this Trainer only templates state for restore — eval never steps
        loss_fn=lambda p, tok, extra: lm_loss_and_metrics(p, tok, cfg, mesh=mesh)[0],
        init_fn=lambda k: init_transformer(k, cfg),
        logical_axes=transformer_logical_axes(cfg),
        config=TrainerConfig(),
    )
    if wl.get("data") == "memmap" and wl.get("corpus"):
        # REAL corpus eval (r5, VERDICT r4 #6): read the SAME memmap the
        # trainer gang reads, but the held-out tail reserved by
        # holdout_windows — carved out before the trainers' rank-sharding
        # (train.data.TokenMemmapDataset), so it is disjoint from every
        # trainer rank by construction and the reported CE measures the
        # corpus, not jax.random noise. Deterministic order (no shuffle)
        # so every scored checkpoint sees identical batches.
        from tf_operator_tpu.train.data import TokenMemmapDataset

        holdout = int(wl.get("holdout_windows", 0))
        if not holdout:
            # Fabricating a holdout here would read windows the TRAINER
            # also trained on (it held out nothing) and report the CE as
            # held-out generalization — refuse instead: the disjointness
            # contract lives in this one shared key.
            raise ValueError(
                'eval over data="memmap" requires workload.holdout_windows '
                "(the same key the trainer uses to reserve the corpus tail "
                "— without it the trainer holds out nothing and eval would "
                "score trained-on windows)"
            )
        # Disjointness is defined in the TRAINER's window geometry:
        # holdout_windows counts windows of the trainer's seq_len
        # (workloads/lm.py). Windowing the corpus with eval_seq_len here
        # would move the tail boundary — with eval_seq_len > seq_len the
        # "holdout" would span tokens the trainer trained on and score
        # memorization as generalization (ADVICE r5 #1). So: carve the
        # tail with the trainer's seq_len, then cut each reserved window
        # into eval_seq_len pieces (requiring eval_seq_len <= seq_len —
        # anything longer cannot fit inside the reserved region's
        # geometry and is refused loudly).
        train_seq = int(wl.get("seq_len", 512))
        if seq > train_seq:
            raise ValueError(
                f"eval_seq_len={seq} > trainer seq_len={train_seq}: eval "
                "windows would extend past the reserved holdout tail into "
                "trained-on tokens; use eval_seq_len <= seq_len"
            )
        ds = TokenMemmapDataset(
            wl["corpus"], 1, train_seq, split="holdout", holdout=holdout,
            shuffle=False, process_shard=False,
        )
        per_window = train_seq // seq
        need_windows = -(-n_batches * batch // per_window)  # ceil
        if len(ds) < need_windows:
            raise ValueError(
                f"holdout_windows={holdout} yields {len(ds)} reserved "
                f"trainer windows = {len(ds) * per_window} eval windows of "
                f"{seq}; eval_batches={n_batches} x batch={batch} needs "
                f"{n_batches * batch}"
            )
        it = ds.epoch(0)
        flat = []
        while len(flat) < n_batches * batch:
            w = next(it)["tokens"][0]  # one trainer-sized holdout window
            flat.extend(
                w[i * seq:(i + 1) * seq] for i in range(per_window)
            )
        import numpy as np

        eval_batches = [
            jax.device_put(
                np.stack(flat[i * batch:(i + 1) * batch]),
                trainer.batch_sharding,
            )
            for i in range(n_batches)
        ]
    else:
        # Synthetic fallback: a seed stream disjoint from the trainers'
        # (they seed data by process rank; 10_000+ is reserved for eval).
        eval_batches = [
            jax.device_put(
                jax.random.randint(
                    jax.random.PRNGKey(10_000 + i), (batch, seq), 0, cfg.vocab
                ),
                trainer.batch_sharding,
            )
            for i in range(n_batches)
        ]

    # Score CROSS-ENTROPY, not the training objective: for MoE configs
    # lm_loss includes the weighted router aux losses, which would skew
    # eval comparisons against dense baselines or no-aux ablations.
    eval_fn = jax.jit(
        lambda params, tok: lm_loss_and_metrics(params, tok, cfg, mesh=mesh)[1][
            "ce_loss"
        ]
    )
    templates = {"params": trainer.state_template().params}

    def score(restored):
        losses = [float(eval_fn(restored["params"], tok)) for tok in eval_batches]
        v = sum(losses) / len(losses)
        return v, {"loss": v}

    return templates, score, min


def _resnet_scorer(wl):
    """ResNet scorer (r4): test-split top-1 accuracy from idx files
    (higher is better). Restores params AND the BN running stats —
    eval-mode inference is wrong without them."""
    import jax

    from tf_operator_tpu.models.resnet import init_resnet, resnet_forward
    from tf_operator_tpu.parallel import build_mesh
    from tf_operator_tpu.train.data import (
        MnistIdxDataset,
        prepare_classification_images,
    )
    from tf_operator_tpu.train.trainer import Trainer, TrainerConfig
    from tf_operator_tpu.workloads.resnet import (
        make_test_accuracy,
        resnet_config_from_workload,
    )

    if not wl.get("data_dir"):
        raise ValueError('eval workload with model="resnet" requires '
                         "workload.data_dir (idx test split to score)")
    cfg = resnet_config_from_workload(wl)
    image_size = int(wl.get("image_size", 32))
    eval_b = int(wl.get("eval_batch_size", 64))

    # dp = gcd(eval_batch, devices), same rule as the LM scorer (r6,
    # VERDICT r5 weak #4: the ResNet evaluator ran serial on one chip —
    # an ImageNet-class test split was a dp=1 bottleneck). Scoring pads
    # each batch to eval_b, so eval_b % dp == 0 (gcd) keeps every batch
    # shardable; spare devices idle, eval is off the gang.
    import math

    dp = math.gcd(eval_b, jax.device_count())
    mesh = build_mesh({"dp": dp}, devices=jax.devices()[:dp])

    def loss_fn(params, data, st):
        # templates only — the evaluator never steps
        logits, new_state = resnet_forward(params, st, data[0], cfg, train=True)
        return logits.sum(), new_state

    trainer = Trainer(
        mesh, loss_fn=loss_fn, init_fn=lambda k: init_resnet(k, cfg),
        config=TrainerConfig(),
    )
    test = MnistIdxDataset(
        wl["data_dir"], batch_size=1, split="test", shuffle=False,
        process_shard=False,
    )
    images = prepare_classification_images(test.arrays["image"], image_size)
    labels = test.arrays["label"]
    tmpl = trainer.state_template()
    templates = {"params": tmpl.params, "extra": tmpl.extra}
    # one jitted eval forward shared across all scored checkpoints; eval
    # batches land with their batch dim sharded over the dp mesh
    accuracy = make_test_accuracy(cfg, batch_sharding=trainer.batch_sharding)

    def score(restored):
        acc = accuracy(restored["params"], restored["extra"], images, labels,
                       eval_b)
        return acc, {"accuracy": acc}

    return templates, score, max


def main(ctx: JobContext) -> None:
    # Evaluators are outside the gang: single-process jax, no rendezvous.
    from tf_operator_tpu.train.checkpoint import CheckpointManager

    wl = ctx.workload
    ckpt_dir = wl.get("checkpoint_dir")
    if not ckpt_dir:
        raise ValueError("eval workload requires workload.checkpoint_dir")
    model = wl.get("model", "lm")
    if model == "resnet":
        templates, score_fn, best_fn = _resnet_scorer(wl)
    elif model == "lm":
        templates, score_fn, best_fn = _lm_scorer(wl)
    else:
        raise ValueError(f'unknown eval model {model!r}; use "lm" or "resnet"')
    poll_s = float(wl.get("poll_interval_s", 2.0))
    train_steps = int(wl.get("train_steps", 0))
    max_wait_s = float(wl.get("max_wait_s", 600.0))

    # readonly: never sweep a live trainer's tmp dirs, never save.
    manager = CheckpointManager(ckpt_dir, readonly=True)
    report_path = wl.get("eval_report")

    def write_report(scored):
        if not report_path:
            return
        tmp = report_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({str(k): v for k, v in sorted(scored.items())}, f)
        os.replace(tmp, report_path)  # atomic: readers never see a partial file

    scored: dict = {}
    pruned: set = set()  # steps that vanished mid-scan (keep-N retention)
    deadline = time.time() + max_wait_s
    done = False
    while not done:
        # The orbax manager caches its step list at construction; reload()
        # re-scans so the trainers' new saves become visible.
        manager.reload()
        # Score EVERY unscored checkpoint, oldest first — when the trainer
        # saves faster than eval scores, scoring only latest_step() would
        # silently skip intermediates and leave gaps in eval_report.
        # One-shot mode keeps its contract: score the latest and exit.
        steps = manager.all_steps()
        if not train_steps:
            steps = steps[-1:]
        for step in steps:
            if step in scored or step in pruned:
                continue
            try:
                restored = manager.restore_subtrees(templates, step=step)
            except Exception as exc:  # noqa: BLE001
                # Keep-N retention can prune an older step between our
                # directory scan and the restore (the exact races-with-a-
                # live-trainer scenario this loop exists for): a vanished
                # checkpoint is a skip, not an evaluator death. The next
                # reload() drops it from all_steps().
                log.warning("checkpoint step=%d vanished mid-scan (%s); skipping",
                            step, exc)
                pruned.add(step)
                continue
            scored[step], metrics = score_fn(restored)
            log.info("eval: checkpoint step=%d %s", step,
                     " ".join(f"{k}={v:.4f}" for k, v in metrics.items()))
            write_report(scored)
            # Surface the score where it is queryable: tpujob get / the
            # dashboard read TPUJobStatus.eval_metrics (best-effort —
            # standalone runs without an operator just skip it).
            ctx.report_eval_metrics(step, metrics)
            deadline = time.time() + max_wait_s  # progress resets the clock
            if train_steps and step >= train_steps:
                done = True
                break
            if not train_steps:
                done = True  # one-shot mode: score the latest and exit
        if not done and time.time() > deadline:
            raise TimeoutError(
                f"no new checkpoint under {ckpt_dir} within {max_wait_s}s "
                f"(scored: {sorted(scored)})"
            )
        if not done:
            time.sleep(poll_s)

    best = best_fn(scored.values())
    log.info("eval done: %d checkpoints scored, best %.4f", len(scored), best)
