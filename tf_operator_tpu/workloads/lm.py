"""Transformer language-model training workload (operator-launchable).

Covers the BASELINE.json BERT-base and Llama-2 configs: joins the gang,
builds the declared mesh (dp/fsdp/tp/cp), trains a transformer preset with
the sharded Trainer on synthetic tokens, logs tokens/sec and MFU.

workload config keys: preset (any models.transformer.PRESETS name:
"tiny"|"tiny-moe"|"gpt-small"|"moe-small"|"bert-base"|"llama2-7b"|
"llama2-13b"|"llama2-70b"), steps, batch_size, seq_len, lr,
attn ("dense"|"ring"|"flash"), profile_dir (capture an XLA trace),
device_loop (K steps per compiled call — lax.scan device loop),
checkpoint_dir, checkpoint_every (steps between saves; restart-based
recovery resumes from the latest checkpoint), grad_accum (microbatch
gradient accumulation — same global batch in 1/N-size activation
footprint; tools.memplan accounts for it), data ("fixed" resident
batch | "stream" synthetic through the prefetching DeviceLoader |
"memmap" + corpus=<path>: a REAL tokenized corpus in the
train.data.write_token_corpus memmap format, window-sharded per
process), plus any
TransformerConfig field as an override (e.g. n_layers, n_experts,
capacity_factor — MoE presets route through parallel.moe over the ep
mesh axis).
"""

from __future__ import annotations

import logging

from tf_operator_tpu.rendezvous.context import JobContext, RetryableFailure
from tf_operator_tpu.train.profile import profile_ctx

log = logging.getLogger("tpujob.lm")


def main(ctx: JobContext) -> None:
    ctx.initialize_distributed()

    import jax

    from tf_operator_tpu.models.transformer import (
        init_transformer,
        lm_loss,
        preset_from_workload,
        transformer_logical_axes,
    )
    from tf_operator_tpu.train.metrics import (
        mfu,
        transformer_train_flops,
        transformer_train_flops_exact,
    )
    from tf_operator_tpu.train.trainer import Trainer, TrainerConfig

    wl = ctx.workload
    steps = max(2, int(wl.get("steps", 10)))
    batch = int(wl.get("batch_size", 8))
    seq = int(wl.get("seq_len", 512))
    cfg = preset_from_workload(wl)
    mesh = ctx.build_mesh()

    def loss_fn(params, tokens, extra):
        del extra
        return lm_loss(params, tokens, cfg, mesh=mesh)

    trainer = Trainer(
        mesh,
        loss_fn=loss_fn,
        init_fn=lambda k: init_transformer(k, cfg),
        logical_axes=transformer_logical_axes(cfg),
        config=TrainerConfig(
            optimizer="adamw", learning_rate=float(wl.get("lr", 3e-4)),
            grad_accum=int(wl.get("grad_accum", 1)),
            # submit-latency path: rbg init sheds the threefry subgraphs
            # (opt-in since r5 — library default stays deterministic)
            fast_init_rng=bool(wl.get("fast_init_rng", True)),
        ),
    )
    from tf_operator_tpu.train.checkpoint import WorkloadCheckpointer

    # ctx wires the warm-restore seam in: peer prefetch before disk
    # (TPUJOB_RESTORE_PEERS), committed-step pushes to this host's depot
    # (TPUJOB_PEER_DEPOT), and save-stall / restore spans on the timeline.
    ckpt = WorkloadCheckpointer(wl, ctx=ctx)
    if ckpt.is_complete(steps):
        log.info("already complete (budget %d); nothing to do", steps)
        return
    loader = None
    data_mode = wl.get("data", "fixed")
    if data_mode == "memmap":
        # REAL tokenized corpus: workload.corpus points at a memmap token
        # stream (train.data.write_token_corpus format); each process reads
        # a disjoint window shard through the prefetching DeviceLoader.
        from tf_operator_tpu.train.data import DeviceLoader, TokenMemmapDataset

        n_proc = jax.process_count()
        if batch % n_proc:
            raise ValueError(f"batch_size {batch} % {n_proc} processes != 0")
        # holdout_windows (r5): reserve the corpus tail for the Evaluator
        # BEFORE rank-sharding — the trainer never sees those windows, so
        # eval CE measures generalization on this corpus, not memorization.
        ds = TokenMemmapDataset(
            wl["corpus"], batch // n_proc, seq,
            holdout=int(wl.get("holdout_windows", 0)),
        )
        loader = DeviceLoader(
            ds, trainer.batch_sharding, skip=ckpt.resume_step()
        )
        tokens = (b["tokens"] for b in loader)
    elif data_mode == "stream":
        from tf_operator_tpu.train.data import SyntheticTokens, local_loader

        # batch_size is GLOBAL; local_loader splits it across processes
        # with rank-distinct data and prefetches onto the mesh. skip= keeps
        # a resumed incarnation from replaying batches steps 0..k consumed.
        loader = local_loader(
            SyntheticTokens, batch, trainer.batch_sharding,
            seq_len=seq, vocab=cfg.vocab, skip=ckpt.resume_step(),
        )
        tokens = (b["tokens"] for b in loader)
    else:
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab),
            trainer.batch_sharding,
        )

    # Fault injection (workload keys fail_at_step + fail_marker): die
    # RETRYABLY once at the given global step — the restart-based-recovery
    # e2e: the gang restarts and the next incarnation must resume from the
    # latest checkpoint, not step 0. The marker file makes it once-only.
    # Granularity: with device_loop=K the on_step callback fires per CHUNK
    # (post-chunk step), so the fault can trigger up to K-1 steps late and
    # after that chunk's save — exact-step chaos scenarios should use
    # device_loop=1 (see WorkloadCheckpointer.run_loop).
    fail_at = int(wl.get("fail_at_step", 0))
    marker = wl.get("fail_marker")
    first_step_marked = []

    def on_step(step: int) -> None:
        if not first_step_marked:
            # TTFS boundary (obs/): the first completed training step of
            # this run — includes rendezvous, restore and compile time.
            first_step_marked.append(step)
            ctx.mark_first_step(step)
        if fail_at and marker and step >= fail_at:
            import os

            if not os.path.exists(marker):
                open(marker, "w").close()
                log.warning("fault injection: requesting retry at step %d", step)
                # routed by the harness to the user-retryable exit code
                raise RetryableFailure(f"fault injection at step {step}")

    try:
        with profile_ctx(wl.get("profile_dir")):
            state, loss, timed, step_s = ckpt.run_loop(
                trainer, jax.random.PRNGKey(0), tokens, steps, on_step=on_step,
                device_loop=int(wl.get("device_loop", 1)),
            )
    finally:
        if loader is not None:
            loader.close()
    if cfg.n_experts:
        # Router health check on the trained params: collapsed routing
        # (entropy << ln(E)) or heavy dropping is a silent quality bug —
        # surface it in the training log where operators look first.
        import math

        from tf_operator_tpu.models.transformer import lm_loss_and_metrics

        probe = tokens if not hasattr(tokens, "__next__") else jax.device_put(
            jax.random.randint(jax.random.PRNGKey(2), (batch, seq), 0, cfg.vocab),
            trainer.batch_sharding,
        )
        _, m = jax.jit(
            lambda p, tok: lm_loss_and_metrics(p, tok, cfg, mesh=mesh)
        )(state.params, probe)
        if "moe_expert_entropy" in m:
            log.info(
                "moe router: expert_entropy=%.3f (uniform=%.3f) "
                "drop_frac=%.3f lb_loss=%.3f z_loss=%.4f",
                float(m["moe_expert_entropy"]), math.log(cfg.n_experts),
                float(m["moe_drop_frac"]), float(m["moe_lb_loss"]),
                float(m["moe_z_loss"]),
            )
        else:
            # pipeline + MoE: per-layer router telemetry doesn't ride the
            # pp aux channel — only the scalar losses do (transformer
            # docstring); a missing key must not fail the job (caught by
            # the pp x ep gang e2e, r4)
            log.info(
                "moe router (pp — scalar losses only): lb_loss=%.3f "
                "z_loss=%.4f",
                float(m["moe_lb_loss"]), float(m["moe_z_loss"]),
            )
    if step_s is not None:
        n_chips = mesh.devices.size
        # active params: for top-1 MoE only one expert's FLOPs count per
        # token; mfu_attn adds the 12·L·t·d attention term (the honest
        # number at long context), mfu_6nd is the scaling-law-comparable one.
        flops_6nd = transformer_train_flops(cfg.n_active_params(), batch * seq)
        flops_exact = transformer_train_flops_exact(
            cfg.n_active_params(), batch * seq, cfg.n_layers, cfg.d_model, seq
        )
        log.info(
            "lm done: preset=%s loss=%.4f step=%.2fms tok/s=%.0f mfu_attn=%.3f "
            "mfu_6nd=%.3f (%d chips)",
            wl.get("preset", "tiny"), loss, step_s * 1e3, batch * seq / step_s,
            mfu(flops_exact, step_s, n_chips), mfu(flops_6nd, step_s, n_chips),
            n_chips,
        )
    else:
        log.info("lm done: preset=%s loss=%.4f (no timed steps remained)",
                 wl.get("preset", "tiny"), loss)
