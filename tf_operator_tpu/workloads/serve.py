"""Continuous-batching LM serving workload (operator-launchable).

Drives serve/engine.py with a models/transformer.py preset under
JobContext: synthetic requests arrive on a seeded Poisson schedule, the
engine serves them with iteration-level continuous batching over the
paged KV cache, and the job exits 0 when every request has completed.

Per-request spans land in the PR 3 trace next to the per-job spans:
``request-admitted`` (arrival → admission), ``first-token`` (arrival →
first generated token: the TTFT the reconciler folds into
``tpujob_request_ttft_seconds`` at terminal) and ``finished`` (arrival →
completion, attrs carry the generated-token count feeding
``tpujob_request_tokens_total``). Span names are deterministic per
(job, request, op), so restarts re-record idempotently.

Live request-count rides the eval_metrics status channel (the same
optimistic RMW the Evaluator uses) every ``report_every`` steps — the
dashboard's serve-job "Requests" column reads it.

workload config keys: preset (+ any TransformerConfig override),
requests, prompt_len, max_new_tokens, arrival_rate (req/s Poisson; 0 ⇒
all at t=0), seed, kv_page_size, kv_pool_pages, max_slots,
prefill_chunk, reserve_full, max_admit_per_step, mode
("continuous"|"static"), report_every.
"""

from __future__ import annotations

import logging
import time

from tf_operator_tpu.rendezvous.context import JobContext

log = logging.getLogger("tpujob.serve")


def synthesize_requests(wl: dict, vocab: int):
    """The seeded request stream (shared with tools/servebench.py so the
    bench and the operator workload replay identical traffic): Poisson
    arrivals, uniform prompt lengths around prompt_len, uniform random
    prompt tokens, ragged generation budgets in [1, max_new_tokens]."""
    import numpy as np

    from tf_operator_tpu.serve.engine import Request

    rng = np.random.RandomState(int(wl.get("seed", 0)))
    n = int(wl.get("requests", 8))
    rate = float(wl.get("arrival_rate", 20.0))
    mean_prompt = max(1, int(wl.get("prompt_len", 8)))
    max_new = max(1, int(wl.get("max_new_tokens", 16)))
    t = 0.0
    reqs = []
    for i in range(n):
        if rate > 0:
            t += float(rng.exponential(1.0 / rate))
        plen = int(rng.randint(max(1, mean_prompt // 2), mean_prompt * 2 + 1))
        reqs.append(
            Request(
                rid=i,
                prompt=[int(x) for x in rng.randint(1, vocab, size=plen)],
                max_new=int(rng.randint(1, max_new + 1)),
                arrival=t,
            )
        )
    return reqs


def _quantile(xs, q):
    if not xs:
        return 0.0
    ys = sorted(xs)
    idx = min(len(ys) - 1, int(round(q * (len(ys) - 1))))
    return ys[idx]


def main(ctx: JobContext) -> None:
    ctx.initialize_distributed()
    if ctx.process_id != 0:
        # the decode engine is single-process (multi-host serving is
        # roadmap); extra ranks just hold their gang slot
        return

    import jax

    from tf_operator_tpu.models.transformer import (
        init_transformer,
        preset_from_workload,
    )
    from tf_operator_tpu.obs.spans import trace8
    from tf_operator_tpu.serve.engine import ServeConfig, ServeEngine

    wl = ctx.workload
    cfg = preset_from_workload(wl)
    scfg = ServeConfig(
        page_size=int(wl.get("kv_page_size", 16)),
        pool_pages=int(wl.get("kv_pool_pages", 64)),
        max_slots=int(wl.get("max_slots", 4)),
        prefill_chunk=int(wl.get("prefill_chunk", 16)),
        reserve_full=bool(wl.get("reserve_full", True)),
        max_admit_per_step=int(wl.get("max_admit_per_step", 0)),
        mode=str(wl.get("mode", "continuous")),
    )
    params = init_transformer(jax.random.PRNGKey(int(wl.get("seed", 0))), cfg)
    engine = ServeEngine(cfg, params, scfg)
    requests = synthesize_requests(wl, cfg.vocab)
    total = len(requests)
    report_every = max(1, int(wl.get("report_every", 4)))

    wall0 = time.time()  # engine offsets → epoch times for spans

    def span_name(rid: int, op: str) -> str:
        return f"{ctx.job_name}-{trace8(ctx.trace_id)}-req{rid}-{op}"

    first_step = []

    def on_event(kind: str, payload) -> None:
        if kind == "step":
            if not first_step:
                first_step.append(payload["step"])
                ctx.mark_first_step(0)
            if payload["step"] % report_every == 0:
                ctx.report_eval_metrics(payload["step"], {
                    "requests_total": float(total),
                    "requests_active": float(payload["active"]),
                    "requests_completed": float(payload["completed"]),
                    "tokens_generated": float(payload["generated"]),
                })
            return
        req = payload
        base = {"request": str(req.rid), "track": "serve"}
        if kind == "admitted":
            ctx.record_span(
                "request-admitted", wall0 + req.arrival, wall0 + req.admitted,
                attrs=base, name=span_name(req.rid, "request-admitted"),
            )
        elif kind == "first_token":
            ctx.record_span(
                "first-token", wall0 + req.arrival, wall0 + req.first_token,
                attrs=base, name=span_name(req.rid, "first-token"),
            )
        elif kind == "finished":
            ctx.record_span(
                "finished", wall0 + req.arrival, wall0 + req.finished,
                attrs={**base, "tokens": str(len(req.tokens))},
                name=span_name(req.rid, "finished"),
            )

    res = engine.run(requests, on_event=on_event)

    leaked = res.free_pages_start - res.free_pages_end
    if leaked:
        raise RuntimeError(
            f"KV page leak: {leaked} pages not returned to the free list"
        )
    ctx.report_eval_metrics(res.steps, {
        "requests_total": float(total),
        "requests_active": 0.0,
        "requests_completed": float(res.completed),
        "tokens_generated": float(res.generated_tokens),
        "tokens_per_s": float(res.tokens_per_s),
    })
    ttfts = res.ttfts()
    log.info(
        "serve done: preset=%s mode=%s requests=%d/%d tokens=%d tok/s=%.1f "
        "ttft_p50=%.3fs ttft_p99=%.3fs steps=%d (0 page leaks)",
        wl.get("preset", "tiny"), scfg.mode, res.completed, total,
        res.generated_tokens, res.tokens_per_s,
        _quantile(ttfts, 0.50), _quantile(ttfts, 0.99), res.steps,
    )
