"""The autopilot decision step the reconciler drives once per job sync.

Split deliberately in two:

- :class:`JobAutopilot` here holds ONLY decision state (hysteresis
  streaks, cooldown clocks) and pure policy calls — ``tick(inputs)``
  returns a list of :class:`Decision` records. No store, no spans, no
  metrics: the whole class is drivable by tests with hand-built
  :class:`TickInputs`.
- The reconciler (controller/reconciler.py ``_autopilot_tick``) gathers
  the inputs from surfaces that already exist (telemetry windows,
  save-stall spans, the cause ledger, StragglerTracker.host_risk(),
  warm-pool gauges) and EXECUTES the decisions through actuators that
  already exist (the checkpoint-cadence status directive,
  ``_try_resize_shrink``, the ``place_gang`` deprioritized set, the
  warm-pool host annotation). The no-new-actuators rule
  (docs/design.md §4.12) lives at that boundary: a Decision can only
  name an actuator the fleet already trusts.

Every executed decision becomes an ``autopilot-decision`` span whose
attrs are exactly ``Decision.attrs`` — the measured numbers that
justified the action ride in the receipt, not in a log line.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tf_operator_tpu.autopilot.policy import (
    Hysteresis,
    cadence_worth_changing,
    host_risk_actionable,
    optimal_checkpoint_every,
    warmpool_target,
)
from tf_operator_tpu.obs.telemetry import HostRisk

# Decision kinds — the ``kind`` label on
# ``tpujob_autopilot_decisions_total`` and in the span attrs.
DECISION_CADENCE = "cadence"  # retune checkpoint_every (status directive)
DECISION_MIGRATE = "migrate"  # pre-emptive shrink away from a risky host
DECISION_DEPRIORITIZE = "deprioritize"  # feed host into place_gang's avoid set
DECISION_WARMPOOL = "warmpool"  # retarget a host's warm-pool size


@dataclass
class AutopilotConfig:
    """Parsed ``run_policy.autopilot`` knob (api/types.py)."""

    cooldown_s: float = 30.0  # min seconds between actions per decision key
    confirm_ticks: int = 2  # consecutive agreeing ticks before acting
    min_checkpoint_every: int = 1
    max_checkpoint_every: int = 64
    cadence: bool = True  # per-actuator gates
    migrate: bool = True
    warmpool: bool = True
    # Blend the fleet ledger's per-cohort MTBF into the cadence input
    # (obs/priors.py) so a fresh job's FIRST decision starts from fleet
    # history instead of the mtbf=inf clamp edge.
    use_fleet_priors: bool = False

    @staticmethod
    def from_run_policy(knob: Any) -> Optional["AutopilotConfig"]:
        """None ⇒ autopilot disabled for this job (the default)."""
        if not knob:
            return None
        if not isinstance(knob, dict):
            return AutopilotConfig()
        if not knob.get("enabled", True):
            return None
        cfg = AutopilotConfig()
        for key in (
            "cooldown_s", "confirm_ticks", "min_checkpoint_every",
            "max_checkpoint_every", "cadence", "migrate", "warmpool",
            "use_fleet_priors",
        ):
            if key in knob:
                setattr(cfg, key, type(getattr(cfg, key))(knob[key]))
        return cfg


@dataclass
class TickInputs:
    """Everything one decision step reads, gathered by the reconciler.

    All numbers are MEASURED: nothing here is an assumed constant, which
    is the whole point of closing the telemetry→policy loop."""

    now: float = 0.0
    # Cadence inputs.
    step_time_s: float = 0.0  # cross-rank median step seconds, latest window
    save_stall_s: float = 0.0  # mean measured stall per accepted save (δ)
    saves_observed: int = 0  # save-stall spans seen (evidence floor for δ)
    failures: int = 0  # restart+preemption+hang events (MTBF denominator)
    run_elapsed_s: float = 0.0  # submit → now (MTBF numerator)
    restart_downtime_s: float = 0.0  # cause-ledger lost seconds (receipt)
    current_every: int = 0  # the checkpoint interval governing the gang now
    directive_epoch: int = 0  # last cadence-directive epoch published
    directive_acked: bool = True  # chief acked the last epoch (or none sent)
    # Fleet prior (obs/priors.py, gathered from the ledger when
    # use_fleet_priors): 0 failures ⇒ no prior, own-data path only.
    prior_mtbf_s: float = 0.0
    prior_failures: int = 0
    prior_jobs: int = 0
    # Placement inputs.
    host_risk: Dict[str, HostRisk] = field(default_factory=dict)
    watchdog_stalled: bool = False  # hang watchdog armed or hung
    elastic_ok: bool = False  # elastic + mesh resizable + chief safe
    world_size: int = 0
    min_world_size: int = 1
    # Warm-pool inputs.
    cold_starts: int = 0  # TTFS cold-classified first-step marks
    warm_starts: int = 0
    warmpool_current: int = 0  # the target currently annotated/default


@dataclass
class Decision:
    """One action the reconciler must execute and receipt."""

    kind: str  # DECISION_*
    action: str  # human-readable choice, e.g. "checkpoint_every 1->8"
    attrs: Dict[str, str] = field(default_factory=dict)  # span payload
    checkpoint_every: int = 0  # DECISION_CADENCE
    host: str = ""  # DECISION_MIGRATE / DECISION_DEPRIORITIZE / DECISION_WARMPOOL
    warmpool_target: int = 0  # DECISION_WARMPOOL


def _fmt(x: float) -> str:
    return "inf" if math.isinf(x) else f"{x:.3f}"


class JobAutopilot:
    """Decision state for one job: hysteresis streaks and cooldown
    clocks. Lives exactly as long as the job's StragglerTracker (both
    are uid-keyed reconciler state, dropped together when the job
    ends), so the two hysteresis loops always observe the same world."""

    def __init__(self, config: AutopilotConfig) -> None:
        self.config = config
        self._hys = Hysteresis(
            confirm_ticks=config.confirm_ticks, cooldown_s=config.cooldown_s
        )

    # -- the decision step -------------------------------------------------

    def tick(self, inp: TickInputs) -> List[Decision]:
        cfg = self.config
        if inp.watchdog_stalled:
            # Never act while the hang plane is armed: a resize or a
            # cadence round-trip against a gang that may be wedged only
            # confuses the watchdog's no-progress clock. The hang path
            # owns recovery; we resume when progress does.
            return []
        decisions: List[Decision] = []
        if cfg.cadence:
            decisions.extend(self._tick_cadence(inp))
        decisions.extend(self._tick_placement(inp))
        if cfg.warmpool:
            decisions.extend(self._tick_warmpool(inp))
        return decisions

    def _tick_cadence(self, inp: TickInputs) -> List[Decision]:
        cfg = self.config
        if inp.step_time_s <= 0 or inp.saves_observed < 1:
            return []  # no measured δ or step time yet: no evidence, no move
        if not inp.directive_acked:
            return []  # the last directive is still in flight — one at a time
        mtbf = (
            inp.run_elapsed_s / inp.failures if inp.failures > 0 else math.inf
        )
        prior_weight = 0.0
        if inp.prior_failures > 0 and inp.prior_mtbf_s > 0:
            # Fleet prior: shrink the (possibly infinite) own-data MTBF
            # toward the ledger cohort's, with the pinned blend rule —
            # own failures progressively buy the weight back.
            from tf_operator_tpu.obs.priors import CadencePrior, blend_mtbf

            mtbf, prior_weight = blend_mtbf(
                CadencePrior(
                    mtbf_s=inp.prior_mtbf_s,
                    failures=inp.prior_failures,
                    jobs=inp.prior_jobs,
                ),
                own_elapsed_s=inp.run_elapsed_s,
                own_failures=inp.failures,
            )
        dec = optimal_checkpoint_every(
            save_stall_s=inp.save_stall_s,
            mtbf_s=mtbf,
            step_time_s=inp.step_time_s,
            min_every=cfg.min_checkpoint_every,
            max_every=cfg.max_checkpoint_every,
        )
        if not cadence_worth_changing(inp.current_every, dec.every):
            self._hys.withdraw("cadence")
            return []
        if not self._hys.propose("cadence", dec.every, inp.now):
            return []
        attrs = {
            "save_stall_s": _fmt(dec.save_stall_s),
            "mtbf_s": _fmt(dec.mtbf_s),
            "failures": str(inp.failures),
            "restart_downtime_s": _fmt(inp.restart_downtime_s),
            "step_time_s": _fmt(dec.step_time_s),
            "tau_s": _fmt(dec.tau_s),
            "clamped": dec.clamped,
            "from_every": str(inp.current_every),
            "to_every": str(dec.every),
        }
        if prior_weight > 0:
            # The fleet-prior receipt the acceptance check reads off the
            # decision span: the prior's MTBF, its sample count, and how
            # much of the blended estimate it contributed.
            attrs["prior_mtbf_s"] = _fmt(inp.prior_mtbf_s)
            attrs["prior_samples"] = str(inp.prior_failures)
            attrs["prior_weight"] = _fmt(prior_weight)
        return [Decision(
            kind=DECISION_CADENCE,
            action=f"checkpoint_every {inp.current_every}->{dec.every}",
            checkpoint_every=dec.every,
            attrs=attrs,
        )]

    def _tick_placement(self, inp: TickInputs) -> List[Decision]:
        cfg = self.config
        decisions: List[Decision] = []
        for host in sorted(inp.host_risk):
            risk = inp.host_risk[host]
            if not host_risk_actionable(risk):
                self._hys.withdraw(f"deprioritize:{host}")
                self._hys.withdraw(f"migrate:{host}")
                continue
            attrs = {
                "host": host,
                "rank": str(risk.rank),
                "flag_age_windows": str(risk.flag_age_windows),
                "slow_ratio": _fmt(risk.slow_ratio),
                "flap_count": str(risk.flap_count),
            }
            if self._hys.propose(f"deprioritize:{host}", True, inp.now):
                decisions.append(Decision(
                    kind=DECISION_DEPRIORITIZE,
                    action=f"deprioritize {host}",
                    host=host, attrs=dict(attrs),
                ))
            # Pre-emptive migrate: shrink away from the risky host BEFORE
            # the watchdog (or the host itself) forces a full restart —
            # only when the gang can spare a member.
            if (
                cfg.migrate
                and inp.elastic_ok
                and inp.world_size - 1 >= inp.min_world_size
                and self._hys.propose(f"migrate:{host}", True, inp.now)
            ):
                decisions.append(Decision(
                    kind=DECISION_MIGRATE,
                    action=f"shrink away from {host}",
                    host=host,
                    attrs={
                        **attrs,
                        "world_size": str(inp.world_size),
                    },
                ))
        return decisions

    def _tick_warmpool(self, inp: TickInputs) -> List[Decision]:
        target = warmpool_target(
            cold_starts=inp.cold_starts,
            warm_starts=inp.warm_starts,
            current_target=inp.warmpool_current,
        )
        if target == inp.warmpool_current:
            self._hys.withdraw("warmpool")
            return []
        if not self._hys.propose("warmpool", target, inp.now):
            return []
        return [Decision(
            kind=DECISION_WARMPOOL,
            action=f"warmpool target {inp.warmpool_current}->{target}",
            warmpool_target=target,
            attrs={
                "cold_starts": str(inp.cold_starts),
                "warm_starts": str(inp.warm_starts),
                "from_target": str(inp.warmpool_current),
                "to_target": str(target),
            },
        )]
