"""Pure autopilot decision functions (no store, no clock, no I/O).

Everything here is a function of measured numbers in, decision out —
the controller half (autopilot/controller.py) owns gathering the
numbers and acting on the answers. Keeping this layer pure is what
makes the policy math pinnable by tests/test_autopilot.py against
hand-computed optima.

The checkpoint-cadence half is the classic optimal-checkpoint-interval
problem (Young 1974; Daly, FGCS 2006): with a per-checkpoint cost of
``δ`` seconds and a mean time between failures of ``M`` seconds, the
work interval that minimizes expected lost time is ``τ ≈ sqrt(2·δ·M)``
(Young's first-order optimum; Daly's higher-order refinement matters
only when ``δ`` approaches ``M``, which a sane fleet never reaches).
What is new here is that BOTH inputs are measured live instead of
assumed: ``δ`` from the save-stall spans the checkpointer records and
``M`` from the cause-ledger's restart history — so the optimum tracks
the fleet as it actually behaves.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict

# -- cause → recovery-action table ------------------------------------------

ACTION_RESTART = "restart"  # full gang restart (the default path)
ACTION_RESIZE = "resize"  # elastic shrink now, re-grow when capacity returns
ACTION_MIGRATE = "migrate"  # shrink away from the host AND deprioritize it

# Causes that must NEVER route to a resize: preemption means the
# capacity comes back (shrinking would orphan the reservation the
# preemptor's exit restores), and OOM is the workload's own doing on
# every member — a smaller gang OOMs harder, not softer. The reconciler's
# _try_resize_shrink refuses these independently; the table exists so
# the autopilot never even proposes them.
_RESTART_ONLY_CAUSES = frozenset({"preemption", "oom"})


def recovery_action(
    cause: str,
    elastic: bool,
    host_flagged: bool = False,
) -> str:
    """Which recovery the autopilot prefers for a failure ``cause``.

    ``elastic`` gates the resize family (non-elastic jobs can only
    restart). ``host_flagged`` means the straggler tracker holds a live
    risk flag against the failed member's host — the difference between
    RESIZE (shrink in place, re-grow on the same host when it returns)
    and MIGRATE (shrink AND deprioritize, so the re-grow lands
    elsewhere). Hangs always restart: the watchdog owns that path and a
    wedged collective says nothing about the host.
    """
    if not elastic:
        return ACTION_RESTART
    if cause in _RESTART_ONLY_CAUSES or cause == "hang":
        return ACTION_RESTART
    if cause in ("node-lost", "node_lost", "crash", "retryable-failure",
                 "straggler"):
        return ACTION_MIGRATE if host_flagged else ACTION_RESIZE
    return ACTION_RESTART


# -- Young/Daly checkpoint cadence ------------------------------------------

# Cadence clamps: never checkpoint more often than every step, never
# let the interval exceed this many steps unless the caller widens it —
# an unbounded interval means a first failure after a quiet week loses
# a week.
DEFAULT_MIN_EVERY = 1
DEFAULT_MAX_EVERY = 64


@dataclass(frozen=True)
class CadenceDecision:
    """The cadence answer plus the numbers that justify it — the
    ``autopilot-decision`` span attrs are exactly these fields."""

    every: int  # recommended checkpoint_every (steps)
    tau_s: float  # Young interval sqrt(2·δ·M), seconds (inf ⇒ no failures)
    save_stall_s: float  # measured δ input
    mtbf_s: float  # measured M input (inf ⇒ zero restart history)
    step_time_s: float  # seconds/step used to convert τ into steps
    clamped: str = ""  # "" | "min" | "max" — which clamp bound, if any


def optimal_checkpoint_every(
    save_stall_s: float,
    mtbf_s: float,
    step_time_s: float,
    min_every: int = DEFAULT_MIN_EVERY,
    max_every: int = DEFAULT_MAX_EVERY,
) -> CadenceDecision:
    """Young-optimal checkpoint interval, in steps.

    τ = sqrt(2·δ·M) seconds of useful work between checkpoints, then
    ``every = round(τ / step_time)`` clamped to [min_every, max_every].

    Degenerate inputs resolve to the clamp that loses least:

    - zero restart history (``mtbf_s`` ≤ 0 or inf): failures have never
      been observed, so checkpoint as rarely as allowed → ``max_every``;
    - free checkpoints (``save_stall_s`` ≈ 0): there is no cost to
      saving, so save as often as allowed → ``min_every``;
    - unusable step time (≤ 0): τ cannot be converted to steps; fall
      back to ``max_every`` with τ reported so the receipt shows why.
    """
    min_every = max(1, int(min_every))
    max_every = max(min_every, int(max_every))
    if save_stall_s <= 0.0:
        return CadenceDecision(
            every=min_every, tau_s=0.0, save_stall_s=save_stall_s,
            mtbf_s=mtbf_s, step_time_s=step_time_s, clamped="min",
        )
    if mtbf_s <= 0.0 or math.isinf(mtbf_s):
        return CadenceDecision(
            every=max_every, tau_s=math.inf, save_stall_s=save_stall_s,
            mtbf_s=mtbf_s, step_time_s=step_time_s, clamped="max",
        )
    tau = math.sqrt(2.0 * save_stall_s * mtbf_s)
    if step_time_s <= 0.0:
        return CadenceDecision(
            every=max_every, tau_s=tau, save_stall_s=save_stall_s,
            mtbf_s=mtbf_s, step_time_s=step_time_s, clamped="max",
        )
    raw = tau / step_time_s
    every = int(round(raw)) or 1
    clamped = ""
    if every < min_every:
        every, clamped = min_every, "min"
    elif every > max_every:
        every, clamped = max_every, "max"
    return CadenceDecision(
        every=every, tau_s=tau, save_stall_s=save_stall_s, mtbf_s=mtbf_s,
        step_time_s=step_time_s, clamped=clamped,
    )


def cadence_worth_changing(
    current: int, proposed: int, deadband: float = 0.25
) -> bool:
    """Deadband against churn: a directive (and the worker round-trip it
    costs) is only worth issuing when the proposal moves the interval by
    more than ``deadband`` relative to the current value. A current of 0
    ("final save only") always changes — any periodic cadence beats
    none once failures are observed."""
    if proposed == current:
        return False
    if current <= 0:
        return True
    return abs(proposed - current) / float(current) > deadband


# -- warm-pool sizing from TTFS cold-miss rates -----------------------------


def warmpool_target(
    cold_starts: int,
    warm_starts: int,
    current_target: int,
    min_slots: int = 0,
    max_slots: int = 4,
    grow_miss_rate: float = 0.25,
    min_samples: int = 4,
) -> int:
    """Warm-pool slot target from the observed TTFS cold/warm split.

    A cold start means a gang member paid interpreter + framework +
    runtime init on the job's critical path because no warm slot was
    idle — the r11 metric pair ``tpujob_time_to_first_step_{warm,cold}``
    counts both populations. Grow one slot while the cold-miss rate
    exceeds ``grow_miss_rate``; shrink one when a full sample window
    saw no cold start at all (idle warm children are not free: each
    pins an interpreter + imports). Under ``min_samples`` launches the
    evidence is noise — hold the current target.
    """
    min_slots = max(0, int(min_slots))
    max_slots = max(min_slots, int(max_slots))
    current = max(min_slots, min(max_slots, int(current_target)))
    total = cold_starts + warm_starts
    if total < min_samples:
        return current
    miss_rate = cold_starts / float(total)
    if miss_rate > grow_miss_rate:
        return min(max_slots, current + 1)
    if cold_starts == 0:
        return max(min_slots, current - 1)
    return current


# -- decision hysteresis ----------------------------------------------------


@dataclass
class _Pending:
    value: object = None
    streak: int = 0
    last_fired: float = -math.inf


class Hysteresis:
    """Per-decision-key damping: a proposal must repeat for
    ``confirm_ticks`` CONSECUTIVE ticks and the key must be outside its
    ``cooldown_s`` window before it fires.

    This is deliberately the same shape as the straggler tracker's
    flag/clear window counting (obs/telemetry.py StragglerTracker) so
    the two never fight: the tracker needs ``flag_windows`` consecutive
    outlier windows to flag a host, and the autopilot then needs
    ``confirm_ticks`` consecutive ticks of that flag to act on it — the
    autopilot can only ever be SLOWER to act than the signal it acts
    on, so a flap the tracker damps can never leak through into a
    resize, and a flag the tracker clears mid-confirmation resets the
    autopilot's streak to zero.
    """

    def __init__(self, confirm_ticks: int = 2, cooldown_s: float = 30.0) -> None:
        self.confirm_ticks = max(1, int(confirm_ticks))
        self.cooldown_s = float(cooldown_s)
        self._pending: Dict[str, _Pending] = {}

    def propose(self, key: str, value, now: float) -> bool:
        """Register ``value`` as this tick's proposal for ``key``;
        returns True when the proposal just fired (confirmed + cooled
        down). The caller must then act AND keep proposing only if it
        still wants the action — firing starts the cooldown."""
        p = self._pending.setdefault(key, _Pending())
        if p.value == value:
            p.streak += 1
        else:
            p.value = value
            p.streak = 1
        if p.streak < self.confirm_ticks:
            return False
        if now - p.last_fired < self.cooldown_s:
            return False
        p.last_fired = now
        p.streak = 0
        return True

    def withdraw(self, key: str) -> None:
        """The condition evaporated (e.g. the straggler flag cleared):
        drop the streak so a re-appearance must re-confirm from zero.
        The cooldown clock is kept — clearing it would let a flapping
        condition fire on every other tick."""
        p = self._pending.get(key)
        if p is not None:
            p.value = None
            p.streak = 0

    def in_cooldown(self, key: str, now: float) -> bool:
        p = self._pending.get(key)
        return p is not None and (now - p.last_fired) < self.cooldown_s


# -- host-risk gate (reads the tracker's shared HostRisk struct) ------------

# Risk gate the autopilot applies before a pre-emptive migrate: the flag
# must have been live this many tracker windows (on top of the tracker's
# own flag_windows ramp), the rank must still be slow by this much, and
# a chronic flapper (≥ flap_limit completed flag→clear cycles) is never
# migrated pre-emptively — it would re-flap on the next host too.
RISK_MIN_FLAG_AGE_WINDOWS = 2
RISK_MIN_SLOW_RATIO = 1.5
RISK_FLAP_LIMIT = 3


def host_risk_actionable(
    risk,
    min_flag_age: int = RISK_MIN_FLAG_AGE_WINDOWS,
    min_slow_ratio: float = RISK_MIN_SLOW_RATIO,
    flap_limit: int = RISK_FLAP_LIMIT,
) -> bool:
    """True when a :class:`~tf_operator_tpu.obs.telemetry.HostRisk`
    snapshot justifies pre-emptive action (migrate / deprioritize)."""
    return (
        risk.flagged
        and risk.flag_age_windows >= min_flag_age
        and risk.slow_ratio >= min_slow_ratio
        and risk.flap_count < flap_limit
    )
