"""Goodput autopilot (r16): the L4 loop that turns telemetry into policy.

PRs 12/13/15 made the fleet observable (goodput decomposition,
``tpujob_lost_seconds_total{cause}``, straggler flags, hang verdicts)
and gracefully degradable (elastic resize) — but every number still
terminated in a dashboard. This package closes the loop:

- :mod:`~tf_operator_tpu.autopilot.policy` — pure, unit-testable
  decision math (Young/Daly optimal checkpoint cadence from *measured*
  save-stall vs *measured* restart downtime, the per-cause
  restart/resize/migrate table, warm-pool sizing from observed TTFS
  cold-miss rates, and the hysteresis helper every actuator shares).
- :mod:`~tf_operator_tpu.autopilot.controller` — the per-job decision
  step the reconciler drives on each sync, acting through EXISTING
  actuators only (the no-new-actuators rule, docs/design.md §4.12).

Every decision is receipted as an ``autopilot-decision`` span carrying
the input numbers and the chosen action, and counted per decision kind
(``tpujob_autopilot_decisions_total{kind}``).
"""

from tf_operator_tpu.autopilot.policy import (  # noqa: F401
    ACTION_MIGRATE,
    ACTION_RESIZE,
    ACTION_RESTART,
    CadenceDecision,
    Hysteresis,
    cadence_worth_changing,
    host_risk_actionable,
    optimal_checkpoint_every,
    recovery_action,
    warmpool_target,
)
