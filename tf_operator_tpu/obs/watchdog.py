"""Gang-progress watchdog: detect the silent hang the exit taxonomy misses.

The operator's whole failure model is exit-code classification — but the
dominant silent failure in real fleets is a *hang*: one rank wedges in a
collective, every other rank blocks with it, no process exits, and every
existing signal stays green:

- the exit taxonomy (utils/exit_codes.py) sees no exit;
- host heartbeats (runtime/agent.py) keep beating — the AGENT is fine;
- the straggler median-rule (obs/telemetry.py detect_stragglers) is
  *designed* to stay silent when all ranks stop together: the median
  moves with the gang, nobody is an outlier.

:class:`GangWatchdog` fills exactly that gap. It is a pure per-job state
machine the reconciler drives from the same Telemetry ring the straggler
tracker reads: the gang's progress marker is ``max(end_step)`` over the
newest window per rank, and the gang is declared HUNG when that marker
has not advanced for ``run_policy.hang_timeout_seconds`` while host
heartbeats stay live (heartbeat-dead hosts route to node-lost handling,
never here — a dead host is a LOUD failure).

Disambiguation rule (the straggler/hang boundary): a single slow rank
moves while the median holds → straggler plane. ALL ranks stop → the
progress marker freezes → watchdog. While a stall is pending
(``stalled`` is True), the reconciler suppresses straggler observation
so a gang-wide freeze can never leak flap-hysteresis state into
:class:`~tf_operator_tpu.obs.telemetry.StragglerTracker`.

False-positive guards:

- **Pre-first-step grace**: before the job's TTFS span exists
  (obs/spans.py first_step_span_name) there is no progress to measure —
  compile/init can legitimately take minutes; the watchdog stays idle.
  Once the first step is marked, the progress clock starts at the LATER
  of the TTFS time and the newest telemetry flush.
- **Resize windows are not hangs**: every observation carries the job's
  resize_epoch; an epoch change resets the progress clock (the gang is
  re-forming — the same epoch-guard rule resize spans use).
- **Flush-boundary hysteresis**: progress is measured against the
  monotonic step high-water mark, not against flush arrival times — a
  rank re-flushing the same window, or ranks flushing out of phase,
  never advances (or regresses) the marker. One observation past the
  timeout arms; the FIRST marker advance clears, no matter how long the
  stall lasted.
- **One hang ⇒ one verdict**: after firing, the watchdog latches
  (``hung``) and returns no further verdicts until progress resumes or
  :meth:`reset` (gang restart) — the reconciler's stack-sweep directive
  epoch dedup rides this latch.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tf_operator_tpu.obs.telemetry import Telemetry

__all__ = ["HangVerdict", "GangWatchdog"]


@dataclass
class HangVerdict:
    """One declared hang: the scene as the watchdog saw it."""

    stuck_step: int  # the step high-water mark nobody advanced past
    since: float  # wall-clock when progress last advanced
    stalled_for: float  # seconds of stall at declaration time
    # Ranks whose newest window reports the stuck step — the last ranks
    # that were still moving when the gang froze. The complement (ranks
    # stuck on an EARLIER step) is the first place to look for the
    # wedge's origin.
    last_moving_ranks: List[int] = field(default_factory=list)


class GangWatchdog:
    """Per-job hang state machine (one per job incarnation).

    The reconciler calls :meth:`observe` on every reconcile of a running
    gang; a non-None return is a freshly declared hang. All state is in
    memory — an operator restart simply re-arms from the live telemetry
    (the stall, if real, is still there ``timeout_s`` later; detection
    latency degrades, correctness doesn't).
    """

    def __init__(self, timeout_s: float) -> None:
        self.timeout_s = max(0.0, float(timeout_s))
        self._max_step = -1  # progress high-water mark (-1: no telemetry yet)
        self._progress_time: Optional[float] = None
        self._epoch: Optional[int] = None  # resize epoch last observed
        self._armed = False  # stall crossed the timeout at least once
        self.hung = False  # latched verdict; cleared on progress or reset()

    # -- derived state ------------------------------------------------------

    @property
    def stalled(self) -> bool:
        """True while a stall is pending or declared — the reconciler's
        cue to suppress straggler observation (disambiguation rule)."""
        return self.hung or self._armed

    def seconds_since_progress(self, now: float) -> Optional[float]:
        if self._progress_time is None:
            return None
        return max(0.0, now - self._progress_time)

    # -- the state machine --------------------------------------------------

    def observe(
        self,
        window: Dict[int, Telemetry],
        now: float,
        resize_epoch: int = 0,
        first_step_time: Optional[float] = None,
    ) -> Optional[HangVerdict]:
        """Consume one reconcile's view of the gang; return a verdict the
        FIRST time the stall crosses the timeout, None otherwise.

        ``window`` is latest_window() over the job's telemetry;
        ``first_step_time`` is the TTFS span's start (None before the
        first step — pre-first-step grace keeps the watchdog idle).
        """
        if self.timeout_s <= 0:
            return None
        # Resize in flight / just landed: the gang is re-forming, steps
        # legitimately pause. Reset the clock, keep the high-water mark
        # (post-resize progress must still ADVANCE it to count).
        if self._epoch is not None and resize_epoch != self._epoch:
            self._progress_time = now
            self._armed = False
            self.hung = False
        self._epoch = resize_epoch

        if not window:
            # No telemetry yet. Idle until the TTFS span proves the data
            # plane produced a first step; from then on, silence itself
            # is the signal (a gang that marked step 1 then never flushed
            # a window is exactly as wedged as one that froze mid-run).
            if first_step_time is None:
                return None
            if self._progress_time is None:
                self._progress_time = min(first_step_time, now)
            return self._check(now, stuck_step=0, moving=[])

        max_step = max(b.end_step for b in window.values())
        if max_step > self._max_step:
            # Progress: advance the mark, restart the clock, clear any
            # armed/declared state (first advance wins, flush cadence
            # irrelevant).
            self._max_step = max_step
            self._progress_time = now
            self._armed = False
            self.hung = False
            return None
        if self._progress_time is None:
            self._progress_time = now if first_step_time is None else max(
                first_step_time, min(b.time for b in window.values())
            )
        moving = sorted(
            r for r, b in window.items() if b.end_step >= self._max_step
        )
        return self._check(now, stuck_step=max(self._max_step, 0), moving=moving)

    def _check(
        self, now: float, stuck_step: int, moving: List[int]
    ) -> Optional[HangVerdict]:
        stalled_for = now - (self._progress_time or now)
        if stalled_for < self.timeout_s:
            return None
        self._armed = True
        if self.hung:
            return None  # latched: one hang, one verdict, one stack sweep
        self.hung = True
        return HangVerdict(
            stuck_step=stuck_step,
            since=self._progress_time or now,
            stalled_for=stalled_for,
            last_moving_ranks=moving,
        )

    def reset(self, now: Optional[float] = None) -> None:
        """Forget everything — called when the gang restarts (the new
        incarnation re-earns its progress baseline)."""
        self._max_step = -1
        self._progress_time = now
        self._epoch = None
        self._armed = False
        self.hung = False
