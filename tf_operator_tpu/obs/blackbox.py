"""Blackbox flight recorder: frozen forensics for hangs and terminal failures.

When a job dies loudly, the exit code says why. When it hangs, or fails
in a way the operator must diagnose after the fact, the scene is gone by
the time anyone looks: the ring evicted old telemetry, processes were
killed and GC'd, and the only artifact is a terminal condition string.
MegaScale-style production postmortems need the opposite — capture the
scene BEFORE recovery destroys it.

Two store-object roles share one kind (:data:`KIND_POSTMORTEM`), both
labeled with the indexed job-name label so listing/GC is one bucket read
(same rule as spans/telemetry):

- **Stack dumps** (``section="stackdump"``): one object per rank per
  stack-sweep epoch, shipped by the HostAgent after SIGUSR2 made the
  harness's faulthandler hook write all-thread stacks to a per-rank
  file. Text is size-capped with an explicit truncation marker —
  forensics are bounded, never unbounded, and truncation is visible,
  never silent.
- **The bundle** (``section="bundle"``): the per-job flight recorder
  frozen at declaration of a hang or any terminal failure: last N
  events, open + recent spans, the last telemetry window per rank,
  bounded status history (the in-memory part — the store only keeps the
  LATEST status), the hang verdict, and whatever stack dumps had been
  shipped. Served at ``GET /api/tpujob/<ns>/<name>/postmortem`` and
  assembled into a tar by ``tpujob debug``.

Everything here is best-effort (a forensics failure must never break
recovery) and GC'd with the job alongside spans/telemetry — after which
``tpujob debug`` fails LOUDLY (404), not with an empty tar.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional

from tf_operator_tpu.api.types import (
    API_GROUP,
    KIND_EVENT,
    KIND_POSTMORTEM,
    LABEL_GROUP,
    LABEL_JOB_NAME,
    ObjectMeta,
)
from tf_operator_tpu.obs.spans import job_trace, trace8
from tf_operator_tpu.obs.telemetry import job_telemetry, latest_window

# NOTE: same import rule as spans.py/telemetry.py — no module-level import
# from tf_operator_tpu.runtime (runtime imports obs); store exception
# types are resolved lazily.

log = logging.getLogger("tpujob.obs")

# Bounds (truncate-with-marker, never drop silently; never unbounded).
BLACKBOX_MAX_EVENTS = 50  # newest events kept in the bundle
BLACKBOX_MAX_SPANS = 120  # newest spans kept (open spans always kept)
BLACKBOX_MAX_STATUS = 50  # in-memory status-transition ring depth
STACKDUMP_MAX_CHARS = 16_000  # per-rank stack text cap
TRUNCATION_MARKER = "\n...[truncated by blackbox size cap]"


@dataclass
class PostmortemArtifact:
    """One forensics store object — a rank's stack dump or the frozen
    per-job bundle (discriminated by ``section``)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    trace_id: str = ""  # job uid
    section: str = ""  # "stackdump" | "bundle"
    reason: str = ""  # bundle: "hang" | "failed"; stackdump: ""
    rank: int = -1  # stackdump only
    epoch: int = 0  # stackdump: sweep epoch that produced it
    payload: Dict[str, Any] = field(default_factory=dict)
    truncated: bool = False  # a size cap bit (marker is in the text too)
    time: float = 0.0
    kind: str = KIND_POSTMORTEM

    def key(self) -> str:
        return self.metadata.key()


def postmortem_labels(job_name: str) -> Dict[str, str]:
    return {LABEL_GROUP: API_GROUP, LABEL_JOB_NAME: job_name}


def postmortem_name(job_name: str, trace_id: str) -> str:
    """Deterministic bundle name: one frozen bundle per job incarnation;
    a second freeze attempt is an AlreadyExists no-op (first scene wins —
    later freezes would capture the recovery, not the failure)."""
    return f"{job_name}-{trace8(trace_id)}-postmortem"


def stackdump_name(job_name: str, trace_id: str, rank: int, epoch: int) -> str:
    """Deterministic per-(rank, sweep-epoch) name — the agent's shipment
    is idempotent and one hang yields exactly one dump per rank."""
    return f"{job_name}-{trace8(trace_id)}-stack-r{rank}-e{epoch}"


def cap_text(text: str, limit: int = STACKDUMP_MAX_CHARS) -> "tuple[str, bool]":
    """Bound a forensic text blob: keep the TAIL (faulthandler prints the
    current — wedged — frame last in each thread block, and the newest
    threads matter most) and mark the cut explicitly."""
    if len(text) <= limit:
        return text, False
    keep = max(0, limit - len(TRUNCATION_MARKER))
    return TRUNCATION_MARKER.lstrip("\n") + "\n" + text[-keep:], True


def ship_stackdump(
    store: Any,
    namespace: str,
    job_name: str,
    trace_id: str,
    rank: int,
    epoch: int,
    text: str,
    host: str = "",
) -> Optional[PostmortemArtifact]:
    """Agent-side: publish one rank's stack text through the store/API
    seam (size-capped). Best-effort; AlreadyExists is success (another
    sweep pass already shipped this rank/epoch)."""
    capped, truncated = cap_text(text)
    art = PostmortemArtifact(
        metadata=ObjectMeta(
            name=stackdump_name(job_name, trace_id, rank, epoch),
            namespace=namespace,
            labels=postmortem_labels(job_name),
        ),
        trace_id=trace_id,
        section="stackdump",
        rank=rank,
        epoch=epoch,
        payload={"text": capped, "host": host},
        truncated=truncated,
        time=time.time(),
    )
    try:
        return store.create(art)
    except Exception as exc:  # noqa: BLE001 — forensics are best-effort
        try:
            from tf_operator_tpu.runtime.store import AlreadyExistsError

            if isinstance(exc, AlreadyExistsError):
                return art
        except Exception:  # noqa: BLE001
            pass
        log.debug("stackdump %s/%s not shipped: %s",
                  namespace, art.metadata.name, exc)
        return None


def job_stackdumps(
    store: Any, namespace: str, job_name: str, epoch: Optional[int] = None
) -> List[PostmortemArtifact]:
    """All shipped stack dumps of a job (optionally one sweep epoch),
    rank order."""
    arts = store.list(
        KIND_POSTMORTEM, namespace=namespace,
        label_selector={LABEL_JOB_NAME: job_name},
    )
    dumps = [a for a in arts if a.section == "stackdump"
             and (epoch is None or a.epoch == epoch)]
    dumps.sort(key=lambda a: (a.epoch, a.rank))
    return dumps


def load_postmortem(
    store: Any, namespace: str, job_name: str
) -> Optional[PostmortemArtifact]:
    """The job's frozen bundle, or None (not yet frozen, or GC'd —
    callers surface that distinction loudly, never as an empty result)."""
    arts = store.list(
        KIND_POSTMORTEM, namespace=namespace,
        label_selector={LABEL_JOB_NAME: job_name},
    )
    for a in arts:
        if a.section == "bundle":
            return a
    return None


class Blackbox:
    """Bounded in-memory flight recorder for ONE job.

    The reconciler owns one per job and feeds it status transitions as
    they happen (the only signal the store does NOT retain history for);
    events/spans/telemetry are pulled from the store at freeze time —
    they are already durable and job-labeled. ``freeze`` assembles and
    persists the bundle exactly once per incarnation.
    """

    def __init__(self, max_status: int = BLACKBOX_MAX_STATUS) -> None:
        self._status: Deque[Dict[str, Any]] = deque(maxlen=max_status)
        self._last_sig: Optional[tuple] = None

    def observe_status(self, job: Any, now: Optional[float] = None) -> None:
        """Record one status snapshot iff it differs from the last one
        (phase/conditions/counters — heartbeat-only churn is skipped)."""
        st = job.status
        conds = [(c.type.value, bool(c.status), c.reason) for c in st.conditions]
        sig = (
            st.phase().value, tuple(conds), st.restart_count,
            st.preemption_count, st.resize_count, st.hang_count,
            st.last_restart_cause,
        )
        if sig == self._last_sig:
            return
        self._last_sig = sig
        self._status.append({
            "time": time.time() if now is None else now,
            "phase": st.phase().value,
            "conditions": [
                {"type": t, "status": s, "reason": r} for t, s, r in conds
            ],
            "restart_count": st.restart_count,
            "preemption_count": st.preemption_count,
            "resize_count": st.resize_count,
            "hang_count": st.hang_count,
            "last_restart_cause": st.last_restart_cause,
        })

    def status_history(self) -> List[Dict[str, Any]]:
        return list(self._status)

    def freeze(
        self,
        store: Any,
        job: Any,
        reason: str,
        detail: Optional[Dict[str, Any]] = None,
        now: Optional[float] = None,
    ) -> Optional[PostmortemArtifact]:
        """Assemble + persist the postmortem bundle (idempotent: the
        first freeze of an incarnation wins). Returns the artifact, or
        None when the store write failed. Never raises."""
        now = time.time() if now is None else now
        ns = job.metadata.namespace
        name = job.metadata.name
        uid = job.metadata.uid
        truncated: List[str] = []
        try:
            events = self._collect_events(store, ns, name, truncated)
            spans = self._collect_spans(store, ns, name, truncated)
            telem = self._collect_telemetry(store, ns, name)
            stacks = [
                {
                    "rank": d.rank, "epoch": d.epoch,
                    "host": d.payload.get("host", ""),
                    "truncated": d.truncated,
                    "text": d.payload.get("text", ""),
                }
                for d in job_stackdumps(store, ns, name)
            ]
        except Exception as exc:  # noqa: BLE001 — forensics are best-effort
            log.debug("postmortem collection for %s/%s degraded: %s",
                      ns, name, exc)
            events, spans, telem, stacks = [], [], {}, []
            truncated.append("collection-error")
        art = PostmortemArtifact(
            metadata=ObjectMeta(
                name=postmortem_name(name, uid),
                namespace=ns,
                labels=postmortem_labels(name),
            ),
            trace_id=uid,
            section="bundle",
            reason=reason,
            payload={
                "job": f"{ns}/{name}",
                "reason": reason,
                "frozen_at": now,
                "detail": dict(detail or {}),
                "status_history": self.status_history(),
                "events": events,
                "spans": spans,
                "telemetry": telem,
                "stackdumps": stacks,
            },
            truncated=bool(truncated),
            time=now,
        )
        if truncated:
            art.payload["truncated_sections"] = truncated
        try:
            return store.create(art)
        except Exception as exc:  # noqa: BLE001
            try:
                from tf_operator_tpu.runtime.store import AlreadyExistsError

                if isinstance(exc, AlreadyExistsError):
                    return art  # first scene already frozen — keep it
            except Exception:  # noqa: BLE001
                pass
            log.debug("postmortem for %s/%s not frozen: %s", ns, name, exc)
            return None

    # -- collection helpers (store → bounded JSON) --------------------------

    @staticmethod
    def _collect_events(store, ns, job_name, truncated) -> List[Dict[str, Any]]:
        evs = [
            e for e in store.list(KIND_EVENT, namespace=ns)
            if e.involved_name == job_name
            or e.involved_name.startswith(job_name + "-")
        ]
        evs.sort(key=lambda e: e.timestamp)
        if len(evs) > BLACKBOX_MAX_EVENTS:
            truncated.append("events")
            evs = evs[-BLACKBOX_MAX_EVENTS:]
        return [
            {
                "time": e.timestamp, "type": e.type.value, "reason": e.reason,
                "object": e.involved_name, "count": e.count,
                "message": e.message,
            }
            for e in evs
        ]

    @staticmethod
    def _collect_spans(store, ns, job_name, truncated) -> List[Dict[str, Any]]:
        spans = job_trace(store, ns, job_name)
        open_spans = [s for s in spans if not s.end_time]
        closed = [s for s in spans if s.end_time]
        keep = BLACKBOX_MAX_SPANS - len(open_spans)
        if len(closed) > keep > 0:
            truncated.append("spans")
            closed = closed[-keep:]
        return [
            {
                "name": s.metadata.name, "op": s.op, "component": s.component,
                "start": s.start_time, "end": s.end_time, "attrs": s.attrs,
                "open": not s.end_time,
            }
            for s in (closed + open_spans)
        ]

    @staticmethod
    def _collect_telemetry(store, ns, job_name) -> Dict[str, Any]:
        window = latest_window(job_telemetry(store, ns, job_name))
        return {
            str(rank): {
                "seq": b.seq, "end_step": b.end_step,
                "step_time_s": b.step_time_s, "tokens_per_s": b.tokens_per_s,
                "data_wait_s": b.data_wait_s, "ckpt_stall_s": b.ckpt_stall_s,
                "time": b.time, "degraded": b.degraded,
            }
            for rank, b in sorted(window.items())
        }


def delete_forensics(store: Any, namespace: str, job_name: str) -> int:
    """GC every forensics object of a job (stack dumps + frozen bundle) —
    called from the reconciler's deletion path next to span/telemetry GC.
    Returns the number deleted; never raises."""
    deleted = 0
    try:
        arts = store.list(
            KIND_POSTMORTEM, namespace=namespace,
            label_selector={LABEL_JOB_NAME: job_name},
        )
    except Exception:  # noqa: BLE001
        return 0
    for a in arts:
        try:
            store.delete(KIND_POSTMORTEM, namespace, a.metadata.name)
            deleted += 1
        except Exception:  # noqa: BLE001 — already gone is fine
            pass
    return deleted
