"""Chrome trace-event export + derived cross-component timings.

``to_chrome_trace`` renders a job's spans in the Chrome trace-event JSON
format (the ``traceEvents`` array of "X"/"i"/"M" events that
chrome://tracing and Perfetto load directly): one Perfetto *process* row
per component (controller / scheduler / agent / trainer), one *thread*
row per track within it, microsecond timestamps relative to job submit.

``derive_timings`` is the span-boundary arithmetic behind the first-class
metrics (controller/metrics.py histograms) and the chaos soak's
recovery-downtime assertion: submit→scheduled, submit→first-step (TTFS),
and per-restart downtime windows (MTTR) all fall straight out of the
timeline instead of being inferred from logs.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from tf_operator_tpu.obs.spans import (
    COMPONENT_AGENT,
    COMPONENT_CONTROLLER,
    COMPONENT_SCHEDULER,
    COMPONENT_TRAINER,
    Span,
)

# Stable Perfetto process-row order; unknown components append after.
COMPONENT_ORDER = (
    COMPONENT_CONTROLLER,
    COMPONENT_SCHEDULER,
    COMPONENT_AGENT,
    COMPONENT_TRAINER,
)


def _track(span: Span) -> str:
    """The thread row a span renders on. Distinct tracks per op (and per
    process for agent/trainer spans) keep partially-overlapping spans —
    e.g. ``scheduled`` and ``admission`` both anchored at submit — from
    sharing a row, which Chrome would mis-nest."""
    return span.attrs.get("track") or span.op


def derive_timings(spans: List[Span], submit_ts: Optional[float] = None) -> Dict[str, Any]:
    """Span-boundary metrics for one trace.

    ``submit_ts`` anchors the latencies (the job's creation timestamp);
    when absent it falls back to the root ``job`` span's start, then the
    earliest span start.
    """
    by_op: Dict[str, List[Span]] = {}
    for s in spans:
        by_op.setdefault(s.op, []).append(s)

    def first(op: str) -> Optional[Span]:
        got = by_op.get(op)
        return min(got, key=lambda s: s.start_time) if got else None

    root = first("job")
    if submit_ts is None:
        if root is not None:
            submit_ts = root.start_time
        elif spans:
            submit_ts = min(s.start_time for s in spans)

    out: Dict[str, Any] = {"submit": submit_ts}
    sched = first("scheduled")
    if sched is not None and sched.end_time and submit_ts:
        out["time_to_scheduled_s"] = max(0.0, sched.end_time - submit_ts)
    fs = first("first-step")
    if fs is not None and submit_ts:
        out["time_to_first_step_s"] = max(0.0, fs.start_time - submit_ts)
    restarts = []
    for s in sorted(by_op.get("restart", ()), key=lambda s: s.start_time):
        restarts.append(
            {
                "cause": s.attrs.get("cause", ""),
                "start": s.start_time,
                "end": s.end_time or None,
                "downtime_s": s.duration(),
            }
        )
    out["restarts"] = restarts
    return out


def to_chrome_trace(spans: List[Span], job: Any = None) -> Dict[str, Any]:
    """Render spans as a Chrome trace-event JSON document.

    ``job`` (a TPUJob, optional) anchors t=0 at submit and contributes
    the summary block; without it t=0 is the earliest span start.
    """
    submit_ts = None
    if job is not None and job.metadata.creation_timestamp:
        submit_ts = job.metadata.creation_timestamp
    t0 = submit_ts
    if t0 is None and spans:
        t0 = min(s.start_time for s in spans if s.start_time > 0)
    t0 = t0 or 0.0

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 1)

    components = [c for c in COMPONENT_ORDER if any(s.component == c for s in spans)]
    components += sorted(
        {s.component for s in spans} - set(components) - {""}
    )
    pid_of = {c: i + 1 for i, c in enumerate(components)}

    events: List[Dict[str, Any]] = []
    for c in components:
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid_of[c],
                "tid": 0,
                "args": {"name": c},
            }
        )

    tid_of: Dict[tuple, int] = {}
    for span in sorted(spans, key=lambda s: (s.start_time, s.metadata.name)):
        pid = pid_of.get(span.component or "", 0) or 1
        tkey = (pid, _track(span))
        if tkey not in tid_of:
            tid_of[tkey] = sum(1 for k in tid_of if k[0] == pid) + 1
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid_of[tkey],
                    "args": {"name": _track(span)},
                }
            )
        tid = tid_of[tkey]
        args = {
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            **span.attrs,
        }
        if span.end_time and span.end_time > span.start_time:
            events.append(
                {
                    "name": span.op,
                    "cat": span.component or "span",
                    "ph": "X",
                    "ts": us(span.start_time),
                    "dur": round((span.end_time - span.start_time) * 1e6, 1),
                    "pid": pid,
                    "tid": tid,
                    "args": args,
                }
            )
        elif span.end_time:  # instantaneous mark (start == end)
            events.append(
                {
                    "name": span.op,
                    "cat": span.component or "span",
                    "ph": "i",
                    "s": "p",
                    "ts": us(span.start_time),
                    "pid": pid,
                    "tid": tid,
                    "args": args,
                }
            )
        else:  # still open — zero-duration slice flagged open
            events.append(
                {
                    "name": span.op,
                    "cat": span.component or "span",
                    "ph": "X",
                    "ts": us(span.start_time),
                    "dur": 0,
                    "pid": pid,
                    "tid": tid,
                    "args": {**args, "open": "true"},
                }
            )

    other: Dict[str, Any] = {
        "spans": len(spans),
        "components": components,
        **derive_timings(spans, submit_ts=submit_ts),
    }
    if spans:
        other["trace_id"] = spans[0].trace_id
    if job is not None:
        other["job"] = job.metadata.key()
        other["phase"] = job.status.phase().value

    return {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "otherData": other,
    }
