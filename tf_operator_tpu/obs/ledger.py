"""FleetLedger: the durable, compacted, cross-job memory of outcomes.

Every observability surface the operator has — spans, telemetry windows,
goodput decompositions, postmortems, autopilot receipts — is scoped to
one live job and evaporates at job GC. The ledger is the layer above: at
every job terminal the reconciler folds a compact :class:`JobRecord`
(terminal phase, per-cause lost-seconds, goodput ratio, TTFS, autopilot
decisions with their justifying numbers, hosts touched) into an
append-only file set that survives operator death, job GC, and even
total store loss. It is the one thing the operator remembers.

Durability reuses the exact ``runtime/persist.py`` WAL recipe — the
idioms, not the files (the ledger has its own directory and lifecycle;
store snapshots GC with the store, ledger records never do):

- ``records-<start_seq>.jsonl``: one CRC32-checked JSON record appended
  per fold, flushed per record. A torn final record of the final segment
  is truncated on open; a bad checksum anywhere else is corruption and
  refuses loudly (``PersistenceError``).
- ``rollup-<seq>.json``: every ``snapshot_every`` folds the full record
  set is written tmp+rename, the segment rotates, and superseded files
  are deleted. Recovery = newest rollup + replay of the segment suffix
  (records with seq > rollup seq) — byte-identical rollups before and
  after an operator SIGKILL.

Exactly-once folding is durable, not in-memory: ``fold()`` dedupes on
job uid against the recovered record set, so an operator SIGKILLed
between writing a job's terminal status and folding it simply folds on
the next incarnation's sweep — and a SIGKILL *after* the fold cannot
double-count, because the uid is already on disk.

Deliberate non-goals (design.md §6.4): the ledger is not a metrics
TSDB — it keeps one compact record per job, never raw telemetry, never
per-step series; queries are whole-fleet rollups recomputed from the
record set, not time-range scans.
"""

from __future__ import annotations

import json
import logging
import math
import os
import re
import threading
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, Optional

from tf_operator_tpu.runtime.persist import (
    PersistenceError,
    _checksum,
    _replay_segment,
    _unlink_quiet,
)

log = logging.getLogger("tpujob.ledger")

_ROLLUP_RE = re.compile(r"^rollup-(\d+)\.json$")
_RECORDS_RE = re.compile(r"^records-(\d+)\.jsonl$")

DEFAULT_ROLLUP_EVERY = 256

# Goodput-ratio histogram bucket edges (upper-inclusive last bucket).
_GOODPUT_EDGES = (0.2, 0.4, 0.6, 0.8, 1.0)

# Host-reputation defaults: "a host that ate three jobs last hour
# starts flagged for the next one".
REPUTATION_WINDOW_S = 3600.0
REPUTATION_THRESHOLD = 3


@dataclass
class JobRecord:
    """One job's terminal outcome, compact enough to keep forever."""

    uid: str = ""
    namespace: str = ""
    name: str = ""
    queue: str = ""
    priority_class: str = ""
    job_class: str = ""
    phase: str = ""  # terminal phase: Succeeded | Failed
    submit_ts: float = 0.0
    end_ts: float = 0.0
    wall_s: float = 0.0  # submit -> terminal (the MTBF numerator)
    restarts: int = 0
    preemptions: int = 0
    hangs: int = 0
    resizes: int = 0
    last_restart_cause: str = ""
    lost_s: Dict[str, float] = field(default_factory=dict)  # per-cause ledger
    goodput_ratio: float = 0.0
    ttfs_s: float = 0.0  # time to first step (0 = never stepped)
    ttfs_kind: str = ""  # "cold" | "warm" | ""
    save_stall_s: float = 0.0  # mean measured stall per accepted save
    saves: int = 0  # save-stall spans backing save_stall_s
    step_time_s: float = 0.0  # last cross-rank median step time
    autopilot_decisions: int = 0  # executed decisions, total
    decisions: List[Dict[str, str]] = field(default_factory=list)  # receipts
    hosts: List[str] = field(default_factory=list)  # hosts touched

    def failures(self) -> int:
        """The MTBF denominator, same accounting as _autopilot_inputs."""
        return self.restarts + self.preemptions + self.hangs


def _failures(rec: Dict[str, Any]) -> int:
    return (
        int(rec.get("restarts", 0))
        + int(rec.get("preemptions", 0))
        + int(rec.get("hangs", 0))
    )


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile (the pinned, hand-computable rule:
    value at index ceil(q*n)-1 of the sorted list)."""
    if not sorted_vals:
        return 0.0
    idx = max(0, math.ceil(q * len(sorted_vals)) - 1)
    return sorted_vals[min(idx, len(sorted_vals) - 1)]


def _r(x: float) -> float:
    """Round for rollup display: keeps summaries deterministic and the
    byte-identical acceptance check independent of float formatting."""
    return round(float(x), 6)


class FleetLedger:
    """Append-only job-outcome ledger with compacted rollups.

    Thread-safe; ``fold`` is called from the reconciler's sync path and
    the HTTP handlers read rollups concurrently.
    """

    def __init__(
        self,
        data_dir: str,
        snapshot_every: int = DEFAULT_ROLLUP_EVERY,
        fsync: bool = False,
    ) -> None:
        self.data_dir = os.path.abspath(data_dir)
        self.snapshot_every = max(1, int(snapshot_every))
        self.fsync = bool(fsync)
        os.makedirs(self.data_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._records: List[Dict[str, Any]] = []  # seq order
        self._by_uid: Dict[str, Dict[str, Any]] = {}
        self._seq = 0
        self._since_rollup = 0
        # Optional provider (cli/operator wires cachesvc.snapshot) whose
        # hit/miss counters fold into summary()["compile_cache"].
        self.cachesvc_stats: Optional[Callable[[], Dict[str, Any]]] = None
        self._recover()
        self._segment_path = os.path.join(
            self.data_dir, f"records-{self._seq + 1}.jsonl"
        )
        self._wal = open(self._segment_path, "ab")

    # -- recovery ----------------------------------------------------------

    def _recover(self) -> None:
        rollup_seq, rollup_records = self._load_rollup()
        for rec in rollup_records:
            self._admit(rec)
        segments = []
        for name in os.listdir(self.data_dir):
            m = _RECORDS_RE.match(name)
            if m:
                segments.append((int(m.group(1)), os.path.join(self.data_dir, name)))
        segments.sort()
        replayed = 0
        for i, (_, path) in enumerate(segments):
            records, _truncated = _replay_segment(path, i == len(segments) - 1)
            for rec in records:
                if int(rec.get("seq", 0)) <= rollup_seq:
                    continue  # already folded into the rollup
                self._admit(rec)
                replayed += 1
        if self._records:
            log.info(
                "fleet ledger at %s: %d records (rollup seq %d + %d replayed)",
                self.data_dir, len(self._records), rollup_seq, replayed,
            )

    def _load_rollup(self) -> "tuple[int, List[Dict[str, Any]]]":
        best_seq, best_path = 0, None
        for name in os.listdir(self.data_dir):
            m = _ROLLUP_RE.match(name)
            if m and int(m.group(1)) > best_seq:
                best_seq = int(m.group(1))
                best_path = os.path.join(self.data_dir, name)
        if best_path is None:
            return 0, []
        try:
            with open(best_path) as f:
                body = json.load(f)
        except (OSError, ValueError) as exc:
            raise PersistenceError(
                f"ledger rollup {best_path} unreadable: {exc}"
            ) from exc
        crc = body.get("crc")
        if crc is not None and crc != _checksum(body):
            raise PersistenceError(
                f"ledger rollup {best_path} failed its checksum"
            )
        return int(body["seq"]), list(body.get("records", []))

    def _admit(self, rec: Dict[str, Any]) -> None:
        """Index one recovered/folded record (lock held or init)."""
        uid = rec.get("uid", "")
        if uid and uid in self._by_uid:
            return  # duplicate uid in damaged-but-recoverable state: keep first
        self._records.append(rec)
        if uid:
            self._by_uid[uid] = rec
        self._seq = max(self._seq, int(rec.get("seq", 0)))

    # -- write path --------------------------------------------------------

    def fold(self, record: Any) -> bool:
        """Fold one terminal job into the ledger. Exactly-once on uid:
        returns False (and writes nothing) when the uid is already
        recorded — durable across operator death, because the dedupe set
        IS the recovered record set."""
        rec = asdict(record) if isinstance(record, JobRecord) else dict(record)
        uid = rec.get("uid", "")
        with self._lock:
            if uid and uid in self._by_uid:
                return False
            self._seq += 1
            rec["seq"] = self._seq
            rec.pop("crc", None)
            rec["crc"] = _checksum(rec)
            self._wal.write(json.dumps(rec, sort_keys=True).encode() + b"\n")
            self._wal.flush()
            if self.fsync:
                os.fsync(self._wal.fileno())
            self._records.append(rec)
            if uid:
                self._by_uid[uid] = rec
            self._since_rollup += 1
            if self._since_rollup >= self.snapshot_every:
                self._rollup()
        return True

    def _rollup(self) -> None:
        """Compact: full record set tmp+renamed, segment rotated,
        superseded files GC'd (lock held)."""
        seq = self._seq
        body: Dict[str, Any] = {"seq": seq, "records": self._records}
        body["crc"] = _checksum(body)
        final = os.path.join(self.data_dir, f"rollup-{seq}.json")
        tmp = final + ".tmp"
        with open(tmp, "w") as f:
            json.dump(body, f, sort_keys=True)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        os.rename(tmp, final)
        self._wal.close()
        self._segment_path = os.path.join(
            self.data_dir, f"records-{seq + 1}.jsonl"
        )
        self._wal = open(self._segment_path, "ab")
        if self.fsync:
            fd = os.open(self.data_dir, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        self._since_rollup = 0
        for name in os.listdir(self.data_dir):
            path = os.path.join(self.data_dir, name)
            if path == self._segment_path:
                continue
            m = _ROLLUP_RE.match(name) or _RECORDS_RE.match(name)
            if m and int(m.group(1)) <= seq and name != f"rollup-{seq}.json":
                _unlink_quiet(path)

    def close(self) -> None:
        with self._lock:
            try:
                self._wal.flush()
                if self.fsync:
                    os.fsync(self._wal.fileno())
            finally:
                self._wal.close()

    # -- read path ---------------------------------------------------------

    def has(self, uid: str) -> bool:
        with self._lock:
            return uid in self._by_uid

    def get(self, uid: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._by_uid.get(uid)
            return dict(rec) if rec else None

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._records]

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def summary(self) -> Dict[str, Any]:
        """The fleet rollup: per-queue MTBF/goodput, per-cause downtime
        percentiles and incident counts, a goodput histogram. Computed
        purely from the record set with pinned rounding, so the JSON
        serialization (sort_keys) is byte-identical across recovery."""
        with self._lock:
            recs = list(self._records)
        out: Dict[str, Any] = {
            "jobs": len(recs),
            "seq": self._seq,
            "phases": {},
            "failures": 0,
            "wall_s": 0.0,
            "mtbf_s": None,
            "queues": {},
            "causes": {},
            "goodput_hist": {},
            "goodput_mean": None,
        }
        if not recs:
            if self.cachesvc_stats is not None:
                out["compile_cache"] = self._compile_cache()
            return out
        total_wall = 0.0
        total_failures = 0
        ratios: List[float] = []
        queues: Dict[str, Dict[str, Any]] = {}
        causes: Dict[str, Dict[str, Any]] = {}
        cause_losses: Dict[str, List[float]] = {}
        hist: Dict[str, int] = {}
        lo = 0.0
        for hi in _GOODPUT_EDGES:
            hist[f"{lo:.1f}-{hi:.1f}"] = 0
            lo = hi
        for rec in recs:
            phase = rec.get("phase", "") or "?"
            out["phases"][phase] = out["phases"].get(phase, 0) + 1
            wall = float(rec.get("wall_s", 0.0))
            fails = _failures(rec)
            total_wall += wall
            total_failures += fails
            ratio = float(rec.get("goodput_ratio", 0.0))
            ratios.append(ratio)
            lo = 0.0
            for hi in _GOODPUT_EDGES:
                if ratio <= hi or hi == _GOODPUT_EDGES[-1]:
                    hist[f"{lo:.1f}-{hi:.1f}"] += 1
                    break
                lo = hi
            q = queues.setdefault(rec.get("queue", ""), {
                "jobs": 0, "failures": 0, "wall_s": 0.0,
                "goodput_sum": 0.0, "saves": 0, "stall_weighted": 0.0,
            })
            q["jobs"] += 1
            q["failures"] += fails
            q["wall_s"] += wall
            q["goodput_sum"] += ratio
            saves = int(rec.get("saves", 0))
            q["saves"] += saves
            q["stall_weighted"] += float(rec.get("save_stall_s", 0.0)) * saves
            for cause, lost in sorted((rec.get("lost_s") or {}).items()):
                c = causes.setdefault(cause, {"incidents": 0, "lost_s": 0.0})
                c["incidents"] += 1
                c["lost_s"] += float(lost)
                cause_losses.setdefault(cause, []).append(float(lost))
        out["failures"] = total_failures
        out["wall_s"] = _r(total_wall)
        out["mtbf_s"] = (
            _r(total_wall / total_failures) if total_failures > 0 else None
        )
        out["goodput_mean"] = _r(sum(ratios) / len(ratios))
        out["goodput_hist"] = hist
        for name in sorted(queues):
            q = queues[name]
            out["queues"][name] = {
                "jobs": q["jobs"],
                "failures": q["failures"],
                "wall_s": _r(q["wall_s"]),
                "mtbf_s": (
                    _r(q["wall_s"] / q["failures"]) if q["failures"] else None
                ),
                "goodput_mean": _r(q["goodput_sum"] / q["jobs"]),
                "save_stall_s": (
                    _r(q["stall_weighted"] / q["saves"]) if q["saves"] else 0.0
                ),
            }
        for cause in sorted(causes):
            vals = sorted(cause_losses[cause])
            out["causes"][cause] = {
                "incidents": causes[cause]["incidents"],
                "lost_s": _r(causes[cause]["lost_s"]),
                "lost_p50_s": _r(_percentile(vals, 0.5)),
                "lost_p90_s": _r(_percentile(vals, 0.9)),
                "lost_p99_s": _r(_percentile(vals, 0.99)),
            }
        if self.cachesvc_stats is not None:
            out["compile_cache"] = self._compile_cache()
        return out

    def _compile_cache(self) -> Dict[str, Any]:
        try:
            stats = self.cachesvc_stats() or {}
        except Exception:  # provider is best-effort observability
            return {}
        hits = int(stats.get("hits", 0))
        misses = int(stats.get("misses", 0))
        total = hits + misses
        return {
            "hits": hits,
            "misses": misses,
            "evictions": int(stats.get("evictions", 0)),
            "intents": int(stats.get("intents", 0)),
            "miss_rate": _r(misses / total) if total else None,
        }

    def hosts(self) -> Dict[str, Dict[str, Any]]:
        """Per-host ledger view: jobs touched, jobs with incidents
        (restart/preemption/hang), last terminal seen."""
        with self._lock:
            recs = list(self._records)
        out: Dict[str, Dict[str, Any]] = {}
        for rec in recs:
            fails = _failures(rec)
            for host in rec.get("hosts") or []:
                h = out.setdefault(host, {
                    "jobs": 0, "incident_jobs": 0, "failures": 0,
                    "last_end_ts": 0.0,
                })
                h["jobs"] += 1
                h["failures"] += fails
                if fails > 0:
                    h["incident_jobs"] += 1
                h["last_end_ts"] = max(
                    h["last_end_ts"], _r(float(rec.get("end_ts", 0.0)))
                )
        return {k: out[k] for k in sorted(out)}

    def host_reputation(
        self,
        now: float,
        window_s: float = REPUTATION_WINDOW_S,
        threshold: int = REPUTATION_THRESHOLD,
    ) -> Dict[str, int]:
        """Hosts that ate >= ``threshold`` incident jobs within the last
        ``window_s`` seconds -> recent incident-job count. The reconciler
        feeds these into the scheduler's soft-deprioritized set so the
        next job starts flagged."""
        with self._lock:
            recs = list(self._records)
        cutoff = now - window_s
        counts: Dict[str, int] = {}
        for rec in recs:
            if _failures(rec) <= 0:
                continue
            if float(rec.get("end_ts", 0.0)) < cutoff:
                continue
            for host in rec.get("hosts") or []:
                counts[host] = counts.get(host, 0) + 1
        return {
            h: n for h, n in sorted(counts.items()) if n >= max(1, threshold)
        }

    def cadence_inputs(
        self, queue: str = "", job_class: str = ""
    ) -> Dict[str, Any]:
        """Aggregated prior inputs for one (queue, job_class) cohort.

        Exact-cohort match first; an empty cohort falls back to the
        whole fleet (a fresh queue still benefits from fleet-wide
        history). Returns {} when the ledger is empty."""
        with self._lock:
            recs = list(self._records)
        if not recs:
            return {}
        cohort = [
            r for r in recs
            if r.get("queue", "") == queue
            and r.get("job_class", "") == job_class
        ]
        if not cohort:
            cohort = recs
        total_wall = sum(float(r.get("wall_s", 0.0)) for r in cohort)
        total_failures = sum(_failures(r) for r in cohort)
        total_saves = sum(int(r.get("saves", 0)) for r in cohort)
        stall_weighted = sum(
            float(r.get("save_stall_s", 0.0)) * int(r.get("saves", 0))
            for r in cohort
        )
        return {
            "jobs": len(cohort),
            "failures": total_failures,
            "wall_s": _r(total_wall),
            "mtbf_s": (
                _r(total_wall / total_failures) if total_failures > 0 else None
            ),
            "save_stall_s": (
                _r(stall_weighted / total_saves) if total_saves > 0 else 0.0
            ),
        }
