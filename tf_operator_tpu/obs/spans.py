"""Span: one timed operation in a job's lifecycle timeline.

Dependency-free tracing built on the store itself: a Span is just
another store object kind (serialized by runtime.serialize, served by
the generic /api/v1 API, watchable), so multi-host gangs report into
the same timeline through the exact seam everything else already uses
— an agent over a RemoteStore and the in-process reconciler write
spans identically.

Model (deliberately smaller than OpenTelemetry):

- ``trace_id`` is the job uid — propagated to gang members via
  ``TPUJOB_TRACE_ID`` (rendezvous/env.py) next to the warm-restart env.
- ``span_id`` defaults to the object name (unique per namespace); the
  trace ROOT span (op ``job``) uses the trace id itself as its span id,
  so every component can parent to the root without a lookup.
- ``end_time == 0`` marks a span still open (e.g. a restart whose gang
  has not come back RUNNING yet).
- Deterministic names make recording idempotent: lifecycle spans that
  must exist once per job (``scheduled``, ``first-step``) use a
  ``{job}-{trace8}-{op}`` name, so a duplicate create is an
  AlreadyExists no-op — the store is the dedupe, not caller locks.

Recording is ALWAYS best-effort: a failed span write must never break
the control plane or a training step; :class:`SpanRecorder` swallows
store errors and returns None.
"""

from __future__ import annotations

import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tf_operator_tpu.api.types import (
    API_GROUP,
    KIND_SPAN,
    LABEL_GROUP,
    LABEL_JOB_NAME,
    ObjectMeta,
)

# NOTE: no module-level import from tf_operator_tpu.runtime here — the
# runtime package imports this module (process_backend records agent
# spans), so the dependency must stay one-way at import time; store
# exception types are resolved lazily inside the recorder.

log = logging.getLogger("tpujob.obs")

# Span components — who recorded it (one Perfetto process row each).
COMPONENT_CONTROLLER = "controller"
COMPONENT_SCHEDULER = "scheduler"
COMPONENT_AGENT = "agent"
COMPONENT_TRAINER = "trainer"


@dataclass
class Span:
    """One timed operation inside a job's trace (store object)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    trace_id: str = ""  # job uid
    span_id: str = ""
    parent_id: str = ""  # "" = root
    op: str = ""  # scheduled / gang-create / restart / process / first-step…
    component: str = ""  # controller / scheduler / agent / trainer
    start_time: float = 0.0  # wall-clock seconds
    end_time: float = 0.0  # 0.0 = still open
    attrs: Dict[str, str] = field(default_factory=dict)
    kind: str = KIND_SPAN

    def key(self) -> str:
        return self.metadata.key()

    def duration(self) -> Optional[float]:
        """Seconds, or None while the span is still open."""
        if not self.end_time:
            return None
        return max(0.0, self.end_time - self.start_time)


def span_labels(job_name: str) -> Dict[str, str]:
    """Labels stamped on every span: the job-name label is INDEXED by the
    store, so listing a whole trace is one bucket read, not a scan."""
    return {LABEL_GROUP: API_GROUP, LABEL_JOB_NAME: job_name}


def trace8(trace_id: str) -> str:
    return (trace_id or "")[:8]


def first_step_span_name(job_name: str, trace_id: str) -> str:
    """Deterministic gang-wide name: every rank may mark its first step,
    the store's AlreadyExists keeps exactly the EARLIEST write — which is
    precisely the job's first step."""
    return f"{job_name}-{trace8(trace_id)}-first-step"


class SpanRecorder:
    """Best-effort span writer for one component.

    ``store`` is anything with the Store CRUD surface (Store, RemoteStore,
    ChaosStore). Every method swallows store failures: tracing must never
    take down the path it observes.
    """

    def __init__(self, store: Any, component: str = COMPONENT_CONTROLLER) -> None:
        self._store = store
        self.component = component

    def record(
        self,
        namespace: str,
        job_name: str,
        trace_id: str,
        op: str,
        start: float,
        end: float,
        attrs: Optional[Dict[str, str]] = None,
        name: Optional[str] = None,
        parent_id: Optional[str] = None,
        span_id: Optional[str] = None,
        component: Optional[str] = None,
    ) -> Optional[Span]:
        """Create one span (complete when ``end`` > 0, open when 0).

        Returns the stored Span, or None when the write failed OR a span
        of the same (deterministic) name already exists — callers use
        that to dedupe derived-metric observations.
        """
        if not trace_id:
            return None
        if name is None:
            name = (
                f"{job_name}-{trace8(trace_id)}-{op}-{uuid.uuid4().hex[:6]}"
            )
        span = Span(
            metadata=ObjectMeta(
                name=name, namespace=namespace, labels=span_labels(job_name)
            ),
            trace_id=trace_id,
            span_id=span_id if span_id is not None else name,
            parent_id=parent_id if parent_id is not None else trace_id,
            op=op,
            component=component or self.component,
            start_time=start,
            end_time=end,
            attrs=dict(attrs or {}),
        )
        try:
            return self._store.create(span)
        except Exception as exc:  # noqa: BLE001 — tracing is best-effort
            from tf_operator_tpu.runtime.store import AlreadyExistsError

            if not isinstance(exc, AlreadyExistsError):
                log.debug("span %s/%s not recorded: %s", namespace, name, exc)
            return None

    def close(
        self,
        namespace: str,
        name: str,
        end: Optional[float] = None,
        attrs: Optional[Dict[str, str]] = None,
    ) -> Optional[Span]:
        """Close an open span (idempotent: an already-closed span is left
        untouched). Returns the closed Span or None."""
        end = time.time() if end is None else end

        def mutate(cur):
            if cur.end_time:
                return False  # already closed — first closer wins
            cur.end_time = end
            if attrs:
                cur.attrs.update(attrs)

        try:
            return self._store.update_with_retry(KIND_SPAN, namespace, name, mutate)
        except Exception as exc:  # noqa: BLE001 — tracing is best-effort
            log.debug("span %s/%s not closed: %s", namespace, name, exc)
            return None


def job_trace(store: Any, namespace: str, job_name: str) -> List[Span]:
    """Every span of a job's trace, ordered by start time (ties: name).
    Served from the store's job-name label index."""
    spans = store.list(
        KIND_SPAN, namespace=namespace, label_selector={LABEL_JOB_NAME: job_name}
    )
    spans.sort(key=lambda s: (s.start_time, s.metadata.name))
    return spans
