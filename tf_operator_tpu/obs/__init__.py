"""Observability: per-job lifecycle tracing (spans) and trace export.

The reference operator has no tracing story at all (SURVEY.md §5:
per-sync latency logs only). This package is the first-class version:
every component — reconciler, gang scheduler, per-host agent/backend,
trainer/workloads — records :class:`Span` objects into the SAME store
the rest of the control plane already shares, keyed by the job's trace
id (its uid) and labeled with the job-name label so the indexed store
serves a whole trace in one bucket read. ``export`` renders a job's
spans as Chrome trace-event JSON (Perfetto-loadable) and derives the
cross-component timings — submit→scheduled, submit→first-step (TTFS),
restart downtime (MTTR) — that BASELINE.md names as north-star metrics.
"""

from tf_operator_tpu.obs.spans import (
    COMPONENT_AGENT,
    COMPONENT_CONTROLLER,
    COMPONENT_SCHEDULER,
    COMPONENT_TRAINER,
    Span,
    SpanRecorder,
    first_step_span_name,
    job_trace,
    span_labels,
)
from tf_operator_tpu.obs.export import derive_timings, to_chrome_trace
from tf_operator_tpu.obs.blackbox import (
    Blackbox,
    PostmortemArtifact,
    load_postmortem,
)
from tf_operator_tpu.obs.watchdog import GangWatchdog, HangVerdict

__all__ = [
    "Blackbox",
    "GangWatchdog",
    "HangVerdict",
    "PostmortemArtifact",
    "load_postmortem",
    "COMPONENT_AGENT",
    "COMPONENT_CONTROLLER",
    "COMPONENT_SCHEDULER",
    "COMPONENT_TRAINER",
    "Span",
    "SpanRecorder",
    "first_step_span_name",
    "job_trace",
    "span_labels",
    "derive_timings",
    "to_chrome_trace",
]
