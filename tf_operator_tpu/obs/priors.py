"""Fleet priors: turning ledger history into a fresh job's first MTBF.

A brand-new job has zero failures, so ``_tick_cadence`` computes
``mtbf = inf`` and Young/Daly clamps the first cadence decision to
``max_checkpoint_every`` — the clamp edge. But the fleet has watched
dozens of jobs die on this queue; their aggregated MTBF is a far better
opening estimate than "this job is immortal". This module is the pinned,
hand-computable blend rule that injects that history WITHOUT letting it
drown the job's own measurements once they exist.

The rule is Bayesian-style shrinkage phrased in failure-count units so
every number in the receipt is auditable by hand:

- The prior contributes ``n_eff = min(prior_failures, PRIOR_CAP)``
  pseudo-failures worth of evidence, each "lasting" the prior MTBF:
  ``t_eff = n_eff * prior_mtbf_s``.
- The blended MTBF is total time over total failures::

      mtbf = (t_eff + own_elapsed_s) / (n_eff + own_failures)

- The blend weight — how much of the failure evidence is the fleet's —
  is ``n_eff / (n_eff + own_failures)``.

Worked example (the one in docs/design.md §6.4 and test_ledger.py): a
prior of MTBF 100s from 4 fleet failures, a job 50s old with 1 failure
of its own: ``mtbf = (4*100 + 50) / (4 + 1) = 90s``, weight ``0.8``.

Properties the tests pin:

- ``own_failures == 0`` ⇒ the blended MTBF is FINITE (the fresh job
  escapes the clamp edge) and the weight is 1.0.
- As own failures accumulate the weight decays toward 0 and the blend
  approaches the job's own ``elapsed/failures`` — the prior yields.
- ``PRIOR_CAP`` bounds the prior's inertia: a thousand historical
  failures still only argue with the strength of ``PRIOR_CAP`` of them,
  so a handful of own-job failures can move the estimate.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

# Max pseudo-failures the fleet prior may claim. 8 keeps the prior
# decisive for a zero-failure fresh job while letting ~8 own-job
# failures reduce it to a coin flip (weight 0.5).
PRIOR_CAP = 8.0


@dataclass
class CadencePrior:
    """Aggregated fleet history for one (queue, workload class) cohort."""

    mtbf_s: float = 0.0  # aggregate fleet MTBF (total wall / total failures)
    save_stall_s: float = 0.0  # saves-weighted mean measured save stall
    failures: int = 0  # raw fleet failure count backing mtbf_s
    jobs: int = 0  # records aggregated


def cadence_prior(
    ledger: Any, queue: str = "", workload_class: str = ""
) -> Optional[CadencePrior]:
    """The MTBF prior for a fresh job on ``(queue, workload_class)``.

    ``ledger`` is anything with ``cadence_inputs(queue, job_class)`` —
    a FleetLedger. Returns None when the fleet has no finite-MTBF
    history for the cohort (zero failures observed ⇒ no prior: an empty
    fleet must not invent one, and the caller falls back to the plain
    own-data path).
    """
    if ledger is None:
        return None
    agg = ledger.cadence_inputs(queue, workload_class)
    if not agg:
        return None
    mtbf = agg.get("mtbf_s")
    failures = int(agg.get("failures", 0))
    if not mtbf or mtbf <= 0 or failures <= 0:
        return None
    return CadencePrior(
        mtbf_s=float(mtbf),
        save_stall_s=float(agg.get("save_stall_s", 0.0)),
        failures=failures,
        jobs=int(agg.get("jobs", 0)),
    )


def blend_mtbf(
    prior: CadencePrior, own_elapsed_s: float, own_failures: int
) -> Tuple[float, float]:
    """(blended MTBF seconds, prior blend weight in [0, 1]).

    The weight is the fraction of failure evidence contributed by the
    fleet — it is what the decision span receipts as ``prior_weight``.
    """
    n_eff = min(float(prior.failures), PRIOR_CAP)
    t_eff = n_eff * prior.mtbf_s
    denom = n_eff + float(own_failures)
    if denom <= 0:  # unreachable given cadence_prior's failures > 0 gate
        return prior.mtbf_s, 1.0
    mtbf = (t_eff + max(0.0, float(own_elapsed_s))) / denom
    weight = n_eff / denom
    return mtbf, weight
