"""Telemetry: live per-step data-plane metrics, batched through the store.

Spans (obs/spans.py) cover the *lifecycle* timeline — submit, schedule,
restart, resize. Once a gang is RUNNING the control plane was blind:
step time, throughput and MFU died inside the worker process
(train/metrics.py accumulators). A :class:`Telemetry` object is the
missing stream: each rank folds N steps into one compact batch and
writes it through the same store/API seam spans use, so the reconciler,
the dashboard and the CLI can all read the data plane live.

Design points:

- **Ring-buffered, hard-capped.** Each rank owns ``TELEMETRY_RING_SLOTS``
  slot objects named ``{job}-{trace8}-telem-r{rank}-s{seq % SLOTS}``; a
  new batch OVERWRITES the oldest slot (create, then replace on
  AlreadyExists). A job can therefore never hold more than
  ``SLOTS × ranks`` telemetry objects in the store, no matter how long
  it runs. ``seq`` is the monotonic batch counter; readers sort by it
  and the wrapped slot is simply the one with the smallest live seq.
- **Delta-batched.** Workers accumulate per-step durations locally and
  flush every ``flush_every`` steps — one small write per window per
  rank, not one per step.
- **Best-effort, degradable.** Mirrors the PR 11 cachesvc contract: a
  worker that cannot reach the API keeps training with local-only
  accounting and marks ``degraded`` on the next batch that does get
  through (plus a ``telemetry-degraded`` span attribute at close). A
  telemetry failure is NEVER a job failure.
- **GC'd with the job.** The reconciler deletes telemetry alongside
  spans when the owning job is deleted.

The module also hosts the two pure consumers so they are unit-testable
without a control plane: :func:`detect_stragglers` (median-ratio
outlier rule over one cross-rank window) with :class:`StragglerTracker`
(flap hysteresis), and :func:`goodput_decomposition` (productive vs
lost seconds by cause, folding span-derived restart/resize downtime
with telemetry-derived data-wait/ckpt-stall).
"""

from __future__ import annotations

import logging
import statistics
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from tf_operator_tpu.api.types import (
    API_GROUP,
    KIND_TELEMETRY,
    LABEL_GROUP,
    LABEL_JOB_NAME,
    ObjectMeta,
)
from tf_operator_tpu.obs.spans import trace8

# NOTE: same import rule as spans.py — no module-level import from
# tf_operator_tpu.runtime (runtime imports obs); store exception types
# are resolved lazily inside the recorder.

log = logging.getLogger("tpujob.obs")

# Per-rank ring size: the hard per-job store footprint is
# TELEMETRY_RING_SLOTS × ranks objects.
TELEMETRY_RING_SLOTS = 8

# Goodput cause taxonomy (docs/design.md §6.2). restart/resize are
# span-derived (single point of truth: the reconciler's span closes);
# the other three come from the telemetry stream / first-step span.
CAUSE_COMPILE_INIT = "compile-init"
CAUSE_DATA_WAIT = "data-wait"
CAUSE_CKPT_STALL = "ckpt-stall"
CAUSE_RESTART = "restart"
CAUSE_RESIZE = "resize"
# Hang (r15): span-derived like restart/resize — the watchdog opens a
# dedicated "hang" span at declaration and the reconciler closes it when
# the recovered gang is running again, so hang downtime is attributed to
# exactly one cause (the recovery restart deliberately does NOT open a
# "restart" span; docs/design.md §6.3 cause-attribution rule).
CAUSE_HANG = "hang"
# Preemption (r19): span-derived like restart — the reconciler opens the
# same "restart" span for a preemption drain but stamps cause=preemption
# in the span attrs, and both decompose() and the controller's
# lost-seconds counter split on that attr. Keeping preempted downtime
# out of cause=restart matters because the two have different remedies
# (quota/priority policy vs. crash-loop debugging) and different
# accounting (preemptions never charge the backoff budget).
CAUSE_PREEMPTION = "preemption"
GOODPUT_CAUSES = (
    CAUSE_COMPILE_INIT,
    CAUSE_DATA_WAIT,
    CAUSE_CKPT_STALL,
    CAUSE_RESTART,
    CAUSE_RESIZE,
    CAUSE_HANG,
    CAUSE_PREEMPTION,
)


@dataclass
class Telemetry:
    """One rank's step-window batch (store object, ring-buffered)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    trace_id: str = ""  # job uid
    rank: int = 0
    host: str = ""
    seq: int = 0  # monotonic batch counter per rank (ring wraps, seq doesn't)
    start_step: int = 0  # first step folded into this batch (inclusive)
    end_step: int = 0  # last step folded into this batch (inclusive)
    steps: int = 0  # number of steps in the window
    step_time_s: float = 0.0  # mean wall-clock step time over the window
    tokens_per_s: float = 0.0
    mfu: float = 0.0
    data_wait_s: float = 0.0  # total input-pipeline wait inside the window
    ckpt_stall_s: float = 0.0  # total checkpoint save stall inside the window
    # Run-cumulative stall totals (since start_step of this incarnation):
    # the ring evicts old windows, so per-window deltas under-count a long
    # run — goodput accounting reads these off each rank's LATEST batch,
    # which the ring never evicts.
    data_wait_total_s: float = 0.0
    ckpt_stall_total_s: float = 0.0
    degraded: int = 0  # 1 ⇒ earlier batches were lost to API unreachability
    time: float = 0.0  # wall-clock flush time
    kind: str = KIND_TELEMETRY

    def key(self) -> str:
        return self.metadata.key()


def telemetry_labels(job_name: str) -> Dict[str, str]:
    """Same indexed job-name label as spans: listing a job's telemetry is
    one bucket read."""
    return {LABEL_GROUP: API_GROUP, LABEL_JOB_NAME: job_name}


def telemetry_slot_name(job_name: str, trace_id: str, rank: int, seq: int) -> str:
    """Deterministic ring-slot name; batch ``seq`` lands in slot
    ``seq % TELEMETRY_RING_SLOTS``, overwriting the batch from
    ``TELEMETRY_RING_SLOTS`` windows ago."""
    slot = seq % TELEMETRY_RING_SLOTS
    return f"{job_name}-{trace8(trace_id)}-telem-r{rank}-s{slot}"


class TelemetryRecorder:
    """Best-effort ring-buffer writer (one per worker process).

    ``store`` is anything with the Store CRUD surface (Store, RemoteStore).
    ``degraded`` latches True after the first failed write and is cleared
    only by reading it — the reporter folds it into the next successful
    batch so the gap is visible downstream.
    """

    def __init__(self, store: Any) -> None:
        self._store = store
        self.degraded = False

    def record(self, batch: Telemetry) -> Optional[Telemetry]:
        """Write one batch into its ring slot (create, replace on
        AlreadyExists). Returns the stored object or None on failure —
        never raises."""
        if not batch.trace_id or not batch.metadata.name:
            return None
        try:
            return self._store.create(batch)
        except Exception as exc:  # noqa: BLE001 — telemetry is best-effort
            try:
                from tf_operator_tpu.runtime.store import AlreadyExistsError

                if isinstance(exc, AlreadyExistsError):
                    return self._replace(batch)
            except Exception:  # noqa: BLE001
                pass
            log.debug(
                "telemetry %s/%s not recorded: %s",
                batch.metadata.namespace, batch.metadata.name, exc,
            )
            self.degraded = True
            return None

    def _replace(self, batch: Telemetry) -> Optional[Telemetry]:
        """Overwrite an existing ring slot with the new batch's payload."""

        def mutate(cur):
            for f in (
                "trace_id", "rank", "host", "seq", "start_step", "end_step",
                "steps", "step_time_s", "tokens_per_s", "mfu", "data_wait_s",
                "ckpt_stall_s", "data_wait_total_s", "ckpt_stall_total_s",
                "degraded", "time",
            ):
                setattr(cur, f, getattr(batch, f))

        try:
            return self._store.update_with_retry(
                KIND_TELEMETRY, batch.metadata.namespace,
                batch.metadata.name, mutate,
            )
        except Exception as exc:  # noqa: BLE001
            log.debug(
                "telemetry slot %s/%s not replaced: %s",
                batch.metadata.namespace, batch.metadata.name, exc,
            )
            self.degraded = True
            return None


def job_telemetry(store: Any, namespace: str, job_name: str) -> List[Telemetry]:
    """Every live telemetry batch of a job, ordered (rank, seq). Served
    from the store's job-name label index, like job_trace."""
    batches = store.list(
        KIND_TELEMETRY, namespace=namespace,
        label_selector={LABEL_JOB_NAME: job_name},
    )
    batches.sort(key=lambda b: (b.rank, b.seq))
    return batches


def latest_window(batches: List[Telemetry]) -> Dict[int, Telemetry]:
    """Newest batch per rank (highest seq)."""
    out: Dict[int, Telemetry] = {}
    for b in batches:
        cur = out.get(b.rank)
        if cur is None or b.seq > cur.seq:
            out[b.rank] = b
    return out


def telemetry_summary(batches: List[Telemetry]) -> Dict[str, Any]:
    """Live roll-up for /telemetry, ``tpujob top`` and the dashboard:
    gang tokens/s + mean MFU from the newest window per rank, and the
    per-rank step-time spread (max/median ratio — the straggler signal)."""
    window = latest_window(batches)
    if not window:
        return {
            "ranks": 0, "tokens_per_s": 0.0, "mfu": 0.0,
            "step_time_s": {}, "spread": 0.0, "last_step": 0,
        }
    times = {r: b.step_time_s for r, b in window.items() if b.step_time_s > 0}
    med = statistics.median(times.values()) if times else 0.0
    spread = (max(times.values()) / med) if med > 0 else 0.0
    mfus = [b.mfu for b in window.values() if b.mfu > 0]
    return {
        "ranks": len(window),
        "tokens_per_s": sum(b.tokens_per_s for b in window.values()),
        "mfu": (sum(mfus) / len(mfus)) if mfus else 0.0,
        "step_time_s": {str(r): round(b.step_time_s, 6) for r, b in sorted(window.items())},
        "spread": round(spread, 4),
        "last_step": max(b.end_step for b in window.values()),
        "degraded": int(any(b.degraded for b in window.values())),
    }


# ---------------------------------------------------------------------------
# Straggler detection (pure; the reconciler drives it)
# ---------------------------------------------------------------------------

# A rank is an outlier when its window step time exceeds RATIO × the
# cross-rank median. Median-based so a uniformly slow gang (all ranks
# slow: compile, global input stall) moves the baseline instead of
# flagging everyone.
STRAGGLER_RATIO = 1.5
# Minimum gang size for a meaningful median comparison.
STRAGGLER_MIN_RANKS = 3
# Hysteresis: flag after N consecutive outlier windows, clear after N
# consecutive clean ones — a single noisy window never flips state.
STRAGGLER_FLAG_WINDOWS = 2
STRAGGLER_CLEAR_WINDOWS = 2


def detect_stragglers(
    step_times: Dict[int, float],
    ratio: float = STRAGGLER_RATIO,
    min_ranks: int = STRAGGLER_MIN_RANKS,
) -> List[int]:
    """One window's outlier ranks by the median-ratio rule.

    ``step_times`` maps rank → mean step seconds for the same window.
    Returns [] when the gang is too small, the window is empty, or every
    rank moves together (all-slow ⇒ median moves ⇒ nobody flagged).
    """
    times = {r: t for r, t in step_times.items() if t > 0}
    if len(times) < min_ranks:
        return []
    med = statistics.median(times.values())
    if med <= 0:
        return []
    return sorted(r for r, t in times.items() if t > ratio * med)


@dataclass
class HostRisk:
    """Typed straggler-risk snapshot for one rank (r16 satellite).

    Produced by :meth:`StragglerTracker.host_risk` so the reconciler's
    `_check_stragglers` surface (gauges, events, slow-host annotations)
    and the autopilot (pre-emptive migrate, place_gang deprioritization)
    read ONE shared struct instead of each re-deriving risk from
    gauges. ``host`` is filled in by the reconciler's rank→host mapping
    — the tracker itself only knows ranks."""

    rank: int
    host: str = ""
    flagged: bool = False
    flag_age_windows: int = 0  # windows since the flag fired (0 = unflagged)
    slow_ratio: float = 0.0  # last window's step time / cross-rank median
    flap_count: int = 0  # completed flag→clear cycles (chronic flapper)
    consecutive_bad: int = 0  # current outlier streak (pre-flag ramp)


class StragglerTracker:
    """Per-job flap damping over detect_stragglers verdicts.

    ``observe(window)`` consumes one cross-rank window and returns
    (newly_flagged, newly_cleared) rank lists. A rank must be an outlier
    in ``flag_windows`` CONSECUTIVE windows to flag, and clean in
    ``clear_windows`` consecutive windows to clear — a host flapping
    between fast and slow never commits either way.
    """

    def __init__(
        self,
        ratio: float = STRAGGLER_RATIO,
        min_ranks: int = STRAGGLER_MIN_RANKS,
        flag_windows: int = STRAGGLER_FLAG_WINDOWS,
        clear_windows: int = STRAGGLER_CLEAR_WINDOWS,
    ) -> None:
        self.ratio = ratio
        self.min_ranks = min_ranks
        self.flag_windows = flag_windows
        self.clear_windows = clear_windows
        self._bad: Dict[int, int] = {}  # rank -> consecutive outlier windows
        self._good: Dict[int, int] = {}  # rank -> consecutive clean windows
        self.flagged: Dict[int, int] = {}  # rank -> windows-to-flag when it fired
        self.windows_seen = 0
        self._flaps: Dict[int, int] = {}  # rank -> completed flag→clear cycles
        self._last_ratio: Dict[int, float] = {}  # rank -> last window t/median

    def observe(self, step_times: Dict[int, float]) -> Tuple[List[int], List[int]]:
        self.windows_seen += 1
        outliers = set(
            detect_stragglers(step_times, ratio=self.ratio, min_ranks=self.min_ranks)
        )
        times = {r: t for r, t in step_times.items() if t > 0}
        med = statistics.median(times.values()) if len(times) >= self.min_ranks else 0.0
        for rank, t in times.items():
            self._last_ratio[rank] = (t / med) if med > 0 else 0.0
        newly_flagged: List[int] = []
        newly_cleared: List[int] = []
        for rank in step_times:
            if rank in outliers:
                self._bad[rank] = self._bad.get(rank, 0) + 1
                self._good[rank] = 0
                if self._bad[rank] >= self.flag_windows and rank not in self.flagged:
                    self.flagged[rank] = self.windows_seen
                    newly_flagged.append(rank)
            else:
                self._good[rank] = self._good.get(rank, 0) + 1
                self._bad[rank] = 0
                if rank in self.flagged and self._good[rank] >= self.clear_windows:
                    del self.flagged[rank]
                    self._flaps[rank] = self._flaps.get(rank, 0) + 1
                    newly_cleared.append(rank)
        return newly_flagged, newly_cleared

    def host_risk(self) -> Dict[int, HostRisk]:
        """Typed risk snapshot for every rank the tracker has seen; the
        one struct `_check_stragglers` and the autopilot share."""
        out: Dict[int, HostRisk] = {}
        ranks = (
            set(self._last_ratio) | set(self.flagged) | set(self._bad)
        )
        for rank in sorted(ranks):
            flagged = rank in self.flagged
            out[rank] = HostRisk(
                rank=rank,
                flagged=flagged,
                flag_age_windows=(
                    self.windows_seen - self.flagged[rank] if flagged else 0
                ),
                slow_ratio=self._last_ratio.get(rank, 0.0),
                flap_count=self._flaps.get(rank, 0),
                consecutive_bad=self._bad.get(rank, 0),
            )
        return out


# ---------------------------------------------------------------------------
# Goodput accounting (pure; reconciler + /telemetry endpoint share it)
# ---------------------------------------------------------------------------


def goodput_decomposition(
    spans: List[Any],
    batches: List[Telemetry],
    submit: float,
    end: float,
) -> Dict[str, Any]:
    """Productive vs lost seconds for one job, by cause.

    - ``compile-init``: submit → first step (the ``first-step`` span's
      start, i.e. everything before the data plane produced work).
    - ``data-wait`` / ``ckpt-stall``: summed from telemetry batches,
      averaged across ranks (they stall the same wall-clock gang step,
      so summing over ranks would over-count the gang's lost wall time).
    - ``restart`` / ``resize``: widths of closed restart/resize spans —
      the same single source the downtime histograms observe, so the
      two surfaces can never disagree or double-count.

    Returns {"wall_s", "lost_s": {cause: s}, "goodput_ratio"} with the
    ratio clamped to [0, 1].
    """
    wall = max(0.0, end - submit)
    lost = {c: 0.0 for c in GOODPUT_CAUSES}
    for s in spans:
        if s.op == "first-step" and s.start_time > 0:
            lost[CAUSE_COMPILE_INIT] = min(wall, max(0.0, s.start_time - submit))
        elif s.op == "restart" and s.end_time:
            attrs = getattr(s, "attrs", None) or {}
            cause = (
                CAUSE_PREEMPTION
                if attrs.get("cause") == CAUSE_PREEMPTION
                else CAUSE_RESTART
            )
            lost[cause] += max(0.0, s.end_time - s.start_time)
        elif s.op == "resize" and s.end_time:
            lost[CAUSE_RESIZE] += max(0.0, s.end_time - s.start_time)
        elif s.op == "hang" and s.end_time:
            lost[CAUSE_HANG] += max(0.0, s.end_time - s.start_time)
    # Per-rank stall totals: prefer the run-cumulative counters on each
    # rank's LATEST batch (eviction-proof — the ring drops old windows but
    # never the newest), falling back to summing window deltas for
    # producers that predate the cumulative fields.
    latest: Dict[int, Telemetry] = {}
    deltas: Dict[int, Dict[str, float]] = {}
    for b in batches:
        if b.rank not in latest or b.seq > latest[b.rank].seq:
            latest[b.rank] = b
        acc = deltas.setdefault(b.rank, {"dw": 0.0, "cs": 0.0})
        acc["dw"] += max(0.0, b.data_wait_s)
        acc["cs"] += max(0.0, b.ckpt_stall_s)
    if latest:
        n = len(latest)
        dw = cs = 0.0
        for rank, b in latest.items():
            if b.data_wait_total_s > 0 or b.ckpt_stall_total_s > 0:
                dw += max(0.0, b.data_wait_total_s)
                cs += max(0.0, b.ckpt_stall_total_s)
            else:
                dw += deltas[rank]["dw"]
                cs += deltas[rank]["cs"]
        lost[CAUSE_DATA_WAIT] = dw / n
        lost[CAUSE_CKPT_STALL] = cs / n
    total_lost = min(wall, sum(lost.values()))
    ratio = 1.0 if wall <= 0 else max(0.0, min(1.0, 1.0 - total_lost / wall))
    return {
        "wall_s": round(wall, 6),
        "lost_s": {c: round(v, 6) for c, v in lost.items()},
        "goodput_ratio": round(ratio, 6),
    }


# ---------------------------------------------------------------------------
# Worker-side reporter (JobContext constructs it; workloads drive it)
# ---------------------------------------------------------------------------


class StepTelemetry:
    """Per-rank step accumulator + delta batcher + profile-directive arm.

    The workload step loop calls ``step(duration_s, ...)`` once per
    completed step; every ``flush_every`` steps the window folds into one
    Telemetry batch and ships through ``recorder``. With a ``poll``
    callback (JobContext wires poll_profile_directive), each flush also
    checks for a new on-demand profile directive; the chief then wraps
    the next N steps in train.profile.profile_ctx and reports the capture
    via ``on_capture`` (epoch, steps, path) when the window closes.

    Everything here is best-effort: a dead API degrades to local-only
    accounting (``degraded`` latches; the next delivered batch carries
    it), never an exception into the step loop.
    """

    def __init__(
        self,
        recorder: Optional[TelemetryRecorder],
        namespace: str,
        job_name: str,
        trace_id: str,
        rank: int,
        host: str = "",
        flush_every: int = 10,
        tokens_per_step: float = 0.0,
        flops_per_step: float = 0.0,
        n_chips: int = 1,
        start_step: int = 0,
        poll_directive: Optional[Callable[[], Optional[Dict[str, Any]]]] = None,
        on_capture: Optional[Callable[[int, int, str], None]] = None,
        profile_root: str = "",
    ) -> None:
        self._recorder = recorder
        self.namespace = namespace
        self.job_name = job_name
        self.trace_id = trace_id
        self.rank = rank
        self.host = host
        self.flush_every = max(1, int(flush_every))
        self.tokens_per_step = float(tokens_per_step)
        self.flops_per_step = float(flops_per_step)
        self.n_chips = max(1, int(n_chips))
        self._step = int(start_step)
        self._window_start = int(start_step) + 1
        self._durations: List[float] = []
        self._data_wait = 0.0
        self._ckpt_stall = 0.0
        self._data_wait_total = 0.0
        self._ckpt_stall_total = 0.0
        self.seq = 0
        self.batches_sent = 0
        self._poll = poll_directive
        self._on_capture = on_capture
        self._profile_root = profile_root
        self._profile_epoch_done = 0
        self._profile: Optional[Dict[str, Any]] = None  # armed capture state

    @property
    def degraded(self) -> bool:
        return bool(self._recorder and self._recorder.degraded)

    def step(
        self,
        duration_s: float,
        data_wait_s: float = 0.0,
        ckpt_stall_s: float = 0.0,
        now: Optional[float] = None,
    ) -> None:
        """Account one completed step; flushes on window boundaries."""
        self._step += 1
        self._durations.append(max(0.0, float(duration_s)))
        self._data_wait += max(0.0, float(data_wait_s))
        self._ckpt_stall += max(0.0, float(ckpt_stall_s))
        self._data_wait_total += max(0.0, float(data_wait_s))
        self._ckpt_stall_total += max(0.0, float(ckpt_stall_s))
        self._tick_profile()
        if len(self._durations) >= self.flush_every:
            self.flush(now=now)

    def flush(self, now: Optional[float] = None) -> Optional[Telemetry]:
        """Fold the open window into one batch and ship it (best-effort).
        Also the profile-directive poll point (between-steps boundary)."""
        batch: Optional[Telemetry] = None
        if self._durations:
            now = time.time() if now is None else now
            mean = sum(self._durations) / len(self._durations)
            batch = Telemetry(
                metadata=ObjectMeta(
                    name=telemetry_slot_name(
                        self.job_name, self.trace_id, self.rank, self.seq
                    ),
                    namespace=self.namespace,
                    labels=telemetry_labels(self.job_name),
                ),
                trace_id=self.trace_id,
                rank=self.rank,
                host=self.host,
                seq=self.seq,
                start_step=self._window_start,
                end_step=self._step,
                steps=len(self._durations),
                step_time_s=mean,
                tokens_per_s=(self.tokens_per_step / mean) if mean > 0 else 0.0,
                mfu=self._mfu(mean),
                data_wait_s=self._data_wait,
                ckpt_stall_s=self._ckpt_stall,
                data_wait_total_s=self._data_wait_total,
                ckpt_stall_total_s=self._ckpt_stall_total,
                degraded=1 if self.degraded else 0,
                time=now,
            )
            if self._recorder is not None:
                was_degraded = self._recorder.degraded
                if self._recorder.record(batch) is not None:
                    self.batches_sent += 1
                    # Delivered: clear the latch AFTER stamping this batch,
                    # so the gap stays visible exactly once.
                    if was_degraded:
                        self._recorder.degraded = False
            self.seq += 1
            self._durations = []
            self._data_wait = 0.0
            self._ckpt_stall = 0.0
            self._window_start = self._step + 1
        self._maybe_arm_profile()
        return batch

    def close(self) -> None:
        """Final flush + abort any capture still open (best-effort)."""
        self.flush()
        self._finish_profile(aborted=True)

    # -- MFU ----------------------------------------------------------------

    def _mfu(self, mean_step_s: float) -> float:
        if not self.flops_per_step or mean_step_s <= 0:
            return 0.0
        try:
            from tf_operator_tpu.train.metrics import mfu

            return float(mfu(self.flops_per_step, mean_step_s, self.n_chips))
        except Exception:  # noqa: BLE001 — no jax / no device: stay finite
            return float(self.flops_per_step / (mean_step_s * self.n_chips * 1e12))

    # -- on-demand profiling ------------------------------------------------

    def _maybe_arm_profile(self) -> None:
        if self._poll is None or self._profile is not None:
            return
        try:
            directive = self._poll()
        except Exception:  # noqa: BLE001
            return
        if not directive:
            return
        epoch = int(directive.get("epoch", 0) or 0)
        steps = int(directive.get("steps", 0) or 0)
        if epoch <= self._profile_epoch_done or steps <= 0:
            return
        root = directive.get("dir") or self._profile_root
        if not root:
            return
        try:
            from tf_operator_tpu.train.profile import profile_ctx

            cm = profile_ctx(str(root))
            cm.__enter__()
        except Exception as exc:  # noqa: BLE001 — profiler missing ⇒ skip
            log.debug("profile capture (epoch %d) not armed: %s", epoch, exc)
            self._profile_epoch_done = epoch
            return
        self._profile = {
            "epoch": epoch, "steps": steps, "remaining": steps,
            "dir": str(root), "cm": cm, "start": time.time(),
        }

    def _tick_profile(self) -> None:
        if self._profile is None:
            return
        self._profile["remaining"] -= 1
        if self._profile["remaining"] <= 0:
            self._finish_profile(aborted=False)

    def _finish_profile(self, aborted: bool) -> None:
        prof = self._profile
        if prof is None:
            return
        self._profile = None
        try:
            prof["cm"].__exit__(None, None, None)
        except Exception as exc:  # noqa: BLE001
            log.debug("profile capture (epoch %d) stop failed: %s",
                      prof["epoch"], exc)
        self._profile_epoch_done = prof["epoch"]
        if aborted or self._on_capture is None:
            return
        try:
            self._on_capture(prof["epoch"], prof["steps"], prof["dir"])
        except Exception as exc:  # noqa: BLE001
            log.debug("profile capture (epoch %d) not reported: %s",
                      prof["epoch"], exc)
