"""Shared informer factory.

Reference parity: ``pkg/client/informers/externalversions/factory.go:1-119``
— one shared informer per kind, created lazily, started together, with a
``WaitForCacheSync`` gate the daemons call before running controllers
(cmd/tf-operator/app/server.go:92, controller.v2/controller.go:245-277).
Listers are the informers themselves (Informer.get/list,
pkg/client/listers/kubeflow/v1alpha2/tfjob.go:1-94 analogue).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, Optional

from tf_operator_tpu.controller.informer import Informer
from tf_operator_tpu.runtime.store import Store


class InformerFactory:
    """Lazily builds at most one Informer per kind over a shared store."""

    def __init__(self, store: Store) -> None:
        self._store = store
        self._lock = threading.Lock()
        self._informers: Dict[str, Informer] = {}
        self._started = False

    def informer(self, kind: str) -> Informer:
        with self._lock:
            inf = self._informers.get(kind)
            if inf is None:
                inf = Informer(self._store, kind)
                self._informers[kind] = inf
                if self._started:  # late request after Start: run it now
                    inf.run()
            return inf

    # lister == informer cache in this design; alias for parity readability
    def lister(self, kind: str) -> Informer:
        return self.informer(kind)

    def start(self) -> None:
        """Start every informer created so far; later ones start on
        creation (factory.Start semantics)."""
        with self._lock:
            self._started = True
            informers = list(self._informers.values())
        for inf in informers:
            inf.run()

    def wait_for_cache_sync(self, timeout: float = 10.0,
                            kinds: Optional[Iterable[str]] = None) -> bool:
        """Block until the named (default: all) informer caches have synced;
        False on timeout (cache.WaitForCacheSync contract)."""
        deadline = time.monotonic() + timeout
        if kinds:
            # Create on demand: asking to sync a kind is asking for its
            # informer (it starts immediately if the factory is started,
            # otherwise this times out to False, per contract).
            targets = [self.informer(k) for k in kinds]
        else:
            with self._lock:
                targets = list(self._informers.values())
        for inf in targets:
            while not inf.has_synced():
                if time.monotonic() > deadline:
                    return False
                time.sleep(0.01)
        return True

    def stop(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.stop()
