"""Typed clientset over the object store.

Reference parity: the generated client layer (SURVEY.md §1 L1) —
``pkg/client/clientset/versioned/typed/kubeflow/v1alpha2/tfjob.go:1-155``
(per-kind typed CRUD with namespace binding and an UpdateStatus
subresource) and its action-recording fake
(``pkg/client/clientset/versioned/.../fake/fake_tfjob.go:1-126``). The
reference generates this layer with k8s code-generator; here one generic
``KindClient`` parameterized by kind serves all four kinds, since every
managed object shares the ObjectMeta + to_dict/from_dict contract.

Controllers may talk to the Store directly (as the operator talks to the
apiserver through client-go); this layer is the *public* programmatic
surface — what ``py/tf_job_client.py`` users would import — and the seam
tests fake (the FakePodControl trick, controller_test.go:66-68).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tf_operator_tpu.api.types import (
    KIND_ENDPOINT,
    KIND_EVENT,
    KIND_PROCESS,
    KIND_TPUJOB,
)
from tf_operator_tpu.runtime.store import Store, Watch


class KindClient:
    """CRUD for one kind, optionally bound to a namespace
    (tfjob.go:1-155: newTFJobs(c, namespace) binding)."""

    def __init__(self, store: Store, kind: str, namespace: Optional[str] = None,
                 recorder: Optional["ActionRecorder"] = None) -> None:
        self._store = store
        self.kind = kind
        self.namespace = namespace
        self._rec = recorder

    def _ns(self, obj=None, namespace: Optional[str] = None) -> str:
        if namespace is not None:
            return namespace
        if obj is not None:
            return obj.metadata.namespace
        if self.namespace is None:
            raise ValueError(f"{self.kind} client not namespace-bound; pass namespace=")
        return self.namespace

    def _record(self, verb: str, namespace: str, name: str) -> None:
        if self._rec is not None:
            self._rec.record(verb, self.kind, namespace, name)

    # -- CRUD (tfjob.go Create/Get/Update/UpdateStatus/Delete/List/Watch) --

    def create(self, obj):
        if self.namespace is not None and not obj.metadata.namespace:
            obj.metadata.namespace = self.namespace
        self._record("create", obj.metadata.namespace, obj.metadata.name)
        return self._store.create(obj)

    def get(self, name: str, namespace: Optional[str] = None):
        ns = self._ns(namespace=namespace)
        self._record("get", ns, name)
        return self._store.get(self.kind, ns, name)

    def update(self, obj, check_version: bool = False):
        self._record("update", obj.metadata.namespace, obj.metadata.name)
        return self._store.update(obj, check_version=check_version)

    def update_status(self, obj, _retries: int = 5):
        """Subresource semantics (UpdateStatus): only ``status`` is taken
        from the caller; spec/labels come from the stored object, so a
        status writer can never clobber a concurrent spec edit. The
        read-modify-write runs under optimistic concurrency with retries —
        a concurrent spec update triggers a re-read, never a lost write."""
        from tf_operator_tpu.runtime.store import ConflictError

        self._record("update_status", obj.metadata.namespace, obj.metadata.name)
        last_exc: Exception = RuntimeError("unreachable")
        for _ in range(_retries):
            stored = self._store.get(
                self.kind, obj.metadata.namespace, obj.metadata.name
            )
            stored.status = obj.status
            try:
                return self._store.update(stored, check_version=True)
            except ConflictError as exc:
                last_exc = exc
        raise last_exc

    def delete(self, name: str, namespace: Optional[str] = None):
        ns = self._ns(namespace=namespace)
        self._record("delete", ns, name)
        return self._store.delete(self.kind, ns, name)

    def list(self, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None) -> List[Any]:
        ns = namespace if namespace is not None else self.namespace
        self._record("list", ns or "*", "*")
        return self._store.list(self.kind, namespace=ns, label_selector=label_selector)

    def delete_collection(self, namespace: Optional[str] = None,
                          label_selector: Optional[Dict[str, str]] = None) -> int:
        """Delete everything matching (DeleteCollection); returns count."""
        n = 0
        for obj in self.list(namespace=namespace, label_selector=label_selector):
            try:
                self.delete(obj.metadata.name, namespace=obj.metadata.namespace)
                n += 1
            except KeyError:
                pass  # raced with another deleter
        return n

    def watch(self) -> Watch:
        self._record("watch", self.namespace or "*", "*")
        return self._store.watch(kinds=[self.kind])


class Clientset:
    """Per-kind typed accessors (versioned clientset,
    pkg/client/clientset/versioned/clientset.go analogue)."""

    def __init__(self, store: Store, recorder: Optional["ActionRecorder"] = None) -> None:
        self.store = store
        self._rec = recorder

    def tpujobs(self, namespace: Optional[str] = None) -> KindClient:
        return KindClient(self.store, KIND_TPUJOB, namespace, self._rec)

    def processes(self, namespace: Optional[str] = None) -> KindClient:
        return KindClient(self.store, KIND_PROCESS, namespace, self._rec)

    def endpoints(self, namespace: Optional[str] = None) -> KindClient:
        return KindClient(self.store, KIND_ENDPOINT, namespace, self._rec)

    def events(self, namespace: Optional[str] = None) -> KindClient:
        return KindClient(self.store, KIND_EVENT, namespace, self._rec)


@dataclass
class Action:
    """One recorded client action (k8s testing.Action analogue)."""

    verb: str
    kind: str
    namespace: str
    name: str


class ActionRecorder:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.actions: List[Action] = []

    def record(self, verb: str, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            self.actions.append(Action(verb, kind, namespace, name))

    def matching(self, verb: Optional[str] = None, kind: Optional[str] = None) -> List[Action]:
        with self._lock:
            return [a for a in self.actions
                    if (verb is None or a.verb == verb) and (kind is None or a.kind == kind)]


class FakeClientset(Clientset):
    """Clientset over a private in-memory store, recording every action —
    the fake clientset tests inject (fake_tfjob.go; used throughout
    training_test.go:21-31). Fully functional: reads/writes hit the
    private store, so tests can both assert intent and observe effects."""

    def __init__(self, store: Optional[Store] = None) -> None:
        self.recorder = ActionRecorder()
        super().__init__(store if store is not None else Store(), self.recorder)

    @property
    def actions(self) -> List[Action]:
        return list(self.recorder.actions)
