"""Client layer: typed clientsets, fakes, and the shared informer factory
(reference L1, pkg/client/** — SURVEY.md §1)."""

from tf_operator_tpu.client.clientset import (
    Action,
    ActionRecorder,
    Clientset,
    FakeClientset,
    KindClient,
)
from tf_operator_tpu.client.factory import InformerFactory

__all__ = [
    "Action",
    "ActionRecorder",
    "Clientset",
    "FakeClientset",
    "KindClient",
    "InformerFactory",
]
