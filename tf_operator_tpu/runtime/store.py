"""Thread-safe in-memory object store with resource versions and watches.

The apiserver analogue (reference L0/L1, SURVEY.md §1): every managed object
(TPUJob, Process, Endpoint, Event) lives here; controllers observe it through
watch streams (feeding the informer, as client-go's ListWatch feeds shared
informers, pkg/util/unstructured/informer.go:25-62) and mutate it through
CRUD calls. Snapshot isolation is by deepcopy on every boundary crossing —
callers never share memory with the store, the same guarantee the apiserver's
serialization boundary provides (and the reason the reference DeepCopies
before mutating, controller.v2/controller.go:357-361).
"""

from __future__ import annotations

import copy
import enum
import itertools
import queue
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Tuple


class NotFoundError(KeyError):
    pass


class AlreadyExistsError(ValueError):
    pass


class ConflictError(ValueError):
    """Stale update: object changed since the caller read it (apiserver 409)."""


class TransientStoreError(RuntimeError):
    """The store is temporarily unreachable (remote transport failure).

    The in-process Store never raises it; RemoteStore's transport errors
    subclass it so shared retry loops can wait out an operator restart
    instead of killing their caller (e.g. a monitor thread holding an
    exit code that must eventually be reported)."""


class WatchEventType(str, enum.Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"
    # Remote-watch control events (the in-process store never emits them):
    # REPLAY_START opens each (re)connection's replay, SYNCED closes it —
    # consumers reconcile local state against the replayed set on SYNCED,
    # because deletions that happened while disconnected are never
    # replayed (obj is None for both).
    REPLAY_START = "REPLAY_START"
    SYNCED = "SYNCED"


@dataclass
class WatchEvent:
    type: WatchEventType
    obj: Any  # deepcopy of the stored object


class Watch:
    """A subscription to store changes. Iterate or poll ``queue``."""

    def __init__(self, store: "Store", kinds: Optional[Tuple[str, ...]]):
        self._store = store
        self.kinds = kinds
        self.queue: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
        self._stopped = False

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._store._remove_watch(self)
            self.queue.put(None)  # sentinel unblocks consumers

    def __iter__(self):
        while True:
            ev = self.queue.get()
            if ev is None:
                return
            yield ev


def _key(kind: str, namespace: str, name: str) -> Tuple[str, str, str]:
    return (kind, namespace, name)


class Store:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._objects: Dict[Tuple[str, str, str], Any] = {}
        self._rv = itertools.count(1)
        self._watches: List[Watch] = []

    # ---- CRUD ----------------------------------------------------------

    def create(self, obj: Any) -> Any:
        with self._lock:
            meta = obj.metadata
            k = _key(obj.kind, meta.namespace, meta.name)
            if k in self._objects:
                raise AlreadyExistsError(f"{obj.kind} {meta.namespace}/{meta.name} already exists")
            stored = copy.deepcopy(obj)
            if not stored.metadata.uid:
                stored.metadata.uid = uuid.uuid4().hex[:12]
            stored.metadata.resource_version = next(self._rv)
            stored.metadata.creation_timestamp = time.time()
            self._objects[k] = stored
            out = copy.deepcopy(stored)
            self._notify(WatchEventType.ADDED, stored)
            return out

    def get(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            k = _key(kind, namespace, name)
            if k not in self._objects:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            return copy.deepcopy(self._objects[k])

    def update(self, obj: Any, check_version: bool = False) -> Any:
        """Replace an object. With ``check_version`` the caller's
        resource_version must match the stored one (optimistic concurrency,
        the contract CRD status updates rely on)."""
        with self._lock:
            meta = obj.metadata
            k = _key(obj.kind, meta.namespace, meta.name)
            if k not in self._objects:
                raise NotFoundError(f"{obj.kind} {meta.namespace}/{meta.name} not found")
            current = self._objects[k]
            if check_version and meta.resource_version != current.metadata.resource_version:
                raise ConflictError(
                    f"{obj.kind} {meta.namespace}/{meta.name}: stale resource_version "
                    f"{meta.resource_version} (current {current.metadata.resource_version})"
                )
            stored = copy.deepcopy(obj)
            stored.metadata.uid = current.metadata.uid
            stored.metadata.creation_timestamp = current.metadata.creation_timestamp
            stored.metadata.resource_version = next(self._rv)
            self._objects[k] = stored
            out = copy.deepcopy(stored)
            self._notify(WatchEventType.MODIFIED, stored)
            return out

    def update_with_retry(
        self, kind: str, namespace: str, name: str, mutate: Any
    ) -> Optional[Any]:
        """Optimistic read-modify-write: get → ``mutate(obj)`` →
        versioned update, retrying on ConflictError. ``mutate`` edits the
        object in place and returns False to abort (e.g. the precondition
        no longer holds — already finished, different incarnation).
        Returns the updated object, or None when aborted or the object is
        gone. The one blessed shape for every status/heartbeat/annotation
        writer — hand-rolled copies of this loop have each grown their own
        NotFound/Conflict edge-case bugs."""
        return update_with_retry_loop(self, kind, namespace, name, mutate)

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            k = _key(kind, namespace, name)
            if k not in self._objects:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            stored = self._objects.pop(k)
            stored.metadata.deletion_timestamp = time.time()
            out = copy.deepcopy(stored)
            self._notify(WatchEventType.DELETED, stored)
            return out

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        """List objects of ``kind``, optionally filtered by namespace and
        exact-match labels (the reference lists children by job labels,
        replicas.go:434-485)."""
        with self._lock:
            out = []
            for (k_kind, k_ns, _), obj in self._objects.items():
                if k_kind != kind:
                    continue
                if namespace is not None and k_ns != namespace:
                    continue
                if label_selector and not _labels_match(obj.metadata.labels, label_selector):
                    continue
                out.append(copy.deepcopy(obj))
            out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
            return out

    # ---- watches -------------------------------------------------------

    def watch(self, kinds: Optional[Iterable[str]] = None) -> Watch:
        """Subscribe to changes; ADDED events for existing objects are
        replayed first (list+watch semantics, the informer's contract)."""
        with self._lock:
            w = Watch(self, tuple(kinds) if kinds else None)
            for obj in self._objects.values():
                if w.kinds is None or obj.kind in w.kinds:
                    w.queue.put(WatchEvent(WatchEventType.ADDED, copy.deepcopy(obj)))
            self._watches.append(w)
            return w

    def _remove_watch(self, w: Watch) -> None:
        with self._lock:
            if w in self._watches:
                self._watches.remove(w)

    def _notify(self, etype: WatchEventType, stored: Any) -> None:
        for w in self._watches:
            if w.kinds is None or stored.kind in w.kinds:
                w.queue.put(WatchEvent(etype, copy.deepcopy(stored)))


def _labels_match(labels: Dict[str, str], selector: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def update_with_retry_loop(
    store: Any, kind: str, namespace: str, name: str, mutate: Any,
    transient_backoff: float = 1.0,
    transient_timeout: Optional[float] = None,
) -> Optional[Any]:
    """The shared optimistic-write loop behind Store.update_with_retry AND
    RemoteStore.update_with_retry (one implementation, not two copies).
    Conflict → re-read and reapply; NotFound → None; TransientStoreError
    (remote transport down) → wait and retry: a status writer must outlast
    an operator restart, not die holding an unreported exit code. With
    ``transient_timeout`` set, transient failures re-raise after that many
    seconds (for shutdown paths that must not block forever)."""
    import logging

    log_ = logging.getLogger("tpujob.store")
    deadline = None if transient_timeout is None else time.time() + transient_timeout

    def transient(exc: TransientStoreError) -> None:
        if deadline is not None and time.time() >= deadline:
            raise exc
        log_.warning("store unreachable (%s); retrying %s/%s", exc, namespace, name)
        time.sleep(transient_backoff)

    while True:
        try:
            obj = store.get(kind, namespace, name)
        except NotFoundError:
            return None
        except TransientStoreError as exc:
            transient(exc)
            continue
        if mutate(obj) is False:
            return None
        try:
            return store.update(obj, check_version=True)
        except ConflictError:
            continue
        except NotFoundError:
            return None
        except TransientStoreError as exc:
            transient(exc)
            continue
