"""Thread-safe in-memory object store with resource versions and watches.

The apiserver analogue (reference L0/L1, SURVEY.md §1): every managed object
(TPUJob, Process, Endpoint, Event) lives here; controllers observe it through
watch streams (feeding the informer, as client-go's ListWatch feeds shared
informers, pkg/util/unstructured/informer.go:25-62) and mutate it through
CRUD calls. Snapshot isolation is by deepcopy on every boundary crossing —
callers never share memory with the store, the same guarantee the apiserver's
serialization boundary provides (and the reason the reference DeepCopies
before mutating, controller.v2/controller.go:357-361).

Scale model (r6): list/watch cost is proportional to the *selected* set,
not the live population. Three indices back ``list``:

- per kind (``list("Host")`` with 5 000 events in the store touches 0
  events),
- per (kind, namespace),
- per (kind, indexed-label-key, value) for ``INDEXED_LABELS`` — the
  job-name label, the one hot selector: the reconciler lists children by
  job labels every sync (replicas.go:434-485 analogue), which was
  O(all processes) per job and O(jobs²) per resync pass on a flat map.

Objects the caller filters OUT are never deepcopied (they are never even
visited when an index applies); ``list_stats()`` exposes scanned-vs-
returned counters so the proportionality is observable (controller
metrics render them as ``tpujob_store_list_*``).

Durability (r8, opt-in): ``persist.open_store(data_dir)`` attaches a
:class:`~tf_operator_tpu.runtime.persist.StorePersister` — every mutation
appends one checksummed WAL record (under the store lock, so WAL order is
apply order) with periodic compacted snapshots; recovery reconstructs the
identical object set and resource_version counter, which is what lets a
restarted operator re-adopt its children instead of double-creating them.

Watch fanout: one snapshot deepcopy per event, SHARED by every watch —
the old per-watch deepcopy made each write O(watches × object size)
inside the store lock. Consequence: **watch events are read-only**;
a consumer that wants to mutate must copy (informers already deepcopy
on cache reads; the agent copies before annotating). Per-watch queues
are bounded: a consumer that stops draining has its watch closed with
``overflowed=True`` (the k8s too-slow-watcher semantics) instead of
growing memory without bound; informers re-subscribe and reconcile
through the replay markers below.
"""

from __future__ import annotations

import copy
import enum
import itertools
import queue
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Tuple

# Label keys indexed by default (api.types.LABEL_JOB_NAME — not imported:
# runtime sits below api in the layering).
INDEXED_LABELS: Tuple[str, ...] = ("tpu_job_name",)

# A watch whose consumer falls this many events behind is closed
# (overflowed) rather than buffering forever. Far above any healthy
# consumer's lag; a wedged consumer thread is the only thing that hits it.
DEFAULT_WATCH_QUEUE_SIZE = 10_000


class NotFoundError(KeyError):
    pass


class AlreadyExistsError(ValueError):
    pass


class ConflictError(ValueError):
    """Stale update: object changed since the caller read it (apiserver 409)."""


class TransientStoreError(RuntimeError):
    """The store is temporarily unreachable (remote transport failure).

    The in-process Store never raises it; RemoteStore's transport errors
    subclass it so shared retry loops can wait out an operator restart
    instead of killing their caller (e.g. a monitor thread holding an
    exit code that must eventually be reported)."""


class WatchEventType(str, enum.Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"
    # Watch control events bracketing a replay of existing objects:
    # REPLAY_START opens it, SYNCED closes it — consumers reconcile local
    # state against the replayed set on SYNCED, because deletions that
    # happened while disconnected (or while an overflowed local watch was
    # closed) are never replayed (obj is None for both). RemoteWatch emits
    # them on every (re)connect; the in-process store emits them for
    # watches created with ``mark_replay=True``.
    REPLAY_START = "REPLAY_START"
    SYNCED = "SYNCED"


@dataclass
class WatchEvent:
    type: WatchEventType
    obj: Any  # READ-ONLY snapshot, shared across watches — copy to mutate


class Watch:
    """A subscription to store changes. Iterate or poll ``queue``.

    ``overflowed`` is set when the store closed this watch because its
    consumer fell more than ``maxsize`` events behind; the consumer must
    re-subscribe (list+watch) to reconverge."""

    def __init__(
        self,
        store: "Store",
        kinds: Optional[Tuple[str, ...]],
        maxsize: int = DEFAULT_WATCH_QUEUE_SIZE,
    ):
        self._store = store
        self.kinds = kinds
        # Bound enforced by the store at enqueue time (not queue.Queue's
        # blocking maxsize: the sentinel must always be deliverable).
        self.maxsize = maxsize
        self.queue: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
        self.overflowed = False
        self._stopped = False

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._store._remove_watch(self)
            self.queue.put(None)  # sentinel unblocks consumers

    def __iter__(self):
        while True:
            ev = self.queue.get()
            if ev is None:
                return
            yield ev


def _key(kind: str, namespace: str, name: str) -> Tuple[str, str, str]:
    return (kind, namespace, name)


class Store:
    def __init__(
        self, indexed_labels: Iterable[str] = INDEXED_LABELS
    ) -> None:
        self._lock = threading.RLock()
        self._objects: Dict[Tuple[str, str, str], Any] = {}
        self._rv = itertools.count(1)
        self._watches: List[Watch] = []
        # Indices (all guarded by _lock; values alias _objects entries —
        # the stored objects are replaced, never mutated in place, so the
        # aliasing is safe):
        self._indexed_labels = tuple(indexed_labels)
        self._by_kind: Dict[str, Dict[Tuple[str, str, str], Any]] = {}
        self._by_kind_ns: Dict[Tuple[str, str], Dict[Tuple[str, str, str], Any]] = {}
        # (kind, label_key, label_value) -> {key: obj}
        self._by_label: Dict[Tuple[str, str, str], Dict[Tuple[str, str, str], Any]] = {}
        # node name -> [live chips, live process count]: the placement
        # capacity index. Maintained incrementally on every Process
        # mutation so GangScheduler._states is O(hosts), not O(all live
        # processes in the fleet). Duck-typed on kind/spec/status shape —
        # runtime sits below api in the layering, same as INDEXED_LABELS.
        self._node_usage: Dict[str, List[int]] = {}
        # list-cost telemetry: candidates visited vs objects returned.
        self._list_calls = 0
        self._list_scanned = 0
        self._list_returned = 0
        # Optional durability (runtime/persist.py): one WAL record per
        # mutation, appended while _lock is held so WAL order == apply
        # order == watch order. None = classic in-memory store.
        self._persister = None

    # ---- durability (runtime/persist.py) --------------------------------

    def attach_persister(self, persister) -> None:
        """Attach a StorePersister: every subsequent create/update/delete
        is WAL-logged (and periodically snapshotted). Call before any
        mutations/watches — open_store() is the normal entry point."""
        with self._lock:
            self._persister = persister
            persister.bind(self)

    def restore_objects(self, objects: Iterable[Any], next_rv: int) -> None:
        """Install recovered objects verbatim (uid / resource_version /
        creation_timestamp preserved) and restore the resource_version
        counter so post-restart allocations continue monotonically —
        watchers and optimistic CAS behave identically to an operator
        that never died. Recovery-only: runs before watches or a
        persister exist, so no events fan out and nothing re-logs."""
        with self._lock:
            assert not self._watches and self._persister is None
            for obj in objects:
                k = _key(obj.kind, obj.metadata.namespace, obj.metadata.name)
                self._objects[k] = obj
                self._index_add(k, obj)
            self._rv = itertools.count(max(next_rv, 1))

    # ---- index maintenance (callers hold _lock) -------------------------

    def _label_buckets(self, obj: Any) -> List[Tuple[str, str, str]]:
        labels = obj.metadata.labels or {}
        return [
            (obj.kind, lk, labels[lk])
            for lk in self._indexed_labels
            if lk in labels
        ]

    @staticmethod
    def _usage_entry(obj: Any) -> Optional[Tuple[str, int]]:
        """(node, chips) for a Process that currently occupies capacity on
        a host: bound (spec.node_name set) and not terminal."""
        if obj.kind != "Process":
            return None
        node = obj.spec.node_name
        if not node or obj.status.phase.value in ("Succeeded", "Failed"):
            return None
        return node, max(obj.spec.chips, 0)

    def _usage_add(self, obj: Any) -> None:
        e = self._usage_entry(obj)
        if e is not None:
            u = self._node_usage.setdefault(e[0], [0, 0])
            u[0] += e[1]
            u[1] += 1

    def _usage_remove(self, obj: Any) -> None:
        e = self._usage_entry(obj)
        if e is not None:
            u = self._node_usage.get(e[0])
            if u is not None:
                u[0] -= e[1]
                u[1] -= 1
                if u[1] <= 0 and u[0] <= 0:
                    del self._node_usage[e[0]]

    def node_usage(self) -> Dict[str, Tuple[int, int]]:
        """Snapshot of node -> (live chips, live process count). O(nodes)."""
        with self._lock:
            return {n: (u[0], u[1]) for n, u in self._node_usage.items()}

    def _index_add(self, k: Tuple[str, str, str], obj: Any) -> None:
        self._by_kind.setdefault(k[0], {})[k] = obj
        self._by_kind_ns.setdefault((k[0], k[1]), {})[k] = obj
        for b in self._label_buckets(obj):
            self._by_label.setdefault(b, {})[k] = obj
        self._usage_add(obj)

    def _index_remove(self, k: Tuple[str, str, str], obj: Any) -> None:
        self._usage_remove(obj)
        for table, tk in (
            (self._by_kind, k[0]),
            (self._by_kind_ns, (k[0], k[1])),
        ):
            bucket = table.get(tk)
            if bucket is not None:
                bucket.pop(k, None)
                if not bucket:
                    del table[tk]
        for b in self._label_buckets(obj):
            bucket = self._by_label.get(b)
            if bucket is not None:
                bucket.pop(k, None)
                if not bucket:
                    del self._by_label[b]

    def _index_replace(self, k: Tuple[str, str, str], old: Any, new: Any) -> None:
        # kind/ns buckets just swap the value; label buckets may move
        # (an update can change labels); node usage may flip (a Process
        # binding to a host or reaching a terminal phase).
        self._usage_remove(old)
        self._usage_add(new)
        self._by_kind[k[0]][k] = new
        self._by_kind_ns[(k[0], k[1])][k] = new
        old_b, new_b = self._label_buckets(old), self._label_buckets(new)
        for b in old_b:
            if b not in new_b:
                bucket = self._by_label.get(b)
                if bucket is not None:
                    bucket.pop(k, None)
                    if not bucket:
                        del self._by_label[b]
        for b in new_b:
            self._by_label.setdefault(b, {})[k] = new

    # ---- CRUD ----------------------------------------------------------

    def create(self, obj: Any) -> Any:
        with self._lock:
            meta = obj.metadata
            k = _key(obj.kind, meta.namespace, meta.name)
            if k in self._objects:
                raise AlreadyExistsError(f"{obj.kind} {meta.namespace}/{meta.name} already exists")
            stored = copy.deepcopy(obj)
            if not stored.metadata.uid:
                stored.metadata.uid = uuid.uuid4().hex[:12]
            stored.metadata.resource_version = next(self._rv)
            stored.metadata.creation_timestamp = time.time()
            self._objects[k] = stored
            self._index_add(k, stored)
            if self._persister is not None:
                self._persister.append(
                    "create", stored, stored.metadata.resource_version
                )
            out = copy.deepcopy(stored)
            self._notify(WatchEventType.ADDED, stored)
            return out

    def get(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            k = _key(kind, namespace, name)
            if k not in self._objects:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            return copy.deepcopy(self._objects[k])

    def update(self, obj: Any, check_version: bool = False) -> Any:
        """Replace an object. With ``check_version`` the caller's
        resource_version must match the stored one (optimistic concurrency,
        the contract CRD status updates rely on)."""
        with self._lock:
            meta = obj.metadata
            k = _key(obj.kind, meta.namespace, meta.name)
            if k not in self._objects:
                raise NotFoundError(f"{obj.kind} {meta.namespace}/{meta.name} not found")
            current = self._objects[k]
            if check_version and meta.resource_version != current.metadata.resource_version:
                raise ConflictError(
                    f"{obj.kind} {meta.namespace}/{meta.name}: stale resource_version "
                    f"{meta.resource_version} (current {current.metadata.resource_version})"
                )
            stored = copy.deepcopy(obj)
            stored.metadata.uid = current.metadata.uid
            stored.metadata.creation_timestamp = current.metadata.creation_timestamp
            stored.metadata.resource_version = next(self._rv)
            self._objects[k] = stored
            self._index_replace(k, current, stored)
            if self._persister is not None:
                self._persister.append(
                    "update", stored, stored.metadata.resource_version
                )
            out = copy.deepcopy(stored)
            self._notify(WatchEventType.MODIFIED, stored)
            return out

    def update_with_retry(
        self, kind: str, namespace: str, name: str, mutate: Any
    ) -> Optional[Any]:
        """Optimistic read-modify-write: get → ``mutate(obj)`` →
        versioned update, retrying on ConflictError. ``mutate`` edits the
        object in place and returns False to abort (e.g. the precondition
        no longer holds — already finished, different incarnation).
        Returns the updated object, or None when aborted or the object is
        gone. The one blessed shape for every status/heartbeat/annotation
        writer — hand-rolled copies of this loop have each grown their own
        NotFound/Conflict edge-case bugs."""
        return update_with_retry_loop(self, kind, namespace, name, mutate)

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            k = _key(kind, namespace, name)
            if k not in self._objects:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            stored = self._objects.pop(k)
            self._index_remove(k, stored)
            stored.metadata.deletion_timestamp = time.time()
            if self._persister is not None:
                # Deletes consume an rv purely as their WAL sequence
                # number (replay order / monotonicity); rv density was
                # never part of the store's contract.
                self._persister.append("delete", stored, next(self._rv))
            out = copy.deepcopy(stored)
            self._notify(WatchEventType.DELETED, stored)
            return out

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        """List objects of ``kind``, optionally filtered by namespace and
        exact-match labels (the reference lists children by job labels,
        replicas.go:434-485). Served from the narrowest applicable index:
        an indexed label selector key wins (its bucket is the selected
        set), then (kind, namespace), then kind — never a scan of the
        whole population, and never a deepcopy of a non-match."""
        with self._lock:
            candidates = None
            residual = dict(label_selector) if label_selector else None
            if residual:
                for lk in self._indexed_labels:
                    if lk in residual:
                        candidates = self._by_label.get(
                            (kind, lk, residual.pop(lk)), {}
                        )
                        break
            if candidates is None:
                if namespace is not None:
                    candidates = self._by_kind_ns.get((kind, namespace), {})
                else:
                    candidates = self._by_kind.get(kind, {})
            out = []
            self._list_calls += 1
            self._list_scanned += len(candidates)
            for (_, k_ns, _), obj in candidates.items():
                if namespace is not None and k_ns != namespace:
                    continue
                if residual and not _labels_match(obj.metadata.labels, residual):
                    continue
                out.append(copy.deepcopy(obj))
            self._list_returned += len(out)
            out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
            return out

    def wal_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-kind WAL append accounting from the attached persister
        (tpujob_wal_{records,bytes}_total{kind}); {} when the store is
        in-memory only."""
        with self._lock:
            if self._persister is None:
                return {}
            return self._persister.wal_stats()

    def list_stats(self) -> Dict[str, int]:
        """Cumulative list-cost counters: calls, candidates scanned,
        objects returned. scanned ≈ returned is the index working;
        scanned ≫ returned is a selector no index covers."""
        with self._lock:
            return {
                "calls": self._list_calls,
                "scanned": self._list_scanned,
                "returned": self._list_returned,
            }

    # ---- watches -------------------------------------------------------

    def watch(
        self,
        kinds: Optional[Iterable[str]] = None,
        mark_replay: bool = False,
        maxsize: int = DEFAULT_WATCH_QUEUE_SIZE,
    ) -> Watch:
        """Subscribe to changes; ADDED events for existing objects are
        replayed first (list+watch semantics, the informer's contract).
        With ``mark_replay`` the replay is bracketed by REPLAY_START /
        SYNCED control events — the same framing RemoteWatch emits — so
        replay-reconciling consumers work identically against both."""
        with self._lock:
            w = Watch(self, tuple(kinds) if kinds else None, maxsize=maxsize)
            if mark_replay:
                w.queue.put(WatchEvent(WatchEventType.REPLAY_START, None))
            for obj in self._iter_kinds(w.kinds):
                w.queue.put(WatchEvent(WatchEventType.ADDED, copy.deepcopy(obj)))
            if mark_replay:
                w.queue.put(WatchEvent(WatchEventType.SYNCED, None))
            self._watches.append(w)
            return w

    def _iter_kinds(self, kinds: Optional[Tuple[str, ...]]):
        if kinds is None:
            return list(self._objects.values())
        out = []
        for kind in kinds:
            out.extend(self._by_kind.get(kind, {}).values())
        return out

    def _remove_watch(self, w: Watch) -> None:
        with self._lock:
            if w in self._watches:
                self._watches.remove(w)

    def _notify(self, etype: WatchEventType, stored: Any) -> None:
        # One snapshot per event, shared by every interested watch (events
        # are read-only by contract). Enqueue stays under the store lock —
        # that is what guarantees every watch sees the same total order —
        # but the per-watch work is a queue append, not a deepcopy.
        ev = None
        overflowed: List[Watch] = []
        for w in self._watches:
            if w.kinds is not None and stored.kind not in w.kinds:
                continue
            if ev is None:
                ev = WatchEvent(etype, copy.deepcopy(stored))
            if w.queue.qsize() >= w.maxsize:
                w.overflowed = True
                overflowed.append(w)
                continue
            w.queue.put(ev)
        for w in overflowed:
            # Too-slow consumer: close its watch (sentinel) instead of
            # buffering unboundedly; it must re-list+watch to reconverge.
            self._watches.remove(w)
            w.queue.put(None)


def _labels_match(labels: Dict[str, str], selector: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def update_with_retry_loop(
    store: Any, kind: str, namespace: str, name: str, mutate: Any,
    transient_backoff: float = 1.0,
    transient_timeout: Optional[float] = None,
) -> Optional[Any]:
    """The shared optimistic-write loop behind Store.update_with_retry AND
    RemoteStore.update_with_retry (one implementation, not two copies).
    Conflict → re-read and reapply; NotFound → None; TransientStoreError
    (remote transport down) → wait and retry: a status writer must outlast
    an operator restart, not die holding an unreported exit code. With
    ``transient_timeout`` set, transient failures re-raise after that many
    seconds (for shutdown paths that must not block forever)."""
    import logging

    log_ = logging.getLogger("tpujob.store")
    deadline = None if transient_timeout is None else time.time() + transient_timeout

    def transient(exc: TransientStoreError) -> None:
        if deadline is not None and time.time() >= deadline:
            raise exc
        log_.warning("store unreachable (%s); retrying %s/%s", exc, namespace, name)
        time.sleep(transient_backoff)

    while True:
        try:
            obj = store.get(kind, namespace, name)
        except NotFoundError:
            return None
        except TransientStoreError as exc:
            transient(exc)
            continue
        if mutate(obj) is False:
            return None
        try:
            return store.update(obj, check_version=True)
        except ConflictError:
            continue
        except NotFoundError:
            return None
        except TransientStoreError as exc:
            transient(exc)
            continue
