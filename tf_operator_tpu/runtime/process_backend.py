"""ProcessControl: the seam between the reconciler and real OS processes.

Reference parity: PodControlInterface / RealPodControl (pod_control.go:54-165)
for the real side, FakePodControl for the hermetic side — the fake records
intended creations/deletions without a cluster, which is what makes the
reference's controller unit-testable (controller_test.go:66-68); we build the
fake first, per SURVEY.md §7 step 2.

The real backend is the kubelet analogue: it launches one OS process per
Process object (the in-process harness resolves the entrypoint), watches it
with a monitor thread, and writes phase/exit-code back into the store, where
the informer-driven reconciler observes it.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from tf_operator_tpu.api.types import KIND_PROCESS
from tf_operator_tpu.rendezvous.env import identity_env
from tf_operator_tpu.runtime.objects import Process, ProcessPhase
from tf_operator_tpu.runtime.store import ConflictError, NotFoundError, Store


_NO_CHILD = object()  # sentinel: key absent from _children entirely


class ProcessControl:
    """Interface (reference: PodControlInterface, pod_control.go:54-76)."""

    def create_process(self, process: Process) -> None:
        raise NotImplementedError

    def delete_process(self, namespace: str, name: str) -> None:
        raise NotImplementedError


class FakeProcessControl(ProcessControl):
    """Records intended actions; optionally injects errors.

    Like the reference's FakePodControl it does NOT write to the store —
    tests that want observable children pre-populate the store themselves,
    and the expectations machinery is what keeps the controller from
    spinning on unobserved creates.
    """

    def __init__(self) -> None:
        self.created: List[Process] = []
        self.deleted: List[str] = []  # "namespace/name"
        self.create_error: Optional[Exception] = None
        self.delete_error: Optional[Exception] = None
        self._lock = threading.Lock()

    def create_process(self, process: Process) -> None:
        if self.create_error is not None:
            raise self.create_error
        with self._lock:
            self.created.append(process)

    def delete_process(self, namespace: str, name: str) -> None:
        if self.delete_error is not None:
            raise self.delete_error
        with self._lock:
            self.deleted.append(f"{namespace}/{name}")

    def clear(self) -> None:
        with self._lock:
            self.created.clear()
            self.deleted.clear()


def default_command_builder(process: Process) -> List[str]:
    """Launch the in-process harness, which resolves spec.entrypoint and
    performs jax.distributed rendezvous (the TF_CONFIG-consuming analogue of
    tf_smoke.py:88-110)."""
    return [sys.executable, "-m", "tf_operator_tpu.rendezvous.harness", *process.spec.args]


class LocalProcessControl(ProcessControl):
    """Real backend: one OS subprocess per Process object.

    Combines RealPodControl (create/delete against the "cluster") with the
    kubelet's duty of reporting container termination state; the monitor
    thread is what turns a child exit into a store status update the
    reconciler can observe (replicas.go:310-363's data source).
    """

    GRACE_SECONDS = 5.0

    LOG_ANNOTATION = "tpujob.dev/log-path"

    def __init__(
        self,
        store: Store,
        command_builder: Callable[[Process], List[str]] = default_command_builder,
        inherit_env: bool = True,
        log_dir: Optional[str] = None,
    ) -> None:
        self._store = store
        self._command_builder = command_builder
        self._inherit_env = inherit_env
        self._log_dir = log_dir
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
        self._lock = threading.Lock()
        # "ns/name" -> Popen, or None while the launch is still in flight.
        self._children: Dict[str, Optional[subprocess.Popen]] = {}
        # Keys deleted while their launch was in flight: the monitor kills
        # the child as soon as Popen returns instead of leaking an orphan.
        self._tombstones: set = set()
        self._shutting_down = False

    # -- ProcessControl ---------------------------------------------------

    def create_process(self, process: Process) -> None:
        if self._log_dir:
            # Combined stdout+stderr log (kubelet log analogue; served by the
            # dashboard's logs endpoint, api_handler.go:236-251). basename()
            # on each component forecloses path traversal via crafted
            # namespace/name (validation also rejects them at admission).
            log_name = (
                f"{os.path.basename(process.metadata.namespace)}"
                f"_{os.path.basename(process.metadata.name)}.log"
            )
            process.metadata.annotations[self.LOG_ANNOTATION] = os.path.join(
                self._log_dir, log_name
            )
        stored = self._store.create(process)
        with self._lock:
            self._children[stored.key()] = None  # reserve before thread start
        thread = threading.Thread(
            target=self._launch_and_monitor, args=(stored,), daemon=True,
            name=f"procmon-{stored.metadata.name}",
        )
        thread.start()

    def delete_process(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        with self._lock:
            child = self._children.pop(key, _NO_CHILD)
            if child is None:
                # Launch in flight: tombstone it; the monitor reaps on arrival.
                self._tombstones.add(key)
        if child not in (None, _NO_CHILD):
            self._terminate(child)
        try:
            self._store.delete(KIND_PROCESS, namespace, name)
        except NotFoundError:
            pass

    def _terminate(self, child: subprocess.Popen) -> None:
        if child.poll() is None:
            child.terminate()
            try:
                child.wait(timeout=self.GRACE_SECONDS)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait()

    # -- internals --------------------------------------------------------

    def _spawn(self, process: Process, env: Dict[str, str], log_path: Optional[str]):
        """Launch the child; returns a Popen-like handle (pid / poll / wait /
        terminate / kill). Raises OSError on any launch failure (log-file
        open or exec). The seam NativeProcessControl overrides."""
        log_file = open(log_path, "ab") if log_path else None
        try:
            return subprocess.Popen(
                self._command_builder(process),
                env=env,
                cwd=process.spec.workdir,
                stdout=log_file,
                stderr=subprocess.STDOUT if log_file else None,
                start_new_session=True,  # isolate signals from the operator
            )
        finally:
            if log_file:
                log_file.close()  # child holds its own descriptor now

    def _launch_and_monitor(self, process: Process) -> None:
        key = process.key()
        env = dict(os.environ) if self._inherit_env else {}
        # Identity first, then controller-provided env (controller wins on
        # conflicts — it may override e.g. the entrypoint for a debug run).
        env.update(identity_env(process.spec, process.metadata.namespace))
        env.update(process.spec.env)
        log_path = process.metadata.annotations.get(self.LOG_ANNOTATION)
        try:
            child = self._spawn(process, env, log_path)
        except OSError as exc:
            # Covers both a failed log-file open and a failed exec: the
            # process must be reported FAILED, never left Pending forever.
            with self._lock:
                self._children.pop(key, None)
                self._tombstones.discard(key)
            self._patch_status(process, ProcessPhase.FAILED, exit_code=127, message=str(exc))
            return
        with self._lock:
            doomed = key in self._tombstones or self._shutting_down
            if doomed:
                self._tombstones.discard(key)
                self._children.pop(key, None)
            else:
                self._children[key] = child
        if doomed:  # deleted while launch was in flight: reap, don't report
            self._terminate(child)
            return
        self._patch_status(process, ProcessPhase.RUNNING, pid=child.pid)
        code = child.wait()
        with self._lock:
            self._children.pop(key, None)
        oom = _was_oom_killed(code)
        phase = ProcessPhase.SUCCEEDED if code == 0 else ProcessPhase.FAILED
        self._patch_status(process, phase, exit_code=code, oom_killed=oom)

    def _patch_status(
        self,
        process: Process,
        phase: ProcessPhase,
        pid: Optional[int] = None,
        exit_code: Optional[int] = None,
        oom_killed: bool = False,
        message: str = "",
    ) -> None:
        meta = process.metadata
        # Optimistic-concurrency loop: only status fields are ours; concurrent
        # spec/label writers must not be clobbered (apiserver status-subresource
        # contract the reference's CRD updates rely on).
        while True:
            try:
                cur = self._store.get(KIND_PROCESS, meta.namespace, meta.name)
            except NotFoundError:
                return  # deleted under us — nothing to report
            if cur.metadata.uid != meta.uid:
                return  # a new incarnation took the name; don't clobber it
            cur.status.phase = phase
            if pid is not None:
                cur.status.pid = pid
                cur.status.start_time = time.time()
            if exit_code is not None:
                cur.status.exit_code = exit_code
                cur.status.finish_time = time.time()
                cur.status.oom_killed = oom_killed
            if message:
                cur.status.message = message
            try:
                self._store.update(cur, check_version=True)
                return
            except ConflictError:
                continue  # re-read and reapply
            except NotFoundError:
                return

    def shutdown(self) -> None:
        """Terminate all children (operator teardown)."""
        with self._lock:
            self._shutting_down = True
            children = [c for c in self._children.values() if c is not None]
            self._children.clear()
        for child in children:
            if child.poll() is None:
                child.terminate()
        for child in children:
            try:
                child.wait(timeout=self.GRACE_SECONDS)
            except subprocess.TimeoutExpired:
                child.kill()


class NativeProcessControl(LocalProcessControl):
    """LocalProcessControl with spawn/monitor/kill supplied by the native
    C++ supervisor (native/supervisor.cc via runtime.native).

    Differences from the pure-Python backend, all in the compiled layer:
    children are setsid process-group leaders and deletion kills the whole
    group (a harness that forked data loaders leaves no orphans); exit
    codes arrive normalized to the 128+signal convention the taxonomy
    (reference pkg/util/train/train_util.go:18-53) is written against
    (SIGKILL → 137, SIGTERM → 143, never Python's -9/-15); and exec
    failures are reported synchronously with the child-side errno instead
    of a generic exit-127 corpse."""

    def __init__(self, *args, **kwargs) -> None:
        from tf_operator_tpu.runtime.native import NativeSupervisor

        super().__init__(*args, **kwargs)
        self._sup = NativeSupervisor()

    def _spawn(self, process: Process, env: Dict[str, str], log_path: Optional[str]):
        return self._sup.spawn(
            self._command_builder(process), env, process.spec.workdir, log_path
        )

    def _terminate(self, child) -> None:
        from tf_operator_tpu.runtime.native import NativeChild

        if isinstance(child, NativeChild):
            # Native escalation: TERM → grace → KILL, on the whole group.
            self._sup.terminate(child, self.GRACE_SECONDS)
        else:  # pragma: no cover - children are always NativeChild here
            super()._terminate(child)


def _was_oom_killed(code: int) -> bool:
    """Best-effort OOM detection: killed by SIGKILL is how the kernel's OOM
    killer presents. The reference reads the runtime's OOMKilled reason; a
    bare host has no such oracle, so this stays conservative (False) unless
    a platform oracle is wired in. Kept as a hook point."""
    del code
    return False
