"""ProcessControl: the seam between the reconciler and real OS processes.

Reference parity: PodControlInterface / RealPodControl (pod_control.go:54-165)
for the real side, FakePodControl for the hermetic side — the fake records
intended creations/deletions without a cluster, which is what makes the
reference's controller unit-testable (controller_test.go:66-68); we build the
fake first, per SURVEY.md §7 step 2.

The real backend is the kubelet analogue: it launches one OS process per
Process object (the in-process harness resolves the entrypoint), watches it
with a monitor thread, and writes phase/exit-code back into the store, where
the informer-driven reconciler observes it.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from tf_operator_tpu.api.types import KIND_PROCESS
from tf_operator_tpu.obs.spans import COMPONENT_AGENT, SpanRecorder
from tf_operator_tpu.rendezvous.env import ENV_TRACE_ID, identity_env
from tf_operator_tpu.runtime.objects import Process, ProcessPhase
from tf_operator_tpu.runtime.store import ConflictError, NotFoundError, Store
from tf_operator_tpu.utils.exit_codes import read_cgroup_oom_kills, was_oom_killed


_NO_CHILD = object()  # sentinel: key absent from _children entirely


class ProcessControl:
    """Interface (reference: PodControlInterface, pod_control.go:54-76)."""

    def create_process(self, process: Process) -> None:
        raise NotImplementedError

    def delete_process(self, namespace: str, name: str) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        """Release backend resources; no-op for backends without any
        (agents call this unconditionally on stop)."""


class FakeProcessControl(ProcessControl):
    """Records intended actions; optionally injects errors.

    Like the reference's FakePodControl it does NOT write to the store —
    tests that want observable children pre-populate the store themselves,
    and the expectations machinery is what keeps the controller from
    spinning on unobserved creates.
    """

    def __init__(self) -> None:
        self.created: List[Process] = []
        self.deleted: List[str] = []  # "namespace/name"
        self.create_error: Optional[Exception] = None
        self.delete_error: Optional[Exception] = None
        self._lock = threading.Lock()

    def create_process(self, process: Process) -> None:
        if self.create_error is not None:
            raise self.create_error
        with self._lock:
            self.created.append(process)

    def delete_process(self, namespace: str, name: str) -> None:
        if self.delete_error is not None:
            raise self.delete_error
        with self._lock:
            self.deleted.append(f"{namespace}/{name}")

    def clear(self) -> None:
        with self._lock:
            self.created.clear()
            self.deleted.clear()


def default_command_builder(process: Process) -> List[str]:
    """Launch the in-process harness, which resolves spec.entrypoint and
    performs jax.distributed rendezvous (the TF_CONFIG-consuming analogue of
    tf_smoke.py:88-110)."""
    return [sys.executable, "-m", "tf_operator_tpu.rendezvous.harness", *process.spec.args]


class LocalProcessControl(ProcessControl):
    """Real backend: one OS subprocess per Process object.

    Combines RealPodControl (create/delete against the "cluster") with the
    kubelet's duty of reporting container termination state; the monitor
    thread is what turns a child exit into a store status update the
    reconciler can observe (replicas.go:310-363's data source).
    """

    GRACE_SECONDS = 5.0

    LOG_ANNOTATION = "tpujob.dev/log-path"

    # OOM oracle seam (tests stub it): returns the supervising cgroup's
    # cumulative oom_kill count, or None when no oracle exists — in which
    # case SIGKILL exits stay plain retryable, never guessed OOM.
    _oom_kills_reader = staticmethod(read_cgroup_oom_kills)

    def __init__(
        self,
        store: Store,
        command_builder: Callable[[Process], List[str]] = default_command_builder,
        inherit_env: bool = True,
        log_dir: Optional[str] = None,
        extra_env: Optional[Dict[str, str]] = None,
    ) -> None:
        self._store = store
        self._command_builder = command_builder
        self._inherit_env = inherit_env
        self._log_dir = log_dir
        # Host-local env injected into every launched child, between the
        # identity env and the controller-provided spec env (controller
        # still wins on conflicts). The host agent uses this for values
        # only the host knows — e.g. its shard-depot URL
        # (TPUJOB_PEER_DEPOT), which the controller cannot stamp because
        # it is per-host, not per-job.
        self.extra_env: Dict[str, str] = dict(extra_env or {})
        # Optional warm worker pool (runtime/warmpool.py), attached by the
        # host agent. When set, _spawn first tries to hand the launch to a
        # pre-warmed child; any miss falls through to a cold spawn.
        self.warm_pool = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
        self._lock = threading.Lock()
        # "ns/name" -> (uid, Popen|None); None while the launch is in flight.
        # The uid disambiguates incarnations: a delete + same-name recreate
        # during a gang restart must never let the OLD incarnation's
        # bookkeeping (tombstone, entry pop) act on the NEW child.
        self._children: Dict[str, tuple] = {}
        # Uids deleted while their launch was in flight: the monitor kills
        # the child as soon as Popen returns instead of leaking an orphan.
        self._tombstones: set = set()
        self._shutting_down = False
        # Lifecycle tracing (obs/): one spawn->exit span per supervised
        # incarnation, into the job timeline named by the controller-
        # injected TPUJOB_TRACE_ID. Best-effort by contract.
        self._tracer = SpanRecorder(store, component=COMPONENT_AGENT)

    # -- ProcessControl ---------------------------------------------------

    def _log_path(self, meta) -> str:
        # Combined stdout+stderr log (kubelet log analogue; served by the
        # dashboard's logs endpoint, api_handler.go:236-251). basename()
        # on each component forecloses path traversal via crafted
        # namespace/name (validation also rejects them at admission).
        return os.path.join(
            self._log_dir,
            f"{os.path.basename(meta.namespace)}_{os.path.basename(meta.name)}.log",
        )

    def create_process(self, process: Process) -> None:
        if self._log_dir:
            process.metadata.annotations[self.LOG_ANNOTATION] = self._log_path(
                process.metadata
            )
        stored = self._store.create(process)
        self.launch_existing(stored)

    def launch_existing(self, stored: Process) -> None:
        """Launch + monitor a Process that already exists in the store —
        the seam the per-host agent uses (it observes creations made by the
        controller instead of making them). No-op if this backend already
        tracks the key (watch replays deliver duplicates)."""
        if self._log_dir and self.LOG_ANNOTATION not in stored.metadata.annotations:
            # ``stored`` may be a shared watch-event snapshot (read-only by
            # the store's fanout contract): copy before annotating.
            import copy as _copy

            stored = _copy.deepcopy(stored)
            path = self._log_path(stored.metadata)
            stored.metadata.annotations[self.LOG_ANNOTATION] = path
            self._annotate_log_path(stored, path)
        stale = _NO_CHILD
        with self._lock:
            entry = self._children.get(stored.key())
            if entry is not None:
                if entry[0] == stored.metadata.uid:
                    return  # already launching/launched (watch-replay dup)
                # A previous incarnation still occupies the name: its store
                # object is gone (a new uid exists), so reap it and proceed.
                stale = self._children.pop(stored.key())[1]
                if stale is None:
                    self._tombstones.add(entry[0])
            self._children[stored.key()] = (stored.metadata.uid, None)  # reserve
        if stale not in (None, _NO_CHILD):
            self._terminate(stale)
        thread = threading.Thread(
            target=self._launch_and_monitor, args=(stored,), daemon=True,
            name=f"procmon-{stored.metadata.name}",
        )
        thread.start()

    def _annotate_log_path(self, process: Process, path: str) -> None:
        """Persist the log-path annotation on an agent-launched process so
        the dashboard's logs endpoint finds it (optimistic retry)."""
        meta = process.metadata

        def mutate(cur):
            if cur.metadata.uid != meta.uid:
                return False
            cur.metadata.annotations[self.LOG_ANNOTATION] = path

        self._store.update_with_retry(KIND_PROCESS, meta.namespace, meta.name, mutate)

    def tracks(self, namespace: str, name: str) -> bool:
        """True when this backend is supervising (or launching) ns/name."""
        with self._lock:
            return f"{namespace}/{name}" in self._children

    def tracked_keys(self) -> set:
        """Keys ("ns/name") of every supervised/launching child — the
        agent's resync sweep diffs these against a watch replay."""
        with self._lock:
            return set(self._children)

    def signal_local(self, namespace: str, name: str, signum: int) -> bool:
        """Deliver ``signum`` to the supervised child for ns/name WITHOUT
        dropping supervision: the monitor thread stays attached and reports
        the resulting exit status (e.g. SIGKILL → 137) through the normal
        path. The fault-injection seam (chaos/injector.py) — a chaos crash
        must look exactly like a real one to the controller. Returns False
        when no launched child is tracked under that key."""
        with self._lock:
            entry = self._children.get(f"{namespace}/{name}")
            child = entry[1] if entry is not None else None
        if child is None or child.poll() is not None:
            return False
        try:
            os.kill(child.pid, signum)
        except OSError:
            return False
        return True

    def kill_local(self, namespace: str, name: str) -> None:
        """Terminate the local child for ns/name without touching the store
        (the store object is already gone when the agent observes DELETED)."""
        key = f"{namespace}/{name}"
        child = _NO_CHILD
        with self._lock:
            entry = self._children.pop(key, None)
            if entry is not None:
                child = entry[1]
                if child is None:
                    # Launch in flight: tombstone THIS incarnation's uid; the
                    # monitor reaps on arrival. A same-name recreate gets a
                    # new uid and is unaffected.
                    self._tombstones.add(entry[0])
        if child not in (None, _NO_CHILD):
            self._terminate(child)

    def delete_process(self, namespace: str, name: str) -> None:
        self.kill_local(namespace, name)
        try:
            self._store.delete(KIND_PROCESS, namespace, name)
        except NotFoundError:
            pass

    def _terminate(self, child: subprocess.Popen) -> None:
        if child.poll() is None:
            child.terminate()
            try:
                child.wait(timeout=self.GRACE_SECONDS)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait()

    # -- internals --------------------------------------------------------

    def _claim_warm(self, process: Process, env: Dict[str, str], log_path: Optional[str]):
        """Try to serve the launch from the attached warm pool. Returns the
        warm child's Popen, or None → the caller cold-spawns. Only launches
        using the default harness command are eligible (a custom
        command_builder changes the command shape and disqualifies itself
        via WarmPool.serves)."""
        pool = self.warm_pool
        if pool is None:
            return None
        try:
            return pool.claim(
                self._command_builder(process), env, log_path,
                cwd=process.spec.workdir,
            )
        except Exception:  # noqa: BLE001 — warm handoff must never fail a launch
            return None

    def _spawn(self, process: Process, env: Dict[str, str], log_path: Optional[str]):
        """Launch the child; returns a Popen-like handle (pid / poll / wait /
        terminate / kill). Raises OSError on any launch failure (log-file
        open or exec). The seam NativeProcessControl overrides."""
        warm = self._claim_warm(process, env, log_path)
        if warm is not None:
            return warm
        log_file = open(log_path, "ab") if log_path else None
        try:
            return subprocess.Popen(
                self._command_builder(process),
                env=env,
                cwd=process.spec.workdir,
                stdout=log_file,
                stderr=subprocess.STDOUT if log_file else None,
                start_new_session=True,  # isolate signals from the operator
            )
        finally:
            if log_file:
                log_file.close()  # child holds its own descriptor now

    def _pop_if_mine(self, key: str, uid) -> None:
        """Drop this incarnation's entry; never a successor's reservation."""
        entry = self._children.get(key)
        if entry is not None and entry[0] == uid:
            self._children.pop(key)

    def _record_proc_span(
        self, process: Process, start: float, end: float,
        exit_code: Optional[int], oom: bool = False, note: str = "",
    ) -> None:
        """One agent-component span per supervised incarnation: spawn ->
        exit, classified by the exit taxonomy. Skipped (not failed) when
        the process carries no trace context."""
        trace_id = process.spec.env.get(ENV_TRACE_ID) or (
            process.metadata.owner_uid or ""
        )
        if not trace_id:
            return
        from tf_operator_tpu.utils.exit_codes import classify_exit_code

        attrs = {
            "node": process.spec.node_name or "local",
            "replica": f"{process.spec.replica_type}/{process.spec.replica_index}",
            "track": f"proc {process.metadata.name}",
        }
        if exit_code is not None:
            attrs["exit_code"] = str(exit_code)
            attrs["exit_class"] = classify_exit_code(exit_code, oom).value
        if note:
            attrs["note"] = note[:200]
        self._tracer.record(
            process.metadata.namespace,
            process.spec.job_name or process.metadata.name,
            trace_id, "process", start, end, attrs=attrs,
            name=f"{process.metadata.name}-{process.metadata.uid}-proc",
        )

    def _launch_and_monitor(self, process: Process) -> None:
        key = process.key()
        uid = process.metadata.uid
        env = dict(os.environ) if self._inherit_env else {}
        # Identity first, then host-local extras, then controller-provided
        # env (controller wins on conflicts — it may override e.g. the
        # entrypoint for a debug run).
        env.update(identity_env(process.spec, process.metadata.namespace))
        env.update(self.extra_env)
        env.update(process.spec.env)
        log_path = process.metadata.annotations.get(self.LOG_ANNOTATION)
        spawn_t = time.time()
        # OOM oracle: snapshot the supervising cgroup's oom_kill counter
        # around the child's lifetime (utils.exit_codes.was_oom_killed
        # promotes SIGKILL-shaped exits to OOM only on a counter delta).
        oom_kills_before = self._oom_kills_reader()
        try:
            child = self._spawn(process, env, log_path)
        except OSError as exc:
            # Covers both a failed log-file open and a failed exec: the
            # process must be reported FAILED, never left Pending forever.
            with self._lock:
                self._pop_if_mine(key, uid)
                self._tombstones.discard(uid)
            self._patch_status(process, ProcessPhase.FAILED, exit_code=127, message=str(exc))
            self._record_proc_span(
                process, spawn_t, time.time(), 127, note=str(exc)
            )
            return
        with self._lock:
            doomed = uid in self._tombstones or self._shutting_down
            if doomed:
                self._tombstones.discard(uid)
                self._pop_if_mine(key, uid)
            else:
                self._children[key] = (uid, child)
        if doomed:  # deleted while launch was in flight: reap, don't report
            self._terminate(child)
            return
        self._patch_status(process, ProcessPhase.RUNNING, pid=child.pid)
        code = child.wait()
        with self._lock:
            self._pop_if_mine(key, uid)
        oom = was_oom_killed(code, oom_kills_before, self._oom_kills_reader())
        phase = ProcessPhase.SUCCEEDED if code == 0 else ProcessPhase.FAILED
        self._patch_status(process, phase, exit_code=code, oom_killed=oom)
        self._record_proc_span(process, spawn_t, time.time(), code, oom=oom)

    def _patch_status(
        self,
        process: Process,
        phase: ProcessPhase,
        pid: Optional[int] = None,
        exit_code: Optional[int] = None,
        oom_killed: bool = False,
        message: str = "",
    ) -> None:
        meta = process.metadata

        # Optimistic-concurrency write: only status fields are ours; concurrent
        # spec/label writers must not be clobbered (apiserver status-subresource
        # contract the reference's CRD updates rely on).
        def mutate(cur):
            if cur.metadata.uid != meta.uid:
                return False  # a new incarnation took the name; don't clobber
            cur.status.phase = phase
            if pid is not None:
                cur.status.pid = pid
                cur.status.start_time = time.time()
            if exit_code is not None:
                cur.status.exit_code = exit_code
                cur.status.finish_time = time.time()
                cur.status.oom_killed = oom_killed
            if message:
                cur.status.message = message

        self._store.update_with_retry(KIND_PROCESS, meta.namespace, meta.name, mutate)

    def shutdown(self) -> None:
        """Terminate all children (operator teardown)."""
        with self._lock:
            self._shutting_down = True
            children = [e[1] for e in self._children.values() if e[1] is not None]
            self._children.clear()
        for child in children:
            if child.poll() is None:
                child.terminate()
        for child in children:
            try:
                child.wait(timeout=self.GRACE_SECONDS)
            except subprocess.TimeoutExpired:
                child.kill()


class NativeProcessControl(LocalProcessControl):
    """LocalProcessControl with spawn/monitor/kill supplied by the native
    C++ supervisor (native/supervisor.cc via runtime.native).

    Differences from the pure-Python backend, all in the compiled layer:
    children are setsid process-group leaders and deletion kills the whole
    group (a harness that forked data loaders leaves no orphans); exit
    codes arrive normalized to the 128+signal convention the taxonomy
    (reference pkg/util/train/train_util.go:18-53) is written against
    (SIGKILL → 137, SIGTERM → 143, never Python's -9/-15); and exec
    failures are reported synchronously with the child-side errno instead
    of a generic exit-127 corpse."""

    def __init__(self, *args, **kwargs) -> None:
        from tf_operator_tpu.runtime.native import NativeSupervisor

        super().__init__(*args, **kwargs)
        self._sup = NativeSupervisor()

    def _spawn(self, process: Process, env: Dict[str, str], log_path: Optional[str]):
        # Warm handoff applies here too; a claimed child is a plain Popen
        # supervised Python-side (exit codes in Python's -signum form for
        # signal deaths — the taxonomy handles both conventions).
        warm = self._claim_warm(process, env, log_path)
        if warm is not None:
            return warm
        return self._sup.spawn(
            self._command_builder(process), env, process.spec.workdir, log_path
        )

    def _terminate(self, child) -> None:
        from tf_operator_tpu.runtime.native import NativeChild

        if isinstance(child, NativeChild):
            # Native escalation: TERM → grace → KILL, on the whole group.
            self._sup.terminate(child, self.GRACE_SECONDS)
        else:
            # Warm-pool handoffs are plain Popen children even under the
            # native backend; the Python escalation path covers them.
            super()._terminate(child)


