"""Per-host agent: launches the processes bound to its Host.

The kubelet analogue. The reconciler (controller.v2 analogue) never
launches anything in multi-host mode — it writes Process objects with a
node binding (pod.spec.nodeName analogue) chosen gang-atomically by the
scheduler, and each host's agent observes its own bindings through the
watch stream and launches them with the local (or native C++) backend —
the same watch-driven split as "controller POSTs Pod to apiserver →
kubelet starts container" (SURVEY.md §1 control/data split).

The agent also owns its Host object: it registers it at start, heartbeats
``status.heartbeat_time`` (NodeStatus heartbeat analogue), and marks it
NotReady on graceful stop. A missed heartbeat is how the controller
detects node loss and triggers gang restart (runtime/scheduler.py TTL).
"""

from __future__ import annotations

import logging
import os
import signal as _signal
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from tf_operator_tpu.api.types import KIND_HOST, KIND_PROCESS, KIND_TPUJOB, ObjectMeta
from tf_operator_tpu.runtime.objects import (
    Host,
    HostPhase,
    HostSpec,
    Process,
    ProcessPhase,
    declare_lost,
)
from tf_operator_tpu.runtime.process_backend import LocalProcessControl
from tf_operator_tpu.runtime.store import (
    AlreadyExistsError,
    Store,
    TransientStoreError,
    WatchEventType,
)

log = logging.getLogger("tpujob.agent")

DEFAULT_HEARTBEAT_INTERVAL = 3.0

# Goodput-autopilot warm-pool retarget (r16): the controller stamps the
# desired per-host warm-slot count on each Host object; the agent's
# heartbeat loop applies it to its local pool. The key mirrors
# controller/reconciler.py's ANNOTATION_WARMPOOL_TARGET — annotation
# keys are wire protocol, shared by value, not by import (an agent
# process must not drag the controller module tree in).
ANNOTATION_WARMPOOL_TARGET = "tpujob.dev/warmpool-target"


class HostAgent:
    def __init__(
        self,
        store: Store,
        name: str,
        address: str = "127.0.0.1",
        total_chips: int = 0,
        slice_type: str = "",
        max_processes: int = 0,
        backend: Optional[LocalProcessControl] = None,
        heartbeat_interval: float = DEFAULT_HEARTBEAT_INTERVAL,
        log_dir: Optional[str] = None,
        depot: bool = False,
        depot_keep: int = 2,
        warm_pool: int = 0,
        warm_import_jax: bool = False,
        stackdump_dir: Optional[str] = None,
    ) -> None:
        """``depot=True`` starts a host-lifetime shard depot
        (rendezvous/statechannel.py): workloads on this host push each
        COMMITTED checkpoint step to it over loopback
        (``TPUJOB_PEER_DEPOT``, injected via the backend's host-local
        env), and because the depot outlives gang teardowns — unlike any
        gang member — a restarted gang can pull warm state from it
        through the controller-stamped ``TPUJOB_RESTORE_PEERS`` instead
        of re-reading disk. The depot URL is announced on the Host record
        (``spec.depot_url``) so the controller can stamp it."""
        self.store = store
        self.name = name
        self.spec = HostSpec(
            address=address,
            slice_type=slice_type,
            total_chips=total_chips,
            max_processes=max_processes,
        )
        self.backend = backend or LocalProcessControl(store, log_dir=log_dir)
        self.depot = None
        if depot:
            from tf_operator_tpu.rendezvous.env import ENV_PEER_DEPOT
            from tf_operator_tpu.rendezvous.statechannel import ShardDepot

            self.depot = ShardDepot(host=address, keep=depot_keep)
            self.spec.depot_url = self.depot.url
            self.backend.extra_env[ENV_PEER_DEPOT] = self.depot.url
        # Warm worker pool (runtime/warmpool.py): N pre-initialized
        # harness runtimes for this host's topology, handed to gang
        # members at launch instead of a cold fork. Attached on the
        # backend's spawn seam; sized 0 = disabled (the r10 cold path).
        self.warm_pool = None
        if warm_pool > 0:
            from tf_operator_tpu.runtime.warmpool import WarmPool

            self.warm_pool = WarmPool(
                warm_pool, topology=slice_type, import_jax=warm_import_jax
            )
            self.backend.warm_pool = self.warm_pool
        # Hang forensics (r15, obs/blackbox.py): host-local directory the
        # harness's SIGUSR2 faulthandler hook dumps stacks into. Injected
        # through the backend's host-local env exactly like the depot URL
        # — the path is per-host knowledge the controller cannot stamp.
        from tf_operator_tpu.rendezvous.env import ENV_STACKDUMP_DIR

        self.stackdump_dir = stackdump_dir or (
            os.path.join(log_dir, "stackdumps") if log_dir
            else os.path.join(tempfile.gettempdir(), f"tpujob-stacks-{name}")
        )
        try:
            os.makedirs(self.stackdump_dir, exist_ok=True)
            # Backends without an env-injection seam (FakeProcessControl)
            # simply get no harness-side dump hook; the agent-side sweep
            # still works against whatever the harness wrote elsewhere.
            if hasattr(self.backend, "extra_env"):
                self.backend.extra_env[ENV_STACKDUMP_DIR] = self.stackdump_dir
        except OSError:
            # Unwritable dump dir degrades the postmortem (no stacks from
            # this host), never the agent.
            self.stackdump_dir = ""
        # (job key, rank) -> directive epoch already swept, so a heartbeat
        # tick never re-signals a rank for the same hang (one hang ⇒ one
        # SIGUSR2 per rank; a NEW epoch sweeps again).
        self._stack_epochs: Dict[Tuple[str, int], int] = {}
        self.heartbeat_interval = heartbeat_interval
        self._stop = threading.Event()
        self._threads: list = []
        self._watch = None
        # Keys of bindings seen during the current watch replay (between
        # REPLAY_START and SYNCED); None outside a replay window.
        self._replay_seen: Optional[set] = None
        # Permanent-failure escalation (UnauthorizedError from the store):
        # set to the reason string; heartbeats stop (Host -> NodeLost) and
        # the daemon wrapper (cli/agent.py) exits nonzero. A dead watch
        # thread behind a live heartbeat would mask NodeLost forever.
        self.fatal: Optional[str] = None
        # Preemption notice received: the Host is DRAINING. Sticky across
        # re-registration — an admin deleting the Host object mid-drain
        # must not resurrect it as Ready (the scheduler would place a
        # fresh gang onto a host about to vanish).
        self._draining = False
        # Heartbeats paused (chaos kill+return faults, r12): the agent
        # stays alive — watch loop, children, depot all keep running — but
        # the Host object's heartbeat goes stale, so the controller's
        # node-lost detection fires exactly as if the machine went silent.
        # stop() is NOT a substitute: it SIGTERMs children (exit 143 =
        # preemption class) and tears down the depot, neither of which a
        # "host went dark and came back" fault implies.
        self._hb_paused = False

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._register()
        self._watch = self.store.watch(kinds=[KIND_PROCESS])
        t1 = threading.Thread(target=self._watch_loop, daemon=True,
                              name=f"agent-{self.name}-watch")
        t2 = threading.Thread(target=self._heartbeat_loop, daemon=True,
                              name=f"agent-{self.name}-heartbeat")
        self._threads = [t1, t2]
        t1.start()
        t2.start()

    def stop(self) -> None:
        """Graceful drain: mark NotReady, stop launching, kill children.

        The NotReady write is best-effort: over a RemoteStore with the
        operator unreachable it would raise, and children MUST still be
        killed — an exception here would orphan every training process."""
        self._stop.set()
        if self._watch is not None:
            self._watch.stop()
        try:
            self._set_phase(HostPhase.NOT_READY, "agent stopped", transient_timeout=5.0)
        except Exception as exc:
            log.warning("agent %s: could not mark NotReady (%s)", self.name, exc)
        if self.warm_pool is not None:
            self.warm_pool.stop()
        self.backend.shutdown()
        if self.depot is not None:
            # Last: a draining host keeps SERVING shards until the very
            # end — the preempted gang's replacement may be pulling from
            # this depot right now.
            self.depot.stop()
        for t in self._threads:
            t.join(timeout=5)

    def notify_preemption(self, message: str = "preemption notice received") -> None:
        """Deliver a preemption notice: mark this Host DRAINING.

        The host stays alive — heartbeats continue, already-running
        children keep running — but the scheduler stops placing onto it
        and the controller gracefully gang-restarts members bound here
        (checkpoint-resumed on surviving hosts, cause=preemption, not
        counted against backoff_limit). The deletion of each binding
        reaches this agent through the watch and SIGTERMs the child
        (exit 143, the preemption-retryable code). Infrastructure later
        reclaims the machine: stop() or heartbeat loss finishes the
        Ready → Draining → gone lifecycle."""
        self._draining = True
        log.warning("agent %s: preemption notice — draining", self.name)
        if self.warm_pool is not None:
            # No new placements are coming; idle pre-warmed runtimes are
            # just memory the reclaiming infrastructure wants back.
            self.warm_pool.invalidate()
        self._set_phase(HostPhase.DRAINING, message)

    @property
    def draining(self) -> bool:
        return self._draining

    # -- host object ------------------------------------------------------

    def _register(self) -> None:
        # Drain is sticky across (re-)registration: an admin deleting the
        # Host object mid-drain must not resurrect it Ready.
        phase = HostPhase.DRAINING if self._draining else HostPhase.READY
        while True:
            host = Host(
                metadata=ObjectMeta(name=self.name, namespace="default"),
                spec=self.spec,
            )
            host.status.phase = phase
            host.status.heartbeat_time = time.time()
            try:
                self.store.create(host)
                return
            except AlreadyExistsError:
                pass
            except TransientStoreError as exc:
                # Operator momentarily unreachable (restart, network blip):
                # an agent daemon must wait it out, not die at startup.
                log.warning(
                    "agent %s: register failed (%s); retrying", self.name, exc
                )
                if self._stop.wait(1.0):
                    return
                continue

            # Re-registration after restart: adopt, refresh spec + phase
            # (Ready, or Draining when a preemption notice is in effect).
            def adopt(cur):
                cur.spec = self.spec
                cur.status.phase = phase
                cur.status.heartbeat_time = time.time()
                cur.status.message = "agent re-registered"

            if self.store.update_with_retry(KIND_HOST, "default", self.name, adopt):
                return
            # Object vanished mid-adoption (admin drain racing a restart):
            # loop and retry the create.

    def pause_heartbeats(self) -> None:
        """Stop touching the Host heartbeat WITHOUT stopping the agent —
        the controller sees a silent host (node-lost after TTL) while
        children, watch loop, and shard depot stay up. The chaos
        kill+return fault's half of "host went dark"; resume_heartbeats()
        is the return."""
        self._hb_paused = True

    def resume_heartbeats(self) -> None:
        """The host 'returns': re-register (node-lost detection may have
        seen the Host object age out or an admin may have deleted it) and
        touch the heartbeat immediately rather than waiting an interval."""
        self._hb_paused = False
        try:
            self._touch_heartbeat()
        except Exception:
            log.exception(
                "agent %s: resume heartbeat failed; loop will retry",
                self.name,
            )

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            if self._hb_paused:
                continue
            # The heartbeat thread must survive ANY error: if it died while
            # the watch loop kept launching, the host would be declared
            # NodeLost and every healthy process on it failed and fenced.
            try:
                self._touch_heartbeat()
            except Exception:
                log.exception("agent %s: heartbeat failed; retrying", self.name)
            # Stack-sweep poll (r15) rides the same cadence: the wedged
            # gang produces no process events, so the watch loop never
            # fires — the heartbeat tick is the agent's only live pulse
            # during a hang.
            try:
                self._sweep_stackdumps()
            except Exception:
                log.exception("agent %s: stack sweep failed; retrying", self.name)

    def _touch_heartbeat(self) -> None:
        # The heartbeat's read-modify-write doubles as the warm-pool
        # retarget poll (r16): the touch closure sees the fresh Host
        # object, so the autopilot's target annotation rides for free —
        # no extra store round-trip on the heartbeat path.
        seen_target: list = []

        def touch(cur):
            cur.status.heartbeat_time = time.time()
            seen_target[:] = [
                cur.metadata.annotations.get(ANNOTATION_WARMPOOL_TARGET)
            ]

        if self.store.update_with_retry(KIND_HOST, "default", self.name, touch) is None:
            # Host object deleted (drained by an admin): re-register.
            self._register()
            return
        raw = seen_target[0] if seen_target else None
        if raw is not None and self.warm_pool is not None:
            try:
                self.warm_pool.resize(int(raw))
            except (ValueError, TypeError):
                log.warning(
                    "agent %s: bad warm-pool target annotation %r",
                    self.name, raw,
                )

    def _set_phase(
        self, phase: HostPhase, message: str, transient_timeout=None
    ) -> None:
        from tf_operator_tpu.runtime.store import update_with_retry_loop

        def mutate(cur):
            cur.status.phase = phase
            cur.status.message = message

        update_with_retry_loop(
            self.store, KIND_HOST, "default", self.name, mutate,
            transient_timeout=transient_timeout,
        )

    # -- process lifecycle ------------------------------------------------

    def _mine(self, proc: Process) -> bool:
        return proc.spec.node_name == self.name

    def _watch_loop(self) -> None:
        from tf_operator_tpu.runtime.remote_store import UnauthorizedError

        assert self._watch is not None
        try:
            self._run_watch()
        except UnauthorizedError as exc:
            # Permanent: go FATAL, not blind. Stopping _stop ends the
            # heartbeat loop too, so the Host goes NodeLost and the
            # controller reacts instead of binding work to a deaf agent.
            self.fatal = str(exc)
            log.critical("agent %s: store credentials rejected; going fatal "
                         "(%s)", self.name, exc)
            self._stop.set()

    def _run_watch(self) -> None:
        for ev in self._watch:
            if self._stop.is_set():
                return
            try:
                self._handle_event(ev)
            except Exception:
                # The watch loop must outlive any single bad event: if it
                # died while the separate heartbeat thread kept the Host
                # Ready, newly bound processes would sit Pending forever
                # with NodeLost detection masked by the fresh heartbeat.
                log.exception(
                    "agent %s: error handling %s for %s; continuing",
                    self.name, ev.type.value,
                    ev.obj.metadata.name if ev.obj is not None else "-",
                )

    def _handle_event(self, ev) -> None:
        # Remote-watch control events: a reconnect replays existing
        # objects but NEVER deletions that happened while disconnected —
        # on SYNCED, any child this agent still supervises that the
        # replay didn't mention is an orphan to kill (the kubelet resync).
        if ev.type is WatchEventType.REPLAY_START:
            self._replay_seen = set()
            return
        if ev.type is WatchEventType.SYNCED:
            if self._replay_seen is not None:
                for key in self.backend.tracked_keys() - self._replay_seen:
                    ns, _, name = key.partition("/")
                    log.warning(
                        "agent %s: reaping %s (absent from watch replay)",
                        self.name, key,
                    )
                    self.backend.kill_local(ns, name)
            self._replay_seen = None
            return
        proc = ev.obj
        if not self._mine(proc):
            return
        if self._replay_seen is not None:
            self._replay_seen.add(proc.metadata.key())
        if ev.type is WatchEventType.DELETED:
            self.backend.kill_local(proc.metadata.namespace, proc.metadata.name)
        elif ev.type is WatchEventType.ADDED:
            # Replays deliver already-finished processes; only Pending
            # ones are launchable (launch_existing dedupes in-flight).
            if proc.status.phase is ProcessPhase.PENDING:
                self.backend.launch_existing(proc)
            elif proc.status.phase is ProcessPhase.RUNNING and not self.backend.tracks(
                proc.metadata.namespace, proc.metadata.name
            ):
                # Agent restarted over a RUNNING binding it no longer
                # supervises (kubelet-restart reconcile): the old child is
                # orphaned — declare it lost so the controller's fenced
                # gang restart takes over. Without this the fresh heartbeat
                # masks the loss and the job hangs forever.
                if declare_lost(
                    self.store, proc,
                    f"agent on {self.name} restarted; process lost",
                ) is not None:
                    log.warning(
                        "declared orphaned process %s/%s lost",
                        proc.metadata.namespace, proc.metadata.name,
                    )

    # -- hang forensics: the stack sweep (r15, obs/blackbox.py) -----------

    # How long after SIGUSR2 delivery to wait before reading the dump
    # file: faulthandler writes synchronously inside the signal handler,
    # but delivery itself is asynchronous to os.kill returning.
    STACKDUMP_SETTLE_SECONDS = 0.3

    def _sweep_stackdumps(self) -> None:
        """Act on pending stackdump directives for jobs whose members this
        agent supervises: deliver SIGUSR2 to each wedged child (the
        harness's faulthandler hook dumps all-thread stacks to the
        per-process file), read the dump back, ship it through the
        store/API seam, and ack the rank into the directive. Epoch-deduped
        per (job, rank): one hang ⇒ one signal per rank, idempotent
        across heartbeat ticks and agent restarts (already-acked ranks
        are skipped store-side). Best-effort end to end."""
        if not self.stackdump_dir:
            return
        tracked = self.backend.tracked_keys()
        if not tracked:
            return
        by_job: Dict[Tuple[str, str], List[Process]] = {}
        for key in tracked:
            ns, _, pname = key.partition("/")
            try:
                proc = self.store.get(KIND_PROCESS, ns, pname)
            except Exception:  # noqa: BLE001 — gone/unreachable: skip
                continue
            if proc.spec.job_name:
                by_job.setdefault((ns, proc.spec.job_name), []).append(proc)
        for (ns, job_name), procs in by_job.items():
            try:
                job = self.store.get(KIND_TPUJOB, ns, job_name)
            except Exception:  # noqa: BLE001
                continue
            directive = job.status.stackdump_directive or {}
            epoch = int(directive.get("epoch", 0) or 0)
            if epoch <= 0:
                continue
            acks = directive.get("acks") or {}
            jkey = f"{ns}/{job_name}"
            signaled = []
            for proc in procs:
                rank = self._proc_rank(proc)
                if str(rank) in acks:
                    self._stack_epochs[(jkey, rank)] = epoch
                    continue
                if self._stack_epochs.get((jkey, rank)) == epoch:
                    continue
                if self.backend.signal_local(
                    proc.metadata.namespace, proc.metadata.name,
                    _signal.SIGUSR2,
                ):
                    signaled.append((proc, rank))
                self._stack_epochs[(jkey, rank)] = epoch
            if not signaled:
                continue
            time.sleep(self.STACKDUMP_SETTLE_SECONDS)
            for proc, rank in signaled:
                self._ship_dump(job, proc, rank, epoch)

    @staticmethod
    def _proc_rank(proc: Process) -> int:
        """The process's gang rank — the controller-stamped rendezvous
        rank when present (matches the telemetry ring's rank axis), the
        replica index otherwise."""
        from tf_operator_tpu.rendezvous.env import ENV_PROCESS_ID

        try:
            return int(
                (proc.spec.env or {}).get(
                    ENV_PROCESS_ID, proc.spec.replica_index
                )
            )
        except (TypeError, ValueError):
            return proc.spec.replica_index

    def _ship_dump(self, job, proc: Process, rank: int, epoch: int) -> None:
        from tf_operator_tpu.obs.blackbox import ship_stackdump
        from tf_operator_tpu.rendezvous.env import ENV_TRACE_ID, stackdump_path

        path = stackdump_path(
            self.stackdump_dir, proc.metadata.namespace,
            proc.spec.job_name, proc.spec.replica_type,
            proc.spec.replica_index,
        )
        try:
            with open(path, "r", errors="replace") as f:
                text = f.read()
        except OSError:
            # No dump file: the harness never installed the hook (old
            # entrypoint, exec failure) — ack with an explicit marker so
            # the reconciler's sweep completes instead of waiting out the
            # grace for a dump that will never come.
            text = ""
        trace_id = (proc.spec.env or {}).get(ENV_TRACE_ID) or (
            proc.metadata.owner_uid or job.metadata.uid
        )
        shipped = None
        if text:
            shipped = ship_stackdump(
                self.store, proc.metadata.namespace, proc.spec.job_name,
                trace_id, rank, epoch, text, host=self.name,
            )
        self._ack_dump(
            proc.metadata.namespace, proc.spec.job_name, rank, epoch,
            shipped.metadata.name if shipped is not None else "",
        )

    def _ack_dump(
        self, namespace: str, job_name: str, rank: int, epoch: int, ref: str
    ) -> None:
        """Publish this rank's ack into the job's stackdump directive
        (refusing superseded epochs — the profile-directive rule). The
        ack value is the shipped artifact's store name, or "" when no
        dump could be produced (hookless harness): the reconciler counts
        EITHER as sweep completion for the rank."""

        def mutate(cur):
            d = cur.status.stackdump_directive or {}
            if int(d.get("epoch", 0) or 0) != epoch:
                return False  # a newer hang's sweep superseded this one
            acks = dict(d.get("acks") or {})
            if str(rank) in acks:
                return False
            acks[str(rank)] = ref
            cur.status.stackdump_directive = {**d, "acks": acks}

        try:
            self.store.update_with_retry(
                KIND_TPUJOB, namespace, job_name, mutate
            )
        except Exception:  # noqa: BLE001 — the reconciler's grace bounds us
            log.exception(
                "agent %s: stackdump ack for %s/%s rank %d failed",
                self.name, namespace, job_name, rank,
            )
