"""Durable store state: per-mutation WAL + compacted snapshots.

The reference operator gets durability for free — every TFJob/Pod record
lives in etcd behind the apiserver, so a controller restart is a pure
cache-rebuild (list+watch) over state that never went away. Our Store is
in-memory; without this module, killing the operator evaporates every
TPUJob/Host/Process record while the real training processes keep
running — the worst kind of partial failure. This module closes that gap
with the classic two-piece recipe every durable KV store uses:

- **WAL** (``wal-<start_rv>.jsonl``): one JSON record appended per store
  mutation, in resource-version order (the store calls :meth:`append`
  while holding its lock, so WAL order IS apply order). Each record
  carries a CRC32 over its canonical encoding; replay verifies it.
  A torn tail — the final record of the final segment cut mid-write by
  a crash — is truncated away on recovery (it was never acknowledged
  to any watcher-visible state that survives either). A bad checksum
  anywhere *else* is corruption, not a crash artifact, and recovery
  refuses it loudly rather than silently dropping history.
- **Snapshots** (``snapshot-<rv>.json``): every ``snapshot_every``
  mutations the full object set is written to a temp file and atomically
  renamed, the WAL rotates to a fresh segment, and older segments/
  snapshots are deleted. Recovery = load newest snapshot, replay the WAL
  suffix (records with rv > snapshot rv), restore the resource_version
  counter to max(rv)+1 — so optimistic CAS, watch ordering, and
  uid-keyed adoption behave identically post-restart.

fsync policy: WAL appends are ``flush()``-ed per record — an operator
*process* crash (SIGKILL, OOM, panic) loses nothing, because the bytes
are in the kernel before the mutation's watch event fans out. ``fsync=
True`` additionally fsyncs per append (and the snapshot + directory on
rotation), extending the guarantee to machine/power loss at a large
per-write cost. Deliberately NOT durable: watch subscriptions, informer
caches, controller expectations, metrics counters, and the live OS
processes themselves (agents re-register and resync orphans; the
reconciler re-adopts recovered children — see controller.record_recovery).
"""

from __future__ import annotations

import json
import logging
import os
import re
import zlib
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from tf_operator_tpu.api.types import KIND_TELEMETRY

log = logging.getLogger("tpujob.persist")

_SNAPSHOT_RE = re.compile(r"^snapshot-(\d+)\.json$")
_SEGMENT_RE = re.compile(r"^wal-(\d+)\.jsonl$")

DEFAULT_SNAPSHOT_EVERY = 1000

OP_CREATE = "create"
OP_UPDATE = "update"
OP_DELETE = "delete"


class PersistenceError(RuntimeError):
    """Durable state is corrupt beyond what crash semantics explain
    (mid-file checksum mismatch, unreadable snapshot). Recovery refuses
    to guess: silently dropping acknowledged history is worse than
    stopping."""


def _canonical(record: Dict[str, Any]) -> bytes:
    """Stable encoding the CRC is computed over (crc field excluded)."""
    body = {k: v for k, v in record.items() if k != "crc"}
    return json.dumps(body, sort_keys=True, separators=(",", ":")).encode()


def _checksum(record: Dict[str, Any]) -> int:
    return zlib.crc32(_canonical(record)) & 0xFFFFFFFF


@dataclass
class RecoveryInfo:
    """What recovery found — the operator logs it and the controller's
    re-adoption pass (record_recovery) stamps it into restart spans."""

    recovered: bool = False  # pre-existing durable state was found
    resource_version: int = 0  # counter restored to this (next alloc is +1)
    objects: int = 0
    snapshot_rv: int = 0
    replayed: int = 0  # WAL records applied on top of the snapshot
    truncated_tail: bool = False  # a torn final record was dropped


class StorePersister:
    """Writes one WAL record per store mutation; compacts periodically.

    All methods are called by the Store WHILE HOLDING its lock — that is
    the ordering guarantee (WAL order == apply order == watch order), and
    it makes the snapshot a consistent cut for free. The persister reads
    the store's object map directly during a snapshot for the same
    reason.
    """

    def __init__(
        self,
        data_dir: str,
        snapshot_every: int = DEFAULT_SNAPSHOT_EVERY,
        fsync: bool = False,
        segment_start: int = 1,
        persist_telemetry: bool = False,
    ) -> None:
        self.data_dir = os.path.abspath(data_dir)
        self.snapshot_every = max(1, int(snapshot_every))
        self.fsync = bool(fsync)
        # Telemetry ring slots are overwrite-churn, not state: every rank
        # rewrites its slot each window, so logging them as full mutations
        # makes the WAL grow with step count instead of object count.
        # Default False skips them (and filters them from snapshots) —
        # after a restart the rings simply refill from live reporters.
        self.persist_telemetry = bool(persist_telemetry)
        os.makedirs(self.data_dir, exist_ok=True)
        self._store: Any = None
        self._since_snapshot = 0
        # Per-kind WAL accounting (tpujob_wal_{records,bytes}_total{kind}
        # + the skipped columns): {"kind": {"records", "bytes", "skipped"}}.
        self._stats: Dict[str, Dict[str, int]] = {}
        self._segment_path = os.path.join(
            self.data_dir, f"wal-{segment_start}.jsonl"
        )
        self._wal = open(self._segment_path, "ab")

    def bind(self, store: Any) -> None:
        """Attach the store whose object map snapshots read (open_store
        wires this; the store holds the persister symmetrically)."""
        self._store = store

    # -- write path (store lock held) -------------------------------------

    def append(self, op: str, obj: Any, rv: int) -> None:
        from tf_operator_tpu.runtime.serialize import to_doc

        stats = self._stats.setdefault(
            obj.kind, {"records": 0, "bytes": 0, "skipped": 0}
        )
        stats["records"] += 1
        if not self.persist_telemetry and obj.kind == KIND_TELEMETRY:
            # No write, no snapshot-counter bump: a skipped record leaves
            # an rv gap, which recovery tolerates (replay applies records
            # by rv order; no surviving object ever carries a skipped rv).
            stats["skipped"] += 1
            return
        meta = obj.metadata
        record: Dict[str, Any] = {
            "rv": rv,
            "op": op,
            "kind": obj.kind,
            "ns": meta.namespace,
            "name": meta.name,
            "obj": None if op == OP_DELETE else to_doc(obj),
        }
        record["crc"] = _checksum(record)
        line = json.dumps(record, sort_keys=True).encode() + b"\n"
        self._wal.write(line)
        stats["bytes"] += len(line)
        self._wal.flush()
        if self.fsync:
            os.fsync(self._wal.fileno())
        self._since_snapshot += 1
        if self._since_snapshot >= self.snapshot_every:
            self._snapshot(rv)

    def wal_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-kind append accounting: {"kind": {"records": calls,
        "bytes": bytes actually written, "skipped": records elided by
        the telemetry-coalescing default}}."""
        return {k: dict(v) for k, v in self._stats.items()}

    def _snapshot(self, rv: int) -> None:
        """Write the full object set at ``rv`` (atomic tmp+rename), rotate
        the WAL, and GC segments/snapshots the new snapshot supersedes."""
        from tf_operator_tpu.runtime.serialize import to_doc

        assert self._store is not None, "persister not bound to a store"
        docs = [
            to_doc(o)
            for o in self._store._objects.values()
            if self.persist_telemetry or o.kind != KIND_TELEMETRY
        ]
        body = {"rv": rv, "objects": docs}
        body["crc"] = _checksum(body)
        final = os.path.join(self.data_dir, f"snapshot-{rv}.json")
        tmp = final + ".tmp"
        with open(tmp, "w") as f:
            json.dump(body, f, sort_keys=True)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        os.rename(tmp, final)
        # Rotate: records after this point carry rv > snapshot rv, so the
        # old segment is fully covered by the snapshot.
        self._wal.close()
        self._segment_path = os.path.join(self.data_dir, f"wal-{rv + 1}.jsonl")
        self._wal = open(self._segment_path, "ab")
        if self.fsync:
            fd = os.open(self.data_dir, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        self._since_snapshot = 0
        # GC: everything the new snapshot supersedes. A crash between the
        # rename above and here just leaves extra files; recovery skips
        # records with rv <= snapshot rv, so they are harmless.
        for name in os.listdir(self.data_dir):
            path = os.path.join(self.data_dir, name)
            if path == self._segment_path:
                continue
            m = _SNAPSHOT_RE.match(name) or _SEGMENT_RE.match(name)
            if m and int(m.group(1)) <= rv and name != f"snapshot-{rv}.json":
                _unlink_quiet(path)

    def close(self) -> None:
        try:
            self._wal.flush()
            if self.fsync:
                os.fsync(self._wal.fileno())
        finally:
            self._wal.close()


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


# ---- recovery -----------------------------------------------------------


def _load_snapshot(data_dir: str) -> Tuple[int, List[Dict[str, Any]]]:
    """Newest snapshot's (rv, object docs); (0, []) when none exists.
    Snapshots are atomic-renamed, so a present file is complete — a
    parse/checksum failure is real corruption and raises."""
    best_rv, best_path = 0, None
    try:
        names = os.listdir(data_dir)
    except OSError:
        return 0, []
    for name in names:
        m = _SNAPSHOT_RE.match(name)
        if m and int(m.group(1)) > best_rv:
            best_rv, best_path = int(m.group(1)), os.path.join(data_dir, name)
    if best_path is None:
        return 0, []
    try:
        with open(best_path) as f:
            body = json.load(f)
    except (OSError, ValueError) as exc:
        raise PersistenceError(f"snapshot {best_path} unreadable: {exc}") from exc
    crc = body.get("crc")
    if crc is not None and crc != _checksum(body):
        raise PersistenceError(f"snapshot {best_path} failed its checksum")
    return int(body["rv"]), list(body.get("objects", []))


def _segments(data_dir: str) -> List[Tuple[int, str]]:
    out = []
    for name in os.listdir(data_dir):
        m = _SEGMENT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(data_dir, name)))
    out.sort()
    return out


def _replay_segment(
    path: str, is_last_segment: bool
) -> Tuple[List[Dict[str, Any]], bool]:
    """Parse one WAL segment's records, verifying checksums.

    Returns (records, truncated). A malformed/mismatched record at the
    very TAIL of the LAST segment is a torn write — the only damage a
    crash can produce, because appends are sequential: the file is
    truncated back to the last good record and recovery proceeds. The
    same defect anywhere else (good records follow it, or a non-final
    segment) means acknowledged history is damaged — raise."""
    with open(path, "rb") as f:
        data = f.read()
    records: List[Dict[str, Any]] = []
    good_end = pos = 0
    bad: Optional[str] = None
    while pos < len(data):
        nl = data.find(b"\n", pos)
        end = len(data) if nl == -1 else nl + 1
        stripped = data[pos:end].strip()
        if stripped:
            try:
                record = json.loads(stripped)
            except ValueError:
                record = None
            if (
                nl == -1  # final record cut mid-write (no newline)
                or not isinstance(record, dict)
                or record.get("crc") != _checksum(record)
            ):
                bad = "torn/unparseable or checksum-mismatched record"
                break
            records.append(record)
        pos = good_end = end
    if bad is None:
        return records, False
    torn_tail = is_last_segment and not data[end:].strip()
    if not torn_tail:
        raise PersistenceError(
            f"WAL {path}: {bad} at offset {pos} with later records present "
            "— corruption, not a crash artifact; refusing to drop history"
        )
    log.warning("WAL %s: %s at offset %d; truncating torn tail", path, bad, pos)
    with open(path, "r+b") as f:
        f.truncate(good_end)
    return records, True


def recover(data_dir: str) -> Tuple[Dict[Tuple[str, str, str], Any], RecoveryInfo]:
    """Rebuild (objects-by-key, RecoveryInfo) from snapshot + WAL suffix."""
    from tf_operator_tpu.runtime.serialize import from_doc

    info = RecoveryInfo()
    if not os.path.isdir(data_dir):
        return {}, info
    snap_rv, snap_docs = _load_snapshot(data_dir)
    segments = _segments(data_dir)
    if snap_rv == 0 and not segments:
        return {}, info

    objects: Dict[Tuple[str, str, str], Any] = {}
    for doc in snap_docs:
        obj = from_doc(doc["kind"], doc)
        objects[(obj.kind, obj.metadata.namespace, obj.metadata.name)] = obj
    info.snapshot_rv = snap_rv
    max_rv = snap_rv

    for i, (_, path) in enumerate(segments):
        records, truncated = _replay_segment(path, i == len(segments) - 1)
        info.truncated_tail = info.truncated_tail or truncated
        for record in records:
            rv = int(record["rv"])
            if rv <= snap_rv:
                continue  # already folded into the snapshot
            max_rv = max(max_rv, rv)
            key = (record["kind"], record["ns"], record["name"])
            if record["op"] == OP_DELETE:
                objects.pop(key, None)
            else:
                objects[key] = from_doc(record["kind"], record["obj"])
            info.replayed += 1

    info.recovered = True
    info.resource_version = max_rv
    info.objects = len(objects)
    return objects, info


def open_store(
    data_dir: str,
    snapshot_every: int = DEFAULT_SNAPSHOT_EVERY,
    fsync: bool = False,
    indexed_labels=None,
    persist_telemetry: bool = False,
):
    """The one entry point: recover (or initialize) durable state under
    ``data_dir`` and return ``(Store, RecoveryInfo)`` with persistence
    attached — every subsequent mutation is WAL-logged. A fresh operator
    pointed at an existing data-dir reconstructs the identical object set
    and resource_version the previous incarnation last acknowledged."""
    from tf_operator_tpu.runtime.store import INDEXED_LABELS, Store

    objects, info = recover(data_dir)
    store = Store(
        indexed_labels=INDEXED_LABELS if indexed_labels is None else indexed_labels
    )
    if objects:
        store.restore_objects(objects.values(), next_rv=info.resource_version + 1)
    elif info.recovered:
        store.restore_objects([], next_rv=info.resource_version + 1)
    persister = StorePersister(
        data_dir,
        snapshot_every=snapshot_every,
        fsync=fsync,
        segment_start=info.resource_version + 1,
        persist_telemetry=persist_telemetry,
    )
    store.attach_persister(persister)
    log.info(
        "durable store at %s: recovered=%s objects=%d rv=%d "
        "(snapshot rv %d + %d WAL records%s)",
        data_dir, info.recovered, info.objects, info.resource_version,
        info.snapshot_rv, info.replayed,
        ", torn tail truncated" if info.truncated_tail else "",
    )
    return store, info
