"""Gang scheduler: slice-atomic placement of a job's processes onto Hosts.

Reference parity + TPU delta: the reference approximates gang scheduling
with a PodDisruptionBudget (minAvailable = Σreplicas) handed to
kube-arbitrator (pkg/trainer/training.go:450-511) — placement itself is
kube-scheduler's per-pod, non-atomic decision. On TPU the slice is the
placement atom: either every gang member lands on a Ready host of the
right slice family with chip capacity, or nothing is created at all
(SURVEY.md §7 hard part b). This module makes that decision; the
reconciler stamps the resulting node bindings before any create, so a
partially-placed gang can never exist.

Single-host mode is the degenerate case: with no Host objects registered
the scheduler reports "unmanaged" and the reconciler launches everything
through the local backend exactly as before.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from tf_operator_tpu.api.types import KIND_HOST, KIND_PROCESS, TPUJob
from tf_operator_tpu.runtime.objects import Host, HostPhase, Process
from tf_operator_tpu.runtime.store import Store

# A host whose agent has not heartbeat within this window is not Ready
# (node-lost detection; feeds gang restart through mark_node_lost).
DEFAULT_HEARTBEAT_TTL = 15.0


class SchedulingError(RuntimeError):
    """The gang cannot be placed atomically right now."""


def _family(slice_type: str) -> str:
    """'v5p-32' -> 'v5p' (generation family; capacity comes from chips)."""
    return slice_type.split("-")[0] if slice_type else ""


def _liveness_anchor(h: Host) -> float:
    """Last proof the host's agent was alive: the heartbeat, else the
    registration (creation) time. A host that registered but NEVER
    heartbeated must still age out — anchored only on heartbeat_time
    (which stays 0.0) it would be Ready forever and never declared lost
    (the stillborn-agent bug: a provisioner-written Host whose agent
    died before its first beat)."""
    return h.status.heartbeat_time or h.metadata.creation_timestamp


def _domain(host: Host) -> str:
    """ICI-domain key: hosts sharing it share an interconnect; a host
    without one is its own domain."""
    return host.spec.topology_domain or host.metadata.name


@dataclass
class _HostState:
    host: Host
    free_chips: int
    procs: int


class GangScheduler:
    def __init__(self, store: Store, heartbeat_ttl: float = DEFAULT_HEARTBEAT_TTL):
        self.store = store
        self.heartbeat_ttl = heartbeat_ttl

    # -- host views -------------------------------------------------------

    def managed(self) -> bool:
        """True when any Host object exists — multi-host mode."""
        return bool(self.store.list(KIND_HOST))

    def ready_hosts(
        self, now: Optional[float] = None, ttl: Optional[float] = None
    ) -> List[Host]:
        """Ready, fresh-heartbeat hosts. ``ttl`` overrides the controller
        default per call (per-job run_policy.heartbeat_ttl_seconds).
        DRAINING hosts are never ready: a preemption notice means stop
        placing here — members already bound get gracefully restarted."""
        now = time.time() if now is None else now
        ttl = self.heartbeat_ttl if ttl is None else ttl
        out = []
        for h in self.store.list(KIND_HOST):
            if h.status.phase is not HostPhase.READY:
                continue
            anchor = _liveness_anchor(h)
            if anchor and (now - anchor > ttl):
                continue
            out.append(h)
        return out

    def lost_hosts(
        self, now: Optional[float] = None, ttl: Optional[float] = None
    ) -> List[Host]:
        """Hosts whose agent stopped heartbeating — or never started
        (stillborn registration ages out against its creation time)."""
        now = time.time() if now is None else now
        ttl = self.heartbeat_ttl if ttl is None else ttl
        return [
            h
            for h in self.store.list(KIND_HOST)
            if (anchor := _liveness_anchor(h)) and now - anchor > ttl
        ]

    def draining_hosts(
        self, now: Optional[float] = None, ttl: Optional[float] = None
    ) -> List[Host]:
        """Hosts under a preemption notice (DRAINING) whose agent is still
        heartbeating. A draining host that stops heartbeating has been
        reclaimed — it appears in lost_hosts instead, and the harsher
        NodeLost path (declare + fence) takes over."""
        now = time.time() if now is None else now
        ttl = self.heartbeat_ttl if ttl is None else ttl
        return [
            h
            for h in self.store.list(KIND_HOST)
            if h.status.phase is HostPhase.DRAINING
            and not (
                (anchor := _liveness_anchor(h)) and now - anchor > ttl
            )
        ]

    def _states(
        self,
        job_slice: str,
        now: Optional[float] = None,
        ttl: Optional[float] = None,
    ) -> List[_HostState]:
        fam = _family(job_slice)
        # Chips already promised to live processes, by node. The store's
        # incrementally-maintained node-usage index makes this O(hosts);
        # a store without one (RemoteStore) falls back to the scan.
        usage_fn = getattr(self.store, "node_usage", None)
        if usage_fn is not None:
            usage = usage_fn()
            used = {n: u[0] for n, u in usage.items()}
            count = {n: u[1] for n, u in usage.items()}
        else:
            used, count = {}, {}
            for p in self.store.list(KIND_PROCESS):
                node = p.spec.node_name
                if node and not p.is_finished():
                    used[node] = used.get(node, 0) + max(p.spec.chips, 0)
                    count[node] = count.get(node, 0) + 1
        states = []
        for h in self.ready_hosts(now, ttl):
            if fam and h.spec.slice_type and _family(h.spec.slice_type) != fam:
                continue
            free = h.spec.total_chips - used.get(h.metadata.name, 0)
            if h.spec.max_processes and count.get(h.metadata.name, 0) >= h.spec.max_processes:
                continue
            states.append(_HostState(h, free, count.get(h.metadata.name, 0)))
        # Stable base order; packing (place_gang) decides preference.
        states.sort(key=lambda s: s.host.metadata.name)
        return states

    def host_states(
        self,
        job_slice: str = "",
        now: Optional[float] = None,
        ttl: Optional[float] = None,
    ) -> List[_HostState]:
        """Public capacity snapshot (fleet-scheduler reservations use it
        to pick which hosts to hold for a queued gang)."""
        return self._states(job_slice, now, ttl)

    # -- placement --------------------------------------------------------

    def place_gang(
        self,
        job: TPUJob,
        procs: List[Process],
        now: Optional[float] = None,
        ranks: Optional[Dict[str, int]] = None,
        bound_slots: Optional[Dict[int, str]] = None,
        ttl: Optional[float] = None,
        reserved: Optional[Dict[str, int]] = None,
        deprioritized: Optional[set] = None,
        overflow: Optional[set] = None,
    ) -> Dict[str, Host]:
        """Atomically choose a Host for every process in ``procs``.

        Returns {process_name: Host}. Placement always uses exactly
        ``max(1, job.spec.topology.num_hosts)`` hosts — the slice shape is
        part of the job's contract, and a member's host SLOT is its gang
        rank modulo num_hosts (mirroring how TPU runtime ranks map onto
        hosts) so a partially-recreated member keeps the same topology
        position it had. ``ranks`` maps process name → gang rank (members
        missing from it — evaluators — pack anywhere with capacity);
        ``bound_slots`` maps slot → host name for LIVE members of the gang,
        pinning those slots to their existing hosts. ``reserved`` maps
        host name → chips held for higher-precedence queued gangs (the
        fleet scheduler's anti-starvation reservations): those chips are
        invisible to this placement, except on hosts already pinned by
        live members. Raises SchedulingError when the gang cannot be
        fully placed — the caller must create nothing in that case.

        Packing policy (replaces the original most-free-first spread):
        open slots go to the fewest ICI domains — domains already holding
        pinned members first, then the tightest single domain that fits
        the whole remainder (best-fit at domain granularity), then
        greedily by descending fit count; within a domain hosts are
        best-fit (least free chips that still fit). Every tie breaks on
        name, so placement is deterministic under equal scores. Best-fit
        leaves the emptiest hosts intact for large gangs; small jobs land
        in fragmentation holes instead of carving up fresh hosts.

        ``deprioritized`` names hosts the straggler detector has flagged
        (obs/telemetry.py): new gangs avoid them whenever the remaining
        fleet can hold the gang, but they stay SCHEDULABLE — a flagged
        host is slow, not broken, and refusing it outright would turn a
        soft signal into artificial capacity loss.

        ``overflow`` names processes allowed OUTSIDE the slice shape
        (r19 over-spec elastic members riding on loaned idle chips):
        like rankless members they try the slot hosts first, but when no
        slot host has room they may take any other schedulable host with
        capacity instead of failing the whole gang.
        """
        want_hosts = max(1, job.spec.topology.num_hosts)
        states = self._states(job.spec.topology.slice_type, now, ttl)
        by_name = {s.host.metadata.name: s for s in states}

        # Slots pinned by live members keep their host (it must still be
        # schedulable) — reservations never apply to them, the members
        # are already physically there.
        slot_host: Dict[int, _HostState] = {}
        for slot, host_name in (bound_slots or {}).items():
            s = by_name.get(host_name)
            if s is None:
                raise SchedulingError(
                    f"host {host_name} (holding live gang members) is not "
                    "schedulable"
                )
            slot_host[slot % want_hosts] = s
        taken = {s.host.metadata.name for s in slot_host.values()}
        if reserved:
            for s in states:
                name = s.host.metadata.name
                if name not in taken:
                    s.free_chips -= reserved.get(name, 0)

        # Per-slot chip demand: ranked members map to slot = rank %
        # want_hosts; a candidate host must fit the heaviest open slot.
        slot_need = [0] * want_hosts
        for proc in procs:
            rank = (ranks or {}).get(proc.metadata.name)
            if rank is not None:
                slot_need[rank % want_hosts] += max(proc.spec.chips, 0)

        open_slots = [s for s in range(want_hosts) if s not in slot_host]
        if open_slots:
            candidates = [s for s in states if s.host.metadata.name not in taken]
            if len(candidates) < len(open_slots):
                raise SchedulingError(
                    f"need {want_hosts} ready host(s) with capacity for "
                    f"slice {job.spec.topology.slice_type or '(any)'}, have "
                    f"{len(states)}"
                )
            pinned_domains = {_domain(st.host) for st in slot_host.values()}
            need = max(slot_need[s] for s in open_slots)
            chosen = None
            if deprioritized:
                # Straggler avoidance: pack on the unflagged fleet first;
                # only when that cannot hold the gang do flagged hosts
                # re-enter the pool (soft preference, not a cordon).
                preferred = [
                    s for s in candidates
                    if s.host.metadata.name not in deprioritized
                ]
                if len(preferred) >= len(open_slots):
                    chosen = _pack_hosts(
                        preferred, k=len(open_slots), need=need,
                        pinned_domains=pinned_domains,
                    )
            if chosen is None:
                chosen = _pack_hosts(
                    candidates,
                    k=len(open_slots),
                    need=need,
                    pinned_domains=pinned_domains,
                )
            if chosen is not None:
                for slot, state in zip(open_slots, chosen):
                    slot_host[slot] = state
            else:
                # No host set fits every open slot's full demand. Fall back
                # to the legacy spread — most-free-first, heaviest slot
                # paired with the freest host — so the per-member capacity
                # check below reports the precise shortfall ("lacks
                # capacity") and heterogeneous slot demands still place.
                by_free = sorted(
                    candidates,
                    key=lambda s: (
                        1 if s.host.metadata.name in (deprioritized or ()) else 0,
                        -s.free_chips,
                        s.host.metadata.name,
                    ),
                )[: len(open_slots)]
                heaviest = sorted(open_slots, key=lambda s: (-slot_need[s], s))
                for slot, state in zip(heaviest, by_free):
                    slot_host[slot] = state

        placement: Dict[str, Host] = {}
        free = {s.host.metadata.name: s.free_chips for s in states}
        counts = {s.host.metadata.name: s.procs for s in states}

        def fits(state: _HostState, need: int) -> bool:
            cap = state.host.spec.max_processes
            return free[state.host.metadata.name] >= need and not (
                cap and counts[state.host.metadata.name] >= cap
            )

        for i, proc in enumerate(procs):
            need = max(proc.spec.chips, 0)
            rank = (ranks or {}).get(proc.metadata.name)
            if rank is not None:
                state = slot_host[rank % want_hosts]
                if not fits(state, need):
                    raise SchedulingError(
                        f"host {state.host.metadata.name} lacks capacity for "
                        f"{proc.metadata.name} ({free[state.host.metadata.name]}"
                        f" chip(s) free)"
                    )
            else:
                # Rankless members (evaluators): first slot host with room.
                state = next(
                    (slot_host[s] for s in range(want_hosts) if fits(slot_host[s], need)),
                    None,
                )
                if state is None and overflow and \
                        proc.metadata.name in overflow:
                    # Over-spec elastic members ride outside the slice
                    # shape by design: the slot hosts are exactly full of
                    # the spec gang, so borrow any other schedulable host
                    # with capacity — most-free first so the loan lands
                    # on the emptiest chips and reclaim frees whole hosts.
                    slot_names = {
                        st.host.metadata.name for st in slot_host.values()
                    }
                    state = next(
                        (
                            st
                            for st in sorted(
                                states,
                                key=lambda st: (
                                    -free[st.host.metadata.name],
                                    st.host.metadata.name,
                                ),
                            )
                            if st.host.metadata.name not in slot_names
                            and fits(st, need)
                        ),
                        None,
                    )
                if state is None:
                    raise SchedulingError(
                        f"no host has capacity for {proc.metadata.name} "
                        f"({need} chip(s))"
                    )
            free[state.host.metadata.name] -= need
            counts[state.host.metadata.name] += 1
            placement[proc.metadata.name] = state.host
        return placement


def _pack_hosts(
    candidates: List[_HostState],
    k: int,
    need: int,
    pinned_domains: set,
) -> Optional[List[_HostState]]:
    """Choose ``k`` hosts (each with ``need`` free chips) packed onto the
    fewest ICI domains. Domain order: pinned first, then whole domains
    (>= k fitting hosts) tightest-total-free first, then partial domains
    by descending fit count; hosts within a domain are best-fit. All ties
    break on name. None when fewer than ``k`` hosts fit."""
    fit = [s for s in candidates if s.free_chips >= need]
    if len(fit) < k:
        return None
    by_domain: Dict[str, List[_HostState]] = {}
    for s in fit:
        by_domain.setdefault(_domain(s.host), []).append(s)
    for hosts in by_domain.values():
        hosts.sort(key=lambda s: (s.free_chips, s.host.metadata.name))

    def domain_rank(item):
        name, hosts = item
        whole = len(hosts) >= k
        total_free = sum(s.free_chips for s in hosts)
        return (
            0 if name in pinned_domains else 1,
            0 if whole else 1,
            # Whole domains best-fit (tightest holds the gang); partial
            # domains largest-first (fewest domains span the remainder).
            total_free if whole else -len(hosts),
            total_free,
            name,
        )

    chosen: List[_HostState] = []
    for _, hosts in sorted(by_domain.items(), key=domain_rank):
        for s in hosts:
            if len(chosen) == k:
                return chosen
            chosen.append(s)
    return chosen if len(chosen) == k else None
