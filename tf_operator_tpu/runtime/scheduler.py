"""Gang scheduler: slice-atomic placement of a job's processes onto Hosts.

Reference parity + TPU delta: the reference approximates gang scheduling
with a PodDisruptionBudget (minAvailable = Σreplicas) handed to
kube-arbitrator (pkg/trainer/training.go:450-511) — placement itself is
kube-scheduler's per-pod, non-atomic decision. On TPU the slice is the
placement atom: either every gang member lands on a Ready host of the
right slice family with chip capacity, or nothing is created at all
(SURVEY.md §7 hard part b). This module makes that decision; the
reconciler stamps the resulting node bindings before any create, so a
partially-placed gang can never exist.

Single-host mode is the degenerate case: with no Host objects registered
the scheduler reports "unmanaged" and the reconciler launches everything
through the local backend exactly as before.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from tf_operator_tpu.api.types import KIND_HOST, KIND_PROCESS, TPUJob
from tf_operator_tpu.runtime.objects import Host, HostPhase, Process
from tf_operator_tpu.runtime.store import Store

# A host whose agent has not heartbeat within this window is not Ready
# (node-lost detection; feeds gang restart through mark_node_lost).
DEFAULT_HEARTBEAT_TTL = 15.0


class SchedulingError(RuntimeError):
    """The gang cannot be placed atomically right now."""


def _family(slice_type: str) -> str:
    """'v5p-32' -> 'v5p' (generation family; capacity comes from chips)."""
    return slice_type.split("-")[0] if slice_type else ""


@dataclass
class _HostState:
    host: Host
    free_chips: int
    procs: int


class GangScheduler:
    def __init__(self, store: Store, heartbeat_ttl: float = DEFAULT_HEARTBEAT_TTL):
        self.store = store
        self.heartbeat_ttl = heartbeat_ttl

    # -- host views -------------------------------------------------------

    def managed(self) -> bool:
        """True when any Host object exists — multi-host mode."""
        return bool(self.store.list(KIND_HOST))

    def ready_hosts(self, now: Optional[float] = None) -> List[Host]:
        now = time.time() if now is None else now
        out = []
        for h in self.store.list(KIND_HOST):
            if h.status.phase is not HostPhase.READY:
                continue
            if h.status.heartbeat_time and (
                now - h.status.heartbeat_time > self.heartbeat_ttl
            ):
                continue
            out.append(h)
        return out

    def lost_hosts(self, now: Optional[float] = None) -> List[Host]:
        """Hosts whose agent stopped heartbeating (NodeLost)."""
        now = time.time() if now is None else now
        return [
            h
            for h in self.store.list(KIND_HOST)
            if h.status.heartbeat_time
            and now - h.status.heartbeat_time > self.heartbeat_ttl
        ]

    def _states(self, job_slice: str, now: Optional[float] = None) -> List[_HostState]:
        fam = _family(job_slice)
        # Chips already promised to live processes, by node.
        used: Dict[str, int] = {}
        count: Dict[str, int] = {}
        for p in self.store.list(KIND_PROCESS):
            node = p.spec.node_name
            if node and not p.is_finished():
                used[node] = used.get(node, 0) + max(p.spec.chips, 0)
                count[node] = count.get(node, 0) + 1
        states = []
        for h in self.ready_hosts(now):
            if fam and h.spec.slice_type and _family(h.spec.slice_type) != fam:
                continue
            free = h.spec.total_chips - used.get(h.metadata.name, 0)
            if h.spec.max_processes and count.get(h.metadata.name, 0) >= h.spec.max_processes:
                continue
            states.append(_HostState(h, free, count.get(h.metadata.name, 0)))
        # Stable order: most free chips first, then name (deterministic).
        states.sort(key=lambda s: (-s.free_chips, s.host.metadata.name))
        return states

    # -- placement --------------------------------------------------------

    def place_gang(
        self, job: TPUJob, procs: List[Process], now: Optional[float] = None
    ) -> Dict[str, Host]:
        """Atomically choose a Host for every process in ``procs``.

        Returns {process_name: Host}. Placement always uses exactly
        ``max(1, job.spec.topology.num_hosts)`` hosts — the slice shape is
        part of the job's contract (rendezvous ranks map onto hosts), so
        the scheduler never silently spreads a gang over more hosts than
        requested. Raises SchedulingError when the gang cannot be fully
        placed on that many hosts — the caller must create nothing then.
        """
        want_hosts = max(1, job.spec.topology.num_hosts)
        states = self._states(job.spec.topology.slice_type, now)
        if len(states) < want_hosts:
            raise SchedulingError(
                f"need {want_hosts} ready host(s) for slice "
                f"{job.spec.topology.slice_type or '(any)'}, have {len(states)}"
            )
        chosen = states[:want_hosts]
        # Round-robin members over the chosen hosts in replica order —
        # process i lands on host i % want_hosts, mirroring how TPU runtime
        # ranks map onto hosts (process_id // local_chips).
        placement: Dict[str, Host] = {}
        free = [s.free_chips for s in chosen]
        counts = [s.procs for s in chosen]
        for i, proc in enumerate(procs):
            hi = i % want_hosts
            need = max(proc.spec.chips, 0)
            if free[hi] < need:
                raise SchedulingError(
                    f"host {chosen[hi].host.metadata.name} lacks {need} free "
                    f"chip(s) for {proc.metadata.name} ({free[hi]} free)"
                )
            cap = chosen[hi].host.spec.max_processes
            if cap and counts[hi] >= cap:
                raise SchedulingError(
                    f"host {chosen[hi].host.metadata.name} at max_processes={cap}"
                )
            free[hi] -= need
            counts[hi] += 1
            placement[proc.metadata.name] = chosen[hi].host
        return placement
