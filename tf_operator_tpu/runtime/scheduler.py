"""Gang scheduler: slice-atomic placement of a job's processes onto Hosts.

Reference parity + TPU delta: the reference approximates gang scheduling
with a PodDisruptionBudget (minAvailable = Σreplicas) handed to
kube-arbitrator (pkg/trainer/training.go:450-511) — placement itself is
kube-scheduler's per-pod, non-atomic decision. On TPU the slice is the
placement atom: either every gang member lands on a Ready host of the
right slice family with chip capacity, or nothing is created at all
(SURVEY.md §7 hard part b). This module makes that decision; the
reconciler stamps the resulting node bindings before any create, so a
partially-placed gang can never exist.

Single-host mode is the degenerate case: with no Host objects registered
the scheduler reports "unmanaged" and the reconciler launches everything
through the local backend exactly as before.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from tf_operator_tpu.api.types import KIND_HOST, KIND_PROCESS, TPUJob
from tf_operator_tpu.runtime.objects import Host, HostPhase, Process
from tf_operator_tpu.runtime.store import Store

# A host whose agent has not heartbeat within this window is not Ready
# (node-lost detection; feeds gang restart through mark_node_lost).
DEFAULT_HEARTBEAT_TTL = 15.0


class SchedulingError(RuntimeError):
    """The gang cannot be placed atomically right now."""


def _family(slice_type: str) -> str:
    """'v5p-32' -> 'v5p' (generation family; capacity comes from chips)."""
    return slice_type.split("-")[0] if slice_type else ""


@dataclass
class _HostState:
    host: Host
    free_chips: int
    procs: int


class GangScheduler:
    def __init__(self, store: Store, heartbeat_ttl: float = DEFAULT_HEARTBEAT_TTL):
        self.store = store
        self.heartbeat_ttl = heartbeat_ttl

    # -- host views -------------------------------------------------------

    def managed(self) -> bool:
        """True when any Host object exists — multi-host mode."""
        return bool(self.store.list(KIND_HOST))

    def ready_hosts(
        self, now: Optional[float] = None, ttl: Optional[float] = None
    ) -> List[Host]:
        """Ready, fresh-heartbeat hosts. ``ttl`` overrides the controller
        default per call (per-job run_policy.heartbeat_ttl_seconds).
        DRAINING hosts are never ready: a preemption notice means stop
        placing here — members already bound get gracefully restarted."""
        now = time.time() if now is None else now
        ttl = self.heartbeat_ttl if ttl is None else ttl
        out = []
        for h in self.store.list(KIND_HOST):
            if h.status.phase is not HostPhase.READY:
                continue
            if h.status.heartbeat_time and (now - h.status.heartbeat_time > ttl):
                continue
            out.append(h)
        return out

    def lost_hosts(
        self, now: Optional[float] = None, ttl: Optional[float] = None
    ) -> List[Host]:
        """Hosts whose agent stopped heartbeating (NodeLost)."""
        now = time.time() if now is None else now
        ttl = self.heartbeat_ttl if ttl is None else ttl
        return [
            h
            for h in self.store.list(KIND_HOST)
            if h.status.heartbeat_time and now - h.status.heartbeat_time > ttl
        ]

    def draining_hosts(
        self, now: Optional[float] = None, ttl: Optional[float] = None
    ) -> List[Host]:
        """Hosts under a preemption notice (DRAINING) whose agent is still
        heartbeating. A draining host that stops heartbeating has been
        reclaimed — it appears in lost_hosts instead, and the harsher
        NodeLost path (declare + fence) takes over."""
        now = time.time() if now is None else now
        ttl = self.heartbeat_ttl if ttl is None else ttl
        return [
            h
            for h in self.store.list(KIND_HOST)
            if h.status.phase is HostPhase.DRAINING
            and not (
                h.status.heartbeat_time and now - h.status.heartbeat_time > ttl
            )
        ]

    def _states(
        self,
        job_slice: str,
        now: Optional[float] = None,
        ttl: Optional[float] = None,
    ) -> List[_HostState]:
        fam = _family(job_slice)
        # Chips already promised to live processes, by node.
        used: Dict[str, int] = {}
        count: Dict[str, int] = {}
        for p in self.store.list(KIND_PROCESS):
            node = p.spec.node_name
            if node and not p.is_finished():
                used[node] = used.get(node, 0) + max(p.spec.chips, 0)
                count[node] = count.get(node, 0) + 1
        states = []
        for h in self.ready_hosts(now, ttl):
            if fam and h.spec.slice_type and _family(h.spec.slice_type) != fam:
                continue
            free = h.spec.total_chips - used.get(h.metadata.name, 0)
            if h.spec.max_processes and count.get(h.metadata.name, 0) >= h.spec.max_processes:
                continue
            states.append(_HostState(h, free, count.get(h.metadata.name, 0)))
        # Stable order: most free chips first, then name (deterministic).
        states.sort(key=lambda s: (-s.free_chips, s.host.metadata.name))
        return states

    # -- placement --------------------------------------------------------

    def place_gang(
        self,
        job: TPUJob,
        procs: List[Process],
        now: Optional[float] = None,
        ranks: Optional[Dict[str, int]] = None,
        bound_slots: Optional[Dict[int, str]] = None,
        ttl: Optional[float] = None,
    ) -> Dict[str, Host]:
        """Atomically choose a Host for every process in ``procs``.

        Returns {process_name: Host}. Placement always uses exactly
        ``max(1, job.spec.topology.num_hosts)`` hosts — the slice shape is
        part of the job's contract, and a member's host SLOT is its gang
        rank modulo num_hosts (mirroring how TPU runtime ranks map onto
        hosts) so a partially-recreated member keeps the same topology
        position it had. ``ranks`` maps process name → gang rank (members
        missing from it — evaluators — pack anywhere with capacity);
        ``bound_slots`` maps slot → host name for LIVE members of the gang,
        pinning those slots to their existing hosts. Raises SchedulingError
        when the gang cannot be fully placed — the caller must create
        nothing in that case.
        """
        want_hosts = max(1, job.spec.topology.num_hosts)
        states = self._states(job.spec.topology.slice_type, now, ttl)
        by_name = {s.host.metadata.name: s for s in states}

        # Slot → host assignment. Slots pinned by live members keep their
        # host (it must still be schedulable); remaining slots take the
        # most-free Ready hosts not already holding a slot.
        slot_host: Dict[int, _HostState] = {}
        for slot, host_name in (bound_slots or {}).items():
            s = by_name.get(host_name)
            if s is None:
                raise SchedulingError(
                    f"host {host_name} (holding live gang members) is not "
                    "schedulable"
                )
            slot_host[slot % want_hosts] = s
        taken = {s.host.metadata.name for s in slot_host.values()}
        spare = [s for s in states if s.host.metadata.name not in taken]
        for slot in range(want_hosts):
            if slot not in slot_host:
                if not spare:
                    raise SchedulingError(
                        f"need {want_hosts} ready host(s) for slice "
                        f"{job.spec.topology.slice_type or '(any)'}, have "
                        f"{len(states)}"
                    )
                slot_host[slot] = spare.pop(0)

        placement: Dict[str, Host] = {}
        free = {s.host.metadata.name: s.free_chips for s in states}
        counts = {s.host.metadata.name: s.procs for s in states}

        def fits(state: _HostState, need: int) -> bool:
            cap = state.host.spec.max_processes
            return free[state.host.metadata.name] >= need and not (
                cap and counts[state.host.metadata.name] >= cap
            )

        for i, proc in enumerate(procs):
            need = max(proc.spec.chips, 0)
            rank = (ranks or {}).get(proc.metadata.name)
            if rank is not None:
                state = slot_host[rank % want_hosts]
                if not fits(state, need):
                    raise SchedulingError(
                        f"host {state.host.metadata.name} lacks capacity for "
                        f"{proc.metadata.name} ({free[state.host.metadata.name]}"
                        f" chip(s) free)"
                    )
            else:
                # Rankless members (evaluators): first slot host with room.
                state = next(
                    (slot_host[s] for s in range(want_hosts) if fits(slot_host[s], need)),
                    None,
                )
                if state is None:
                    raise SchedulingError(
                        f"no host has capacity for {proc.metadata.name} "
                        f"({need} chip(s))"
                    )
            free[state.host.metadata.name] -= need
            counts[state.host.metadata.name] += 1
            placement[proc.metadata.name] = state.host
        return placement
