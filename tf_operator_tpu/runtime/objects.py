"""Runtime objects: Process (Pod analogue), Endpoint (headless-Service
analogue), Event.

Reference parity: the operator manages exactly three kinds of child objects —
Pods, Services (headless, one per replica index, replicas.go:139-169), and
Events (pod_control.go:37-51). A Process here is one OS process driving some
number of TPU chips; an Endpoint is the stable address record other processes
use to find the rendezvous coordinator (the surviving remnant of the
reference's per-replica DNS machinery, SURVEY.md §5 "communication backend").
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tf_operator_tpu.api.types import (
    KIND_ENDPOINT,
    KIND_EVENT,
    KIND_HOST,
    KIND_LEASE,
    KIND_PROCESS,
    ObjectMeta,
)


class ProcessPhase(str, enum.Enum):
    """Pod-phase analogue (k8s PodPhase as consumed by
    controller_status.go:136-154 and replicas.go:310-363)."""

    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


@dataclass
class ProcessSpec:
    """What to run. Identity fields mirror the labels the reference stamps on
    pods (job name, replica type, replica/task index — replicas.go:121-136)."""

    job_name: str = ""
    replica_type: str = ""
    replica_index: int = 0
    entrypoint: str = ""  # "pkg.module:fn"
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    chips: int = 0  # TPU chips this process drives
    port: int = 0  # rendezvous port (meaningful on the coordinator process)
    workdir: Optional[str] = None
    # Host binding (pod.spec.nodeName analogue): set by the gang scheduler;
    # empty means "launch wherever the backend runs" (single-host mode).
    node_name: str = ""


@dataclass
class ProcessStatus:
    """Observed process state (analogue of PodStatus + the container
    termination state the reference mines for exit codes,
    replicas.go:333-341)."""

    phase: ProcessPhase = ProcessPhase.PENDING
    pid: Optional[int] = None
    exit_code: Optional[int] = None
    oom_killed: bool = False
    start_time: Optional[float] = None
    finish_time: Optional[float] = None
    message: str = ""
    # Exit code of the previous incarnation, preserved across in-place
    # restarts (LastTerminationState analogue, replicas.go:333-341).
    last_termination_exit_code: Optional[int] = None
    # True when this failure was declared, not observed: the supervising
    # agent/host vanished (NodeLost) or an agent restarted over an untracked
    # child. The process may still be ALIVE somewhere — restart handling
    # must fence it out (full gang restart + fresh rendezvous port).
    node_lost: bool = False


@dataclass
class Process:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ProcessSpec = field(default_factory=ProcessSpec)
    status: ProcessStatus = field(default_factory=ProcessStatus)
    kind: str = KIND_PROCESS

    def key(self) -> str:
        return self.metadata.key()

    def is_finished(self) -> bool:
        return self.status.phase in (ProcessPhase.SUCCEEDED, ProcessPhase.FAILED)


@dataclass
class EndpointAddress:
    host: str = "127.0.0.1"
    port: int = 0

    def __str__(self) -> str:
        return f"{self.host}:{self.port}"


@dataclass
class Endpoint:
    """Stable address record for a replica (headless-Service analogue,
    controller_service.go:91-149). On a single host this is
    127.0.0.1:port; on a real multi-host deployment the provisioner fills
    in the host's reachable address."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    address: EndpointAddress = field(default_factory=EndpointAddress)
    target_process: str = ""  # name of the Process this endpoint fronts
    kind: str = KIND_ENDPOINT

    def key(self) -> str:
        return self.metadata.key()


class HostPhase(str, enum.Enum):
    """Node-condition analogue: Ready hosts accept placements.

    DRAINING is the preemption-notice state (cloud TPU maintenance/spot
    eviction): the host is still alive and heartbeating, but the scheduler
    stops placing onto it and the reconciler gracefully gang-restarts any
    members bound to it (checkpoint-resumed, not counted against
    backoff_limit). Lifecycle: Ready → Draining → gone (NotReady or
    heartbeat-TTL NodeLost when the machine is actually reclaimed)."""

    READY = "Ready"
    NOT_READY = "NotReady"
    DRAINING = "Draining"


@dataclass
class HostSpec:
    """A TPU host that can run processes (k8s Node analogue). On TPU the
    interesting capacity is chips; slice_type scopes which jobs may land
    here (gang placement is slice-atomic, SURVEY.md §2.3 gang row)."""

    address: str = "127.0.0.1"  # reachable address for rendezvous traffic
    slice_type: str = ""  # e.g. "v5p-32"; "" accepts any job
    total_chips: int = 0
    max_processes: int = 0  # 0 = unlimited
    # ICI-domain label (e.g. the pod/superpod this host's chips share an
    # interconnect with): gangs pack onto the fewest domains. "" means the
    # host is its own domain (single-host rack, DCN-only fleet).
    topology_domain: str = ""
    # Shard-depot endpoint (rendezvous/statechannel.py): where this host
    # serves committed checkpoint shards for peer warm restore. "" means
    # the host runs no depot — restores on it fall back to disk.
    depot_url: str = ""


@dataclass
class HostStatus:
    phase: HostPhase = HostPhase.READY
    heartbeat_time: float = 0.0  # agent liveness (NodeStatus heartbeat)
    message: str = ""


@dataclass
class Host:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: HostSpec = field(default_factory=HostSpec)
    status: HostStatus = field(default_factory=HostStatus)
    kind: str = KIND_HOST

    def key(self) -> str:
        return self.metadata.key()


def declare_lost(store, process: "Process", message: str) -> Optional["Process"]:
    """Declare a process lost (FAILED, exit 137, node_lost=True): its host or
    supervising agent vanished, so the failure is INFERRED — the child may
    still be alive somewhere, and restart handling must fence it out (full
    gang restart + fresh rendezvous port). Versioned optimistic write: a
    concurrent terminal status (e.g. the real supervisor reporting SUCCEEDED)
    always wins over the inference. Returns the updated Process, or None if
    it was already finished / gone / a different incarnation."""
    meta = process.metadata

    def mutate(cur):
        if cur.metadata.uid != meta.uid or cur.is_finished():
            return False
        cur.status.phase = ProcessPhase.FAILED
        cur.status.exit_code = 137  # SIGKILL-class: retryable
        cur.status.finish_time = time.time()
        cur.status.message = message
        cur.status.node_lost = True

    return store.update_with_retry(KIND_PROCESS, meta.namespace, meta.name, mutate)


@dataclass
class Lease:
    """Leader-election lease record (coordination.k8s.io Lease analogue,
    reference: EndpointsLock in cmd/tf-operator/app/server.go:109-132).

    ``acquired``/``renewed`` are wall-clock stamps for observability ONLY —
    expiry is decided by each candidate's *local* observation clock (the
    record's resource_version must stand still for a full lease_duration of
    the observer's monotonic time before takeover), the client-go rule that
    makes the protocol immune to clock skew between machines. An empty
    ``holder`` means explicitly released (immediately acquirable)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder: str = ""
    acquired: float = 0.0
    renewed: float = 0.0
    lease_duration: float = 15.0
    kind: str = KIND_LEASE

    def key(self) -> str:
        return self.metadata.key()


class EventType(str, enum.Enum):
    NORMAL = "Normal"
    WARNING = "Warning"


@dataclass
class Event:
    """Recorded occurrence (k8s Event analogue). Events double as a test
    oracle exactly as in the reference, where the e2e driver asserts
    creation-event counts equal replica counts (py/test_runner.py:311-338)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    type: EventType = EventType.NORMAL
    reason: str = ""
    message: str = ""
    involved_kind: str = ""
    involved_name: str = ""
    involved_namespace: str = ""
    count: int = 1
    timestamp: float = 0.0
    # Onset of the FIRST occurrence: aggregation (count++) refreshes
    # ``timestamp`` but never this, so a repeated event keeps its original
    # anchor — usable as a span/timeline reference (k8s firstTimestamp).
    first_timestamp: float = 0.0
    kind: str = KIND_EVENT
