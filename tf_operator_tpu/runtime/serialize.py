"""Wire (de)serialization for every store object kind.

The seam that lets the store be served over HTTP (dashboard server's
generic object API) and consumed by a RemoteStore on another machine —
the reference's equivalent is the apiserver's JSON encoding of typed
objects plus the generated clientsets (pkg/client/**). Encode reuses the
generic dataclass walker (`api.types._to_jsonable`); decode is explicit
per kind because enums and nested dataclasses must be rebuilt typed.
"""

from __future__ import annotations

from typing import Any, Dict

from tf_operator_tpu.api.types import (
    KIND_ENDPOINT,
    KIND_EVENT,
    KIND_HOST,
    KIND_LEASE,
    KIND_POSTMORTEM,
    KIND_PRIORITY_CLASS,
    KIND_PROCESS,
    KIND_QUEUE,
    KIND_SPAN,
    KIND_TELEMETRY,
    KIND_TPUJOB,
    ObjectMeta,
    TPUJob,
    _to_jsonable,
)
from tf_operator_tpu.obs.blackbox import PostmortemArtifact
from tf_operator_tpu.obs.spans import Span
from tf_operator_tpu.obs.telemetry import Telemetry
from tf_operator_tpu.sched.objects import PriorityClass, Queue, QueueSpec
from tf_operator_tpu.runtime.objects import (
    Endpoint,
    EndpointAddress,
    Event,
    EventType,
    Host,
    HostPhase,
    HostSpec,
    HostStatus,
    Lease,
    Process,
    ProcessPhase,
    ProcessSpec,
    ProcessStatus,
)


def to_doc(obj: Any) -> Dict[str, Any]:
    """Typed store object -> JSON-ready dict (kind field included)."""
    return _to_jsonable(obj)


def _meta(doc: Dict[str, Any]) -> ObjectMeta:
    return ObjectMeta(**doc.get("metadata", {}))


def _process_from_doc(doc: Dict[str, Any]) -> Process:
    spec = ProcessSpec(**doc.get("spec", {}))
    st = dict(doc.get("status", {}))
    if "phase" in st:
        st["phase"] = ProcessPhase(st["phase"])
    return Process(metadata=_meta(doc), spec=spec, status=ProcessStatus(**st))


def _host_from_doc(doc: Dict[str, Any]) -> Host:
    st = dict(doc.get("status", {}))
    if "phase" in st:
        st["phase"] = HostPhase(st["phase"])
    return Host(
        metadata=_meta(doc),
        spec=HostSpec(**doc.get("spec", {})),
        status=HostStatus(**st),
    )


def _endpoint_from_doc(doc: Dict[str, Any]) -> Endpoint:
    return Endpoint(
        metadata=_meta(doc),
        address=EndpointAddress(**doc.get("address", {})),
        target_process=doc.get("target_process", ""),
    )


def _event_from_doc(doc: Dict[str, Any]) -> Event:
    d = {k: v for k, v in doc.items() if k not in ("metadata", "kind")}
    if "type" in d:
        d["type"] = EventType(d["type"])
    return Event(metadata=_meta(doc), **d)


def _lease_from_doc(doc: Dict[str, Any]) -> Lease:
    d = {k: v for k, v in doc.items() if k not in ("metadata", "kind")}
    return Lease(metadata=_meta(doc), **d)


def _span_from_doc(doc: Dict[str, Any]) -> Span:
    d = {k: v for k, v in doc.items() if k not in ("metadata", "kind")}
    return Span(metadata=_meta(doc), **d)


def _telemetry_from_doc(doc: Dict[str, Any]) -> Telemetry:
    d = {k: v for k, v in doc.items() if k not in ("metadata", "kind")}
    return Telemetry(metadata=_meta(doc), **d)


def _postmortem_from_doc(doc: Dict[str, Any]) -> PostmortemArtifact:
    d = {k: v for k, v in doc.items() if k not in ("metadata", "kind")}
    return PostmortemArtifact(metadata=_meta(doc), **d)


def _priority_class_from_doc(doc: Dict[str, Any]) -> PriorityClass:
    d = {k: v for k, v in doc.items() if k not in ("metadata", "kind")}
    return PriorityClass(metadata=_meta(doc), **d)


def _queue_from_doc(doc: Dict[str, Any]) -> Queue:
    return Queue(metadata=_meta(doc), spec=QueueSpec(**doc.get("spec", {})))


_DECODERS = {
    KIND_PROCESS: _process_from_doc,
    KIND_HOST: _host_from_doc,
    KIND_ENDPOINT: _endpoint_from_doc,
    KIND_EVENT: _event_from_doc,
    KIND_LEASE: _lease_from_doc,
    KIND_SPAN: _span_from_doc,
    KIND_TELEMETRY: _telemetry_from_doc,
    KIND_POSTMORTEM: _postmortem_from_doc,
    KIND_PRIORITY_CLASS: _priority_class_from_doc,
    KIND_QUEUE: _queue_from_doc,
    KIND_TPUJOB: lambda doc: TPUJob.from_dict(doc),
}


def from_doc(kind: str, doc: Dict[str, Any]) -> Any:
    """JSON dict -> typed store object. Raises KeyError on unknown kind."""
    return _DECODERS[kind](doc)


KNOWN_KINDS = tuple(_DECODERS)
