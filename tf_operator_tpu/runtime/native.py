"""ctypes binding for the native (C++) process supervisor.

The compiled half of the runtime substrate (native/supervisor.cc): spawn
with setsid + log redirection, thread-safe wait/poll with normalized exit
codes (128+signal for signal deaths — the convention the exit-code
taxonomy, reference pkg/util/train/train_util.go:18-53, is written
against), and group-kill with grace escalation. This module loads the
shared library, building it on demand with g++ (the toolchain is part of
the runtime environment; there is no separate install step, mirroring how
the reference ships its Go operator as one self-contained binary).

``NativeChild`` adapts a supervised pid to the subset of the
``subprocess.Popen`` surface the process backend drives (pid / poll /
wait / terminate / kill), so ``NativeProcessControl`` reuses the whole
monitor/status machinery of ``LocalProcessControl`` unchanged.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from typing import Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
LIB_PATH = os.path.join(NATIVE_DIR, "build", "libtpujob_supervisor.so")

# Native libraries this module can build/load. "supervisor" is the process
# runtime; "dataops" is the host input-pipeline kernels (train/data.py
# dispatches its augmentation gather there when available).
_LIBS = {
    "supervisor": (os.path.join(NATIVE_DIR, "supervisor.cc"), LIB_PATH),
    "dataops": (
        os.path.join(NATIVE_DIR, "dataops.cc"),
        os.path.join(NATIVE_DIR, "build", "libtpujob_dataops.so"),
    ),
}

_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_dataops_lib: Optional[ctypes.CDLL] = None


class NativeBuildError(RuntimeError):
    pass


def _fresh(lib_name: str = "supervisor") -> bool:
    source, lib_path = _LIBS[lib_name]
    return os.path.exists(lib_path) and (
        not os.path.exists(source)
        or os.path.getmtime(lib_path) >= os.path.getmtime(source)
    )


def ensure_built(lib_name: str = "supervisor") -> str:
    """Compile a native library if missing or older than its source.

    Safe across threads (in-process lock) AND processes (flock + compile to
    a temp name, atomically os.replace'd in): several operator candidates
    on one host may race here, and dlopen of a half-written .so crashes."""
    import fcntl

    source, lib_path = _LIBS[lib_name]
    with _build_lock:
        if _fresh(lib_name):
            return lib_path
        if not os.path.exists(source):
            raise NativeBuildError(f"native source not found: {source}")
        os.makedirs(os.path.dirname(lib_path), exist_ok=True)
        lock_fd = os.open(lib_path + ".buildlock", os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(lock_fd, fcntl.LOCK_EX)
            if _fresh(lib_name):  # another process built it while we waited
                return lib_path
            # The Makefile is the single source of truth for build flags;
            # build into a private BUILD dir and atomically replace in, so
            # a concurrent dlopen never sees a half-written .so. Direct g++
            # only as fallback when make itself is absent.
            tmp_dir = os.path.join(NATIVE_DIR, "build", f".mk.{os.getpid()}")
            tmp_lib = os.path.join(tmp_dir, os.path.basename(lib_path))
            cmds = [
                ["make", "-C", NATIVE_DIR, f"BUILD={tmp_dir}"],
                [
                    os.environ.get("CXX", "g++"),
                    "-std=c++17", "-O2", "-Wall", "-Wextra", "-fPIC", "-pthread",
                    "-shared", "-o", tmp_lib, source,
                ],
            ]
            try:
                os.makedirs(tmp_dir, exist_ok=True)
                for i, cmd in enumerate(cmds):
                    try:
                        proc = subprocess.run(
                            cmd, cwd=NATIVE_DIR, capture_output=True, text=True,
                            timeout=120,
                        )
                    except OSError as exc:
                        if i + 1 < len(cmds):  # make missing: try g++
                            continue
                        raise NativeBuildError(f"failed to run {cmd[0]}: {exc}") from exc
                    except subprocess.TimeoutExpired as exc:
                        raise NativeBuildError(f"build timed out: {exc}") from exc
                    if proc.returncode != 0:
                        raise NativeBuildError(
                            f"native build failed ({proc.returncode}):\n{proc.stderr}"
                        )
                    break
                # make builds every library into tmp_dir; install them all
                # while we hold the lock (the g++ fallback builds just one)
                for _, other_path in _LIBS.values():
                    cand = os.path.join(tmp_dir, os.path.basename(other_path))
                    if os.path.exists(cand):
                        os.replace(cand, other_path)
                if not os.path.exists(lib_path):
                    raise NativeBuildError(f"build produced no {lib_path}")
            finally:
                import shutil

                shutil.rmtree(tmp_dir, ignore_errors=True)
            return lib_path
        finally:
            os.close(lock_fd)


def load_library() -> ctypes.CDLL:
    """Load (building if needed) the supervisor library; cached."""
    global _lib
    if _lib is not None:
        return _lib
    path = ensure_built()
    lib = ctypes.CDLL(path)
    lib.tpuj_spawn.restype = ctypes.c_long
    lib.tpuj_spawn.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_char_p,
        ctypes.c_char_p,
    ]
    lib.tpuj_wait.restype = ctypes.c_int
    lib.tpuj_wait.argtypes = [ctypes.c_long]
    lib.tpuj_poll.restype = ctypes.c_int
    lib.tpuj_poll.argtypes = [ctypes.c_long, ctypes.POINTER(ctypes.c_int)]
    lib.tpuj_signal.restype = ctypes.c_int
    lib.tpuj_signal.argtypes = [ctypes.c_long, ctypes.c_int]
    lib.tpuj_terminate.restype = ctypes.c_int
    lib.tpuj_terminate.argtypes = [ctypes.c_long, ctypes.c_int]
    lib.tpuj_kill_group.restype = ctypes.c_int
    lib.tpuj_kill_group.argtypes = [ctypes.c_long, ctypes.c_int]
    lib.tpuj_forget.restype = None
    lib.tpuj_forget.argtypes = [ctypes.c_long]
    lib.tpuj_tracked_count.restype = ctypes.c_int
    lib.tpuj_tracked_count.argtypes = []
    _lib = lib
    return lib


def load_dataops() -> ctypes.CDLL:
    """Load (building if needed) the host data-ops library; cached."""
    global _dataops_lib
    if _dataops_lib is not None:
        return _dataops_lib
    path = ensure_built("dataops")
    lib = ctypes.CDLL(path)
    lib.tpuj_augment.restype = ctypes.c_int
    lib.tpuj_augment.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
    ]
    _dataops_lib = lib
    return lib


def _c_str_array(items: List[bytes]) -> ctypes.Array:
    arr = (ctypes.c_char_p * (len(items) + 1))()
    for i, s in enumerate(items):
        arr[i] = s
    arr[len(items)] = None
    return arr


class NativeChild:
    """Popen-compatible handle over one supervised pid."""

    def __init__(self, lib: ctypes.CDLL, pid: int) -> None:
        self._lib = lib
        self.pid = pid
        self.returncode: Optional[int] = None

    def _finish(self, code: int) -> int:
        if self.returncode is None:
            self.returncode = code
            # Leader reaped ⇒ its whole setsid group goes too: members it
            # forked (data loaders …) must not outlive it holding devices,
            # ports, or the log file. Then drop the registry slot (pids
            # recycle; a stale done-entry would lie about a future child).
            import signal as _signal

            self._lib.tpuj_kill_group(self.pid, _signal.SIGKILL)
            self._lib.tpuj_forget(self.pid)
        return self.returncode

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        code = ctypes.c_int()
        if self._lib.tpuj_poll(self.pid, ctypes.byref(code)) == 1:
            return self._finish(code.value)
        return None

    def wait(self, timeout: Optional[float] = None) -> int:
        if self.returncode is not None:
            return self.returncode
        if timeout is None:
            # Blocking waitpid in C; ctypes releases the GIL for the call.
            return self._finish(self._lib.tpuj_wait(self.pid))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            rc = self.poll()
            if rc is not None:
                return rc
            time.sleep(0.01)
        rc = self.poll()
        if rc is not None:
            return rc
        raise subprocess.TimeoutExpired(cmd=f"pid {self.pid}", timeout=timeout)

    def terminate(self) -> None:
        import signal as _signal

        self._lib.tpuj_signal(self.pid, _signal.SIGTERM)

    def kill(self) -> None:
        import signal as _signal

        self._lib.tpuj_signal(self.pid, _signal.SIGKILL)


class NativeSupervisor:
    """Spawn/track children through the native library."""

    def __init__(self) -> None:
        self._lib = load_library()

    def spawn(
        self,
        argv: List[str],
        env: Dict[str, str],
        workdir: Optional[str] = None,
        log_path: Optional[str] = None,
    ) -> NativeChild:
        """Launch argv; raises OSError (with the child-side errno for exec
        failures) so callers report a FAILED process, not a hung one."""
        if not argv:
            raise OSError(22, "empty argv")
        if log_path:
            # Pre-validate here: the C side can't distinguish a failed log
            # open from a failed exec in its -errno, and a log-open error
            # blamed on the executable sends debugging the wrong way.
            open(log_path, "ab").close()
        exe = argv[0]
        if os.sep not in exe:  # execve takes a path, not a $PATH lookup
            import shutil

            resolved = shutil.which(exe, path=env.get("PATH", os.environ.get("PATH")))
            if resolved is None:
                raise OSError(2, f"executable not found: {exe}")
            argv = [resolved] + list(argv[1:])
        c_argv = _c_str_array([a.encode() for a in argv])
        c_envp = _c_str_array([f"{k}={v}".encode() for k, v in env.items()])
        pid = self._lib.tpuj_spawn(
            c_argv,
            c_envp,
            workdir.encode() if workdir else None,
            log_path.encode() if log_path else None,
        )
        if pid < 0:
            err = -pid
            raise OSError(err, f"{os.strerror(err)}: {argv[0]}")
        return NativeChild(self._lib, pid)

    def terminate(self, child: NativeChild, grace_seconds: float) -> int:
        """Graceful group stop with native escalation (TERM → grace → KILL)."""
        if child.returncode is not None:
            return child.returncode
        code = self._lib.tpuj_terminate(child.pid, int(grace_seconds * 1000))
        if code < 0:
            # Never record a -errno as an exit code (it would poison the
            # registry slot for a recycled pid); let the winner's record
            # resolve through the idempotent wait path.
            return child.wait()
        return child._finish(code)

    def tracked_count(self) -> int:
        return self._lib.tpuj_tracked_count()
