"""Runtime substrate: the framework's "cluster".

Where the reference leans on the Kubernetes apiserver (objects, watches,
events) and the kubelet (starting containers, reporting exit status), this
package supplies TPU-native equivalents that work on a bare host or a slice:

- ``objects``          — Process / Endpoint / Event records (Pod / headless
                         Service / Event analogues)
- ``store``            — thread-safe object store with resource versions and
                         watch streams (apiserver analogue; the informer feeds
                         from it)
- ``persist``          — opt-in durability for the store (per-mutation WAL +
                         compacted snapshots; ``open_store(data_dir)`` recovers
                         the identical object set and resource_version after an
                         operator crash — etcd's job in the reference)
- ``process_backend``  — ``ProcessControl`` seam with a real subprocess
                         launcher and a fake that records intended actions
                         (reference: RealPodControl pod_control.go:54-165 and
                         FakePodControl, the trick that makes the whole
                         controller testable, controller_test.go:66-68)
- ``scheduler``        — gang-atomic placement of processes onto Hosts
                         (slice-atomic: replaces the reference's PDB
                         gang-scheduling hack, training.go:450-511)
- ``agent``            — per-host launcher daemon (kubelet analogue):
                         watches its node's Process bindings, launches via
                         the local/native backend, heartbeats its Host
"""

from tf_operator_tpu.runtime.objects import (  # noqa: F401
    Endpoint,
    Event,
    EventType,
    Host,
    HostPhase,
    HostSpec,
    HostStatus,
    Process,
    ProcessPhase,
    ProcessSpec,
    ProcessStatus,
)
from tf_operator_tpu.runtime.agent import HostAgent  # noqa: F401
from tf_operator_tpu.runtime.remote_store import (  # noqa: F401
    RemoteStore,
    RemoteStoreError,
)
from tf_operator_tpu.runtime.scheduler import (  # noqa: F401
    GangScheduler,
    SchedulingError,
)
from tf_operator_tpu.runtime.persist import (  # noqa: F401
    PersistenceError,
    RecoveryInfo,
    open_store,
)
from tf_operator_tpu.runtime.store import (  # noqa: F401
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    Store,
    WatchEventType,
)
from tf_operator_tpu.runtime.process_backend import (  # noqa: F401
    FakeProcessControl,
    LocalProcessControl,
    NativeProcessControl,
    ProcessControl,
)
