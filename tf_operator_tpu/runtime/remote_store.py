"""RemoteStore: the Store interface over the operator's generic object API.

The piece that takes the runtime multi-machine: a HostAgent (or any other
store consumer) on a different host points at the operator's HTTP server
and uses the same create/get/update/delete/list/watch surface as the
in-process Store — the analogue of the reference's generated clientsets
talking to the apiserver (pkg/client/**), with watches as an ndjson
stream. Raises the SAME exception types as Store (NotFoundError,
AlreadyExistsError, ConflictError), so callers cannot tell the
difference; ``update_with_retry`` therefore works unchanged.
"""

from __future__ import annotations

import json
import logging
import random
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Iterable, List, Optional

from tf_operator_tpu.runtime.serialize import from_doc, to_doc
from tf_operator_tpu.runtime.store import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    TransientStoreError,
    WatchEvent,
    WatchEventType,
    update_with_retry_loop,
)

log = logging.getLogger("tpujob.remote_store")


class RemoteStoreError(TransientStoreError):
    """Transport/server failure that is not an object-level condition.
    Subclasses TransientStoreError: shared retry loops wait it out."""


class UnauthorizedError(Exception):
    """The server rejected our credentials (401/403) — on any route:
    request or watch. Deliberately NOT an OSError/TransientStoreError:
    auth failure is permanent, and a client that retried it would run
    blind forever while /healthz stays green. Consumers escalate: the
    agent daemon goes fatal (heartbeat stops -> NodeLost) and exits
    nonzero; the informer records failure instead of claiming sync."""


class Backoff:
    """Exponential backoff with full-range jitter and a cap — reconnect
    pacing for watch streams. A flapping server (accepts then drops, or
    refuses outright) must cost the client exponentially-spaced attempts,
    not a busy-spin; jitter keeps a fleet of agents from reconnecting in
    lockstep after an operator restart. ``reset()`` is called once a
    stream proves healthy (delivered data), so a genuine one-off blip
    still reconnects fast."""

    def __init__(
        self,
        initial: float = 0.5,
        cap: float = 30.0,
        factor: float = 2.0,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.initial = initial
        self.cap = cap
        self.factor = factor
        self._rng = rng or random.Random()
        self._attempt = 0

    def next_delay(self) -> float:
        """The next sleep: jittered into [d/2, d] where d doubles per
        consecutive failure up to the cap."""
        d = min(self.cap, self.initial * self.factor ** self._attempt)
        self._attempt += 1
        return d * (0.5 + 0.5 * self._rng.random())

    def reset(self) -> None:
        self._attempt = 0


class RemoteWatch:
    """Iterable of WatchEvents from the server's ndjson stream.

    Auto-reconnects on connection loss: the server replays existing
    objects as ADDED on every (re)connect — the list+watch contract —
    and consumers (agents, informers) are already replay-tolerant.
    Reconnects are paced by :class:`Backoff` (reset once a stream
    delivers data) and counted in ``reconnects`` — the old behavior
    reconnected a dropped stream immediately in a tight loop, which
    against a flapping server was a busy-spin of TCP connects.

    Uses a raw HTTPConnection (not urllib) so ``stop()`` can
    ``shutdown()`` the socket: closing a buffered response from another
    thread deadlocks on the reader lock the blocked consumer holds."""

    def __init__(self, base: str, kinds, connect_timeout: float = 10.0,
                 token: Optional[str] = None,
                 backoff: Optional[Backoff] = None,
                 reconnect_counter: Optional[Any] = None) -> None:
        u = urllib.parse.urlsplit(base)
        self._host = u.hostname
        self._port = u.port or (443 if u.scheme == "https" else 80)
        self._tls = u.scheme == "https"
        self.kinds = tuple(kinds) if kinds else None
        self._connect_timeout = connect_timeout
        self._token = token
        self._stopped = threading.Event()
        self._sock = None
        self._lock = threading.Lock()
        self.backoff = backoff or Backoff()
        # (Re)connection attempts after the first — surfaced per watch,
        # and aggregated on the owning RemoteStore when it passed a
        # shared counter.
        self.reconnects = 0
        self._shared_counter = reconnect_counter

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            sock = self._sock
            self._sock = None
        if sock is not None:
            # shutdown (not close): unblocks a reader mid-recv without
            # touching the buffered response object the consumer thread
            # holds the lock on.
            import socket as _socket

            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass

    def _connect(self):
        import http.client

        conn_cls = http.client.HTTPSConnection if self._tls else http.client.HTTPConnection
        conn = conn_cls(self._host, self._port, timeout=self._connect_timeout)
        q = f"?kinds={','.join(self.kinds)}" if self.kinds else ""
        from tf_operator_tpu.utils.auth import bearer_headers

        conn.request("GET", "/api/v1/watch" + q, headers=bearer_headers(self._token))
        # Grab the socket BEFORE getresponse(): a close-delimited response
        # detaches conn.sock, but the socket object stays valid for
        # settimeout/shutdown (the response reads through its own dup'd
        # file wrapper).
        sock = conn.sock
        resp = conn.getresponse()
        if resp.status in (401, 403):
            conn.close()
            raise UnauthorizedError(
                f"watch HTTP {resp.status}: missing/wrong bearer token "
                "(server has auth enabled; provide TPUJOB_AUTH_TOKEN[_FILE] "
                "or --auth-token-file)"
            )
        if resp.status != 200:
            body = resp.read(200)
            conn.close()
            raise OSError(f"watch HTTP {resp.status}: {body!r}")
        # The stream is silent between events: drop the connect timeout so
        # a quiet cluster doesn't look like a dead connection.
        sock.settimeout(None)
        return sock, resp

    def _note_reconnect(self) -> None:
        self.reconnects += 1
        if self._shared_counter is not None:
            self._shared_counter.inc()

    def __iter__(self):
        import http.client

        first_attempt = True
        while not self._stopped.is_set():
            if not first_attempt:
                self._note_reconnect()
            first_attempt = False
            try:
                sock, resp = self._connect()
            except (OSError, http.client.HTTPException) as exc:
                if self._stopped.is_set():
                    return
                delay = self.backoff.next_delay()
                log.warning(
                    "watch connect failed (%s); retrying in %.1fs", exc, delay
                )
                if self._stopped.wait(delay):
                    return
                continue
            with self._lock:
                if self._stopped.is_set():
                    resp.close()
                    return
                self._sock = sock
            # Control event: a fresh replay is beginning. Consumers reset
            # their per-connection seen-set; on SYNCED they reconcile
            # (deletions during a disconnect are never replayed).
            yield WatchEvent(WatchEventType.REPLAY_START, None)
            got_data = False
            try:
                for raw in resp:
                    if self._stopped.is_set():
                        return
                    if not got_data:
                        # The stream is live (data or keep-alive arrived):
                        # this connection was real, not a flap — reconnect
                        # fast if it drops later.
                        got_data = True
                        self.backoff.reset()
                    if not raw.strip():
                        continue
                    d = json.loads(raw)
                    if d["type"] == "PING":
                        continue  # server keep-alive on an idle stream
                    etype = WatchEventType(d["type"])
                    if etype is WatchEventType.SYNCED:
                        yield WatchEvent(etype, None)
                        continue
                    yield WatchEvent(etype, from_doc(d["kind"], d["object"]))
            except (OSError, ValueError, http.client.HTTPException) as exc:
                if self._stopped.is_set():
                    return
                delay = self.backoff.next_delay()
                log.warning(
                    "watch stream dropped (%s); reconnecting in %.1fs",
                    exc, delay,
                )
                if self._stopped.wait(delay):
                    return
            else:
                # Clean EOF. After a healthy stream (data flowed) an
                # immediate reconnect is right — the server restarted.
                # An accept-then-close flap (no data ever) must still
                # pay backoff or the loop is a busy-spin of connects.
                if not self._stopped.is_set() and not got_data:
                    if self._stopped.wait(self.backoff.next_delay()):
                        return
            finally:
                with self._lock:
                    if self._sock is sock:
                        self._sock = None
                try:
                    resp.close()
                except Exception:
                    pass


class _Counter:
    """Tiny thread-safe counter shared by a RemoteStore's watches."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0

    def inc(self) -> None:
        with self._lock:
            self.value += 1


class RemoteStore:
    """Store-compatible client over HTTP (see module docstring)."""

    def __init__(self, base_url: str, timeout: float = 10.0,
                 token: Optional[str] = None) -> None:
        """``token``: bearer secret for an auth-enabled server. Defaults to
        the ambient credential (``$TPUJOB_AUTH_TOKEN`` / token file via
        utils.auth.resolve_token) so controller-launched children — e.g.
        the evaluator's status write-back — inherit access without every
        call site threading the secret."""
        from tf_operator_tpu.utils.auth import resolve_token

        self.base = base_url.rstrip("/")
        self.timeout = timeout
        self.token = token if token is not None else resolve_token()
        # Aggregated watch reconnect-attempt count across every watch
        # this store created (per-watch counts live on the RemoteWatch).
        self._watch_reconnects = _Counter()

    @property
    def watch_reconnects_total(self) -> int:
        return self._watch_reconnects.value

    # -- plumbing ---------------------------------------------------------

    def _request(self, method: str, path: str, payload: Optional[dict] = None) -> dict:
        from tf_operator_tpu.utils.auth import bearer_headers

        body = json.dumps(payload).encode() if payload is not None else None
        headers = bearer_headers(self.token)
        if body:
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(
            self.base + path,
            data=body,
            method=method,
            headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as exc:
            detail = {}
            try:
                detail = json.loads(exc.read() or b"{}")
            except ValueError:
                pass
            msg = detail.get("error", str(exc))
            if exc.code == 404:
                raise NotFoundError(msg) from None
            if exc.code == 409:
                if detail.get("code") == "already_exists":
                    raise AlreadyExistsError(msg) from None
                raise ConflictError(msg) from None
            if exc.code in (401, 403):
                # permanent, NOT transient: retry loops must not wait out a
                # missing/rotated token forever looking "momentarily
                # unreachable"
                raise UnauthorizedError(
                    f"{method} {path}: HTTP {exc.code}: missing/wrong bearer "
                    "token (provide TPUJOB_AUTH_TOKEN[_FILE] or "
                    "--auth-token-file)"
                ) from None
            raise RemoteStoreError(f"{method} {path}: HTTP {exc.code}: {msg}") from None
        except OSError as exc:
            raise RemoteStoreError(f"{method} {path}: {exc}") from None

    # -- Store surface ----------------------------------------------------

    @staticmethod
    def _obj_path(kind: str, namespace: str, name: str) -> str:
        qt = lambda s: urllib.parse.quote(s, safe="")  # noqa: E731
        return f"/api/v1/{qt(kind)}/{qt(namespace)}/{qt(name)}"

    def create(self, obj: Any) -> Any:
        doc = self._request("POST", f"/api/v1/{obj.kind}", to_doc(obj))
        return from_doc(obj.kind, doc)

    def get(self, kind: str, namespace: str, name: str) -> Any:
        return from_doc(kind, self._request("GET", self._obj_path(kind, namespace, name)))

    def update(self, obj: Any, check_version: bool = False) -> Any:
        meta = obj.metadata
        q = "?check_version=1" if check_version else ""
        doc = self._request(
            "PUT", self._obj_path(obj.kind, meta.namespace, meta.name) + q, to_doc(obj)
        )
        return from_doc(obj.kind, doc)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._request("DELETE", self._obj_path(kind, namespace, name))

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        params = []
        if namespace:
            params.append(("namespace", namespace))
        for k, v in (label_selector or {}).items():
            params.append(("label", f"{k}={v}"))  # filtered server-side
        q = "?" + urllib.parse.urlencode(params) if params else ""
        return [
            from_doc(kind, d)
            for d in self._request("GET", f"/api/v1/{kind}{q}")["items"]
        ]

    def watch(self, kinds: Optional[Iterable[str]] = None) -> RemoteWatch:
        # Connect phase uses self.timeout; the established stream clears
        # its socket timeout (a watch is long-lived and silent between
        # events).
        return RemoteWatch(
            self.base, kinds, connect_timeout=self.timeout, token=self.token,
            reconnect_counter=self._watch_reconnects,
        )

    def update_with_retry(self, kind: str, namespace: str, name: str, mutate: Any):
        """Same contract as Store.update_with_retry, over the wire —
        the one shared loop, which also waits out transport failures."""
        return update_with_retry_loop(self, kind, namespace, name, mutate)
