"""Warm worker pools: pre-initialized runtimes for sub-second gang spawn.

The third leg of the r11 TTFS attack (with cachesvc/ and AOT-at-
admission): even with every executable cached, a cold gang member pays
interpreter start + framework imports + jax runtime/backend init before
its first step — hundreds of ms on CPU hosts, seconds on TPU hosts
(libtpu init + mesh bring-up). The host agent therefore keeps N
**pre-warmed children** per host: forked processes that have already
paid those costs and then block on stdin waiting for an assignment.
When the backend launches a gang member whose command is the default
harness command, it hands the member a warm slot — writes the identity/
rendezvous env + args as one JSON line — instead of forking cold. The
child adopts the env, redirects its logs, and calls the ordinary
harness main; from the store's and monitor's point of view it is
indistinguishable from a cold spawn (same Popen supervision, same
phase/exit-code reporting, same spans).

Topology note: pools are per-host, and a host has one topology — its
slice. A v5e-8 host's warm runtime IS a v5e-8 runtime, so "N slots per
topology" reduces to "N slots on each host of that topology"; the
``topology`` label rides along for spans and logs.

Lifecycle/invalidation (docs/design.md §4.10): a claimed slot is
replaced asynchronously; a slot older than ``max_age_s`` is recycled at
claim time (a pre-warmed runtime pinned for hours drifts from the
host's env/driver state); ``invalidate()`` drains the pool explicitly
(the agent calls it on drain); pool shutdown kills idle children. A
warm child that dies while idle is reaped by the next claim. Claiming
is strictly best-effort — any protocol hiccup falls back to a cold
spawn, never to a launch failure.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

log = logging.getLogger("tpujob.warmpool")

# The only command shape a warm slot can serve: the in-process harness.
# Anything else (custom spec.command, debug wrappers) cold-spawns.
_HARNESS_PREFIX = [sys.executable, "-m", "tf_operator_tpu.rendezvous.harness"]

DEFAULT_MAX_AGE_S = 600.0


class _Slot:
    def __init__(self, child: subprocess.Popen, born: float) -> None:
        self.child = child
        self.born = born
        self.warm = threading.Event()  # set once the child printed WARM


class WarmPool:
    def __init__(
        self,
        size: int,
        topology: str = "",
        import_jax: bool = False,
        max_age_s: float = DEFAULT_MAX_AGE_S,
    ) -> None:
        self.size = max(0, int(size))
        self.topology = topology
        self.import_jax = import_jax
        self.max_age_s = float(max_age_s)
        self._lock = threading.Lock()
        self._idle: List[_Slot] = []
        self._stopping = False
        self.claimed = 0  # telemetry: warm handoffs served
        for _ in range(self.size):
            self._add_slot()

    # -- pool maintenance --------------------------------------------------

    def _add_slot(self) -> None:
        cmd = [sys.executable, "-m", "tf_operator_tpu.runtime.warmpool", "--child"]
        if self.import_jax:
            cmd.append("--import-jax")
        try:
            child = subprocess.Popen(
                cmd,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,  # the WARM handshake
                stderr=None,  # inherited: pre-assignment noise goes to the agent
                start_new_session=True,
            )
        except OSError as exc:
            log.warning("warm pool could not pre-spawn a child: %s", exc)
            return
        slot = _Slot(child, time.time())

        def _handshake():
            # The child prints WARM once its imports/runtime init are
            # done; until then the slot exists but is not claimable.
            line = child.stdout.readline()
            if line.strip() == b"WARM":
                slot.warm.set()
            child.stdout.close()

        threading.Thread(target=_handshake, daemon=True,
                         name=f"warmpool-handshake-{child.pid}").start()
        with self._lock:
            if self._stopping:
                self._kill(slot)
                return
            self._idle.append(slot)

    def _kill(self, slot: _Slot) -> None:
        try:
            if slot.child.poll() is None:
                slot.child.kill()
            slot.child.wait()
        except OSError:
            pass
        try:
            slot.child.stdin.close()
        except OSError:
            pass

    def _refill_async(self) -> None:
        threading.Thread(target=self._add_slot, daemon=True,
                         name="warmpool-refill").start()

    # -- the handoff -------------------------------------------------------

    def serves(self, command: List[str]) -> bool:
        return command[: len(_HARNESS_PREFIX)] == _HARNESS_PREFIX

    def warm_idle(self) -> int:
        """Idle slots that are warm and alive right now (the
        ``tpujob_warmpool_warm_idle`` gauge)."""
        with self._lock:
            return sum(
                1 for s in self._idle
                if s.warm.is_set() and s.child.poll() is None
            )

    def ready(self, timeout: float = 10.0) -> bool:
        """Wait until at least one slot is warm (bench/tests sync point)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if any(s.warm.is_set() and s.child.poll() is None
                       for s in self._idle):
                    return True
            time.sleep(0.02)
        return False

    def claim(
        self,
        command: List[str],
        env: Dict[str, str],
        log_path: Optional[str],
        cwd: Optional[str] = None,
    ) -> Optional[subprocess.Popen]:
        """Hand a warm slot the assignment; returns its Popen (now running
        the harness under the given identity), or None when no slot
        matches and the caller must cold-spawn. Never raises."""
        if not self.serves(command):
            return None
        while True:
            with self._lock:
                if self._stopping or not self._idle:
                    return None
                slot = self._idle.pop(0)
            if slot.child.poll() is not None:
                continue  # died while idle; reap and try the next
            if time.time() - slot.born > self.max_age_s:
                # Age invalidation: a runtime warmed long ago may predate
                # env/driver changes on this host — recycle it.
                self._kill(slot)
                self._refill_async()
                continue
            if not slot.warm.wait(timeout=0.5):
                # Still importing: a cold spawn beats waiting on it. Put
                # it back for the next launch.
                with self._lock:
                    self._idle.append(slot)
                return None
            assignment = {
                "args": command[len(_HARNESS_PREFIX):],
                "env": env,
                "log_path": log_path,
                "cwd": cwd,
            }
            try:
                slot.child.stdin.write(json.dumps(assignment).encode() + b"\n")
                slot.child.stdin.flush()
                slot.child.stdin.close()
            except (OSError, ValueError):
                self._kill(slot)
                self._refill_async()
                continue
            self.claimed += 1
            self._refill_async()
            return slot.child

    def resize(self, target: int) -> bool:
        """Retarget the pool size (the autopilot's warm-pool actuator,
        applied by the host agent's heartbeat loop). Growing pre-spawns
        the shortfall asynchronously; shrinking kills surplus *idle*
        slots only — claimed children are jobs and are never touched.
        Returns True when the target changed."""
        target = max(0, int(target))
        with self._lock:
            if self._stopping or target == self.size:
                return False
            old, self.size = self.size, target
            surplus: List[_Slot] = []
            while len(self._idle) > target:
                surplus.append(self._idle.pop())
        for slot in surplus:
            self._kill(slot)
        # _refill_async keeps replacing claimed slots; top up the idle
        # set toward the new target here (best-effort, like __init__).
        for _ in range(max(0, target - old)):
            self._refill_async()
        log.info("warm pool resized: %d -> %d slots", old, target)
        return True

    def invalidate(self) -> None:
        """Drain every idle slot (agent drain / env change): claimed
        children are untouched — they are jobs now."""
        with self._lock:
            idle, self._idle = self._idle, []
        for slot in idle:
            self._kill(slot)

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            idle, self._idle = self._idle, []
        for slot in idle:
            self._kill(slot)


# -- the pre-warmed child ---------------------------------------------------


def _child_main(import_jax: bool) -> int:
    # Pay the cold-start costs NOW, while no job is waiting: interpreter
    # start already happened; import the harness chain (context, store
    # client, span machinery) plus the modules every workload touches on
    # its way to the first step — the compile cache (whose package init
    # pulls the full train/ stack: the single biggest import in the
    # tree) and the span/store client used by mark_first_step. Without
    # these the child is only *lukewarm*: it would pay the heavy imports
    # after the assignment, on the job's critical path.
    import tf_operator_tpu.rendezvous.harness  # noqa: F401  (the point is the import)
    import tf_operator_tpu.obs.spans  # noqa: F401
    import tf_operator_tpu.runtime.remote_store  # noqa: F401
    import tf_operator_tpu.train.compile_cache  # noqa: F401

    if import_jax:
        try:
            import jax

            jax.devices()  # force backend/runtime init, the expensive part
        except Exception:  # noqa: BLE001 — pre-warm must never kill the slot
            log.warning("warm child: jax runtime pre-init failed", exc_info=True)
    sys.stdout.write("WARM\n")
    sys.stdout.flush()
    line = sys.stdin.readline()
    if not line:
        return 0  # pool shutdown: stdin closed without an assignment
    try:
        assignment = json.loads(line)
    except ValueError:
        return 2
    env = assignment.get("env") or {}
    os.environ.clear()
    os.environ.update(env)
    from tf_operator_tpu.rendezvous.env import ENV_WARM_SLOT

    os.environ[ENV_WARM_SLOT] = "1"
    log_path = assignment.get("log_path")
    if log_path:
        # Adopt the cold spawn's log contract: combined stdout+stderr
        # into the per-process log file the dashboard serves.
        fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    else:
        fd = 2  # no log dir: fold stdout into the inherited stderr
    os.dup2(fd, 1)
    os.dup2(fd, 2)
    if fd > 2:
        os.close(fd)
    cwd = assignment.get("cwd")
    if cwd:
        try:
            os.chdir(cwd)
        except OSError:
            return 127
    from tf_operator_tpu.rendezvous import harness

    return harness.main(assignment.get("args") or None)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if "--child" in args:
        return _child_main(import_jax="--import-jax" in args)
    print("usage: python -m tf_operator_tpu.runtime.warmpool --child "
          "[--import-jax]", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
