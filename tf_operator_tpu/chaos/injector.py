"""ChaosInjector: applies a FaultSchedule to a live cluster.

Wraps the three seams the runtime exposes:

- **Store** — :class:`ChaosStore` is a Store-compatible wrapper sharing
  one knob block per injector: per-op latency windows, TransientStoreError
  budgets (an operator restart blip), and heartbeat blackholes (a host's
  Host-object heartbeat writes are silently swallowed so the controller's
  TTL detection fires while the host process keeps running — the
  split-brain NodeLost scenario).
- **Agents** — preemption notices are delivered through
  ``HostAgent.notify_preemption()`` (Host → DRAINING, the graceful drain
  path), falling back to a direct Host-phase write when the injector only
  has the store (remote agents).
- **Process backend** — crashes go through
  ``LocalProcessControl.signal_local`` when an agent supervises the
  victim (the monitor thread reports the exit like a real crash), then
  ``os.kill`` by pid, then a direct store status write for store-only
  rigs (unit tests over FakeProcessControl).
- **Operator** — OPERATOR_CRASH kills and restarts the control plane
  itself through an ``operator`` handle (``restart()``): the soak's
  restartable operator tears down its API server + controller and
  recovers a fresh incarnation from the durable store (--data-dir),
  while agents ride RemoteStore retries across the outage.

Faults fire strictly in schedule order; a fault whose conditions hold but
whose target does not exist yet (e.g. a preemption scheduled against the
post-restart gang while it is still being recreated) is retried on the
next poll tick, so the *sequence* of applied faults is deterministic.
``applied`` records every applied fault — the replay oracle soak tests
compare across runs of the same seed.
"""

from __future__ import annotations

import logging
import signal as _signal
import threading
import time
from typing import Any, Dict, List, Optional

from tf_operator_tpu.api.types import (
    KIND_HOST,
    KIND_PROCESS,
    KIND_TPUJOB,
    ReplicaType,
)
from tf_operator_tpu.chaos.faults import (
    WEDGE_MARKER,
    Fault,
    FaultKind,
    FaultSchedule,
)
from tf_operator_tpu.runtime.objects import HostPhase, ProcessPhase
from tf_operator_tpu.runtime.store import (
    NotFoundError,
    TransientStoreError,
    update_with_retry_loop,
)
from tf_operator_tpu.train.checkpoint import latest_checkpoint_step

log = logging.getLogger("tpujob.chaos")


class _Knobs:
    """Shared mutable chaos state across every ChaosStore of one injector."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.latency_s = 0.0
        self.latency_until = 0.0  # monotonic deadline
        self.error_budget = 0
        self.blocked_hosts: Dict[str, float] = {}  # host -> monotonic deadline

    def heartbeat_blocked(self, host: str) -> bool:
        with self.lock:
            dl = self.blocked_hosts.get(host)
            if dl is None:
                return False
            if time.monotonic() >= dl:
                del self.blocked_hosts[host]
                return False
            return True


class ChaosStore:
    """Store-compatible wrapper applying an injector's knobs to every op.

    Latency and error injection cover the CRUD surface; watches are left
    untouched (they are long-lived subscriptions, not ops). Heartbeat
    blackholing intercepts ``update_with_retry`` on Host objects — the
    exact call shape of ``HostAgent._touch_heartbeat`` — and pretends
    success without writing, so the agent soldiers on while the
    controller sees a silent host. Phase writes (drain, NotReady) go
    through ``update_with_retry_loop`` against get/update and are NOT
    blackholed: a draining host must still be able to say so."""

    def __init__(self, inner: Any, knobs: _Knobs) -> None:
        self._inner = inner
        self._knobs = knobs

    # -- chaos ------------------------------------------------------------

    def _perturb(self) -> None:
        with self._knobs.lock:
            if self._knobs.error_budget > 0:
                self._knobs.error_budget -= 1
                raise TransientStoreError("chaos: injected store error")
            lat = (
                self._knobs.latency_s
                if time.monotonic() < self._knobs.latency_until
                else 0.0
            )
        if lat > 0:
            time.sleep(lat)

    # -- Store surface ----------------------------------------------------

    def create(self, obj):
        self._perturb()
        return self._inner.create(obj)

    def get(self, kind, namespace, name):
        self._perturb()
        return self._inner.get(kind, namespace, name)

    def update(self, obj, check_version: bool = False):
        self._perturb()
        return self._inner.update(obj, check_version=check_version)

    def delete(self, kind, namespace, name):
        self._perturb()
        return self._inner.delete(kind, namespace, name)

    def list(self, kind, namespace=None, label_selector=None):
        self._perturb()
        return self._inner.list(kind, namespace=namespace, label_selector=label_selector)

    def watch(self, kinds=None, **kw):
        # Pass mark_replay/maxsize through: the informer's replay-aware
        # loop needs the inner store's replay framing under chaos too.
        return self._inner.watch(kinds=kinds, **kw)

    def update_with_retry(self, kind, namespace, name, mutate):
        if kind == KIND_HOST and self._knobs.heartbeat_blocked(name):
            # Swallow the write, pretend success: returning None here
            # would read as "host deleted" and make the agent re-register
            # (which would refresh the heartbeat and defeat the stall).
            try:
                return self._inner.get(kind, namespace, name)
            except NotFoundError:
                return None
        return update_with_retry_loop(self, kind, namespace, name, mutate)

    def __getattr__(self, name):  # uncommon surface (e.g. _remove_watch)
        return getattr(self._inner, name)


class ChaosInjector:
    """Drives a FaultSchedule against a store + agents cluster."""

    def __init__(
        self,
        schedule: FaultSchedule,
        store: Any,
        job_name: Optional[str] = None,
        namespace: str = "default",
        agents: Optional[Dict[str, Any]] = None,
        checkpoint_dir: Optional[str] = None,
        poll_interval: float = 0.1,
        operator: Optional[Any] = None,
    ) -> None:
        """``operator``: handle with a ``restart()`` method (kill + recover
        the control plane) — required only when the schedule contains an
        OPERATOR_CRASH fault. The injector's own ``store`` should be a
        RemoteStore in that rig so its trigger reads survive the outage."""
        self.schedule = schedule
        self.store = store
        self.job_name = job_name
        self.namespace = namespace
        self.agents: Dict[str, Any] = dict(agents or {})
        self.checkpoint_dir = checkpoint_dir
        self.poll_interval = poll_interval
        self.operator = operator
        self.knobs = _Knobs()
        # Applied faults, in order: {"kind", "target", "t_s", ...detail}.
        self.applied: List[Dict[str, Any]] = []
        # KILL_RETURN hosts waiting to come back: {"host", "resume_at"}.
        self._pending_returns: List[Dict[str, Any]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0

    # -- wiring -----------------------------------------------------------

    def wrap(self, store: Any = None) -> ChaosStore:
        """A Store-compatible view carrying this injector's knobs; hand it
        to agents and process backends."""
        return ChaosStore(store if store is not None else self.store, self.knobs)

    # -- lifecycle --------------------------------------------------------

    def arm(self) -> None:
        self._t0 = time.monotonic()
        self._thread = threading.Thread(
            target=self._loop, name="chaos-injector", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    @property
    def done(self) -> bool:
        # A KILL_RETURN fault is only half-applied until its host has
        # come back; the soak must not declare the churn finished while
        # a member is still gone.
        return (
            len(self.applied) >= len(self.schedule.faults)
            and not self._pending_returns
        )

    # -- trigger state ----------------------------------------------------

    def _elapsed(self) -> float:
        return time.monotonic() - self._t0

    def _ckpt_step(self) -> int:
        if not self.checkpoint_dir:
            return 0
        return latest_checkpoint_step(self.checkpoint_dir)

    def _restarts(self) -> int:
        if not self.job_name:
            return 0
        try:
            job = self.store.get(KIND_TPUJOB, self.namespace, self.job_name)
        except Exception:
            return 0
        return job.status.restart_count + job.status.preemption_count

    def _ready(self, fault: Fault) -> bool:
        if self._elapsed() < fault.at_s:
            return False
        if fault.at_step and self._ckpt_step() < fault.at_step:
            return False
        if fault.after_restarts and self._restarts() < fault.after_restarts:
            return False
        return True

    # -- driver -----------------------------------------------------------

    def _loop(self) -> None:
        for fault in self.schedule.faults:
            while not self._stop.is_set():
                self._tick_returns()
                try:
                    if self._ready(fault) and self._fire(fault):
                        break
                except Exception:
                    log.exception("chaos: fault %s failed; retrying", fault.kind)
                if self._stop.wait(self.poll_interval):
                    return
            if self._stop.is_set():
                return
        # All faults fired; keep ticking until every killed host is back.
        while not self._stop.is_set() and self._pending_returns:
            self._tick_returns()
            if self._stop.wait(self.poll_interval):
                return

    def _tick_returns(self) -> None:
        """Resume heartbeats on killed hosts whose return is due."""
        now = time.monotonic()
        due = [r for r in self._pending_returns if now >= r["resume_at"]]
        for rec in due:
            def ready(cur):
                cur.status.phase = HostPhase.READY
                cur.status.message = "chaos: kill-return — host back"

            try:
                self.store.update_with_retry(
                    KIND_HOST, "default", rec["host"], ready
                )
            except Exception:
                log.exception("chaos: re-ready(%s) failed", rec["host"])
            agent = self.agents.get(rec["host"])
            if agent is not None:
                try:
                    agent.resume_heartbeats()
                except Exception:
                    log.exception("chaos: resume_heartbeats(%s) failed",
                                  rec["host"])
            self._pending_returns.remove(rec)
            log.warning("chaos: host %s returned after %.1fs",
                        rec["host"], now - rec["killed_at"])

    def _record(self, fault: Fault, target: str, **detail: Any) -> None:
        rec = {"kind": fault.kind.value, "target": target,
               "t_s": round(self._elapsed(), 3), **detail}
        self.applied.append(rec)
        log.warning("chaos: applied %s", rec)

    # -- fault handlers ---------------------------------------------------

    def _live_processes(self):
        procs = [
            p
            for p in self.store.list(KIND_PROCESS, namespace=self.namespace)
            if not p.is_finished()
            and (self.job_name is None or p.spec.job_name == self.job_name)
        ]
        procs.sort(key=lambda p: p.metadata.name)
        return procs

    def _fire(self, fault: Fault) -> bool:
        """Apply one fault; False ⇒ no eligible target yet, retry."""
        if fault.kind is FaultKind.CRASH:
            return self._fire_crash(fault)
        if fault.kind is FaultKind.PREEMPT:
            return self._fire_preempt(fault)
        if fault.kind is FaultKind.STALL_HEARTBEAT:
            return self._fire_stall(fault)
        if fault.kind is FaultKind.STORE_LATENCY:
            with self.knobs.lock:
                self.knobs.latency_s = fault.latency_s
                self.knobs.latency_until = time.monotonic() + fault.duration_s
            self._record(fault, "store", latency_s=fault.latency_s,
                         duration_s=fault.duration_s)
            return True
        if fault.kind is FaultKind.STORE_ERROR:
            with self.knobs.lock:
                self.knobs.error_budget += fault.errors
            self._record(fault, "store", errors=fault.errors)
            return True
        if fault.kind is FaultKind.OPERATOR_CRASH:
            return self._fire_operator_crash(fault)
        if fault.kind is FaultKind.KILL_RETURN:
            return self._fire_kill_return(fault)
        if fault.kind is FaultKind.HANG:
            return self._fire_hang(fault)
        raise ValueError(f"unknown fault kind {fault.kind!r}")

    def _fire_hang(self, fault: Fault) -> bool:
        """Wedge the whole gang: write the marker file the soak workload
        polls for (chaos/faults.py WEDGE_MARKER). Gated on a fully
        RUNNING gang so every rank is mid-step-loop and stops within one
        step of the marker landing — the stall the watchdog sees is then
        whole-gang, never a half-launched partial. The marker is left in
        place afterwards: only COLD (resume_step == 0) incarnations obey
        it, so the warm-resumed gang runs through."""
        if not self.checkpoint_dir:
            raise ValueError(
                "schedule contains HANG but the injector has no "
                "checkpoint_dir (the wedge marker lives there)"
            )
        running = [
            p for p in self._live_processes()
            if p.status.phase is ProcessPhase.RUNNING
        ]
        gang = self._gang_size()
        if not running or (gang and len(running) < gang):
            return False
        import os

        os.makedirs(self.checkpoint_dir, exist_ok=True)
        marker = os.path.join(self.checkpoint_dir, WEDGE_MARKER)
        with open(marker, "w") as f:
            f.write(f"chaos: wedge armed at t={self._elapsed():.3f}s\n")
        self._record(fault, marker, wall_time=time.time())
        return True

    def _fire_operator_crash(self, fault: Fault) -> bool:
        """Kill + restart the control plane over a live gang. Gated on a
        fully RUNNING gang (like preemption): crashing the operator while
        a gang recreate is in flight would test a different, racier
        scenario each run and break sequence reproducibility."""
        if self.operator is None:
            raise ValueError(
                "schedule contains OPERATOR_CRASH but the injector has no "
                "operator handle (pass operator= to ChaosInjector)"
            )
        running = [
            p for p in self._live_processes()
            if p.status.phase is ProcessPhase.RUNNING
        ]
        gang = self._gang_size()
        if not running or (gang and len(running) < gang):
            return False
        self.operator.restart()
        self._record(
            fault, "operator",
            restarts=getattr(self.operator, "restarts", None),
        )
        return True

    def _fire_crash(self, fault: Fault) -> bool:
        # Victims must be observably RUNNING: killing a Pending member
        # races its launch and the fault would be a silent no-op.
        procs = [p for p in self._live_processes()
                 if p.status.phase is ProcessPhase.RUNNING]
        if not procs:
            return False
        victim = procs[fault.target % len(procs)]
        code = fault.exit_code
        signum = code - 128 if 128 < code < 160 else _signal.SIGKILL
        ns, name = victim.metadata.namespace, victim.metadata.name
        # 1) through the supervising agent's backend (exit reported by the
        #    monitor thread, exactly like a real crash)
        agent = self.agents.get(victim.spec.node_name)
        backend = getattr(agent, "backend", None)
        if backend is not None and getattr(backend, "signal_local", None):
            if backend.signal_local(ns, name, signum):
                self._record(fault, victim.metadata.key(), exit_code=code,
                             via="backend")
                return True
        # 2) by pid (single-host rigs where the controller launched it)
        if victim.status.pid:
            import os

            try:
                os.kill(victim.status.pid, signum)
            except OSError:
                return False
            self._record(fault, victim.metadata.key(), exit_code=code, via="pid")
            return True

        # 3) store-only rigs (FakeProcessControl): declare the failure with
        #    the scheduled exit code, uid-guarded like declare_lost.
        uid = victim.metadata.uid

        def mutate(cur):
            if cur.metadata.uid != uid or cur.is_finished():
                return False
            cur.status.phase = ProcessPhase.FAILED
            cur.status.exit_code = code
            cur.status.finish_time = time.time()
            cur.status.message = "chaos: injected crash"

        if self.store.update_with_retry(KIND_PROCESS, ns, name, mutate) is None:
            return False
        self._record(fault, victim.metadata.key(), exit_code=code, via="store")
        return True

    def _chief_name(self) -> Optional[str]:
        """Deterministic chief process name (chief-present vs worker-0,
        mirroring the reconciler's _chief_role)."""
        if not self.job_name:
            return None
        try:
            job = self.store.get(KIND_TPUJOB, self.namespace, self.job_name)
        except Exception:
            return None
        rtype = (
            ReplicaType.COORDINATOR
            if ReplicaType.COORDINATOR in job.spec.replica_specs
            else ReplicaType.WORKER
        )
        return f"{self.job_name}-{rtype.value.lower()}-0"

    def _fire_kill_return(self, fault: Fault) -> bool:
        """SIGKILL a non-chief member AND silence its host, then bring the
        host back ``duration_s`` later (via _tick_returns).

        Gated on a FULLY RUNNING gang so that consecutive kill/return
        faults always see the previous cycle's re-grow completed — the
        shrink→grow sequence stays deterministic. The chief is never a
        victim: every member's rendezvous points at it, so losing it is a
        legitimate full restart, which the elastic soak forbids. The
        host's heartbeats are PAUSED, not the agent stopped: stopping the
        agent would SIGTERM its children (exit 143 ⇒ preemption class ⇒
        full restart) and tear down its shard depot, which the survivors
        need as a peer restore source."""
        if self._pending_returns:
            # A previous kill's host is still gone: firing now would race
            # the store's view of the last victim (it can read RUNNING for
            # milliseconds after the SIGKILL) and stack cycles.
            return False
        running = [
            p for p in self._live_processes()
            if p.status.phase is ProcessPhase.RUNNING
        ]
        gang = self._gang_size()
        if not running or (gang and len(running) < gang):
            return False
        chief = self._chief_name()
        victims = [p for p in running
                   if p.metadata.name != chief and p.spec.node_name]
        if not victims:
            return False
        victim = victims[fault.target % len(victims)]
        host = victim.spec.node_name
        agent = self.agents.get(host)
        # Silence the host FIRST so the reconciler never sees a fresh
        # heartbeat from a host whose member just died — the loss must
        # read as a hard host loss, not a crashed process on a live host
        # (which would be recreated in place instead of shrunk around).
        if agent is not None and getattr(agent, "pause_heartbeats", None):
            agent.pause_heartbeats()
        code = fault.exit_code
        signum = code - 128 if 128 < code < 160 else _signal.SIGKILL
        ns, name = victim.metadata.namespace, victim.metadata.name
        killed = False
        backend = getattr(agent, "backend", None)
        if backend is not None and getattr(backend, "signal_local", None):
            killed = bool(backend.signal_local(ns, name, signum))
        if not killed and victim.status.pid:
            import os

            try:
                os.kill(victim.status.pid, signum)
                killed = True
            except OSError:
                killed = False
        if not killed:
            # Store-only rigs: declare the failure, uid-guarded.
            uid = victim.metadata.uid

            def mutate(cur):
                if cur.metadata.uid != uid or cur.is_finished():
                    return False
                cur.status.phase = ProcessPhase.FAILED
                cur.status.exit_code = code
                cur.status.finish_time = time.time()
                cur.status.message = "chaos: injected kill-return"

            killed = (
                self.store.update_with_retry(KIND_PROCESS, ns, name, mutate)
                is not None
            )
        if not killed:
            if agent is not None and getattr(agent, "resume_heartbeats", None):
                agent.resume_heartbeats()
            return False
        # Close the within-TTL window: a paused host still carries a fresh
        # heartbeat for up to heartbeat_ttl, during which the re-grow
        # could place straight back onto the "gone" host (its agent is
        # alive, only silenced). NOT_READY is the cloud provider's
        # instant instance-terminated signal; _tick_returns flips it back.
        def not_ready(cur):
            cur.status.phase = HostPhase.NOT_READY
            cur.status.message = "chaos: kill-return — host gone"

        self.store.update_with_retry(KIND_HOST, "default", host, not_ready)
        now = time.monotonic()
        self._pending_returns.append(
            {"host": host, "resume_at": now + fault.duration_s,
             "killed_at": now}
        )
        self._record(fault, victim.metadata.key(), exit_code=code,
                     host=host, return_after_s=round(fault.duration_s, 3))
        return True

    def _candidate_hosts(self) -> List[str]:
        """Hosts currently holding live processes of the target job,
        sorted; the deterministic preemption/stall target pool."""
        nodes = sorted({
            p.spec.node_name for p in self._live_processes() if p.spec.node_name
        })
        return nodes

    def _gang_size(self) -> int:
        """Coordinator + worker replicas of the target job (0 if unknown)."""
        if not self.job_name:
            return 0
        try:
            job = self.store.get(KIND_TPUJOB, self.namespace, self.job_name)
        except Exception:
            return 0
        n = 0
        for rtype, rs in job.spec.replica_specs.items():
            if rtype in (ReplicaType.COORDINATOR, ReplicaType.WORKER):
                n += rs.replicas or 1
        return n

    def _fire_preempt(self, fault: Fault) -> bool:
        # Deliver the notice only against a FULLY RUNNING gang: preempting
        # a host while the previous restart's recreation is still in
        # flight can drain a host that ends up holding nothing — the
        # notice lands but no graceful restart is exercised, and the
        # sequence stops being reproducible.
        running = [
            p for p in self._live_processes()
            if p.status.phase is ProcessPhase.RUNNING and p.spec.node_name
        ]
        gang = self._gang_size()
        if not running or (gang and len(running) < gang):
            return False
        nodes = sorted({p.spec.node_name for p in running})
        host = nodes[fault.target % len(nodes)]
        agent = self.agents.get(host)
        if agent is not None:
            agent.notify_preemption("chaos: injected preemption notice")
        else:
            def mutate(cur):
                cur.status.phase = HostPhase.DRAINING
                cur.status.message = "chaos: injected preemption notice"

            if self.store.update_with_retry(KIND_HOST, "default", host, mutate) is None:
                return False
        self._record(fault, host)
        return True

    def _fire_stall(self, fault: Fault) -> bool:
        nodes = self._candidate_hosts()
        if not nodes:
            return False
        host = nodes[fault.target % len(nodes)]
        with self.knobs.lock:
            self.knobs.blocked_hosts[host] = time.monotonic() + fault.duration_s
        self._record(fault, host, duration_s=fault.duration_s)
        return True
