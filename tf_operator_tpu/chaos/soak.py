"""Seeded chaos soak: a real multi-host local job under a fault schedule.

Stands up the full managed-mode stack in one process — Store, controller,
N HostAgents launching real OS processes over loopback gloo — submits a
checkpointing LM training job, arms a :class:`ChaosInjector`, and watches
the recovery invariants the whole subsystem exists to guarantee:

1. **Completion** — the job reaches Succeeded despite every scheduled
   fault.
2. **Gang atomicity** — no *persistent* partial gang: at no point does a
   strict, nonempty subset of the gang exist for longer than the grace
   window (transient partials during sequential create/delete are
   physics; a partial gang that sticks is the bug the atomic scheduler
   forecloses).
3. **Warm restarts** — every post-fault incarnation carries a
   ``TPUJOB_RESUME_STEP`` > 0 (it resumes, not retrains), and the declared
   resume steps never decrease across incarnations.
4. **Backoff exemption** — preemption restarts increment
   ``preemption_count``, never ``restart_count``, so they cannot exhaust
   ``backoff_limit``.
5. **Reproducibility** — the applied fault sequence matches the schedule,
   and the schedule is a pure function of the seed.
6. **Bounded recovery downtime, from the trace** — every preemption
   restart span in the job's timeline (obs/: opened when the controller
   tears the gang down, closed when the recreated gang reports RUNNING)
   is closed, and its width — the measured gang downtime — stays under
   ``downtime_bound_s``. Previously recovery latency could only be
   inferred indirectly; now it is read off the same trace ``tpujob
   trace`` exports.

Runnable standalone (the CI ``chaos-soak`` stage)::

    python -m tf_operator_tpu.chaos.soak --seed 7 --steps 8

Exits nonzero when any invariant is violated.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tf_operator_tpu.api.types import (
    KIND_PROCESS,
    ConditionType,
    ObjectMeta,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    TopologySpec,
    TPUJob,
    TPUJobSpec,
)
from tf_operator_tpu.chaos.faults import FaultSchedule
from tf_operator_tpu.chaos.injector import ChaosInjector
from tf_operator_tpu.controller import TPUJobController
from tf_operator_tpu.controller.status import has_condition, is_finished
from tf_operator_tpu.obs.export import derive_timings
from tf_operator_tpu.obs.spans import job_trace
from tf_operator_tpu.rendezvous.env import ENV_RESUME_STEP
from tf_operator_tpu.runtime import (
    FakeProcessControl,
    HostAgent,
    LocalProcessControl,
    Store,
)
from tf_operator_tpu.runtime.store import WatchEventType

log = logging.getLogger("tpujob.soak")

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Data-plane env for launched gang members: CPU jax with loopback gloo
# collectives, ambient TPU plugin hooks disabled (mirrors the e2e tests).
DATAPLANE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "",
}


def default_schedule(seed: int) -> FaultSchedule:
    """The acceptance recipe: one mid-run crash (after the first
    checkpoint exists, so recovery is warm) then one preemption notice
    delivered to the post-restart gang. Pure function of the seed."""
    return FaultSchedule.generate(
        seed, crashes=1, preemptions=1, first_step=2, spread_s=0.0
    )


@dataclass
class SoakResult:
    succeeded: bool = False
    restart_count: int = 0
    preemption_count: int = 0
    last_restart_cause: str = ""
    conditions: List[tuple] = field(default_factory=list)
    # Controller-declared resume steps, one per created gang process, in
    # creation (watch ADDED) order.
    resume_steps: List[int] = field(default_factory=list)
    partial_gang_violations: List[str] = field(default_factory=list)
    applied: List[dict] = field(default_factory=list)
    schedule: Optional[FaultSchedule] = None
    # Trace-derived restart windows (obs.export.derive_timings "restarts"
    # rows: cause / start / end / downtime_s) and the bound invariant 6
    # checks them against.
    restart_windows: List[dict] = field(default_factory=list)
    downtime_bound_s: float = 60.0

    def check(self) -> List[str]:
        """Invariant failures, empty when the soak passed."""
        errs = []
        if not self.succeeded:
            errs.append(f"job did not succeed: {self.conditions}")
        if self.partial_gang_violations:
            errs.append(f"partial gang persisted: {self.partial_gang_violations}")
        if self.resume_steps != sorted(self.resume_steps):
            errs.append(f"resume steps not monotonic: {self.resume_steps}")
        if not any(s > 0 for s in self.resume_steps):
            errs.append(
                f"no warm restart observed (resume steps {self.resume_steps})"
            )
        sched_kinds = [f.kind.value for f in (self.schedule.faults if self.schedule else ())]
        applied_kinds = [a["kind"] for a in self.applied]
        if applied_kinds != sched_kinds:
            errs.append(
                f"applied fault sequence {applied_kinds} != schedule {sched_kinds}"
            )
        if any(a["kind"] == "preempt" for a in self.applied) and (
            self.preemption_count < 1
        ):
            errs.append("preemption applied but preemption_count is 0")
        # Invariant 6: recovery downtime measured FROM THE TRACE. Every
        # preemption restart span must have closed (the gang came back
        # RUNNING) within the bound.
        preempt_windows = [
            w for w in self.restart_windows if w.get("cause") == "preemption"
        ]
        if any(a["kind"] == "preempt" for a in self.applied):
            if not preempt_windows:
                errs.append(
                    "preemption applied but the trace has no preemption "
                    f"restart span (windows: {self.restart_windows})"
                )
        for w in preempt_windows:
            if w.get("downtime_s") is None:
                errs.append(
                    f"preemption restart span never closed (gang did not "
                    f"return to RUNNING): {w}"
                )
            elif w["downtime_s"] > self.downtime_bound_s:
                errs.append(
                    f"preemption recovery downtime {w['downtime_s']:.1f}s "
                    f"exceeds bound {self.downtime_bound_s:.0f}s: {w}"
                )
        return errs


class _InvariantWatcher:
    """Watches gang-atomicity and warm-restart invariants live.

    Partial-gang detection is persistence-based: sequential store
    creates/deletes make instantaneous strict subsets unavoidable, so a
    violation is a strict nonempty subset that survives ``grace_s``
    continuously — the steady state the atomic scheduler must foreclose."""

    def __init__(self, store: Store, job_name: str, gang_names: List[str],
                 grace_s: float = 10.0) -> None:
        self.store = store
        self.job_name = job_name
        self.gang_names = set(gang_names)
        self.grace_s = grace_s
        self.violations: List[str] = []
        self.resume_steps: List[int] = []
        self._partial_since: Optional[float] = None
        self._stop = threading.Event()
        self._watch = store.watch(kinds=[KIND_PROCESS])
        self._threads = [
            threading.Thread(target=self._watch_loop, daemon=True,
                             name="soak-watch"),
            threading.Thread(target=self._poll_loop, daemon=True,
                             name="soak-invariant"),
        ]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        self._watch.stop()
        for t in self._threads:
            t.join(timeout=5)

    def _watch_loop(self) -> None:
        for ev in self._watch:
            if self._stop.is_set():
                return
            if ev.type is not WatchEventType.ADDED or ev.obj is None:
                continue
            p = ev.obj
            if p.metadata.name in self.gang_names:
                self.resume_steps.append(
                    int(p.spec.env.get(ENV_RESUME_STEP, "0") or 0)
                )

    def _poll_loop(self) -> None:
        while not self._stop.wait(0.2):
            live = {
                p.metadata.name
                for p in self.store.list(KIND_PROCESS, namespace="default")
                if p.metadata.name in self.gang_names and not p.is_finished()
            }
            if live and live != self.gang_names:
                now = time.monotonic()
                if self._partial_since is None:
                    self._partial_since = now
                elif now - self._partial_since > self.grace_s:
                    self.violations.append(
                        f"members {sorted(live)} of {sorted(self.gang_names)} "
                        f"alone for > {self.grace_s}s"
                    )
                    self._partial_since = now  # one report per episode
            else:
                self._partial_since = None


def _soak_job(
    name: str,
    workers: int,
    num_hosts: int,
    ckpt_dir: str,
    steps: int,
    checkpoint_every: int,
    backoff_limit: int,
    heartbeat_ttl: Optional[float],
    data_plane: str = "light",
    step_sleep_s: float = 1.0,
) -> TPUJob:
    """``data_plane='light'`` (default) runs workloads/soak.py — real
    checkpoint subsystem, no cross-process collectives, so the soak works
    in containers whose jax cannot do multi-process CPU SPMD (where ALL
    real-gang e2es fail). ``'lm'`` runs the full gloo-collectives LM
    trainer for environments that support it."""
    env = dict(DATAPLANE_ENV)
    env["PYTHONPATH"] = _ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
    if data_plane == "lm":
        entrypoint = "tf_operator_tpu.workloads.lm:main"
        workload = {
            "preset": "tiny",
            "steps": steps,
            "batch_size": 4,
            "seq_len": 32,
            "checkpoint_dir": ckpt_dir,
            "checkpoint_every": checkpoint_every,
            # chaos needs exact-step semantics; the device loop fires
            # callbacks per chunk (see WorkloadCheckpointer.run_loop)
            "device_loop": 1,
        }
    else:
        entrypoint = "tf_operator_tpu.workloads.soak:main"
        workload = {
            "steps": steps,
            "step_sleep_s": step_sleep_s,
            "checkpoint_dir": ckpt_dir,
            "checkpoint_every": checkpoint_every,
        }
    job = TPUJob(
        metadata=ObjectMeta(name=name),
        spec=TPUJobSpec(
            replica_specs={
                ReplicaType.WORKER: ReplicaSpec(
                    replicas=workers,
                    template=ProcessTemplate(
                        entrypoint=entrypoint,
                        env=env,
                        chips_per_process=1,
                    ),
                )
            },
            topology=TopologySpec(num_hosts=num_hosts, chips_per_host=1),
        ),
    )
    job.spec.run_policy.backoff_limit = backoff_limit
    job.spec.run_policy.heartbeat_ttl_seconds = heartbeat_ttl
    job.spec.workload = workload
    return job


def run_soak(
    seed: int = 0,
    schedule: Optional[FaultSchedule] = None,
    hosts: int = 3,
    num_hosts: int = 2,
    workers: int = 2,
    steps: int = 8,
    checkpoint_every: int = 2,
    backoff_limit: int = 2,
    timeout: float = 420.0,
    workdir: Optional[str] = None,
    heartbeat_ttl: float = 3.0,
    data_plane: str = "light",
    step_sleep_s: float = 1.0,
    downtime_bound_s: float = 60.0,
) -> SoakResult:
    """Run one seeded soak; returns the observations (see SoakResult.check).

    ``hosts`` > ``num_hosts`` leaves spare capacity so a preempted gang has
    somewhere to move — a drained host is not schedulable."""
    schedule = schedule if schedule is not None else default_schedule(seed)
    tmp = workdir or tempfile.mkdtemp(prefix="tpujob-soak-")
    ckpt_dir = os.path.join(tmp, "ckpt")
    job_name = "soak-lm"

    store = Store()
    injector = ChaosInjector(
        schedule, store, job_name=job_name, checkpoint_dir=ckpt_dir,
    )
    agents = [
        HostAgent(
            injector.wrap(),
            f"soak-h{i}",
            total_chips=workers,  # any single host could hold the full gang
            heartbeat_interval=0.25,
            backend=LocalProcessControl(
                injector.wrap(), log_dir=os.path.join(tmp, "logs")
            ),
        )
        for i in range(hosts)
    ]
    injector.agents = {a.name: a for a in agents}
    # The controller's own process control must stay idle in managed mode
    # (every gang member is host-bound); a fake makes a leak loud.
    fake = FakeProcessControl()
    ctl = TPUJobController(store, fake, resync_period=0.5)
    ctl.scheduler.heartbeat_ttl = heartbeat_ttl

    gang_names = [f"{job_name}-worker-{i}" for i in range(workers)]
    watcher = _InvariantWatcher(store, job_name, gang_names)
    result = SoakResult(schedule=schedule)
    for a in agents:
        a.start()
    ctl.run(workers=2)
    watcher.start()
    try:
        store.create(
            _soak_job(job_name, workers, num_hosts, ckpt_dir, steps,
                      checkpoint_every, backoff_limit, heartbeat_ttl,
                      data_plane=data_plane, step_sleep_s=step_sleep_s)
        )
        injector.arm()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = store.get("TPUJob", "default", job_name).status
            if is_finished(st) and injector.done:
                break
            time.sleep(0.25)
        st = store.get("TPUJob", "default", job_name).status
        result.succeeded = has_condition(st, ConditionType.SUCCEEDED)
        result.restart_count = st.restart_count
        result.preemption_count = st.preemption_count
        result.last_restart_cause = st.last_restart_cause
        result.conditions = [
            (c.type.value, c.reason, c.message) for c in st.conditions
        ]
    finally:
        injector.stop()
        watcher.stop()
        ctl.stop()
        for a in agents:
            a.stop()
        fake.clear()
    result.resume_steps = list(watcher.resume_steps)
    result.partial_gang_violations = list(watcher.violations)
    result.applied = list(injector.applied)
    # Invariant 6 input: restart windows read off the job's trace — the
    # same spans `tpujob trace` exports, not log inference.
    result.downtime_bound_s = downtime_bound_s
    result.restart_windows = derive_timings(
        job_trace(store, "default", job_name)
    ).get("restarts", [])
    if fake.created:
        result.partial_gang_violations.append(
            "controller launched through its own backend in managed mode: "
            f"{[p.metadata.name for p in fake.created]}"
        )
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tpujob-soak", description="seeded chaos soak runner"
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--hosts", type=int, default=3)
    p.add_argument("--num-hosts", type=int, default=2)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--checkpoint-every", type=int, default=2)
    p.add_argument("--backoff-limit", type=int, default=2)
    p.add_argument("--timeout", type=float, default=420.0)
    p.add_argument("--workdir", default=None)
    p.add_argument("--data-plane", choices=("light", "lm"), default="light",
                   help="'light' = real checkpoints, no cross-process "
                        "collectives (works everywhere); 'lm' = full gloo "
                        "LM trainer (needs multi-process-capable jax)")
    p.add_argument("--step-sleep", type=float, default=1.0,
                   help="light data plane: seconds per step (the fault "
                        "landing window)")
    p.add_argument("--downtime-bound", type=float, default=60.0,
                   help="max allowed preemption recovery downtime "
                        "(seconds), asserted from the trace's restart "
                        "spans (invariant 6)")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s [%(levelname)s] %(message)s",
        stream=sys.stderr,
    )
    result = run_soak(
        seed=args.seed, steps=args.steps, hosts=args.hosts,
        num_hosts=args.num_hosts, workers=args.workers,
        checkpoint_every=args.checkpoint_every,
        backoff_limit=args.backoff_limit, timeout=args.timeout,
        workdir=args.workdir, data_plane=args.data_plane,
        step_sleep_s=args.step_sleep, downtime_bound_s=args.downtime_bound,
    )
    downtimes = [
        round(w["downtime_s"], 2) if w.get("downtime_s") is not None else None
        for w in result.restart_windows
    ]
    print(
        f"soak seed={args.seed}: succeeded={result.succeeded} "
        f"restarts={result.restart_count} preemptions={result.preemption_count} "
        f"last_cause={result.last_restart_cause!r} "
        f"resume_steps={result.resume_steps} applied={result.applied} "
        f"trace_downtimes_s={downtimes}"
    )
    errors = result.check()
    for e in errors:
        print(f"INVARIANT VIOLATED: {e}", file=sys.stderr)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
