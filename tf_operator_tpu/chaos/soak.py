"""Seeded chaos soak: a real multi-host local job under a fault schedule.

Stands up the full managed-mode stack in one process — Store, controller,
N HostAgents launching real OS processes over loopback gloo — submits a
checkpointing LM training job, arms a :class:`ChaosInjector`, and watches
the recovery invariants the whole subsystem exists to guarantee:

1. **Completion** — the job reaches Succeeded despite every scheduled
   fault.
2. **Gang atomicity** — no *persistent* partial gang: at no point does a
   strict, nonempty subset of the gang exist for longer than the grace
   window (transient partials during sequential create/delete are
   physics; a partial gang that sticks is the bug the atomic scheduler
   forecloses).
3. **Warm restarts** — every post-fault incarnation carries a
   ``TPUJOB_RESUME_STEP`` > 0 (it resumes, not retrains), and the declared
   resume steps never decrease across incarnations.
4. **Backoff exemption** — preemption restarts increment
   ``preemption_count``, never ``restart_count``, so they cannot exhaust
   ``backoff_limit``.
5. **Reproducibility** — the applied fault sequence matches the schedule,
   and the schedule is a pure function of the seed.
6. **Bounded recovery downtime, from the trace** — every preemption
   restart span in the job's timeline (obs/: opened when the controller
   tears the gang down, closed when the recreated gang reports RUNNING)
   is closed, and its width — the measured gang downtime — stays under
   ``downtime_bound_s``. Previously recovery latency could only be
   inferred indirectly; now it is read off the same trace ``tpujob
   trace`` exports.
7. **Zero duplicate gang-member creates** — distinct incarnations
   (uids) per gang name never exceed 1 + restart_count +
   preemption_count: no sync — least of all a RESTARTED controller's
   first — ever re-created a child it should have re-adopted.
8. **Control-plane crash recovery** (``--operator-crash``) — the rig
   becomes the real multi-process topology: a RestartableOperator
   (durable store via runtime/persist.py + controller + HTTP API) with
   agents and the injector on RemoteStore. A scheduled OPERATOR_CRASH
   kills and recovers the whole control plane mid-run; the job must
   still satisfy every invariant above, and the outage must be VISIBLE
   as a ``controller-restart`` span in the job's trace.
9. **Peer warm restore** (``--p2p``) — agents run host-lifetime shard
   depots (rendezvous/statechannel.py); at least one post-fault
   incarnation must restore from a PEER (its restore span carries
   ``source=peer``), proving the depot survived the gang teardown and
   the controller's ``TPUJOB_RESTORE_PEERS`` hint reached the workload.
   Recovery downtime is additionally measured as EFFECTIVE downtime —
   restart-span start to the matching restore span's end — because the
   restart span closes at gang RUNNING, before the workload's restore
   (and its modeled slow-store read, ``--disk-restore-delay``) runs.

``--compare-restore`` runs the SAME seed twice — disk-only baseline,
then p2p — and asserts the p2p effective-downtime p50 cuts the disk
baseline by more than 2x (the acceptance receipt; JSON artifact under
``--workdir``).

Runnable standalone (the CI ``chaos-soak`` / ``crash-soak`` /
``ckpt-soak`` stages)::

    python -m tf_operator_tpu.chaos.soak --seed 7 --steps 8
    python -m tf_operator_tpu.chaos.soak --seed 11 --steps 8 --operator-crash
    python -m tf_operator_tpu.chaos.soak --seed 13 --steps 6 --p2p \\
        --disk-restore-delay 8 --compare-restore

Exits nonzero when any invariant is violated.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from tf_operator_tpu.api.types import (
    KIND_PROCESS,
    ConditionType,
    ObjectMeta,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    TopologySpec,
    TPUJob,
    TPUJobSpec,
)
from tf_operator_tpu.chaos.faults import FaultKind, FaultSchedule
from tf_operator_tpu.chaos.injector import ChaosInjector
from tf_operator_tpu.controller import TPUJobController
from tf_operator_tpu.controller.status import has_condition, is_finished
from tf_operator_tpu.obs.export import derive_timings
from tf_operator_tpu.obs.spans import job_trace
from tf_operator_tpu.rendezvous.env import ENV_RESUME_STEP
from tf_operator_tpu.runtime import (
    FakeProcessControl,
    HostAgent,
    LocalProcessControl,
    RemoteStore,
    Store,
)
from tf_operator_tpu.runtime.store import (
    NotFoundError,
    TransientStoreError,
    WatchEventType,
)

log = logging.getLogger("tpujob.soak")

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Data-plane env for launched gang members: CPU jax with loopback gloo
# collectives, ambient TPU plugin hooks disabled (mirrors the e2e tests).
DATAPLANE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "",
}


def default_schedule(seed: int, operator_crash: bool = False) -> FaultSchedule:
    """The acceptance recipe: one mid-run crash (after the first
    checkpoint exists, so recovery is warm) then one preemption notice
    delivered to the post-restart gang. With ``operator_crash``, the
    control plane itself is killed+recovered between the two — so the
    preemption drain is executed by the RESTARTED controller over
    re-adopted state. Pure function of the seed."""
    return FaultSchedule.generate(
        seed, crashes=1, preemptions=1,
        operator_crashes=1 if operator_crash else 0,
        first_step=2, spread_s=0.0,
    )


class RestartableOperator:
    """The OPERATOR_CRASH target: a full in-process operator — durable
    store (``runtime/persist.py`` WAL + snapshots under ``data_dir``),
    reconciling controller, and the HTTP API server agents connect to —
    that can be killed and brought back on the SAME port mid-soak.

    ``restart()`` is the crash: the API server dies first (agents'
    RemoteStore calls start failing and their watches drop), then the
    controller threads, and the store object is simply dropped — nothing
    is flushed or handed over beyond what the WAL already captured per
    mutation, which is exactly the SIGKILL contract. The new incarnation
    recovers from disk, re-runs informers, and executes the controller's
    re-adoption pass (record_recovery)."""

    def __init__(
        self,
        data_dir: str,
        heartbeat_ttl: float,
        resync_period: float = 0.5,
        snapshot_every: int = 50,
        ledger_dir: Optional[str] = None,
    ) -> None:
        self.data_dir = data_dir
        self.heartbeat_ttl = heartbeat_ttl
        self.resync_period = resync_period
        self.snapshot_every = snapshot_every
        self.ledger_dir = ledger_dir
        self.port = 0  # first start picks an ephemeral port, then pins it
        self.restarts = 0
        # One FakeProcessControl per incarnation: in managed mode every
        # gang member is host-bound, so ANY create through a controller's
        # own backend — any incarnation's — is a leak the soak reports.
        self.fakes: List[FakeProcessControl] = []
        self.store: Optional[Store] = None
        self.controller = None
        self.dashboard = None
        self.ledger = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> None:
        from tf_operator_tpu.dashboard import DashboardServer
        from tf_operator_tpu.runtime.persist import open_store

        store, info = open_store(
            self.data_dir, snapshot_every=self.snapshot_every
        )
        fake = FakeProcessControl()
        ctl = TPUJobController(store, fake, resync_period=self.resync_period)
        ctl.scheduler.heartbeat_ttl = self.heartbeat_ttl
        ledger = None
        if self.ledger_dir is not None:
            from tf_operator_tpu.obs.ledger import FleetLedger

            # Re-opened every incarnation: recovery is rollup + segment
            # replay, and attach_ledger's sweep folds any terminal the
            # dead incarnation observed but never folded.
            ledger = FleetLedger(self.ledger_dir)
            ctl.attach_ledger(ledger)
        dashboard = DashboardServer(
            store, host="127.0.0.1", port=self.port, ledger=ledger
        )
        dashboard.start()
        self.port = dashboard.port
        ctl.api_url = dashboard.url
        ctl.run(workers=2)
        if info.recovered:
            ctl.record_recovery(info)
        self.store, self.controller, self.dashboard = store, ctl, dashboard
        self.ledger = ledger
        self.fakes.append(fake)
        log.warning(
            "operator up on %s (recovered=%s objects=%d rv=%d)",
            self.url, info.recovered, info.objects, info.resource_version,
        )

    def crash(self) -> None:
        """Tear the control plane down ungracefully-in-spirit: no drain,
        no handoff — durability must come from the WAL alone."""
        self.dashboard.stop()
        self.controller.stop()
        if self.ledger is not None:
            # fold() flushes per record, so close() adds no durability —
            # it only releases the segment handle (the SIGKILL contract
            # holds either way; this just avoids two writers post-restart).
            self.ledger.close()
            self.ledger = None
        self.store = None

    def restart(self) -> None:
        self.restarts += 1
        log.warning("chaos: killing the operator (restart #%d)", self.restarts)
        self.crash()
        self.start()

    def created_through_controller(self) -> List[str]:
        """Process names any incarnation's controller launched through its
        OWN backend — must be empty in managed mode."""
        return [
            p.metadata.name for fake in self.fakes for p in fake.created
        ]


@dataclass
class SoakResult:
    succeeded: bool = False
    restart_count: int = 0
    preemption_count: int = 0
    last_restart_cause: str = ""
    conditions: List[tuple] = field(default_factory=list)
    # Controller-declared resume steps, one per created gang process
    # INCARNATION (deduped by uid — remote watch replays redeliver), in
    # first-observed (creation) order.
    resume_steps: List[int] = field(default_factory=list)
    partial_gang_violations: List[str] = field(default_factory=list)
    applied: List[dict] = field(default_factory=list)
    schedule: Optional[FaultSchedule] = None
    # Trace-derived restart windows (obs.export.derive_timings "restarts"
    # rows: cause / start / end / downtime_s) and the bound invariant 6
    # checks them against.
    restart_windows: List[dict] = field(default_factory=list)
    downtime_bound_s: float = 60.0
    # Distinct uids created per gang-member name (watch ADDED, deduped):
    # invariant 7 pins this to 1 + restart_count + preemption_count —
    # an operator restart that double-created gang members would exceed it.
    gang_incarnations: Dict[str, int] = field(default_factory=dict)
    # Control-plane crash bookkeeping (invariant 8): how many times the
    # operator was killed+recovered, and every span op in the job's trace
    # (the restart must be VISIBLE as a controller-restart span).
    operator_restarts: int = 0
    trace_ops: List[str] = field(default_factory=list)
    # Peer warm-restore bookkeeping (invariant 9): whether the rig ran
    # with shard depots, the source of every restore span in the trace
    # (chronological), and the EFFECTIVE recovery downtime per restart —
    # restart-span start to the matching restore span's end. The plain
    # restart window closes at gang RUNNING, BEFORE the workload's
    # restore (and any slow-store read) runs; effective downtime is what
    # an operator actually waits for training to resume.
    p2p: bool = False
    restore_sources: List[str] = field(default_factory=list)
    effective_downtimes_s: List[Optional[float]] = field(default_factory=list)
    # Goodput attribution (invariant 10, r13): the controller's per-cause
    # tpujob_lost_seconds_total counters, scraped before teardown.
    # goodput_scraped=False (crash mode: counters reset with the operator)
    # skips the invariant.
    goodput_scraped: bool = False
    lost_seconds: Dict[str, float] = field(default_factory=dict)
    # Goodput-autopilot receipts (r16, the A/B soak's raw material): the
    # full goodput decomposition (same function the reconciler folds at
    # terminal), the job's autopilot status mirror + cadence directive,
    # every autopilot-decision span (attrs carry the justifying
    # numbers), and per-op closed-span width sums for the cause-ledger
    # cross-check (restart/resize/hang must each equal their own spans'
    # widths, however the families interleave).
    goodput: Dict[str, Any] = field(default_factory=dict)
    autopilot_status: Dict[str, Any] = field(default_factory=dict)
    cadence_directive: Dict[str, Any] = field(default_factory=dict)
    decision_spans: List[dict] = field(default_factory=list)
    span_widths_by_op: Dict[str, float] = field(default_factory=dict)
    downtime_spans: List[dict] = field(default_factory=list)

    def check(self) -> List[str]:
        """Invariant failures, empty when the soak passed."""
        errs = []
        if not self.succeeded:
            errs.append(f"job did not succeed: {self.conditions}")
        if self.partial_gang_violations:
            errs.append(f"partial gang persisted: {self.partial_gang_violations}")
        if self.resume_steps != sorted(self.resume_steps):
            errs.append(f"resume steps not monotonic: {self.resume_steps}")
        if not any(s > 0 for s in self.resume_steps):
            errs.append(
                f"no warm restart observed (resume steps {self.resume_steps})"
            )
        sched_kinds = [f.kind.value for f in (self.schedule.faults if self.schedule else ())]
        applied_kinds = [a["kind"] for a in self.applied]
        if applied_kinds != sched_kinds:
            errs.append(
                f"applied fault sequence {applied_kinds} != schedule {sched_kinds}"
            )
        if any(a["kind"] == "preempt" for a in self.applied) and (
            self.preemption_count < 1
        ):
            errs.append("preemption applied but preemption_count is 0")
        # Invariant 6: recovery downtime measured FROM THE TRACE. Every
        # preemption restart span must have closed (the gang came back
        # RUNNING) within the bound.
        preempt_windows = [
            w for w in self.restart_windows if w.get("cause") == "preemption"
        ]
        if any(a["kind"] == "preempt" for a in self.applied):
            if not preempt_windows:
                errs.append(
                    "preemption applied but the trace has no preemption "
                    f"restart span (windows: {self.restart_windows})"
                )
        for w in preempt_windows:
            if w.get("downtime_s") is None:
                errs.append(
                    f"preemption restart span never closed (gang did not "
                    f"return to RUNNING): {w}"
                )
            elif w["downtime_s"] > self.downtime_bound_s:
                errs.append(
                    f"preemption recovery downtime {w['downtime_s']:.1f}s "
                    f"exceeds bound {self.downtime_bound_s:.0f}s: {w}"
                )
        # Invariant 7: zero duplicate gang-member creates. Every create of
        # a gang name is accounted for by exactly one fault-driven gang
        # restart (+1 for the original) — a controller that restarted and
        # re-created children it should have re-adopted shows up here.
        expected_incarnations = 1 + self.restart_count + self.preemption_count
        for name, n in sorted(self.gang_incarnations.items()):
            if n > expected_incarnations:
                errs.append(
                    f"duplicate gang-member creates: {name} created {n}x "
                    f"but only {expected_incarnations} incarnations are "
                    f"accounted for ({self.restart_count} restarts + "
                    f"{self.preemption_count} preemptions + the original)"
                )
        # Invariant 8: an operator crash actually happened when scheduled,
        # and the restart is visible in the job trace as a
        # controller-restart span (the recovery pass records one per live
        # job — obs/ is how an SRE sees the control-plane outage inline
        # with the job's own timeline).
        if any(a["kind"] == "operator-crash" for a in self.applied):
            if self.operator_restarts < 1:
                errs.append("operator-crash applied but the operator never restarted")
            if "controller-restart" not in self.trace_ops:
                errs.append(
                    "operator crashed+recovered but the job trace has no "
                    f"controller-restart span (ops: {sorted(set(self.trace_ops))})"
                )
        # Invariant 9: with shard depots armed, at least one post-fault
        # incarnation restored from a PEER — the depot outlived the gang
        # teardown and the TPUJOB_RESTORE_PEERS hint closed the loop. The
        # effective downtimes (restart start -> restore end, the number
        # that includes the workload's restore) also honor the bound —
        # the TIGHTENED check the plain RUNNING-closed window can't see.
        if self.p2p:
            if "peer" not in self.restore_sources:
                errs.append(
                    "p2p soak: no restart restored from a peer (restore "
                    f"sources: {self.restore_sources})"
                )
            for d in self.effective_downtimes_s:
                if d is not None and d > self.downtime_bound_s:
                    errs.append(
                        f"effective recovery downtime {d:.1f}s (restart -> "
                        f"restore committed) exceeds bound "
                        f"{self.downtime_bound_s:.0f}s"
                    )
        # Invariant 10 (r13): goodput attribution. Every closed restart
        # window's downtime must land under lost_seconds{cause="restart"}
        # — the counter is incremented at the same span-close point as the
        # downtime histogram, so the sums must agree — and NONE of it may
        # leak into cause="resize" (no resizes happen here; the two span
        # families must never double-count one outage).
        if self.goodput_scraped:
            expected = sum(
                w["downtime_s"] for w in self.restart_windows
                if w.get("downtime_s") is not None
            )
            got = self.lost_seconds.get("restart", 0.0)
            if expected > 0 and abs(got - expected) > max(0.5, 0.05 * expected):
                errs.append(
                    f"lost_seconds{{cause=restart}} {got:.2f}s != closed "
                    f"restart-window downtime {expected:.2f}s"
                )
            if self.lost_seconds.get("resize", 0.0) > 0:
                errs.append(
                    "restart downtime leaked into cause=resize: "
                    f"{self.lost_seconds}"
                )
        return errs


class _InvariantWatcher:
    """Watches gang-atomicity and warm-restart invariants live.

    Partial-gang detection is persistence-based: sequential store
    creates/deletes make instantaneous strict subsets unavoidable, so a
    violation is a strict nonempty subset that survives ``grace_s``
    continuously — the steady state the atomic scheduler must foreclose.

    Works against a local Store OR a RemoteStore (the operator-crash
    rig): remote watches reconnect and REPLAY existing objects, so every
    observation dedupes by uid — a replayed ADDED is the same
    incarnation, not a new create. List polls during an operator outage
    raise TransientStoreError; the poll loop skips those ticks (the
    partial-gang clock also resets: with the store dark there is no
    evidence either way)."""

    def __init__(self, store: Any, job_name: str, gang_names: List[str],
                 grace_s: float = 10.0, allowed_subset_fn=None) -> None:
        self.store = store
        self.job_name = job_name
        self.gang_names = set(gang_names)
        self.grace_s = grace_s
        # Elastic soak: a DELIBERATE shrink is a sanctioned strict subset
        # — the callback returns the set of member names the job's live
        # resize directive currently blesses (or None for "full gang
        # only"). A subset that matches neither is still a violation.
        self.allowed_subset_fn = allowed_subset_fn
        self.violations: List[str] = []
        self.resume_steps: List[int] = []
        # name -> set of uids observed for it (distinct incarnations
        # actually created; the duplicate-create oracle).
        self.created_uids: Dict[str, set] = {}
        self._seen_uids: set = set()
        self._partial_since: Optional[float] = None
        self._stop = threading.Event()
        self._watch = store.watch(kinds=[KIND_PROCESS])
        self._threads = [
            threading.Thread(target=self._watch_loop, daemon=True,
                             name="soak-watch"),
            threading.Thread(target=self._poll_loop, daemon=True,
                             name="soak-invariant"),
        ]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        self._watch.stop()
        for t in self._threads:
            t.join(timeout=5)

    def _watch_loop(self) -> None:
        for ev in self._watch:
            if self._stop.is_set():
                return
            if ev.type is not WatchEventType.ADDED or ev.obj is None:
                continue
            p = ev.obj
            if p.metadata.name not in self.gang_names:
                continue
            if p.metadata.uid in self._seen_uids:
                continue  # watch-reconnect replay of a known incarnation
            self._seen_uids.add(p.metadata.uid)
            self.created_uids.setdefault(p.metadata.name, set()).add(
                p.metadata.uid
            )
            self.resume_steps.append(
                int(p.spec.env.get(ENV_RESUME_STEP, "0") or 0)
            )

    def _poll_loop(self) -> None:
        from tf_operator_tpu.runtime.store import TransientStoreError

        while not self._stop.wait(0.2):
            try:
                live = {
                    p.metadata.name
                    for p in self.store.list(KIND_PROCESS, namespace="default")
                    if p.metadata.name in self.gang_names and not p.is_finished()
                }
            except TransientStoreError:
                self._partial_since = None  # store dark (operator outage)
                continue
            if live and live != self.gang_names and self.allowed_subset_fn:
                try:
                    allowed = self.allowed_subset_fn()
                except Exception:
                    allowed = None
                if allowed is not None and live == allowed:
                    self._partial_since = None
                    continue
            if live and live != self.gang_names:
                now = time.monotonic()
                if self._partial_since is None:
                    self._partial_since = now
                elif now - self._partial_since > self.grace_s:
                    self.violations.append(
                        f"members {sorted(live)} of {sorted(self.gang_names)} "
                        f"alone for > {self.grace_s}s"
                    )
                    self._partial_since = now  # one report per episode
            else:
                self._partial_since = None


def _soak_job(
    name: str,
    workers: int,
    num_hosts: int,
    ckpt_dir: str,
    steps: int,
    checkpoint_every: int,
    backoff_limit: int,
    heartbeat_ttl: Optional[float],
    data_plane: str = "light",
    step_sleep_s: float = 1.0,
    disk_restore_delay_s: float = 0.0,
    workload_extra: Optional[Dict[str, Any]] = None,
    autopilot: Optional[Dict[str, Any]] = None,
) -> TPUJob:
    """``data_plane='light'`` (default) runs workloads/soak.py — real
    checkpoint subsystem, no cross-process collectives, so the soak works
    in containers whose jax cannot do multi-process CPU SPMD (where ALL
    real-gang e2es fail). ``'lm'`` runs the full gloo-collectives LM
    trainer for environments that support it."""
    env = dict(DATAPLANE_ENV)
    env["PYTHONPATH"] = _ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
    if data_plane == "lm":
        entrypoint = "tf_operator_tpu.workloads.lm:main"
        workload = {
            "preset": "tiny",
            "steps": steps,
            "batch_size": 4,
            "seq_len": 32,
            "checkpoint_dir": ckpt_dir,
            "checkpoint_every": checkpoint_every,
            # chaos needs exact-step semantics; the device loop fires
            # callbacks per chunk (see WorkloadCheckpointer.run_loop)
            "device_loop": 1,
        }
    else:
        entrypoint = "tf_operator_tpu.workloads.soak:main"
        workload = {
            "steps": steps,
            "step_sleep_s": step_sleep_s,
            "checkpoint_dir": ckpt_dir,
            "checkpoint_every": checkpoint_every,
            # The chunked async npy pipeline is the one under test — it
            # is also the backend whose commit hook feeds the shard
            # depots (docs/design.md §4.9), which invariant 9 needs.
            "checkpoint_backend": "npy",
            # Models the flagship slow-store read: a resumed chief whose
            # restore source is DISK sleeps this long; the peer path
            # skips it (workloads/soak.py).
            "disk_restore_delay_s": disk_restore_delay_s,
        }
    job = TPUJob(
        metadata=ObjectMeta(name=name),
        spec=TPUJobSpec(
            replica_specs={
                ReplicaType.WORKER: ReplicaSpec(
                    replicas=workers,
                    template=ProcessTemplate(
                        entrypoint=entrypoint,
                        env=env,
                        chips_per_process=1,
                    ),
                )
            },
            topology=TopologySpec(num_hosts=num_hosts, chips_per_host=1),
        ),
    )
    if workload_extra:
        workload.update(workload_extra)
    job.spec.run_policy.backoff_limit = backoff_limit
    job.spec.run_policy.heartbeat_ttl_seconds = heartbeat_ttl
    if autopilot is not None:
        job.spec.run_policy.autopilot = dict(autopilot)
    job.spec.workload = workload
    return job


def run_soak(
    seed: int = 0,
    schedule: Optional[FaultSchedule] = None,
    hosts: int = 3,
    num_hosts: int = 2,
    workers: int = 2,
    steps: int = 8,
    checkpoint_every: int = 2,
    backoff_limit: int = 2,
    timeout: float = 420.0,
    workdir: Optional[str] = None,
    heartbeat_ttl: float = 3.0,
    data_plane: str = "light",
    step_sleep_s: float = 1.0,
    downtime_bound_s: float = 60.0,
    operator_crash: bool = False,
    p2p_restore: bool = False,
    disk_restore_delay_s: float = 0.0,
    workload_extra: Optional[Dict[str, Any]] = None,
    autopilot: Optional[Dict[str, Any]] = None,
) -> SoakResult:
    """Run one seeded soak; returns the observations (see SoakResult.check).

    ``hosts`` > ``num_hosts`` leaves spare capacity so a preempted gang has
    somewhere to move — a drained host is not schedulable.

    ``p2p_restore`` arms the peer warm-restore path: every agent runs a
    host-lifetime shard depot, the controller stamps
    ``TPUJOB_RESTORE_PEERS``, and invariant 9 requires at least one
    post-fault incarnation to restore from a peer.
    ``disk_restore_delay_s`` is the modeled slow-store read a DISK
    restore pays (and a peer restore skips) in the light data plane.

    ``operator_crash`` (or a schedule containing OPERATOR_CRASH) switches
    the rig to the crash-recovery topology: the operator is a
    :class:`RestartableOperator` (durable store under ``workdir/store`` +
    controller + HTTP API), agents and the injector talk to it over
    RemoteStore, and the scheduled fault kills+recovers the whole control
    plane mid-run while the data plane keeps training."""
    schedule = (
        schedule if schedule is not None
        else default_schedule(seed, operator_crash=operator_crash)
    )
    crash_mode = any(
        f.kind is FaultKind.OPERATOR_CRASH for f in schedule.faults
    )
    tmp = workdir or tempfile.mkdtemp(prefix="tpujob-soak-")
    ckpt_dir = os.path.join(tmp, "ckpt")
    job_name = "soak-lm"

    operator: Optional[RestartableOperator] = None
    if crash_mode:
        # Operator downtime must never masquerade as host loss: the
        # recovered Host records carry pre-crash heartbeats, and agents
        # need a beat to reconnect before the TTL reaper runs — a
        # NodeLost fence during the outage would gang-restart a healthy
        # gang and fail the duplicate-create invariant for the wrong
        # reason.
        heartbeat_ttl = max(heartbeat_ttl, 10.0)
        operator = RestartableOperator(
            os.path.join(tmp, "store"), heartbeat_ttl=heartbeat_ttl
        )
        operator.start()
        store: Any = RemoteStore(operator.url, timeout=5.0)
    else:
        store = Store()
    injector = ChaosInjector(
        schedule, store, job_name=job_name, checkpoint_dir=ckpt_dir,
        operator=operator,
    )
    agents = [
        HostAgent(
            injector.wrap(),
            f"soak-h{i}",
            total_chips=workers,  # any single host could hold the full gang
            heartbeat_interval=0.25,
            backend=LocalProcessControl(
                injector.wrap(), log_dir=os.path.join(tmp, "logs")
            ),
            # p2p mode: host-lifetime shard depots — they outlive every
            # gang teardown, which is what invariant 9 exercises.
            depot=p2p_restore,
        )
        for i in range(hosts)
    ]
    injector.agents = {a.name: a for a in agents}
    if crash_mode:
        ctl = None
        fake = None
        dashboard = None
    else:
        # The controller's own process control must stay idle in managed
        # mode (every gang member is host-bound); a fake makes a leak loud.
        fake = FakeProcessControl()
        ctl = TPUJobController(store, fake, resync_period=0.5)
        ctl.scheduler.heartbeat_ttl = heartbeat_ttl
        # Workload-side spans (restore-source, save-stall) travel through
        # the operator API (ENV_API_SERVER); without one they drop
        # silently and invariant 9 is blind. Crash mode gets this from
        # RestartableOperator; managed mode needs its own.
        from tf_operator_tpu.dashboard import DashboardServer

        dashboard = DashboardServer(store, host="127.0.0.1", port=0)
        dashboard.start()
        ctl.api_url = dashboard.url

    gang_names = [f"{job_name}-worker-{i}" for i in range(workers)]
    watcher = _InvariantWatcher(store, job_name, gang_names)
    result = SoakResult(schedule=schedule)
    for a in agents:
        a.start()
    if ctl is not None:
        ctl.run(workers=2)
    watcher.start()
    try:
        store.create(
            _soak_job(job_name, workers, num_hosts, ckpt_dir, steps,
                      checkpoint_every, backoff_limit, heartbeat_ttl,
                      data_plane=data_plane, step_sleep_s=step_sleep_s,
                      disk_restore_delay_s=disk_restore_delay_s,
                      workload_extra=workload_extra, autopilot=autopilot)
        )
        injector.arm()
        deadline = time.monotonic() + timeout
        st = None
        while time.monotonic() < deadline:
            try:
                st = store.get("TPUJob", "default", job_name).status
            except TransientStoreError:
                time.sleep(0.25)  # operator mid-restart
                continue
            if is_finished(st) and injector.done:
                break
            time.sleep(0.25)
        st = store.get("TPUJob", "default", job_name).status
        result.succeeded = has_condition(st, ConditionType.SUCCEEDED)
        result.restart_count = st.restart_count
        result.preemption_count = st.preemption_count
        result.last_restart_cause = st.last_restart_cause
        result.conditions = [
            (c.type.value, c.reason, c.message) for c in st.conditions
        ]
        # Invariant 6/8 input: the trace — read while the store is still
        # up. Same spans `tpujob trace` exports, not log inference.
        trace = job_trace(store, "default", job_name)
        result.restart_windows = derive_timings(trace).get("restarts", [])
        result.trace_ops = [s.op for s in trace]
        # Restore-source spans + effective downtime (invariant 9): each
        # restart window is matched to the earliest CLOSED restore span
        # starting at/after the window opened — effective = restore end -
        # restart start. A window with no matching restore (the gang came
        # back but never reported one) falls back to the RUNNING-closed
        # width so the bound still sees it.
        restore_spans = sorted(
            (s for s in trace if s.op == "restore" and s.end_time),
            key=lambda s: s.start_time,
        )
        result.restore_sources = [
            s.attrs.get("source", "disk") for s in restore_spans
        ]
        windows = sorted(result.restart_windows, key=lambda w: w["start"])
        starts = [w["start"] for w in windows]
        for i, w in enumerate(windows):
            nxt = starts[i + 1] if i + 1 < len(starts) else float("inf")
            match = next(
                (s for s in restore_spans
                 if w["start"] <= s.start_time < nxt),
                None,
            )
            if match is not None:
                result.effective_downtimes_s.append(
                    max(0.0, match.end_time - w["start"])
                )
            else:
                result.effective_downtimes_s.append(w.get("downtime_s"))
        if ctl is not None:
            result.lost_seconds = _scrape_lost_seconds(ctl.metrics)
            result.goodput_scraped = True
            # Autopilot receipts (r16): the goodput decomposition (the
            # SAME pure function the reconciler folds at terminal, over
            # the same trace + telemetry — the A/B gate's numerator),
            # the status-mirrored decisions, and per-op closed-span
            # width sums for the cause-ledger cross-check.
            from tf_operator_tpu.obs.telemetry import (
                goodput_decomposition,
                job_telemetry,
            )

            job_obj = store.get("TPUJob", "default", job_name)
            end = st.completion_time or time.time()
            result.goodput = goodput_decomposition(
                trace, job_telemetry(store, "default", job_name),
                job_obj.metadata.creation_timestamp, end,
            )
            result.autopilot_status = dict(job_obj.status.autopilot or {})
            result.cadence_directive = dict(
                job_obj.status.checkpoint_cadence_directive or {}
            )
            result.decision_spans = [
                {"name": s.metadata.name, "attrs": dict(s.attrs or {})}
                for s in trace if s.op == "autopilot-decision"
            ]
            result.span_widths_by_op = {
                op: sum(
                    max(0.0, s.end_time - s.start_time)
                    for s in trace if s.op == op and s.end_time
                )
                for op in ("restart", "resize", "hang")
            }
            result.downtime_spans = [
                {
                    "name": s.metadata.name, "op": s.op,
                    "attrs": dict(s.attrs or {}),
                    "width_s": round(max(0.0, s.end_time - s.start_time), 6),
                }
                for s in trace
                if s.op in ("restart", "resize", "hang") and s.end_time
            ]
    finally:
        injector.stop()
        watcher.stop()
        if ctl is not None:
            ctl.stop()
        for a in agents:
            a.stop()
        if dashboard is not None:
            dashboard.stop()
        if operator is not None:
            operator.crash()  # agents stopped; tear the API down last
        if fake is not None:
            fake.clear()
    result.resume_steps = list(watcher.resume_steps)
    result.partial_gang_violations = list(watcher.violations)
    result.applied = list(injector.applied)
    result.downtime_bound_s = downtime_bound_s
    result.p2p = p2p_restore
    result.gang_incarnations = {
        name: len(uids) for name, uids in watcher.created_uids.items()
    }
    if operator is not None:
        result.operator_restarts = operator.restarts
        leaked = operator.created_through_controller()
    else:
        leaked = [p.metadata.name for p in fake.created]
    if leaked:
        result.partial_gang_violations.append(
            "controller launched through its own backend in managed mode: "
            f"{leaked}"
        )
    return result


def default_autopilot_schedule(seed: int) -> FaultSchedule:
    """The autopilot A/B recipe: ONE mid-run crash (after checkpoint
    progress, so recovery is warm). The crash is what gives the ON
    lane's Young/Daly policy a finite measured MTBF — before it the
    cadence stretches on the zero-failure clamp, after it the interval
    re-derives from δ and the observed failure rate. Pure function of
    the seed, shared verbatim by both lanes."""
    return FaultSchedule.generate(
        seed, crashes=1, preemptions=0, first_step=2, spread_s=0.0
    )


@dataclass
class AutopilotSoakResult:
    """Two same-seed, same-fault-schedule soak lanes: ``run_policy.
    autopilot`` off then on. ``check()`` gates the goodput gain and the
    receipt discipline (every executed decision present as an
    autopilot-decision span carrying its justifying numbers), and
    extends the r13 cause-attribution invariant to both lanes: each of
    restart/resize/hang's ledger lost-seconds must equal the sum of its
    OWN closed spans' widths — however autopilot-triggered resizes and
    watchdog windows interleave, nothing double-counts."""

    off: SoakResult
    on: SoakResult
    min_gain: float = 1.10

    # Every numeric attr a cadence decision span must justify itself with.
    CADENCE_RECEIPT_KEYS = (
        "save_stall_s", "mtbf_s", "step_time_s", "tau_s",
        "from_every", "to_every", "epoch",
    )

    def gain(self) -> Optional[float]:
        off_r = self.off.goodput.get("goodput_ratio", 0.0)
        on_r = self.on.goodput.get("goodput_ratio", 0.0)
        return (on_r / off_r) if off_r else None

    def check(self) -> List[str]:
        errs: List[str] = []
        for tag, lane in (("off", self.off), ("on", self.on)):
            errs.extend(f"[{tag}] {e}" for e in lane.check())
            # Satellite 6 (extends invariant 10): per-cause single-source
            # attribution. The restart/resize/hang counters increment
            # ONLY at their own span closes, so each must match its own
            # spans' summed widths — an autopilot resize interleaving
            # with a watchdog hang in one incarnation must not leak
            # either window into the other's cause.
            if lane.goodput_scraped:
                for cause in ("restart", "resize", "hang"):
                    got = lane.lost_seconds.get(cause, 0.0)
                    want = lane.span_widths_by_op.get(cause, 0.0)
                    if abs(got - want) > max(0.5, 0.05 * want):
                        errs.append(
                            f"[{tag}] lost_seconds{{cause={cause}}} "
                            f"{got:.2f}s != closed {cause}-span widths "
                            f"{want:.2f}s"
                        )
        # The off lane must be autopilot-silent: no decisions, no spans.
        if self.off.decision_spans or self.off.autopilot_status:
            errs.append(
                "autopilot-off lane recorded autopilot activity: "
                f"spans={len(self.off.decision_spans)} "
                f"status={self.off.autopilot_status}"
            )
        # The on lane acted, and every action is receipted.
        decisions_total = int(
            self.on.autopilot_status.get("decisions_total", 0)
        )
        if decisions_total < 1:
            errs.append("autopilot-on lane executed no decisions")
        if len(self.on.decision_spans) != decisions_total:
            errs.append(
                f"autopilot receipt mismatch: {len(self.on.decision_spans)} "
                f"decision spans != decisions_total {decisions_total}"
            )
        cadence = [
            d for d in self.on.decision_spans
            if d["attrs"].get("kind") == "cadence"
        ]
        if not cadence:
            errs.append(
                "autopilot-on lane never retuned the checkpoint cadence "
                f"(decisions: {self.on.decision_spans})"
            )
        for d in cadence:
            for key in self.CADENCE_RECEIPT_KEYS:
                v = d["attrs"].get(key)
                try:
                    valid = v is not None and (v == "inf" or float(v) >= 0)
                except ValueError:
                    valid = False
                if not valid:
                    errs.append(
                        f"cadence decision span {d['name']} missing "
                        f"justifying number {key!r}: attrs={d['attrs']}"
                    )
        # The directive round-tripped. The controller only authors epoch
        # N+1 after the chief acked N, so the ack may trail the final
        # epoch by at most one (a directive issued in the run's last
        # poll interval is legitimately still in flight at completion) —
        # but at least one epoch must have been applied.
        cd = self.on.cadence_directive
        applied = int(cd.get("applied_epoch", 0))
        epoch = int(cd.get("epoch", 0))
        if applied < 1 or applied < epoch - 1:
            errs.append(
                f"cadence directive never round-tripped: epoch {epoch}, "
                f"applied_epoch={cd.get('applied_epoch')}"
            )
        # The mechanism receipt: the retune actually cut save-stall loss.
        off_stall = self.off.goodput.get("lost_s", {}).get("ckpt-stall", 0.0)
        on_stall = self.on.goodput.get("lost_s", {}).get("ckpt-stall", 0.0)
        if not on_stall < off_stall:
            errs.append(
                f"autopilot did not cut ckpt-stall loss: on {on_stall:.2f}s "
                f">= off {off_stall:.2f}s"
            )
        # THE gate: autopilot-on goodput >= min_gain x the off lane.
        off_r = self.off.goodput.get("goodput_ratio", 0.0)
        on_r = self.on.goodput.get("goodput_ratio", 0.0)
        if not (off_r > 0 and on_r >= self.min_gain * off_r):
            errs.append(
                f"goodput gain gate failed: on {on_r:.4f} < "
                f"{self.min_gain:.2f}x off {off_r:.4f}"
            )
        return errs


def run_autopilot_soak(
    seed: int = 0,
    steps: int = 20,
    step_sleep_s: float = 0.2,
    save_stall_extra_s: float = 0.8,
    timeout: float = 180.0,
    workdir: Optional[str] = None,
    min_gain: float = 1.10,
    max_checkpoint_every: int = 8,
) -> AutopilotSoakResult:
    """The A/B autopilot soak: the SAME seed and fault schedule, run
    twice — ``run_policy.autopilot`` off, then on. Identical workload in
    both lanes: ``checkpoint_every=1`` with a modeled per-save blocking
    stall (``save_stall_extra_s``), so the off lane pays the stall on
    every step while the on lane's measured-δ/measured-MTBF retune
    stretches the interval and recovers the difference as goodput.

    A single worker keeps the A/B clean: the telemetry-averaged
    ckpt-stall loss is then exactly the chief's stall seconds, so the
    gate measures the cadence policy, not rank-dilution artifacts.

    Sizing: steps x save_stall_extra_s is the off lane's stall loss —
    the A/B signal. It must dwarf the lanes' uncontrolled noise
    (process startup / compile-init varies by a couple of seconds run
    to run), or the 1.10x gate flakes. The defaults put ~16 s of
    recoverable stall against ~2 s of noise."""
    root = workdir or tempfile.mkdtemp(prefix="tpujob-autopilot-soak-")
    workload_extra = {
        # The modeled flagship save cost the retune amortizes.
        "save_stall_extra_s": save_stall_extra_s,
        # One telemetry window per step: the autopilot needs fresh
        # step-time medians at test timescales.
        "telemetry_every": 1,
        # Per-step directive polling (no throttle): a retune must land
        # at the very next step boundary.
        "cadence_poll_s": 0.0,
    }

    def lane(tag: str, autopilot: Optional[Dict[str, Any]]) -> SoakResult:
        return run_soak(
            seed=seed,
            # Re-derived per lane from the seed: pure function, so both
            # lanes see byte-identical fault schedules.
            schedule=default_autopilot_schedule(seed),
            hosts=2, num_hosts=1, workers=1, steps=steps,
            checkpoint_every=1, backoff_limit=2, timeout=timeout,
            workdir=os.path.join(root, tag), heartbeat_ttl=3.0,
            step_sleep_s=step_sleep_s, workload_extra=workload_extra,
            autopilot=autopilot,
        )

    off = lane("off", None)
    on = lane("on", {
        "enabled": True,
        # Test-timescale hysteresis: still >= the straggler tracker's
        # flag_windows (the no-flap contract), just with a short cooldown.
        "cooldown_s": 1.0,
        "confirm_ticks": 2,
        "max_checkpoint_every": max_checkpoint_every,
    })
    return AutopilotSoakResult(off=off, on=on, min_gain=min_gain)


def autopilot_artifact(
    result: AutopilotSoakResult, seed: int
) -> Dict[str, Any]:
    """The checked-in A/B receipt (artifacts/autopilotbench_r16.json)."""
    errors = result.check()

    def lane(r: SoakResult) -> Dict[str, Any]:
        return {
            "succeeded": r.succeeded,
            "restarts": r.restart_count,
            "goodput": r.goodput,
            "lost_seconds": r.lost_seconds,
            "span_widths_by_op": r.span_widths_by_op,
            "downtime_spans": r.downtime_spans,
            "resume_steps": r.resume_steps,
            "applied": [a["kind"] for a in r.applied],
        }

    return {
        "bench": "autopilot-ab-soak",
        "seed": seed,
        "gate_min_gain": result.min_gain,
        "off": lane(result.off),
        "on": {
            **lane(result.on),
            "decisions_total": result.on.autopilot_status.get(
                "decisions_total", 0
            ),
            "active_checkpoint_every": result.on.autopilot_status.get(
                "active_checkpoint_every", 0
            ),
            "cadence_directive": result.on.cadence_directive,
            "decisions": result.on.decision_spans,
        },
        "goodput_gain": result.gain(),
        "errors": errors,
        "pass": not errors,
    }


@dataclass
class FleetLedgerSoakResult:
    """Observations from the fleet-ledger soak (r18): durable cross-job
    memory under operator death, job GC, and the prior-fed first cadence
    decision of a fresh job. See run_fleet_ledger_soak."""

    history: List[Dict[str, Any]] = field(default_factory=list)
    prior_mtbf_s: float = 0.0
    prior_failures: int = 0
    prior_jobs: int = 0
    summary_before: bytes = b""
    summary_after: bytes = b""
    operator_restarts: int = 0
    gc_uid_present: bool = False
    gc_jobs_folded_before: int = 0
    gc_jobs_folded_after: int = 0
    wal_stats: Dict[str, Any] = field(default_factory=dict)
    on: Dict[str, Any] = field(default_factory=dict)
    off: Dict[str, Any] = field(default_factory=dict)
    max_checkpoint_every: int = 24
    within: float = 1.5

    @staticmethod
    def first_decision(lane: Dict[str, Any]) -> Dict[str, Any]:
        ds = lane.get("cadence_decisions") or []
        return dict(ds[0]) if ds else {}

    def converged_every(self) -> Optional[int]:
        """The Young/Daly optimum the prior-fed first decision is gated
        against: the ON lane's own measured stall and step time, but the
        LEDGER's converged MTBF instead of the lane's (nonexistent) own
        failure history."""
        first = self.first_decision(self.on)
        try:
            stall = float(first["save_stall_s"])
            step = float(first["step_time_s"])
        except (KeyError, ValueError):
            return None
        if self.prior_mtbf_s <= 0:
            return None
        from tf_operator_tpu.autopilot.policy import optimal_checkpoint_every

        return optimal_checkpoint_every(
            stall, self.prior_mtbf_s, step,
            min_every=1, max_every=self.max_checkpoint_every,
        ).every

    def check(self) -> List[str]:
        errs: List[str] = []
        for obs in self.history:
            name = obs.get("name")
            if not obs.get("succeeded"):
                errs.append(f"history job {name} did not succeed")
            if int(obs.get("restarts") or 0) < 1:
                errs.append(f"history job {name} saw no crash restart")
            if not obs.get("folded"):
                errs.append(f"history job {name} never folded into the ledger")
        if not (0 < self.prior_mtbf_s < float("inf")):
            errs.append(
                f"ledger prior MTBF not finite-positive: {self.prior_mtbf_s}"
            )
        if self.prior_failures < len(self.history):
            errs.append(
                f"ledger prior failures {self.prior_failures} < history "
                f"incidents {len(self.history)}"
            )
        if self.operator_restarts < 1:
            errs.append("operator was never killed+restarted")
        if not self.summary_before or self.summary_before != self.summary_after:
            errs.append(
                "fleet summary not byte-identical across operator restart "
                f"({len(self.summary_before)}B vs {len(self.summary_after)}B)"
            )
        if not self.gc_uid_present:
            errs.append("job GC removed the ledger record (must survive)")
        if self.gc_jobs_folded_after != self.gc_jobs_folded_before:
            errs.append(
                f"ledger jobs-folded count changed across GC: "
                f"{self.gc_jobs_folded_before} -> {self.gc_jobs_folded_after}"
            )
        # OFF lane: a fresh job with no fleet prior has infinite own MTBF,
        # so its first retune must sit at the clamp edge, receipt-free.
        off1 = self.first_decision(self.off)
        if not off1:
            errs.append("off lane made no cadence decision")
        else:
            if int(off1.get("to_every") or -1) != self.max_checkpoint_every:
                errs.append(
                    f"off lane first decision not at clamp edge "
                    f"{self.max_checkpoint_every}: {off1}"
                )
            if off1.get("mtbf_s") != "inf":
                errs.append(
                    f"off lane first decision has finite MTBF (fleet prior "
                    f"leaked?): {off1}"
                )
            if "prior_mtbf_s" in off1:
                errs.append(
                    f"off lane decision carries a fleet-prior receipt: {off1}"
                )
        # ON lane: the first decision must be prior-receipted and land
        # within `within`x of the converged optimum.
        on1 = self.first_decision(self.on)
        opt = self.converged_every()
        if not on1:
            errs.append("on lane made no cadence decision")
        elif opt is None:
            errs.append(f"on lane first decision missing its numbers: {on1}")
        else:
            for k in ("prior_mtbf_s", "prior_samples", "prior_weight"):
                if k not in on1:
                    errs.append(
                        f"on lane first decision missing receipt attr "
                        f"{k}: {on1}"
                    )
            to = int(on1.get("to_every") or -1)
            if not (to <= self.within * opt and opt <= self.within * to):
                errs.append(
                    f"on lane first cadence {to} not within {self.within}x "
                    f"of converged optimum {opt} "
                    f"(prior mtbf {self.prior_mtbf_s:.2f}s)"
                )
            # Distinguishability: the clamp edge must NOT satisfy the ON
            # gate, or the A/B proves nothing.
            if not self.max_checkpoint_every > self.within * opt:
                errs.append(
                    f"A/B not distinguishable: clamp edge "
                    f"{self.max_checkpoint_every} <= {self.within}x "
                    f"optimum {opt}"
                )
        # Telemetry-heavy run, coalesced WAL: zero Telemetry bytes, the
        # skip counter proves the traffic existed, and control-plane
        # kinds carry all the durable bytes.
        tel = self.wal_stats.get("Telemetry", {})
        if tel.get("bytes", -1) != 0 or tel.get("skipped", 0) <= 0:
            errs.append(f"telemetry WAL not coalesced: {tel}")
        control = sum(
            (v or {}).get("bytes", 0)
            for k, v in self.wal_stats.items()
            if k in ("TPUJob", KIND_PROCESS)
        )
        if not control > 0:
            errs.append(
                f"no TPUJob/Process WAL bytes recorded: {self.wal_stats}"
            )
        return errs


def _run_ledger_job(
    operator: RestartableOperator,
    root: str,
    name: str,
    schedule: Optional[FaultSchedule],
    steps: int,
    step_sleep_s: float,
    save_stall_extra_s: float,
    autopilot: Optional[Dict[str, Any]],
    timeout: float,
    heartbeat_ttl: float = 10.0,
) -> Dict[str, Any]:
    """Run ONE job through the standing operator — its own agents (per-job
    host names, so no host accumulates enough incidents to trip the
    reputation threshold mid-soak) and an optional per-job injector over
    RemoteStore — wait for terminal AND the ledger fold, and return the
    observation dict the fleet-ledger gates consume."""
    ckpt_dir = os.path.join(root, name, "ckpt")
    store = RemoteStore(operator.url, timeout=5.0)
    injector = (
        ChaosInjector(schedule, store, job_name=name, checkpoint_dir=ckpt_dir)
        if schedule is not None
        else None
    )

    def client() -> Any:
        return (
            injector.wrap()
            if injector is not None
            else RemoteStore(operator.url, timeout=5.0)
        )

    agents = [
        HostAgent(
            client(), f"{name}-h{i}", total_chips=1,
            heartbeat_interval=0.25,
            backend=LocalProcessControl(
                client(), log_dir=os.path.join(root, name, "logs")
            ),
        )
        for i in range(2)
    ]
    if injector is not None:
        injector.agents = {a.name: a for a in agents}
    obs: Dict[str, Any] = {"name": name}
    for a in agents:
        a.start()
    try:
        store.create(
            _soak_job(
                name, 1, 1, ckpt_dir, steps,
                checkpoint_every=1, backoff_limit=2,
                heartbeat_ttl=heartbeat_ttl, data_plane="light",
                step_sleep_s=step_sleep_s,
                workload_extra={
                    # Same geometry as the autopilot A/B: a modeled
                    # per-save blocking stall worth retuning away, fresh
                    # telemetry every step, unthrottled directive polls.
                    "save_stall_extra_s": save_stall_extra_s,
                    "telemetry_every": 1,
                    "cadence_poll_s": 0.0,
                },
                autopilot=autopilot,
            )
        )
        if injector is not None:
            injector.arm()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                st = store.get("TPUJob", "default", name).status
            except TransientStoreError:
                time.sleep(0.25)
                continue
            if is_finished(st) and (injector is None or injector.done):
                break
            time.sleep(0.25)
        job_obj = store.get("TPUJob", "default", name)
        st = job_obj.status
        obs["uid"] = job_obj.metadata.uid
        obs["succeeded"] = has_condition(st, ConditionType.SUCCEEDED)
        obs["restarts"] = st.restart_count
        obs["preemptions"] = st.preemption_count
        # The tentpole contract: terminal observed => the record is IN
        # the ledger (durably) before the soak moves on.
        fold_deadline = time.monotonic() + 15.0
        folded = False
        while time.monotonic() < fold_deadline:
            led = operator.ledger
            if led is not None and led.has(obs["uid"]):
                folded = True
                break
            time.sleep(0.1)
        obs["folded"] = folded
        trace = job_trace(store, "default", name)
        obs["cadence_decisions"] = [
            dict(s.attrs or {})
            for s in sorted(
                (s for s in trace
                 if s.op == "autopilot-decision"
                 and (s.attrs or {}).get("kind") == "cadence"),
                key=lambda s: s.start_time,
            )
        ]
        obs["applied"] = (
            [a["kind"] for a in injector.applied] if injector is not None
            else []
        )
    finally:
        if injector is not None:
            injector.stop()
        for a in agents:
            a.stop()
    return obs


def run_fleet_ledger_soak(
    seed: int = 0,
    history_jobs: int = 2,
    history_steps: int = 6,
    fresh_steps: int = 16,
    step_sleep_s: float = 0.2,
    save_stall_extra_s: float = 0.8,
    max_checkpoint_every: int = 24,
    within: float = 1.5,
    timeout: float = 120.0,
    workdir: Optional[str] = None,
) -> FleetLedgerSoakResult:
    """The fleet-ledger acceptance soak (r18), four phases against ONE
    standing operator with a durable FleetLedger:

    1. **History** — seeded crash-faulted jobs run to Succeeded; each
       terminal folds exactly once, leaving the ledger a finite fleet
       MTBF (the prior the fresh job will consume).
    2. **Operator death** — the operator is killed and restarted;
       ``GET /api/fleet/summary`` must be byte-identical across the
       bounce (rollup + segment replay + dedup re-sweep).
    3. **Prior A/B** — two identical fresh fault-free jobs, autopilot on
       in both, differing ONLY in ``use_fleet_priors``. The OFF lane has
       no failure history, so its first retune clamps to
       ``max_checkpoint_every`` with ``mtbf_s=inf``; the ON lane's first
       decision must carry the prior receipt attrs and land within
       ``within``x of the Young/Daly optimum at the LEDGER's MTBF. The
       clamp edge is sized to fail the ON gate (distinguishability).
       ON runs first so neither lane's own fold can perturb the other's
       prior (the OFF lane never consults the ledger at all).
    4. **Job GC** — a history job is deleted from the store; the ledger
       record must survive (jobs-folded count unchanged).

    Also captures first-incarnation ``wal_stats()``: with per-step
    telemetry from every job, Telemetry WAL bytes must be ZERO (skipped
    counter positive) while TPUJob/Process kinds carry the durable bytes
    — the coalescing satellite's receipt."""
    import urllib.request

    root = workdir or tempfile.mkdtemp(prefix="tpujob-fleet-ledger-")
    operator = RestartableOperator(
        os.path.join(root, "store"),
        # Operator downtime must not masquerade as host loss (same
        # reasoning as crash mode in run_soak).
        heartbeat_ttl=10.0,
        ledger_dir=os.path.join(root, "ledger"),
    )
    operator.start()
    result = FleetLedgerSoakResult(
        max_checkpoint_every=max_checkpoint_every, within=within
    )

    def fetch(path: str) -> bytes:
        with urllib.request.urlopen(operator.url + path, timeout=5.0) as r:
            return r.read()

    try:
        # Phase 1: build fleet history.
        for i in range(history_jobs):
            result.history.append(
                _run_ledger_job(
                    operator, root, f"fleet-hist-{i}",
                    schedule=FaultSchedule.generate(
                        seed + i, crashes=1, preemptions=0,
                        first_step=2, spread_s=0.0,
                    ),
                    steps=history_steps, step_sleep_s=step_sleep_s,
                    save_stall_extra_s=save_stall_extra_s,
                    autopilot=None, timeout=timeout,
                )
            )
        led = operator.ledger
        prior = led.cadence_inputs("", "") if led is not None else {}
        result.prior_mtbf_s = float(prior.get("mtbf_s") or 0.0)
        result.prior_failures = int(prior.get("failures") or 0)
        result.prior_jobs = int(prior.get("jobs") or 0)
        # First-incarnation WAL accounting, before the restart resets the
        # in-memory counters.
        result.wal_stats = operator.store.wal_stats()
        result.summary_before = fetch("/api/fleet/summary")
        # Phase 2: kill + recover the whole control plane.
        operator.restart()
        result.operator_restarts = operator.restarts
        result.summary_after = fetch("/api/fleet/summary")
        # Phase 3: the prior A/B (ON first — see docstring).
        base = {
            "enabled": True,
            "cooldown_s": 1.0,
            "confirm_ticks": 2,
            "max_checkpoint_every": max_checkpoint_every,
        }
        result.on = _run_ledger_job(
            operator, root, "fleet-fresh-on", schedule=None,
            steps=fresh_steps, step_sleep_s=step_sleep_s,
            save_stall_extra_s=save_stall_extra_s,
            autopilot={**base, "use_fleet_priors": True}, timeout=timeout,
        )
        result.off = _run_ledger_job(
            operator, root, "fleet-fresh-off", schedule=None,
            steps=fresh_steps, step_sleep_s=step_sleep_s,
            save_stall_extra_s=save_stall_extra_s,
            autopilot={**base, "use_fleet_priors": False}, timeout=timeout,
        )
        # Phase 4: GC a history job; its ledger record must survive.
        led = operator.ledger
        victim = result.history[0]
        result.gc_jobs_folded_before = len(led) if led is not None else 0
        store = RemoteStore(operator.url, timeout=5.0)
        store.delete("TPUJob", "default", victim["name"])
        gc_deadline = time.monotonic() + 15.0
        while time.monotonic() < gc_deadline:
            try:
                store.get("TPUJob", "default", victim["name"])
            except NotFoundError:
                break
            except TransientStoreError:
                pass
            time.sleep(0.25)
        # Let the controller's GC sync (informer-cached None) run its
        # gauge sweep before we assert.
        time.sleep(1.5)
        result.gc_uid_present = bool(
            victim.get("uid")
            and led is not None
            and led.has(victim["uid"])
        )
        result.gc_jobs_folded_after = len(led) if led is not None else 0
    finally:
        operator.crash()
    return result


def fleetledger_artifact(
    result: FleetLedgerSoakResult, seed: int
) -> Dict[str, Any]:
    """The checked-in receipt (artifacts/fleetledger_r18.json)."""
    import json as _json

    errors = result.check()

    def lane(obs: Dict[str, Any]) -> Dict[str, Any]:
        return {
            "name": obs.get("name"),
            "succeeded": obs.get("succeeded"),
            "restarts": obs.get("restarts"),
            "folded": obs.get("folded"),
            "applied": obs.get("applied"),
            "first_cadence_decision": result.first_decision(obs),
            "cadence_decisions": obs.get("cadence_decisions"),
        }

    try:
        summary = _json.loads(result.summary_after.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        summary = None
    return {
        "bench": "fleet-ledger-soak",
        "seed": seed,
        "history": [lane(o) for o in result.history],
        "prior": {
            "mtbf_s": result.prior_mtbf_s,
            "failures": result.prior_failures,
            "jobs": result.prior_jobs,
        },
        "operator_restarts": result.operator_restarts,
        "summary_byte_identical_across_restart": bool(
            result.summary_before
            and result.summary_before == result.summary_after
        ),
        "gc": {
            "record_survived": result.gc_uid_present,
            "jobs_folded_before": result.gc_jobs_folded_before,
            "jobs_folded_after": result.gc_jobs_folded_after,
        },
        "wal_stats": result.wal_stats,
        "gate_within": result.within,
        "max_checkpoint_every": result.max_checkpoint_every,
        "converged_optimum_every": result.converged_every(),
        "on": lane(result.on),
        "off": lane(result.off),
        "fleet_summary": summary,
        "errors": errors,
        "pass": not errors,
    }


def default_elastic_schedule(
    seed: int, kills: int = 2, spread_s: float = 6.0
) -> FaultSchedule:
    """The elastic acceptance recipe: ``kills`` kill/return faults against
    non-chief members, each returning 3-6s later. Pure function of the
    seed."""
    return FaultSchedule.generate_elastic(
        seed, kills=kills, first_step=1, spread_s=spread_s,
        return_after_s=(3.0, 6.0),
    )


@dataclass
class ElasticSoakResult:
    """Observations of one elastic soak (see check for the gates)."""

    succeeded: bool = False
    restart_count: int = 0
    preemption_count: int = 0
    resize_count: int = 0
    resize_epoch: int = 0
    world_size: int = 0
    last_restart_cause: str = ""
    resize_history: List[dict] = field(default_factory=list)
    conditions: List[tuple] = field(default_factory=list)
    applied: List[dict] = field(default_factory=list)
    schedule: Optional[FaultSchedule] = None
    partial_gang_violations: List[str] = field(default_factory=list)
    # Eval digests: the faulted run's (from workdir/gang/done.json) vs the
    # uninterrupted stream's (position-ordered canonical consumption) —
    # equality IS the bit-identical gate.
    digest: str = ""
    expected_digest: str = ""
    # Controller resize spans from the trace: direction + downtime_s
    # (None = never closed).
    resize_windows: List[dict] = field(default_factory=list)
    restore_sources: List[str] = field(default_factory=list)
    # Consumption rate (positions/s) before the first shrink, while
    # shrunk, and after the first re-grow.
    tokens_per_s: Dict[str, Optional[float]] = field(default_factory=dict)
    downtime_bound_s: float = 60.0
    # Goodput attribution (r13): per-cause lost-seconds counters scraped
    # from the live controller before teardown.
    goodput_scraped: bool = False
    lost_seconds: Dict[str, float] = field(default_factory=dict)
    # Device-state mode (r19, tentpole leg a): the chief's final params
    # digest vs the uninterrupted run's (the SAME jitted row update over
    # the canonical order), and the chief's merged ReshardPlan counters —
    # at least one row must have been re-laid-out device-to-device AND at
    # least one re-fetched, or the re-shard never actually ran.
    device_state: bool = False
    params_digest: str = ""
    expected_params_digest: str = ""
    reshard_plan: Dict[str, Any] = field(default_factory=dict)
    # Resize x preemption composition (r19, tentpole leg b): a fleet
    # preemption annotation stamped MID-SHRINK (the directive published,
    # the barrier not yet). The reconciler must defer the drain to the
    # post-resize epoch: the stamped shrink span closes BEFORE the
    # preemption restart span opens.
    preempt_during_resize: bool = False
    preempt_stamp_time: float = 0.0
    preempt_stamped_epoch: int = 0
    restart_windows: List[dict] = field(default_factory=list)
    # Store-observed quota oracle: live gang chips of every job in the
    # soak's Queue, polled continuously, must never exceed the quota —
    # held-for-regrow and mid-drain chips included (no double-count).
    quota_violations: List[str] = field(default_factory=list)

    @property
    def params_bit_identical(self) -> bool:
        return bool(self.params_digest) and (
            self.params_digest == self.expected_params_digest
        )

    @property
    def bit_identical(self) -> bool:
        return bool(self.digest) and self.digest == self.expected_digest

    @property
    def peer_restores(self) -> int:
        return sum(1 for s in self.restore_sources if s == "peer")

    def check(self) -> List[str]:
        errs = []
        if not self.succeeded:
            errs.append(f"job did not succeed: {self.conditions}")
        # THE tentpole gate: member loss + return handled entirely by
        # shrink/re-grow — zero full gang restarts of any flavor. The
        # composed drain-during-shrink schedule (r19) sanctions exactly
        # ONE gang teardown: the deliberately injected fleet preemption,
        # which must land as a preemption (never a counted restart).
        allowed_preempts = 1 if self.preempt_during_resize else 0
        if self.restart_count or self.preemption_count != allowed_preempts:
            errs.append(
                f"unexpected gang restarts (restarts="
                f"{self.restart_count} preemptions={self.preemption_count} "
                f"want 0/{allowed_preempts} "
                f"last_cause={self.last_restart_cause!r}) — member loss "
                "must resize, not restart"
            )
        kills = sum(
            1 for f in (self.schedule.faults if self.schedule else ())
            if f.kind is FaultKind.KILL_RETURN
        )
        if self.resize_count < 2 * kills:
            errs.append(
                f"resize_count {self.resize_count} < {2 * kills} "
                f"(each of {kills} kill/returns must shrink AND re-grow)"
            )
        directions = [h.get("direction") for h in self.resize_history]
        if "shrink" not in directions or "grow" not in directions:
            errs.append(f"resize history lacks a direction: {directions}")
        if self.partial_gang_violations:
            errs.append(
                f"unsanctioned partial gang: {self.partial_gang_violations}"
            )
        sched_kinds = [
            f.kind.value for f in (self.schedule.faults if self.schedule else ())
        ]
        applied_kinds = [a["kind"] for a in self.applied]
        if applied_kinds != sched_kinds:
            errs.append(
                f"applied fault sequence {applied_kinds} != schedule "
                f"{sched_kinds}"
            )
        if not self.bit_identical:
            errs.append(
                f"eval digest mismatch after resizes: got "
                f"{self.digest[:16] or '<none>'} want "
                f"{self.expected_digest[:16]} — a token was dropped, "
                "duplicated, or reordered"
            )
        if self.peer_restores < 1:
            errs.append(
                "no resize restored from a peer depot (restore sources: "
                f"{self.restore_sources}) — the re-grown member must pull "
                "missing shards from survivors, disk is last resort"
            )
        for w in self.resize_windows:
            if w.get("downtime_s") is None:
                errs.append(f"resize span never closed: {w}")
            elif w["downtime_s"] > self.downtime_bound_s:
                errs.append(
                    f"resize downtime {w['downtime_s']:.1f}s exceeds bound "
                    f"{self.downtime_bound_s:.0f}s: {w}"
                )
        # Goodput attribution (r13): resize downtime lands under
        # lost_seconds{cause="resize"} (same span-close point as the
        # downtime histogram) and never doubles into cause="restart" —
        # the elastic gate above already demands zero full restarts.
        if self.goodput_scraped:
            expected = sum(
                w["downtime_s"] for w in self.resize_windows
                if w.get("downtime_s") is not None
            )
            got = self.lost_seconds.get("resize", 0.0)
            if expected > 0 and abs(got - expected) > max(0.5, 0.05 * expected):
                errs.append(
                    f"lost_seconds{{cause=resize}} {got:.2f}s != closed "
                    f"resize-window downtime {expected:.2f}s"
                )
            if self.lost_seconds.get("restart", 0.0) > 0:
                errs.append(
                    "resize downtime leaked into cause=restart: "
                    f"{self.lost_seconds}"
                )
            # Satellite (r19): in the composed schedule the preemption's
            # own downtime lands under cause=preemption and equals its
            # own restart-span widths — resize and preemption never
            # double-count one outage, however they interleave.
            if self.preempt_during_resize:
                p_expected = sum(
                    w["downtime_s"] for w in self.restart_windows
                    if w.get("cause") == "preemption"
                    and w.get("downtime_s") is not None
                )
                p_got = self.lost_seconds.get("preemption", 0.0)
                if p_expected > 0 and abs(p_got - p_expected) > max(
                    0.5, 0.05 * p_expected
                ):
                    errs.append(
                        f"lost_seconds{{cause=preemption}} {p_got:.2f}s != "
                        f"closed preemption-window downtime "
                        f"{p_expected:.2f}s"
                    )
        # Device-state gates (r19 tentpole leg a): final params
        # bit-identical to the uninterrupted run, and the chief's merged
        # plan proves the re-shard both re-laid-out device rows AND
        # re-fetched rows other members advanced.
        if self.device_state:
            if not self.params_bit_identical:
                errs.append(
                    f"device-state params NOT bit-identical: got "
                    f"{self.params_digest[:16] or '<none>'} want "
                    f"{self.expected_params_digest[:16]} — a row was "
                    "lost, duplicated, or mis-sourced across a resize"
                )
            # A full restart (preemption drain) wipes every member's
            # device state, so the new chief's merged plan starts from
            # scratch and may legitimately contain zero device-to-device
            # re-layouts — the store re-fetch gate below still applies
            # (that is exactly how a wiped gang recovers the rows).
            if int(self.reshard_plan.get("relaid", 0) or 0) < 1 and not (
                self.restart_count or self.preemption_count
            ):
                errs.append(
                    f"re-shard never re-laid-out a device row: "
                    f"{self.reshard_plan}"
                )
            if int(self.reshard_plan.get("refetched", 0) or 0) < 1:
                errs.append(
                    f"re-shard never re-fetched a row from the store: "
                    f"{self.reshard_plan}"
                )
        # Composition gates (r19 tentpole leg b): the annotation landed
        # mid-shrink, and the drain was DEFERRED — the in-flight shrink
        # span closed before the preemption restart span opened.
        if self.preempt_during_resize:
            if not self.preempt_stamp_time:
                errs.append(
                    "composition probe never caught a shrink mid-flight "
                    "to stamp the preempt annotation"
                )
            preempts = [
                w for w in self.restart_windows
                if w.get("cause") == "preemption"
            ]
            if len(preempts) != 1:
                errs.append(
                    f"expected exactly one preemption restart window: "
                    f"{self.restart_windows}"
                )
            elif self.preempt_stamp_time:
                w = preempts[0]
                if w.get("downtime_s") is None:
                    errs.append(f"preemption restart span never closed: {w}")
                shrink = next(
                    (z for z in self.resize_windows
                     if z.get("direction") == "shrink"
                     and str(z.get("epoch")) == str(self.preempt_stamped_epoch)),
                    None,
                )
                if shrink is None or shrink.get("end") is None:
                    errs.append(
                        f"stamped shrink epoch {self.preempt_stamped_epoch} "
                        f"has no closed resize span: {self.resize_windows}"
                    )
                elif w["start"] < shrink["end"] - 1e-6:
                    errs.append(
                        f"drain NOT deferred: preemption restart opened at "
                        f"{w['start']:.3f} before the in-flight shrink "
                        f"closed at {shrink['end']:.3f}"
                    )
        if self.quota_violations:
            errs.append(
                f"store-observed quota violations "
                f"({len(self.quota_violations)}): {self.quota_violations[:3]}"
            )
        return errs


def _scrape_lost_seconds(metrics) -> Dict[str, float]:
    """{cause: seconds} from a live ControllerMetrics'
    ``tpujob_lost_seconds_total`` counters (parsed from exposition text so
    the soak reads the same surface Prometheus would)."""
    import re

    out: Dict[str, float] = {}
    for line in metrics.render().splitlines():
        m = re.match(
            r'tpujob_lost_seconds_total\{[^}]*cause="([^"]+)"[^}]*\} (\S+)',
            line,
        )
        if m:
            out[m.group(1)] = out.get(m.group(1), 0.0) + float(m.group(2))
    return out


def _percentile(xs: List[float], q: float) -> Optional[float]:
    vals = sorted(xs)
    if not vals:
        return None
    idx = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
    return vals[idx]


def _elastic_phase_rates(
    records: List[dict], history: List[dict]
) -> Dict[str, Optional[float]]:
    """Positions/s before the first shrink, while shrunk, and after the
    first re-grow — from the durable consumption records' timestamps
    against the resize history's wall-clock marks."""
    ts = sorted(float(r["t"]) for r in records if "t" in r)
    shrinks = [float(h["time"]) for h in history
               if h.get("direction") == "shrink" and h.get("time")]
    if not ts or not shrinks:
        return {}
    s1 = shrinks[0]
    g1 = next((float(h["time"]) for h in history
               if h.get("direction") == "grow"
               and float(h.get("time", 0) or 0) > s1), None)

    def rate(a: float, b: Optional[float]) -> Optional[float]:
        if b is None or b <= a:
            return None
        n = sum(1 for t in ts if a <= t < b)
        return round(n / (b - a), 2)

    return {
        "before": rate(ts[0], s1),
        "during_shrink": rate(s1, g1),
        "after_regrow": rate(g1, ts[-1] + 1e-9) if g1 else None,
    }


class _QuotaOracle(threading.Thread):
    """Store-observed quota auditor (r19): at no sampled instant may the
    summed live chips of a queue's jobs exceed its ``quota_chips``.
    Over-spec loans are charged to the queue (grow-beyond-spec worlds
    must still fit inside it), so this single invariant covers normal
    admission, the composed resize×preemption schedule, AND the
    grow/reclaim probe. Reads the store like an external auditor —
    nothing the controller could fudge."""

    def __init__(
        self, store, queue_name: str, quota: int, poll_s: float = 0.15
    ):
        super().__init__(daemon=True)
        self.store = store
        self.queue_name = queue_name
        self.quota = int(quota)
        self.poll_s = poll_s
        self.violations: List[str] = []
        self._halt = threading.Event()

    def _sample(self) -> int:
        from tf_operator_tpu.api.types import LABEL_JOB_NAME

        used = 0
        for j in self.store.list("TPUJob", namespace="default"):
            if getattr(j.spec.scheduling, "queue", "") != self.queue_name:
                continue
            used += sum(
                max(p.spec.chips, 0)
                for p in self.store.list(
                    KIND_PROCESS,
                    namespace="default",
                    label_selector={LABEL_JOB_NAME: j.metadata.name},
                )
                if not p.is_finished()
            )
        return used

    def run(self) -> None:
        while not self._halt.is_set():
            try:
                used = self._sample()
                if used > self.quota and len(self.violations) < 32:
                    msg = (
                        f"queue {self.queue_name} quota {self.quota} "
                        f"exceeded: live chips = {used}"
                    )
                    if not self.violations or self.violations[-1] != msg:
                        self.violations.append(msg)
            except Exception:
                pass
            self._halt.wait(self.poll_s)

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=5)


def run_elastic_soak(
    seed: int = 0,
    schedule: Optional[FaultSchedule] = None,
    kills: int = 2,
    workers: int = 3,
    total_windows: int = 900,
    step_sleep_s: float = 0.06,
    checkpoint_every: int = 10,
    backoff_limit: int = 2,
    timeout: float = 150.0,
    workdir: Optional[str] = None,
    heartbeat_ttl: float = 2.0,
    downtime_bound_s: float = 60.0,
    device_state: bool = False,
    preempt_during_resize: bool = False,
    queue_quota: int = 0,
) -> ElasticSoakResult:
    """Seeded kill/return soak over an ELASTIC job (run_policy.elastic):
    every member loss must be absorbed by a shrink directive and every
    host return by a symmetric re-grow — zero full gang restarts, the
    consumed stream bit-identical to an uninterrupted run, and the
    re-grown member restoring from a surviving peer's shard depot.

    One member per host (each agent holds exactly one chip), so a killed
    member IS a lost host; agents run host-lifetime shard depots.

    r19 knobs:

    - ``device_state``: the workload carries a real params/opt pytree on
      device through every resize (train/reshard.py); the gate hardens
      to *bit-identical final params* vs the uninterrupted run.
    - ``preempt_during_resize``: a probe thread stamps the fleet preempt
      annotation the instant a shrink directive is mid-flight; the
      reconciler must DEFER the drain until the resize epoch closes
      (exactly one preemption restart, opening strictly after the
      stamped shrink span ends).
    - ``queue_quota``: creates a Queue with that many chips, binds the
      job to it, and runs a store-polling quota oracle for the whole
      soak — any sampled exceedance fails the run."""
    from tf_operator_tpu.train.data import elastic_global_order
    from tf_operator_tpu.workloads.elastic import _digest, _read_records

    schedule = (
        schedule if schedule is not None
        else default_elastic_schedule(seed, kills=kills)
    )
    tmp = workdir or tempfile.mkdtemp(prefix="tpujob-elastic-soak-")
    ckpt_dir = os.path.join(tmp, "ckpt")
    gang_dir = os.path.join(tmp, "gang")
    os.makedirs(gang_dir, exist_ok=True)
    job_name = "soak-elastic"

    store = Store()
    injector = ChaosInjector(
        schedule, store, job_name=job_name, checkpoint_dir=ckpt_dir,
    )
    agents = [
        HostAgent(
            injector.wrap(),
            f"soak-h{i}",
            total_chips=1,  # one member per host: a kill IS a host loss
            heartbeat_interval=0.25,
            backend=LocalProcessControl(
                injector.wrap(), log_dir=os.path.join(tmp, "logs")
            ),
            depot=True,  # survivors' depots are the re-grow restore source
        )
        for i in range(workers)
    ]
    injector.agents = {a.name: a for a in agents}
    fake = FakeProcessControl()
    ctl = TPUJobController(store, fake, resync_period=0.5)
    ctl.scheduler.heartbeat_ttl = heartbeat_ttl
    from tf_operator_tpu.dashboard import DashboardServer

    dashboard = DashboardServer(store, host="127.0.0.1", port=0)
    dashboard.start()
    ctl.api_url = dashboard.url

    env = dict(DATAPLANE_ENV)
    env["PYTHONPATH"] = _ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
    job = TPUJob(
        metadata=ObjectMeta(name=job_name),
        spec=TPUJobSpec(
            replica_specs={
                ReplicaType.WORKER: ReplicaSpec(
                    replicas=workers,
                    template=ProcessTemplate(
                        entrypoint="tf_operator_tpu.workloads.elastic:main",
                        env=env,
                        chips_per_process=1,
                    ),
                )
            },
            topology=TopologySpec(num_hosts=workers, chips_per_host=1),
        ),
    )
    job.spec.run_policy.backoff_limit = backoff_limit
    job.spec.run_policy.heartbeat_ttl_seconds = heartbeat_ttl
    job.spec.run_policy.elastic = True
    job.spec.workload = {
        "workdir": gang_dir,
        "total_windows": total_windows,
        "step_sleep_s": step_sleep_s,
        "data_seed": seed,
        "checkpoint_dir": ckpt_dir,
        "checkpoint_every": checkpoint_every,
        "checkpoint_backend": "npy",
        "elastic": True,
    }
    if device_state:
        job.spec.workload["device_state"] = True

    oracle: Optional[_QuotaOracle] = None
    queue_name = "elastic-soak-q"
    if queue_quota > 0:
        from tf_operator_tpu.sched.objects import Queue, QueueSpec

        store.create(
            Queue(
                metadata=ObjectMeta(name=queue_name, namespace="default"),
                spec=QueueSpec(quota_chips=queue_quota),
            )
        )
        job.spec.scheduling.queue = queue_name
        oracle = _QuotaOracle(store, queue_name, queue_quota)

    gang_names = [f"{job_name}-worker-{i}" for i in range(workers)]

    def sanctioned_subset() -> Optional[set]:
        """The member set the live shrink directive blesses, if any."""
        try:
            st = store.get("TPUJob", "default", job_name).status
        except Exception:
            return None
        d = st.resize_directive or {}
        if d.get("direction") == "shrink" and d.get("members"):
            return set(d["members"])
        return None

    watcher = _InvariantWatcher(
        store, job_name, gang_names, allowed_subset_fn=sanctioned_subset
    )
    result = ElasticSoakResult(
        schedule=schedule, downtime_bound_s=downtime_bound_s,
        device_state=device_state,
        preempt_during_resize=preempt_during_resize,
    )

    stamp_halt = threading.Event()

    def _stamp_preempt_mid_shrink() -> None:
        # Composition probe (r19 leg b): the instant a shrink directive
        # is in flight (published, barrier not yet closed), stamp the
        # fleet preempt annotation. The reconciler must defer the drain
        # to the post-resize epoch — check() verifies the preemption
        # restart span opens only after the stamped shrink span closed.
        from tf_operator_tpu.controller.reconciler import ANNOTATION_PREEMPT

        while not stamp_halt.is_set():
            try:
                j = store.get("TPUJob", "default", job_name)
                d = j.status.resize_directive or {}
                if (
                    d.get("direction") == "shrink"
                    and "boundary_remaining" not in d
                ):
                    epoch = int(d.get("epoch", 0) or 0)

                    def _stamp(fresh):
                        if fresh.metadata.annotations.get(ANNOTATION_PREEMPT):
                            return False
                        fresh.metadata.annotations[ANNOTATION_PREEMPT] = (
                            "chaos-soak/fleet-pressure"
                        )

                    if store.update_with_retry(
                        "TPUJob", "default", job_name, _stamp
                    ) is not None:
                        result.preempt_stamp_time = time.monotonic()
                        result.preempt_stamped_epoch = epoch
                    return
            except Exception:
                pass
            stamp_halt.wait(0.02)

    stamper = (
        threading.Thread(target=_stamp_preempt_mid_shrink, daemon=True)
        if preempt_during_resize else None
    )
    for a in agents:
        a.start()
    ctl.run(workers=2)
    watcher.start()
    if oracle is not None:
        oracle.start()
    try:
        store.create(job)
        injector.arm()
        if stamper is not None:
            stamper.start()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = store.get("TPUJob", "default", job_name).status
            if is_finished(st) and injector.done:
                break
            time.sleep(0.25)
        st = store.get("TPUJob", "default", job_name).status
        result.succeeded = has_condition(st, ConditionType.SUCCEEDED)
        result.restart_count = st.restart_count
        result.preemption_count = st.preemption_count
        result.resize_count = st.resize_count
        result.resize_epoch = st.resize_epoch
        result.world_size = st.world_size
        result.last_restart_cause = st.last_restart_cause
        result.resize_history = list(st.resize_history or [])
        result.conditions = [
            (c.type.value, c.reason, c.message) for c in st.conditions
        ]
        trace = job_trace(store, "default", job_name)
        result.resize_windows = [
            {
                "direction": s.attrs.get("direction", ""),
                "epoch": s.attrs.get("epoch", ""),
                "start": s.start_time,
                "end": s.end_time or None,
                "downtime_s": (
                    round(s.end_time - s.start_time, 3) if s.end_time else None
                ),
            }
            for s in trace if s.op == "resize"
        ]
        result.restart_windows = derive_timings(trace).get("restarts", [])
        result.restore_sources = [
            s.attrs.get("source", "disk")
            for s in sorted(
                (s for s in trace if s.op == "restore" and s.end_time),
                key=lambda s: s.start_time,
            )
        ]
        records = _read_records(gang_dir)
        result.tokens_per_s = _elastic_phase_rates(
            records, result.resize_history
        )
        digest_path = os.path.join(gang_dir, "eval_digest.txt")
        if os.path.exists(digest_path):
            with open(digest_path) as f:
                result.digest = f.read().strip()
        order = elastic_global_order(total_windows, seed=seed)
        result.expected_digest = _digest(
            [{"p": p, "w": int(order[p])} for p in range(total_windows)],
            total_windows,
        )
        if device_state:
            # Device-state receipts: the chief's done.json carries the
            # assembled-params digest and the merged re-shard plan; the
            # expected digest re-derives the uninterrupted run through
            # the SAME jitted update the members ran.
            import json as _json

            from tf_operator_tpu.train import reshard as _reshard

            done_path = os.path.join(gang_dir, "done.json")
            if os.path.exists(done_path):
                with open(done_path) as f:
                    done = _json.load(f)
                result.params_digest = done.get("params_digest", "")
                result.reshard_plan = dict(done.get("reshard", {}))
            result.expected_params_digest = _reshard.params_digest(
                _reshard.expected_params(
                    total_windows, _reshard.PARAM_DIM, seed, order
                )
            )
        result.lost_seconds = _scrape_lost_seconds(ctl.metrics)
        result.goodput_scraped = True
    finally:
        injector.stop()
        stamp_halt.set()
        if oracle is not None:
            oracle.stop()
            result.quota_violations = list(oracle.violations)
        watcher.stop()
        ctl.stop()
        for a in agents:
            a.stop()
        dashboard.stop()
        fake.clear()
    result.applied = list(injector.applied)
    result.partial_gang_violations = list(watcher.violations)
    leaked = [p.metadata.name for p in fake.created]
    if leaked:
        result.partial_gang_violations.append(
            "controller launched through its own backend in managed mode: "
            f"{leaked}"
        )
    return result


def elastic_artifact(result: ElasticSoakResult, seed: int) -> Dict[str, Any]:
    """The elasticbench receipt (one JSON object; CI writes it to
    ``artifacts/elasticbench_r12.json`` and ``genjob --bench-elastic``
    prints it on one line)."""
    downtimes = [
        w["downtime_s"] for w in result.resize_windows
        if w.get("downtime_s") is not None
    ]
    return {
        "bench": "elastic-soak",
        "seed": seed,
        "resize_count": result.resize_count,
        "resize_epoch": result.resize_epoch,
        "resizes": result.resize_windows,
        "resize_downtime_p50_s": _percentile(downtimes, 0.5),
        "resize_downtime_p99_s": _percentile(downtimes, 0.99),
        "tokens_per_s": result.tokens_per_s,
        "zero_full_restarts": (
            result.restart_count == 0
            and result.preemption_count
            == (1 if result.preempt_during_resize else 0)
        ),
        "restart_count": result.restart_count,
        "preemption_count": result.preemption_count,
        "digest": result.digest,
        "expected_digest": result.expected_digest,
        "bit_identical": result.bit_identical,
        "peer_restores": result.peer_restores,
        "restore_sources": result.restore_sources,
        "applied": result.applied,
        "lost_seconds": {
            k: round(v, 3) for k, v in sorted(result.lost_seconds.items())
        },
        **(
            {
                "params_digest": result.params_digest,
                "expected_params_digest": result.expected_params_digest,
                "params_bit_identical": result.params_bit_identical,
                "reshard": result.reshard_plan,
            }
            if result.device_state else {}
        ),
        **(
            {
                "preempt_stamped_epoch": result.preempt_stamped_epoch,
                "restart_windows": result.restart_windows,
                "quota_violations": result.quota_violations,
            }
            if result.preempt_during_resize else {}
        ),
        "pass": not result.check(),
    }


@dataclass
class GrowBeyondSpecResult:
    """Observations of one grow-beyond-spec probe (r19 tentpole leg c):
    a running elastic job with ``scheduling.elastic_max_world`` above its
    spec must borrow idle in-quota chips and grow past spec, then shrink
    cleanly back when a queued admission applies quota pressure — no
    restart, no backoff charge, and the queue never over quota."""

    spec_world: int = 0
    max_world: int = 0
    # Largest world_size ever observed on the primary job, and the
    # largest status.overspec_workers alongside it.
    grew_to: int = 0
    overspec_seen: int = 0
    primary_succeeded: bool = False
    pressure_succeeded: bool = False
    restart_count: int = 0
    preemption_count: int = 0
    final_overspec: int = 0
    resize_history: List[dict] = field(default_factory=list)
    conditions: List[tuple] = field(default_factory=list)
    pressure_conditions: List[tuple] = field(default_factory=list)
    quota_violations: List[str] = field(default_factory=list)

    def check(self) -> List[str]:
        errs = []
        if not self.primary_succeeded:
            errs.append(
                f"primary elastic job did not succeed: {self.conditions}"
            )
        if not self.pressure_succeeded:
            errs.append(
                f"pressure job did not succeed (reclaim never freed its "
                f"chips?): {self.pressure_conditions}"
            )
        if self.grew_to <= self.spec_world:
            errs.append(
                f"never grew beyond spec: world peaked at {self.grew_to} "
                f"(spec {self.spec_world}, elastic_max_world "
                f"{self.max_world})"
            )
        if self.overspec_seen < 1:
            errs.append("status.overspec_workers never went positive")
        if self.restart_count or self.preemption_count:
            errs.append(
                f"reclaim charged a restart (restarts={self.restart_count} "
                f"preemptions={self.preemption_count}) — over-spec "
                "reclaim must shrink, not tear down"
            )
        causes = {h.get("cause") for h in self.resize_history}
        if "grow-beyond-spec" not in causes:
            errs.append(
                f"resize history lacks a grow-beyond-spec entry: "
                f"{self.resize_history}"
            )
        if "overspec-reclaim" not in causes:
            errs.append(
                f"resize history lacks an overspec-reclaim entry: "
                f"{self.resize_history}"
            )
        if self.final_overspec:
            errs.append(
                f"job ended still holding an over-spec loan: "
                f"{self.final_overspec} member(s)"
            )
        if self.quota_violations:
            errs.append(
                f"store-observed quota violations "
                f"({len(self.quota_violations)}): {self.quota_violations[:3]}"
            )
        return errs


def run_grow_beyond_spec_probe(
    seed: int = 0,
    workers: int = 2,
    max_world: int = 3,
    total_windows: int = 600,
    step_sleep_s: float = 0.05,
    timeout: float = 120.0,
    workdir: Optional[str] = None,
) -> GrowBeyondSpecResult:
    """Grow-beyond-spec probe (r19 tentpole leg c). ``max_world`` hosts
    with one chip each, a Queue whose quota covers all of them, and an
    elastic job specced at ``workers`` with ``elastic_max_world`` =
    ``max_world``: the fleet must offer the idle in-quota chips and the
    job grow past spec. Then a 1-chip pressure job joins the queue —
    quota pressure must reclaim the loan FIRST (the job shrinks back to
    spec with no restart and no backoff charge) and the pressure job run
    to completion on the freed chip. A store-polling quota oracle audits
    the whole composition."""
    tmp = workdir or tempfile.mkdtemp(prefix="tpujob-grow-spec-")
    gang_dir = os.path.join(tmp, "gang")
    ckpt_dir = os.path.join(tmp, "ckpt")
    os.makedirs(gang_dir, exist_ok=True)
    primary, pressure = "grow-primary", "grow-pressure"
    queue_name = "grow-q"

    from tf_operator_tpu.sched.objects import Queue, QueueSpec

    store = Store()
    agents = [
        HostAgent(
            store,
            f"grow-h{i}",
            total_chips=1,
            heartbeat_interval=0.25,
            backend=LocalProcessControl(
                store, log_dir=os.path.join(tmp, "logs")
            ),
            depot=True,
        )
        for i in range(max_world)
    ]
    fake = FakeProcessControl()
    ctl = TPUJobController(store, fake, resync_period=0.5)
    from tf_operator_tpu.dashboard import DashboardServer

    dashboard = DashboardServer(store, host="127.0.0.1", port=0)
    dashboard.start()
    ctl.api_url = dashboard.url

    env = dict(DATAPLANE_ENV)
    env["PYTHONPATH"] = _ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
    job = TPUJob(
        metadata=ObjectMeta(name=primary),
        spec=TPUJobSpec(
            replica_specs={
                ReplicaType.WORKER: ReplicaSpec(
                    replicas=workers,
                    template=ProcessTemplate(
                        entrypoint="tf_operator_tpu.workloads.elastic:main",
                        env=env,
                        chips_per_process=1,
                    ),
                )
            },
            topology=TopologySpec(num_hosts=workers, chips_per_host=1),
        ),
    )
    job.spec.run_policy.elastic = True
    job.spec.run_policy.heartbeat_ttl_seconds = 2.0
    job.spec.scheduling.queue = queue_name
    job.spec.scheduling.elastic_max_world = max_world
    job.spec.workload = {
        "workdir": gang_dir,
        "total_windows": total_windows,
        "step_sleep_s": step_sleep_s,
        "data_seed": seed,
        "checkpoint_dir": ckpt_dir,
        "checkpoint_every": 10,
        "checkpoint_backend": "npy",
        "elastic": True,
    }
    presser = TPUJob(
        metadata=ObjectMeta(name=pressure),
        spec=TPUJobSpec(
            replica_specs={
                ReplicaType.WORKER: ReplicaSpec(
                    replicas=1,
                    template=ProcessTemplate(
                        entrypoint="tf_operator_tpu.workloads.noop:main",
                        env=env,
                        chips_per_process=1,
                    ),
                )
            },
            topology=TopologySpec(num_hosts=1, chips_per_host=1),
        ),
    )
    presser.spec.scheduling.queue = queue_name
    presser.spec.workload = {"sleep_s": 2.0}

    store.create(
        Queue(
            metadata=ObjectMeta(name=queue_name, namespace="default"),
            spec=QueueSpec(quota_chips=max_world),
        )
    )
    oracle = _QuotaOracle(store, queue_name, max_world)
    result = GrowBeyondSpecResult(spec_world=workers, max_world=max_world)
    for a in agents:
        a.start()
    ctl.run(workers=2)
    oracle.start()
    try:
        store.create(job)
        deadline = time.monotonic() + timeout
        injected = False
        while time.monotonic() < deadline:
            st = store.get("TPUJob", "default", primary).status
            result.grew_to = max(result.grew_to, st.world_size)
            result.overspec_seen = max(
                result.overspec_seen, st.overspec_workers
            )
            if is_finished(st):
                if injected:
                    pst = store.get("TPUJob", "default", pressure).status
                    if is_finished(pst):
                        break
                else:
                    break  # finished before the pressure landed: probe fails
            if not injected and st.world_size >= max_world:
                # Beyond spec on loaned chips: now apply quota pressure.
                store.create(presser)
                injected = True
            time.sleep(0.1)
        st = store.get("TPUJob", "default", primary).status
        result.primary_succeeded = has_condition(st, ConditionType.SUCCEEDED)
        result.restart_count = st.restart_count
        result.preemption_count = st.preemption_count
        result.final_overspec = st.overspec_workers
        result.resize_history = list(st.resize_history or [])
        result.conditions = [
            (c.type.value, c.reason, c.message) for c in st.conditions
        ]
        if injected:
            pst = store.get("TPUJob", "default", pressure).status
            result.pressure_succeeded = has_condition(
                pst, ConditionType.SUCCEEDED
            )
            result.pressure_conditions = [
                (c.type.value, c.reason, c.message) for c in pst.conditions
            ]
    finally:
        oracle.stop()
        result.quota_violations = list(oracle.violations)
        ctl.stop()
        for a in agents:
            a.stop()
        dashboard.stop()
        fake.clear()
    return result


def run_elastic_general_soak(
    seed: int = 0, workdir: Optional[str] = None, timeout: float = 150.0
) -> Tuple[ElasticSoakResult, ElasticSoakResult, GrowBeyondSpecResult]:
    """The r19 acceptance composition (CI ``elastic-general-soak``):

    1. **device-state soak** — the r12 kill/return schedule with a real
       device param/opt pytree carried through every resize; gate is
       bit-identical final params + eval digest vs the uninterrupted
       run, with >=1 peer-depot shard restore.
    2. **drain-during-shrink** — one kill/return overlapped with a fleet
       preemption stamped mid-shrink, under a store-audited Queue; gate
       is the deferred drain (exactly one preemption restart, opening
       after the stamped shrink closed), zero quota violations, and the
       same bit-identity.
    3. **grow-beyond-spec probe** — world_size past spec on loaned
       in-quota chips, first-reclaimed cleanly under injected pressure.
    """
    base = workdir or tempfile.mkdtemp(prefix="tpujob-elastic-general-")
    device = run_elastic_soak(
        seed=seed, kills=2, workers=3, total_windows=900,
        step_sleep_s=0.06, device_state=True, timeout=timeout,
        workdir=os.path.join(base, "device"),
    )
    # Slow, short windows: each step is a wide stamp-landing target, so
    # the probe reliably catches the shrink between directive publish
    # and barrier completion.
    drain = run_elastic_soak(
        seed=seed + 1, kills=1, workers=3, total_windows=90,
        step_sleep_s=0.4, device_state=True, preempt_during_resize=True,
        queue_quota=3, timeout=timeout,
        workdir=os.path.join(base, "drain"),
    )
    grow = run_grow_beyond_spec_probe(
        seed=seed + 2, workdir=os.path.join(base, "grow"),
        timeout=timeout,
    )
    return device, drain, grow


def elastic_general_artifact(
    device: ElasticSoakResult,
    drain: ElasticSoakResult,
    grow: GrowBeyondSpecResult,
    seed: int,
) -> Dict[str, Any]:
    """The elasticbench receipt for the composed r19 acceptance (CI
    writes it to ``artifacts/elasticbench_r19.json``)."""
    return {
        "bench": "elastic-general-soak",
        "seed": seed,
        "device_state_soak": elastic_artifact(device, seed),
        "drain_during_shrink": elastic_artifact(drain, seed + 1),
        "grow_beyond_spec": {
            "spec_world": grow.spec_world,
            "elastic_max_world": grow.max_world,
            "grew_to": grow.grew_to,
            "overspec_seen": grow.overspec_seen,
            "restart_count": grow.restart_count,
            "preemption_count": grow.preemption_count,
            "resize_history": grow.resize_history,
            "quota_violations": grow.quota_violations,
            "pass": not grow.check(),
        },
        "pass": not (device.check() or drain.check() or grow.check()),
    }


def default_hang_schedule(seed: int) -> FaultSchedule:
    """The hang acceptance recipe: ONE whole-gang wedge, gated on the
    first checkpoint (warm recovery + at least one telemetry flush per
    rank before progress freezes). Pure function of the seed."""
    return FaultSchedule.generate_hang(seed, first_step=2, spread_s=0.0)


@dataclass
class HangSoakResult:
    """Observations of one hang soak (see check for the gates)."""

    succeeded: bool = False
    hang_count: int = 0
    restart_count: int = 0
    preemption_count: int = 0
    last_restart_cause: str = ""
    conditions: List[tuple] = field(default_factory=list)
    applied: List[dict] = field(default_factory=list)
    schedule: Optional[FaultSchedule] = None
    resume_steps: List[int] = field(default_factory=list)
    partial_gang_violations: List[str] = field(default_factory=list)
    # Hang spans from the trace: stuck step + measured downtime (span
    # start is BACKDATED to when progress stopped; close is gang-RUNNING
    # again — the span width IS the wedge window as charged to goodput).
    hang_windows: List[dict] = field(default_factory=list)
    # Declaration latency: stackdump_directive["time"] (when the
    # reconciler declared HUNG) minus the hang span's backdated start
    # (when progress actually stopped). >= hang_timeout by construction;
    # the gate bounds the slack above it.
    detect_latency_s: Optional[float] = None
    directive_epoch: int = 0
    ack_ranks: List[str] = field(default_factory=list)
    # The frozen bundle's payload (None = never frozen) and the shipped
    # per-rank stack dumps.
    bundle: Optional[Dict[str, Any]] = None
    bundle_reason: str = ""
    stackdumps: List[dict] = field(default_factory=list)
    goodput_scraped: bool = False
    lost_seconds: Dict[str, float] = field(default_factory=dict)
    workers: int = 0
    hang_timeout_s: float = 0.0
    detect_bound_s: float = 10.0
    downtime_bound_s: float = 60.0

    WEDGE_FRAME = "_fake_collective_all_reduce"

    def check(self) -> List[str]:
        errs = []
        if not self.succeeded:
            errs.append(f"job did not succeed: {self.conditions}")
        sched_kinds = [
            f.kind.value for f in (self.schedule.faults if self.schedule else ())
        ]
        applied_kinds = [a["kind"] for a in self.applied]
        if applied_kinds != sched_kinds:
            errs.append(
                f"applied fault sequence {applied_kinds} != schedule "
                f"{sched_kinds}"
            )
        if self.hang_count != 1:
            errs.append(
                f"hang_count {self.hang_count} != 1 (one wedge must yield "
                "exactly one declaration — the verdict latch failed)"
            )
        # Cause attribution: a hang restart is charged to restart_count
        # under ON_FAILURE (it consumes backoff budget) with the hang
        # cause, and it never reads as a preemption.
        if self.restart_count != 1 or self.last_restart_cause != "hang":
            errs.append(
                f"hang recovery miscounted: restart_count="
                f"{self.restart_count} last_restart_cause="
                f"{self.last_restart_cause!r} (want 1 / 'hang')"
            )
        if self.preemption_count:
            errs.append(
                f"hang leaked into preemption_count={self.preemption_count}"
            )
        if self.partial_gang_violations:
            errs.append(f"partial gang persisted: {self.partial_gang_violations}")
        # Detection bound: declared within hang_timeout + slack of the
        # moment progress stopped.
        if self.detect_latency_s is None:
            errs.append("no detection latency measurable (no declaration)")
        elif not (
            self.hang_timeout_s - 0.5
            <= self.detect_latency_s
            <= self.hang_timeout_s + self.detect_bound_s
        ):
            errs.append(
                f"detection latency {self.detect_latency_s:.2f}s outside "
                f"[{self.hang_timeout_s:.1f}, "
                f"{self.hang_timeout_s + self.detect_bound_s:.1f}]s"
            )
        # The wedge window, from the trace: exactly one hang span, closed
        # (the gang came back RUNNING), at least the timeout wide, under
        # the bound.
        if len(self.hang_windows) != 1:
            errs.append(f"expected exactly one hang span: {self.hang_windows}")
        for w in self.hang_windows:
            if w.get("downtime_s") is None:
                errs.append(f"hang span never closed: {w}")
            elif w["downtime_s"] > self.downtime_bound_s:
                errs.append(
                    f"hang downtime {w['downtime_s']:.1f}s exceeds bound "
                    f"{self.downtime_bound_s:.0f}s: {w}"
                )
            elif w["downtime_s"] < self.hang_timeout_s - 0.5:
                errs.append(
                    f"hang span {w['downtime_s']:.1f}s narrower than the "
                    f"timeout {self.hang_timeout_s:.1f}s — the start was "
                    "not backdated to when progress stopped"
                )
        # Warm recovery: the post-hang incarnation resumed, not retrained.
        if not any(s > 0 for s in self.resume_steps):
            errs.append(
                f"no warm restart observed (resume steps {self.resume_steps})"
            )
        # Bundle completeness: frozen with reason=hang, every rank's
        # stack present and naming the wedged frame, last telemetry
        # windows and the open hang span captured in the scene.
        if self.bundle is None:
            errs.append("no postmortem bundle was frozen")
        else:
            if self.bundle_reason != "hang":
                errs.append(f"bundle reason {self.bundle_reason!r} != 'hang'")
            stacks = self.bundle.get("stackdumps", [])
            got_ranks = sorted(int(s.get("rank", -1)) for s in stacks)
            if got_ranks != list(range(self.workers)):
                errs.append(
                    f"bundle stack ranks {got_ranks} != all ranks "
                    f"{list(range(self.workers))}"
                )
            for s in stacks:
                if self.WEDGE_FRAME not in s.get("text", ""):
                    errs.append(
                        f"rank {s.get('rank')} stack does not name the "
                        f"wedged frame {self.WEDGE_FRAME!r}"
                    )
            if not self.bundle.get("telemetry"):
                errs.append("bundle has no last-telemetry windows")
            if not any(
                sp.get("op") == "hang" and sp.get("open")
                for sp in self.bundle.get("spans", [])
            ):
                errs.append(
                    "bundle spans do not include the open hang span "
                    "(the scene was frozen after recovery, not before)"
                )
        # One hang ⇒ one stack sweep: every shipped dump belongs to the
        # single directive epoch, exactly one per rank.
        epochs = sorted({d["epoch"] for d in self.stackdumps})
        if self.stackdumps and epochs != [self.directive_epoch]:
            errs.append(
                f"stack dumps span sweep epochs {epochs} "
                f"(directive epoch {self.directive_epoch}) — sweep dedup "
                "failed"
            )
        if len(self.stackdumps) != self.workers:
            errs.append(
                f"{len(self.stackdumps)} stack dumps shipped for "
                f"{self.workers} ranks"
            )
        # Goodput attribution: the wedge window lands under
        # lost_seconds{cause="hang"} within 5%, with ZERO leakage into
        # the restart/resize causes (a hang recovery opens no restart
        # span).
        if self.goodput_scraped:
            expected = sum(
                w["downtime_s"] for w in self.hang_windows
                if w.get("downtime_s") is not None
            )
            got = self.lost_seconds.get("hang", 0.0)
            if expected > 0 and abs(got - expected) > max(0.5, 0.05 * expected):
                errs.append(
                    f"lost_seconds{{cause=hang}} {got:.2f}s != hang-window "
                    f"downtime {expected:.2f}s (±5%)"
                )
            for leak in ("restart", "preemption", "resize", "resize-shrink",
                         "resize-grow"):
                if self.lost_seconds.get(leak, 0.0) > 0:
                    errs.append(
                        f"hang downtime leaked into cause={leak}: "
                        f"{self.lost_seconds}"
                    )
        return errs


def run_hang_soak(
    seed: int = 0,
    schedule: Optional[FaultSchedule] = None,
    hosts: int = 2,
    num_hosts: int = 2,
    workers: int = 2,
    steps: int = 10,
    checkpoint_every: int = 2,
    backoff_limit: int = 2,
    hang_timeout: float = 4.0,
    timeout: float = 150.0,
    workdir: Optional[str] = None,
    heartbeat_ttl: float = 3.0,
    step_sleep_s: float = 0.4,
    detect_bound_s: float = 10.0,
    downtime_bound_s: float = 60.0,
) -> HangSoakResult:
    """Seeded whole-gang-wedge soak (the r15 acceptance rig).

    A HANG fault wedges every rank inside a named fake collective while
    heartbeats stay live. The gates: the watchdog declares within bound,
    the SIGUSR2 sweep ships every rank's stack naming the wedged frame,
    the bundle freezes the scene BEFORE recovery destroys it, the victim
    warm-resumes to Succeeded with ``last_restart_cause=hang`` and the
    restart charged per ON_FAILURE, and goodput attributes the wedge
    window to ``cause="hang"`` with zero leakage into restart/resize."""
    from tf_operator_tpu.obs.blackbox import job_stackdumps, load_postmortem

    schedule = (
        schedule if schedule is not None else default_hang_schedule(seed)
    )
    tmp = workdir or tempfile.mkdtemp(prefix="tpujob-hang-soak-")
    ckpt_dir = os.path.join(tmp, "ckpt")
    job_name = "soak-hang"

    store = Store()
    injector = ChaosInjector(
        schedule, store, job_name=job_name, checkpoint_dir=ckpt_dir,
    )
    agents = [
        HostAgent(
            injector.wrap(),
            f"soak-h{i}",
            total_chips=workers,
            heartbeat_interval=0.25,
            backend=LocalProcessControl(
                injector.wrap(), log_dir=os.path.join(tmp, "logs")
            ),
            stackdump_dir=os.path.join(tmp, "stackdumps", f"soak-h{i}"),
        )
        for i in range(hosts)
    ]
    injector.agents = {a.name: a for a in agents}
    fake = FakeProcessControl()
    ctl = TPUJobController(store, fake, resync_period=0.5)
    ctl.scheduler.heartbeat_ttl = heartbeat_ttl
    from tf_operator_tpu.dashboard import DashboardServer

    dashboard = DashboardServer(store, host="127.0.0.1", port=0)
    dashboard.start()
    ctl.api_url = dashboard.url

    job = _soak_job(
        job_name, workers, num_hosts, ckpt_dir, steps, checkpoint_every,
        backoff_limit, heartbeat_ttl, data_plane="light",
        step_sleep_s=step_sleep_s,
    )
    job.spec.run_policy.hang_timeout_seconds = hang_timeout

    gang_names = [f"{job_name}-worker-{i}" for i in range(workers)]
    watcher = _InvariantWatcher(store, job_name, gang_names)
    result = HangSoakResult(
        schedule=schedule, workers=workers, hang_timeout_s=hang_timeout,
        detect_bound_s=detect_bound_s, downtime_bound_s=downtime_bound_s,
    )
    for a in agents:
        a.start()
    ctl.run(workers=2)
    watcher.start()
    try:
        store.create(job)
        injector.arm()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = store.get("TPUJob", "default", job_name).status
            if is_finished(st) and injector.done:
                break
            time.sleep(0.25)
        st = store.get("TPUJob", "default", job_name).status
        result.succeeded = has_condition(st, ConditionType.SUCCEEDED)
        result.hang_count = st.hang_count
        result.restart_count = st.restart_count
        result.preemption_count = st.preemption_count
        result.last_restart_cause = st.last_restart_cause
        result.conditions = [
            (c.type.value, c.reason, c.message) for c in st.conditions
        ]
        directive = st.stackdump_directive or {}
        result.directive_epoch = int(directive.get("epoch", 0) or 0)
        result.ack_ranks = sorted((directive.get("acks") or {}).keys())
        trace = job_trace(store, "default", job_name)
        result.hang_windows = [
            {
                "stuck_step": s.attrs.get("stuck_step", ""),
                "start": s.start_time,
                "downtime_s": (
                    round(s.end_time - s.start_time, 3) if s.end_time else None
                ),
            }
            for s in trace if s.op == "hang"
        ]
        declared_at = float(directive.get("time", 0.0) or 0.0)
        hang_starts = [s.start_time for s in trace if s.op == "hang"]
        if declared_at and hang_starts:
            result.detect_latency_s = round(declared_at - min(hang_starts), 3)
        bundle = load_postmortem(store, "default", job_name)
        if bundle is not None:
            result.bundle = bundle.payload
            result.bundle_reason = bundle.reason
        result.stackdumps = [
            {
                "rank": d.rank, "epoch": d.epoch,
                "host": d.payload.get("host", ""),
                "names_wedge_frame": (
                    HangSoakResult.WEDGE_FRAME in d.payload.get("text", "")
                ),
            }
            for d in job_stackdumps(store, "default", job_name)
        ]
        result.lost_seconds = _scrape_lost_seconds(ctl.metrics)
        result.goodput_scraped = True
    finally:
        injector.stop()
        watcher.stop()
        ctl.stop()
        for a in agents:
            a.stop()
        dashboard.stop()
        fake.clear()
    result.resume_steps = list(watcher.resume_steps)
    result.partial_gang_violations = list(watcher.violations)
    result.applied = list(injector.applied)
    leaked = [p.metadata.name for p in fake.created]
    if leaked:
        result.partial_gang_violations.append(
            "controller launched through its own backend in managed mode: "
            f"{leaked}"
        )
    return result


def hang_artifact(result: HangSoakResult, seed: int) -> Dict[str, Any]:
    """The hangbench receipt (one JSON object; CI writes it to
    ``artifacts/hangbench_r15.json``)."""
    downtimes = [
        w["downtime_s"] for w in result.hang_windows
        if w.get("downtime_s") is not None
    ]
    return {
        "bench": "hang-soak",
        "seed": seed,
        "hang_timeout_s": result.hang_timeout_s,
        "hangs_total": result.hang_count,
        "detect_latency_s": result.detect_latency_s,
        "hang_windows": result.hang_windows,
        "hang_downtime_p50_s": _percentile(downtimes, 0.5),
        "wedge_frame": HangSoakResult.WEDGE_FRAME,
        "stackdumps": result.stackdumps,
        "all_ranks_named_wedge_frame": (
            len(result.stackdumps) == result.workers
            and all(d["names_wedge_frame"] for d in result.stackdumps)
        ),
        "bundle_frozen": result.bundle is not None,
        "bundle_reason": result.bundle_reason,
        "resume_steps": result.resume_steps,
        "restart_count": result.restart_count,
        "last_restart_cause": result.last_restart_cause,
        "lost_seconds": {
            k: round(v, 3) for k, v in sorted(result.lost_seconds.items())
        },
        "applied": result.applied,
        "pass": not result.check(),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tpujob-soak", description="seeded chaos soak runner"
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--hosts", type=int, default=3)
    p.add_argument("--num-hosts", type=int, default=2)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--checkpoint-every", type=int, default=2)
    p.add_argument("--backoff-limit", type=int, default=2)
    p.add_argument("--timeout", type=float, default=420.0)
    p.add_argument("--workdir", default=None)
    p.add_argument("--data-plane", choices=("light", "lm"), default="light",
                   help="'light' = real checkpoints, no cross-process "
                        "collectives (works everywhere); 'lm' = full gloo "
                        "LM trainer (needs multi-process-capable jax)")
    p.add_argument("--step-sleep", type=float, default=1.0,
                   help="light data plane: seconds per step (the fault "
                        "landing window)")
    p.add_argument("--downtime-bound", type=float, default=60.0,
                   help="max allowed preemption recovery downtime "
                        "(seconds), asserted from the trace's restart "
                        "spans (invariant 6)")
    p.add_argument("--operator-crash", action="store_true",
                   help="crash-recovery mode: the operator (durable store "
                        "+ controller + API) is killed and restarted "
                        "mid-run by a scheduled OPERATOR_CRASH fault while "
                        "agents ride RemoteStore retries; adds the "
                        "zero-duplicate-creates and restart-in-trace "
                        "invariants")
    p.add_argument("--p2p", action="store_true",
                   help="peer warm-restore mode: agents run host-lifetime "
                        "shard depots; invariant 9 requires >=1 restart to "
                        "restore from a peer, and recovery downtime is "
                        "measured through the restore span (effective)")
    p.add_argument("--disk-restore-delay", type=float, default=0.0,
                   help="modeled slow-store read (seconds) a DISK restore "
                        "pays in the light data plane; the peer path "
                        "skips it")
    p.add_argument("--compare-restore", action="store_true",
                   help="run the same seed twice (disk-only baseline, then "
                        "p2p) and assert the p2p effective-downtime p50 "
                        "cuts the disk baseline by >2x; writes "
                        "restore-compare.json under --workdir")
    p.add_argument("--elastic", action="store_true",
                   help="elastic soak: seeded kill/return schedule over an "
                        "elastic job — member loss must shrink (never full "
                        "restart), host return must re-grow, the consumed "
                        "stream must be bit-identical to an uninterrupted "
                        "run, and >=1 resize must restore from a peer depot")
    p.add_argument("--hang", action="store_true",
                   help="hang soak: a HANG fault wedges every rank inside "
                        "a fake collective (heartbeats stay live); gates "
                        "watchdog detection latency, the SIGUSR2 stack "
                        "sweep naming the wedged frame on every rank, the "
                        "frozen postmortem bundle, warm recovery with "
                        "last_restart_cause=hang, and goodput attribution "
                        "of the wedge window to cause=hang")
    p.add_argument("--hang-timeout", type=float, default=4.0,
                   help="hang soak: run_policy.hang_timeout_seconds")
    p.add_argument("--detect-bound", type=float, default=10.0,
                   help="hang soak: max allowed slack (seconds) of the "
                        "declaration past the hang timeout")
    p.add_argument("--autopilot-ab", action="store_true",
                   help="goodput-autopilot A/B soak: the same seed and "
                        "fault schedule run twice (run_policy.autopilot "
                        "off, then on); gates autopilot-on goodput_ratio "
                        ">= --min-goodput-gain x the off lane, the "
                        "per-decision span receipts, and the per-cause "
                        "lost-seconds == own-span-widths ledger invariant")
    p.add_argument("--min-goodput-gain", type=float, default=1.10,
                   help="autopilot A/B: required on/off goodput_ratio "
                        "multiple")
    p.add_argument("--save-stall-extra", type=float, default=0.8,
                   help="autopilot A/B: modeled per-save blocking stall "
                        "(seconds) the cadence retune amortizes")
    p.add_argument("--fleet-ledger", action="store_true",
                   help="fleet-ledger soak (r18): seeded crash-faulted "
                        "history jobs fold into a durable FleetLedger; "
                        "gates byte-identical /api/fleet/summary across an "
                        "operator kill+restart, record survival across job "
                        "GC, telemetry-coalesced WAL accounting, and the "
                        "prior A/B — a fresh job with use_fleet_priors "
                        "must make its FIRST cadence decision within 1.5x "
                        "of the converged Young/Daly optimum (receipted "
                        "with the prior numbers) while the no-prior lane "
                        "sits at the clamp edge")
    p.add_argument("--elastic-general", action="store_true",
                   help="composed r19 elastic acceptance: (1) the "
                        "kill/return soak with a REAL device param/opt "
                        "pytree re-sharded through every resize "
                        "(bit-identical final params), (2) a fleet "
                        "preemption stamped mid-shrink under a "
                        "store-audited Queue (drain deferred, zero quota "
                        "violations), (3) the grow-beyond-spec probe "
                        "(world past spec on loaned chips, clean "
                        "first-reclaim under pressure)")
    p.add_argument("--kills", type=int, default=2,
                   help="elastic soak: number of kill/return faults")
    p.add_argument("--total-windows", type=int, default=900,
                   help="elastic soak: corpus positions to consume")
    p.add_argument("--artifact", default=None,
                   help="elastic soak: also write the bench receipt JSON "
                        "to this path")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s [%(levelname)s] %(message)s",
        stream=sys.stderr,
    )

    def one(p2p: bool, workdir: Optional[str]) -> SoakResult:
        return run_soak(
            seed=args.seed, steps=args.steps, hosts=args.hosts,
            num_hosts=args.num_hosts, workers=args.workers,
            checkpoint_every=args.checkpoint_every,
            backoff_limit=args.backoff_limit, timeout=args.timeout,
            workdir=workdir, data_plane=args.data_plane,
            step_sleep_s=args.step_sleep,
            downtime_bound_s=args.downtime_bound,
            operator_crash=args.operator_crash,
            p2p_restore=p2p, disk_restore_delay_s=args.disk_restore_delay,
        )

    def report(result: SoakResult, tag: str = "") -> List[str]:
        downtimes = [
            round(w["downtime_s"], 2) if w.get("downtime_s") is not None
            else None
            for w in result.restart_windows
        ]
        effective = [
            round(d, 2) if d is not None else None
            for d in result.effective_downtimes_s
        ]
        print(
            f"soak{tag} seed={args.seed}: succeeded={result.succeeded} "
            f"restarts={result.restart_count} "
            f"preemptions={result.preemption_count} "
            f"last_cause={result.last_restart_cause!r} "
            f"resume_steps={result.resume_steps} applied={result.applied} "
            f"trace_downtimes_s={downtimes} "
            f"effective_downtimes_s={effective} "
            f"restore_sources={result.restore_sources} "
            f"operator_restarts={result.operator_restarts} "
            f"gang_incarnations={result.gang_incarnations}"
        )
        errors = result.check()
        for e in errors:
            print(f"INVARIANT VIOLATED{tag}: {e}", file=sys.stderr)
        return errors

    if args.autopilot_ab:
        import json as _json

        # Deliberately NOT forwarding --steps/--step-sleep: the A/B's
        # lane geometry is sized so the recoverable stall dwarfs startup
        # noise (see run_autopilot_soak); the generic soak defaults
        # would shrink the signal into the noise floor.
        aresult = run_autopilot_soak(
            seed=args.seed,
            save_stall_extra_s=args.save_stall_extra,
            timeout=args.timeout, workdir=args.workdir,
            min_gain=args.min_goodput_gain,
        )
        artifact = autopilot_artifact(aresult, args.seed)
        print(_json.dumps(artifact))
        if args.artifact:
            os.makedirs(
                os.path.dirname(os.path.abspath(args.artifact)), exist_ok=True
            )
            with open(args.artifact, "w") as f:
                _json.dump(artifact, f, indent=2)
            print(f"autopilot A/B receipt -> {args.artifact}")
        errors = aresult.check()
        for e in errors:
            print(f"AUTOPILOT INVARIANT VIOLATED: {e}", file=sys.stderr)
        return 1 if errors else 0

    if args.fleet_ledger:
        import json as _json

        # Like --autopilot-ab, the lane geometry is deliberately NOT
        # driven by --steps/--step-sleep: the prior A/B needs the clamp
        # edge well clear of 1.5x the converged optimum.
        fresult = run_fleet_ledger_soak(
            seed=args.seed,
            save_stall_extra_s=args.save_stall_extra,
            timeout=args.timeout, workdir=args.workdir,
        )
        artifact = fleetledger_artifact(fresult, args.seed)
        print(_json.dumps(artifact))
        if args.artifact:
            os.makedirs(
                os.path.dirname(os.path.abspath(args.artifact)), exist_ok=True
            )
            with open(args.artifact, "w") as f:
                _json.dump(artifact, f, indent=2)
            print(f"fleet-ledger receipt -> {args.artifact}")
        errors = fresult.check()
        for e in errors:
            print(f"FLEET LEDGER INVARIANT VIOLATED: {e}", file=sys.stderr)
        return 1 if errors else 0

    if args.hang:
        import json as _json

        hresult = run_hang_soak(
            seed=args.seed, workers=args.workers, steps=args.steps,
            checkpoint_every=args.checkpoint_every,
            backoff_limit=args.backoff_limit,
            hang_timeout=args.hang_timeout, timeout=args.timeout,
            workdir=args.workdir, step_sleep_s=args.step_sleep,
            detect_bound_s=args.detect_bound,
            downtime_bound_s=args.downtime_bound,
        )
        artifact = hang_artifact(hresult, args.seed)
        print(_json.dumps(artifact))
        if args.artifact:
            os.makedirs(
                os.path.dirname(os.path.abspath(args.artifact)), exist_ok=True
            )
            with open(args.artifact, "w") as f:
                _json.dump(artifact, f, indent=2)
            print(f"hang soak receipt -> {args.artifact}")
        errors = hresult.check()
        for e in errors:
            print(f"HANG INVARIANT VIOLATED: {e}", file=sys.stderr)
        return 1 if errors else 0

    if args.elastic_general:
        import json as _json

        device, drain, grow = run_elastic_general_soak(
            seed=args.seed, workdir=args.workdir, timeout=args.timeout
        )
        artifact = elastic_general_artifact(device, drain, grow, args.seed)
        print(_json.dumps(artifact))
        if args.artifact:
            os.makedirs(
                os.path.dirname(os.path.abspath(args.artifact)), exist_ok=True
            )
            with open(args.artifact, "w") as f:
                _json.dump(artifact, f, indent=2)
            print(f"elastic-general receipt -> {args.artifact}")
        errors = []
        for tag, errs in (
            ("device-state", device.check()),
            ("drain-during-shrink", drain.check()),
            ("grow-beyond-spec", grow.check()),
        ):
            for e in errs:
                print(
                    f"ELASTIC INVARIANT VIOLATED [{tag}]: {e}",
                    file=sys.stderr,
                )
                errors.append(e)
        return 1 if errors else 0

    if args.elastic:
        import json as _json

        eresult = run_elastic_soak(
            seed=args.seed, kills=args.kills, workers=args.workers,
            total_windows=args.total_windows, timeout=args.timeout,
            workdir=args.workdir, backoff_limit=args.backoff_limit,
            downtime_bound_s=args.downtime_bound,
        )
        artifact = elastic_artifact(eresult, args.seed)
        print(_json.dumps(artifact))
        if args.artifact:
            os.makedirs(
                os.path.dirname(os.path.abspath(args.artifact)), exist_ok=True
            )
            with open(args.artifact, "w") as f:
                _json.dump(artifact, f, indent=2)
            print(f"elastic soak receipt -> {args.artifact}")
        errors = eresult.check()
        for e in errors:
            print(f"ELASTIC INVARIANT VIOLATED: {e}", file=sys.stderr)
        return 1 if errors else 0

    if not args.compare_restore:
        result = one(args.p2p, args.workdir)
        return 1 if report(result) else 0

    # Compare mode: same seed, same schedule, disk-only then p2p. The
    # disk baseline pays the modeled slow-store read on every restore;
    # the acceptance receipt is the p2p p50 cutting it by >2x.
    import json as _json

    root = args.workdir or tempfile.mkdtemp(prefix="tpujob-ckpt-soak-")
    disk = one(False, os.path.join(root, "disk"))
    errors = report(disk, tag="[disk]")
    p2p = one(True, os.path.join(root, "p2p"))
    errors += report(p2p, tag="[p2p]")

    def p50(xs: List[Optional[float]]) -> Optional[float]:
        vals = sorted(x for x in xs if x is not None)
        return vals[len(vals) // 2] if vals else None

    disk_p50, p2p_p50 = p50(disk.effective_downtimes_s), p50(
        p2p.effective_downtimes_s
    )
    if disk_p50 is None or p2p_p50 is None:
        errors.append(
            f"compare: missing effective downtimes (disk={disk_p50} "
            f"p2p={p2p_p50})"
        )
    elif not p2p_p50 * 2 < disk_p50:
        errors.append(
            f"compare: p2p effective-downtime p50 {p2p_p50:.2f}s does not "
            f"cut the disk baseline {disk_p50:.2f}s by >2x"
        )
    artifact = {
        "seed": args.seed,
        "disk_restore_delay_s": args.disk_restore_delay,
        "disk": {
            "effective_downtimes_s": disk.effective_downtimes_s,
            "restore_sources": disk.restore_sources,
            "p50_s": disk_p50,
        },
        "p2p": {
            "effective_downtimes_s": p2p.effective_downtimes_s,
            "restore_sources": p2p.restore_sources,
            "p50_s": p2p_p50,
        },
        "cut_factor": (
            disk_p50 / p2p_p50 if disk_p50 and p2p_p50 else None
        ),
        "pass": not errors,
    }
    path = os.path.join(root, "restore-compare.json")
    with open(path, "w") as f:
        _json.dump(artifact, f, indent=2)
    print(
        f"restore-compare: disk_p50={disk_p50} p2p_p50={p2p_p50} "
        f"cut={artifact['cut_factor']} -> {path}"
    )
    for e in errors:
        print(f"COMPARE FAILED: {e}", file=sys.stderr)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
