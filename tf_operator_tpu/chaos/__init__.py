"""Chaos subsystem: deterministic, seeded fault injection.

The reference operator's whole value is surviving failure, yet nothing in
it could *exercise* those paths on demand — its ``--chaos-level`` flag
shipped as a placeholder and our ChaosMonkey (cli/operator.py) is random,
so a failure found by soak cannot be replayed. This package supplies the
deterministic version:

- ``faults``   — declarative fault schedules (crash / preemption notice /
                 heartbeat stall / store latency / store error), seeded
                 generation: same seed ⇒ same schedule.
- ``injector`` — applies a schedule by wrapping the Store (ChaosStore)
                 and driving host agents / process backends; records the
                 applied sequence for replay assertions.
- ``soak``     — a runnable harness (``python -m tf_operator_tpu.chaos.soak``)
                 that stands up a multi-host local cluster, runs a real
                 checkpointing training job under a schedule, and asserts
                 the recovery invariants (job completes, no partial gang
                 persists, warm restarts resume monotonically, preemption
                 restarts never consume backoff).
"""

from tf_operator_tpu.chaos.faults import (  # noqa: F401
    Fault,
    FaultKind,
    FaultSchedule,
)
from tf_operator_tpu.chaos.injector import ChaosInjector, ChaosStore  # noqa: F401
