"""Declarative fault schedules.

A schedule is an ordered tuple of :class:`Fault` records. Each fault
declares *when* it fires — a minimum time since arm (``at_s``), an
optional checkpoint-progress gate (``at_step``: fire once the job's
checkpoint directory holds a step >= N), and an optional restart gate
(``after_restarts``: fire once the job has restarted N times, counting
preemptions) — and *what* it does:

- ``CRASH``            kill a running gang process so it exits ``exit_code``
                       (137 ⇒ SIGKILL, 143 ⇒ SIGTERM; store-mode targets
                       are marked Failed with the code directly)
- ``PREEMPT``          deliver a preemption notice to a host agent
                       (Host → DRAINING; the graceful drain path)
- ``STALL_HEARTBEAT``  freeze a host's heartbeat writes for ``duration_s``
                       (NodeLost detection path, host process untouched)
- ``STORE_LATENCY``    inject ``latency_s`` per store op for ``duration_s``
- ``STORE_ERROR``      make the next ``errors`` store ops raise
                       TransientStoreError (operator-restart blip)
- ``OPERATOR_CRASH``   kill and restart the operator itself (durable
                       store + controller + API server); requires the
                       injector to hold an operator handle

Faults fire strictly in schedule order (a fault waits for its
predecessors), so the *sequence* is deterministic even though wall-clock
firing times depend on job progress. Target selection is by deterministic
index over a sorted candidate list — no RNG at apply time. The only
randomness lives in :meth:`FaultSchedule.generate`, which derives a
schedule from a seed: same seed ⇒ identical schedule, which is what makes
a soak failure reproducible.
"""

from __future__ import annotations

import enum
import random
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, Tuple


class FaultKind(str, enum.Enum):
    CRASH = "crash"
    PREEMPT = "preempt"
    STALL_HEARTBEAT = "stall-heartbeat"
    STORE_LATENCY = "store-latency"
    STORE_ERROR = "store-error"
    # Kill and restart the OPERATOR itself (durable store + controller +
    # API) mid-run — the control-plane half of the failure matrix. Agents
    # ride RemoteStore retries/watch reconnects across the outage; the
    # restarted operator recovers from its --data-dir and re-adopts the
    # live gang (runtime/persist.py + controller.record_recovery).
    OPERATOR_CRASH = "operator-crash"
    # Elastic member churn: SIGKILL a non-chief gang process AND pause
    # its host's heartbeats so the reconciler sees a hard member loss
    # (not a clean exit), then — ``duration_s`` later — resume the
    # heartbeats so the host comes back and the returning member can be
    # re-created. On an elastic job this drives a shrink followed by a
    # symmetric re-grow instead of two full gang restarts.
    KILL_RETURN = "kill-return"
    # Whole-gang wedge (r15): drop a marker file into the job's checkpoint
    # directory; every COLD-incarnation gang member of the soak workload
    # checks for it each step and, on sight, blocks forever inside a named
    # fake collective (`_fake_collective_all_reduce`). Processes stay
    # alive and heartbeating — only step progress stops — which is exactly
    # the failure the hang watchdog exists to catch. Recovered (warm,
    # resume_step > 0) incarnations ignore the marker, so one fault is
    # one wedge.
    HANG = "hang"


# Marker-file name both sides of the HANG contract compute independently:
# the injector writes ``<checkpoint_dir>/WEDGE_MARKER``, the soak workload
# polls for it (workloads/soak.py).
WEDGE_MARKER = "chaos-wedge.marker"


@dataclass(frozen=True)
class Fault:
    """One scheduled fault. Only the fields relevant to ``kind`` are read."""

    kind: FaultKind
    # Trigger: all conditions must hold (and all earlier faults fired).
    at_s: float = 0.0          # min seconds since injector.arm()
    at_step: int = 0           # min checkpointed step in the job's ckpt dir
    after_restarts: int = 0    # min restart_count + preemption_count
    # Target: index into the sorted candidate list (processes for CRASH,
    # hosts for PREEMPT/STALL_HEARTBEAT); wraps modulo the list length.
    target: int = 0
    # CRASH
    exit_code: int = 137
    # STALL_HEARTBEAT / STORE_LATENCY window
    duration_s: float = 0.0
    # STORE_LATENCY per-op delay
    latency_s: float = 0.0
    # STORE_ERROR: number of consecutive ops to fail
    errors: int = 0

    def to_dict(self) -> Dict[str, Any]:
        d = asdict(self)
        d["kind"] = self.kind.value
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Fault":
        d = dict(d)
        d["kind"] = FaultKind(d["kind"])
        return Fault(**d)


@dataclass(frozen=True)
class FaultSchedule:
    """An ordered, reproducible fault sequence."""

    seed: int = 0
    faults: Tuple[Fault, ...] = field(default_factory=tuple)

    def to_dict(self) -> Dict[str, Any]:
        return {"seed": self.seed, "faults": [f.to_dict() for f in self.faults]}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "FaultSchedule":
        return FaultSchedule(
            seed=int(d.get("seed", 0)),
            faults=tuple(Fault.from_dict(f) for f in d.get("faults", [])),
        )

    @staticmethod
    def generate(
        seed: int,
        crashes: int = 1,
        preemptions: int = 1,
        stalls: int = 0,
        store_blips: int = 0,
        operator_crashes: int = 0,
        first_step: int = 2,
        spread_s: float = 20.0,
    ) -> "FaultSchedule":
        """Derive a schedule from ``seed`` — the soak's default recipe.

        Every fault is gated on checkpoint progress (``at_step >=
        first_step``) so recovery is always *warm*: a crash before the
        first checkpoint would legitimately resume from step 0 and the
        soak's resume-step assertions would be vacuous. Crashes come
        first, then operator crashes (the control plane dies over a live
        gang — deliberately before the preemptions so the RESTARTED
        controller must execute the graceful drain), then preemptions
        (each gated one restart later so they hit the post-crash gang),
        then stalls/blips. Operator crashes do not advance the restart
        gate: killing the control plane must not restart the job. Same
        seed ⇒ identical schedule; that plus in-order firing is the
        reproducibility contract."""
        rng = random.Random(seed)
        faults = []
        restarts_so_far = 0
        for _ in range(crashes):
            faults.append(
                Fault(
                    FaultKind.CRASH,
                    at_s=rng.uniform(0.0, spread_s),
                    at_step=first_step,
                    after_restarts=restarts_so_far,
                    target=rng.randrange(16),
                    # SIGKILL-shaped: a counted retryable failure
                    exit_code=137,
                )
            )
            restarts_so_far += 1
        for _ in range(operator_crashes):
            faults.append(
                Fault(
                    FaultKind.OPERATOR_CRASH,
                    at_s=rng.uniform(0.0, spread_s),
                    at_step=first_step,
                    after_restarts=restarts_so_far,
                )
            )
        for _ in range(preemptions):
            faults.append(
                Fault(
                    FaultKind.PREEMPT,
                    at_s=rng.uniform(0.0, spread_s),
                    at_step=first_step,
                    after_restarts=restarts_so_far,
                    target=rng.randrange(16),
                )
            )
            restarts_so_far += 1
        for _ in range(stalls):
            faults.append(
                Fault(
                    FaultKind.STALL_HEARTBEAT,
                    at_s=rng.uniform(0.0, spread_s),
                    at_step=first_step,
                    after_restarts=restarts_so_far,
                    target=rng.randrange(16),
                    duration_s=rng.uniform(5.0, 15.0),
                )
            )
            restarts_so_far += 1
        for _ in range(store_blips):
            faults.append(
                Fault(
                    FaultKind.STORE_ERROR,
                    at_s=rng.uniform(0.0, spread_s),
                    errors=rng.randint(1, 3),
                )
            )
        return FaultSchedule(seed=seed, faults=tuple(faults))

    @staticmethod
    def generate_elastic(
        seed: int,
        kills: int = 2,
        first_step: int = 1,
        spread_s: float = 12.0,
        return_after_s: Tuple[float, float] = (4.0, 9.0),
    ) -> "FaultSchedule":
        """Seeded kill/return schedule for the elastic soak.

        Every fault is KILL_RETURN: lose one non-chief member, get it
        back ``duration_s`` later. Gates are wall-clock + checkpoint
        progress only — ``after_restarts`` stays 0 because the whole
        point of an elastic job is that the restart counter never
        advances, so a restart-gated fault would wait forever. The
        injector resolves ``target`` over the sorted *non-chief*
        candidate list, so rank 0 is never the victim (losing the chief
        is a legitimate full restart, which the elastic soak forbids)."""
        rng = random.Random(seed)
        faults = []
        for _ in range(max(1, kills)):
            faults.append(
                Fault(
                    FaultKind.KILL_RETURN,
                    at_s=rng.uniform(0.0, spread_s),
                    at_step=first_step,
                    target=rng.randrange(16),
                    exit_code=137,
                    duration_s=rng.uniform(*return_after_s),
                )
            )
        return FaultSchedule(seed=seed, faults=tuple(faults))

    @staticmethod
    def generate_hang(
        seed: int,
        first_step: int = 2,
        spread_s: float = 2.0,
    ) -> "FaultSchedule":
        """Seeded schedule for the hang soak: ONE whole-gang wedge.

        Gated on checkpoint progress (``at_step``) for two reasons: the
        recovery must be *warm* (a pre-checkpoint wedge would resume from
        step 0 and the soak's resume assertions would be vacuous), and
        every rank must have flushed at least one telemetry batch before
        progress freezes — a watchdog staring at an empty ring is the
        TTFS-grace path, not the stall path under test."""
        rng = random.Random(seed)
        return FaultSchedule(
            seed=seed,
            faults=(
                Fault(
                    FaultKind.HANG,
                    at_s=rng.uniform(0.0, spread_s),
                    at_step=first_step,
                ),
            ),
        )
