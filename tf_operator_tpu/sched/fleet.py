"""FleetScheduler: multi-tenant admission in front of the GangScheduler.

The kube-batch/Volcano-shaped layer the reference design doc explicitly
left to kube-arbitrator (training.go:450-511 only writes a
PodDisruptionBudget and hopes). Responsibilities:

- **Admission**: a job must clear its Queue's chip/job quota before any
  placement happens. Over-quota jobs park in the QUEUED condition
  (ordered by (priority desc, submit time asc)) instead of hot-looping
  SchedulingError retries through the workqueue's rate limiter.
- **Preempt-by-priority**: a higher-priority job over quota (or without
  fleet capacity) picks the lowest-priority, newest admitted victims;
  the reconciler drains them through the PR 1 preemption lifecycle
  (cause ``preemption``: checkpoint warm-resume, never charged to
  backoff) rather than killing them.
- **Backfill without starvation**: the head-of-line gang that cannot
  place yet holds a host/chip reservation; smaller jobs may run only on
  capacity the reservation doesn't cover, so they fill fragmentation
  holes but can never delay the reserved gang.

Deliberately NOT implemented (see docs/design.md): fair-share / DRF
across queues, cross-queue quota borrowing, and preemption of
equal-priority jobs.

Concurrency: the scheduler is a plain mutable object with NO lock of its
own — every method is called under the controller's ``_sched_lock``,
which already serializes admission+placement+commit across sync workers
(that atomicity is what makes "usage never exceeds quota" a real
invariant rather than a race window).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from tf_operator_tpu.api.types import (
    KIND_PRIORITY_CLASS,
    JOB_CLASS_SERVING,
    KIND_PROCESS,
    KIND_QUEUE,
    KIND_TPUJOB,
    LABEL_JOB_NAME,
    ConditionType,
    JobPhase,
    TPUJob,
)
from tf_operator_tpu.runtime.store import NotFoundError
from tf_operator_tpu.sched.objects import Queue, job_demand

# Decision actions.
ADMIT = "admit"  # proceed to placement
WAIT = "wait"  # park in QUEUED; a release/resync will retry
FAIL = "fail"  # permanently unsatisfiable (demand > quota)
PREEMPT = "preempt"  # drain victims, then park until their chips free up
# Reclaim over-spec chips (r19): victims are elastic jobs that grew
# beyond spec; they shrink back through the resize protocol (no drain,
# no backoff charge) and their loaned chips return to the queue.
RECLAIM = "reclaim"

# Default TTL for elastic re-grow holds (r19 satellite): a hold whose
# lost host never returns converts back into ordinary free capacity
# after this long, instead of pinning fleet capacity forever.
DEFAULT_HOLD_TTL_SECONDS = 900.0

# PriorityClass objects are cluster-scoped in spirit; they live in this
# namespace and are resolved by name from any tenant namespace.
PRIORITY_CLASS_NAMESPACE = "default"

# Effective priority of a job_class="serving" job with no explicit
# PriorityClass: high enough to preempt any class-less training job
# (priority 0), low enough that a named class can rank above it.
SERVING_DEFAULT_PRIORITY = 100


@dataclass
class Decision:
    action: str
    reason: str = ""
    victims: List[str] = field(default_factory=list)  # TPUJob keys to drain


@dataclass
class _JobInfo:
    key: str
    namespace: str
    queue: str
    priority: int
    demand: int
    ctime: float

    def precedence(self) -> Tuple[int, float, str]:
        # Lower sorts first: priority desc, submit asc, name as tiebreak —
        # the admission-queue order (deterministic under equal scores).
        return (-self.priority, self.ctime, self.key)


class FleetScheduler:
    def __init__(self, store: Any, gang: Any) -> None:
        self.store = store
        self.gang = gang  # GangScheduler: capacity oracle for reservations
        self._admitted: Dict[str, _JobInfo] = {}
        self._queued: Dict[str, _JobInfo] = {}
        # (namespace, queue) -> [chips, jobs] held by admitted jobs.
        # Maintained incrementally so admit() never rescans the store.
        self._usage: Dict[Tuple[str, str], List[int]] = {}
        # Head-of-line capacity reservations: job key -> {host: chips}
        # held for a queued gang so backfillers can't starve it.
        self._reservations: Dict[str, Dict[str, int]] = {}
        # Preemption victims mid-drain: still admitted (their gang is
        # winding down, the chips are NOT free yet) but barred from
        # re-creating. release() is deferred until the reconciler
        # observes the drained gang gone — so victim and preemptor can
        # never hold the same quota headroom at once, even transiently.
        self._draining: set = set()
        # Elastic re-grow holds (r12): job key -> {host: chips} a SHRUNK
        # running job still claims for the members it lost. The job stays
        # admitted (quota held — release() is never called on a resize),
        # but placement-level capacity on the lost host would otherwise be
        # backfillable by other jobs, making the symmetric re-grow
        # impossible. Merged into reserved_for_others() for every OTHER
        # job; cleared when the gang re-grows or the job ends.
        self._regrow_holds: Dict[str, Dict[str, int]] = {}
        # When each job's re-grow hold was last (re)stamped; past
        # hold_ttl_seconds the hold expires into free capacity (r19).
        self._regrow_hold_since: Dict[str, float] = {}
        self.hold_ttl_seconds: float = DEFAULT_HOLD_TTL_SECONDS
        # Grow-beyond-spec loans (r19): job key -> extra chips this
        # elastic job holds ABOVE its spec demand. Charged to queue usage
        # while outstanding; the first thing any quota pressure reclaims.
        self._overspec: Dict[str, int] = {}
        # Autopilot host deprioritization (r16): host -> expiry timestamp.
        # A risk-flagged host (straggler tracker via the autopilot) is fed
        # into place_gang's deprioritized set fleet-wide — SOFT avoidance:
        # placement still uses the host when nothing else fits, exactly
        # like the reconciler's own slow-host set. TTL-bounded so a host
        # that was migrated away from (and therefore produces no further
        # telemetry to clear itself with) does not stay tainted forever.
        self._deprioritized_hosts: Dict[str, float] = {}
        self._synced = False

    # ---- store lookups --------------------------------------------------

    def priority_of(self, job: TPUJob) -> int:
        # job_class (r10): a "serving" job is latency-sensitive by
        # declaration — it outranks the priority-0 training baseline with
        # ZERO PriorityClass setup, so serve preempts training out of the
        # box (the victim drains + warm-resumes and later backfills the
        # serve-idle capacity). An explicit priority_class still wins —
        # operators can rank serve tiers or even park a serve job below
        # training by naming a class.
        base = (
            SERVING_DEFAULT_PRIORITY
            if getattr(job.spec.scheduling, "job_class", "") == JOB_CLASS_SERVING
            else 0
        )
        name = job.spec.scheduling.priority_class
        if not name:
            return base
        try:
            pc = self.store.get(KIND_PRIORITY_CLASS, PRIORITY_CLASS_NAMESPACE, name)
        except NotFoundError:
            return base  # missing class degrades to the class baseline
        return int(pc.value)

    def queue_for(self, job: TPUJob) -> Optional[Queue]:
        name = job.spec.scheduling.queue
        if not name:
            return None
        try:
            return self.store.get(KIND_QUEUE, job.metadata.namespace, name)
        except NotFoundError:
            return None  # unquota'd until the Queue object appears

    def _info(self, job: TPUJob) -> _JobInfo:
        return _JobInfo(
            key=job.key(),
            namespace=job.metadata.namespace,
            queue=job.spec.scheduling.queue,
            priority=self.priority_of(job),
            demand=job_demand(job),
            ctime=job.metadata.creation_timestamp or time.time(),
        )

    # ---- crash/restart resync -------------------------------------------

    def ensure_synced(self) -> None:
        """Rebuild admission state from the store on first use (covers
        controller restart): a job with live children is admitted and
        holds quota; a job parked in the QUEUED condition re-enters the
        queue with its original precedence (ctime is durable)."""
        if self._synced:
            return
        self._synced = True
        for job in self.store.list(KIND_TPUJOB):
            if _terminal(job):
                continue
            info = self._info(job)
            procs = self.store.list(
                KIND_PROCESS,
                namespace=job.metadata.namespace,
                label_selector={LABEL_JOB_NAME: job.metadata.name},
            )
            if any(not p.is_finished() for p in procs):
                self._commit(info)
            elif job.status.phase() is JobPhase.QUEUED:
                self._queued[info.key] = info

    # ---- bookkeeping ----------------------------------------------------

    def _commit(self, info: _JobInfo) -> None:
        if info.key in self._admitted:
            return
        self._queued.pop(info.key, None)
        self._reservations.pop(info.key, None)
        self._admitted[info.key] = info
        u = self._usage.setdefault((info.namespace, info.queue), [0, 0])
        u[0] += info.demand
        u[1] += 1

    def commit(self, job: TPUJob) -> None:
        """The gang placed and its processes are being created: its demand
        now counts against the queue quota. Idempotent."""
        self._commit(self._info(job))

    def begin_preempt(self, key: str) -> None:
        """First half of the preemption handoff: mark an admitted victim
        as draining. It keeps holding its quota (the gang still occupies
        chips) but admit() parks it instead of re-creating; the second
        half is release(), called once the gang is observably gone."""
        self.ensure_synced()  # the victim may predate any admit() call
        if key in self._admitted:
            self._draining.add(key)

    def draining(self, key: str) -> bool:
        return key in self._draining

    def hold_for_regrow(self, key: str, host_chips: Dict[str, int]) -> None:
        """A running elastic job shrank: keep claiming the lost members'
        per-host chips so the symmetric re-grow can place where the gang
        lost capacity. Accumulates across consecutive shrinks."""
        if not host_chips:
            return
        hold = self._regrow_holds.setdefault(key, {})
        for host, chips in host_chips.items():
            hold[host] = hold.get(host, 0) + max(int(chips), 0)
        self._regrow_hold_since[key] = time.time()

    def clear_regrow_hold(self, key: str) -> None:
        """The gang re-grew to full strength (or the job ended): stop
        claiming capacity for its lost members."""
        self._regrow_holds.pop(key, None)
        self._regrow_hold_since.pop(key, None)

    def expire_regrow_holds(self, now: Optional[float] = None) -> List[str]:
        """Drop holds older than ``hold_ttl_seconds`` (r19 satellite): a
        hold whose lost host never returns must not pin capacity forever.
        The job stays admitted and can still re-grow — it just competes
        for placement like any other gang. Returns the expired keys."""
        now = time.time() if now is None else now
        if self.hold_ttl_seconds <= 0:
            return []
        expired = [
            k
            for k, t in self._regrow_hold_since.items()
            if now - t > self.hold_ttl_seconds
        ]
        for k in expired:
            self._regrow_holds.pop(k, None)
            self._regrow_hold_since.pop(k, None)
        return expired

    # ---- grow-beyond-spec loans (r19) -----------------------------------

    def offer_grow(self, job: TPUJob, extra_chips: int) -> int:
        """Offer idle in-quota chips to a running elastic job so it can
        grow past its spec world size. Returns the chips granted (0 ⇒
        refused). Strictly after every queued admission: ANY queued job
        in the same (namespace, queue) vetoes the offer, so backfill
        growth can never starve the admission queue. Granted chips are
        charged to queue usage immediately and tracked as an over-spec
        loan — the first thing reclaimed under quota pressure."""
        self.ensure_synced()
        key = job.key()
        if extra_chips <= 0 or key in self._draining:
            return 0
        info = self._admitted.get(key)
        if info is None:
            return 0  # not admitted ⇒ nothing to grow
        if any(
            w.namespace == info.namespace and w.queue == info.queue
            for w in self._queued.values()
        ):
            return 0
        q = self.queue_for(job)
        if q is not None:
            quota = max(q.spec.quota_chips, 0)
            used, _ = self._usage.get((info.namespace, info.queue), (0, 0))
            if quota and used + extra_chips > quota:
                return 0
        u = self._usage.setdefault((info.namespace, info.queue), [0, 0])
        u[0] += extra_chips
        self._overspec[key] = self._overspec.get(key, 0) + extra_chips
        return extra_chips

    def reclaim_overspec(self, key: str, chips: Optional[int] = None) -> int:
        """Second half of a grow-beyond-spec reclaim: called once the
        over-spec processes are observably gone, returning their chips to
        the queue. Mirrors the begin_preempt→release two-phase handoff —
        quota is NOT freed at reclaim-request time, so a waiting admitter
        and the over-spec member can never hold the same headroom at
        once. ``chips`` limits the return to that many (the grow-rollback
        path returns only the chips it just borrowed); default is the
        whole loan. Returns the chips freed."""
        if chips is None:
            extra = self._overspec.pop(key, 0)
        else:
            extra = min(max(chips, 0), self._overspec.get(key, 0))
            left = self._overspec.get(key, 0) - extra
            if left > 0:
                self._overspec[key] = left
            else:
                self._overspec.pop(key, None)
        if not extra:
            return 0
        info = self._admitted.get(key)
        if info is not None:
            u = self._usage.get((info.namespace, info.queue))
            if u is not None:
                u[0] = max(0, u[0] - extra)
        return extra

    def overspec_chips(self, key: str) -> int:
        return self._overspec.get(key, 0)

    def deprioritize_host(self, host: str, until: float) -> None:
        """Autopilot actuator (r16): soft-avoid ``host`` for new gang
        placements until ``until`` (unix seconds). Re-flagging extends
        the window; the registry never hard-excludes a host. Callers
        hold the reconciler's scheduling lock, like every other method
        here."""
        if host:
            self._deprioritized_hosts[host] = max(
                until, self._deprioritized_hosts.get(host, 0.0)
            )

    def deprioritized_hosts(self, now: float) -> set:
        """Live (unexpired) deprioritized hosts; expired entries are
        dropped on read so the registry cannot grow unbounded."""
        expired = [
            h for h, t in self._deprioritized_hosts.items() if t <= now
        ]
        for h in expired:
            del self._deprioritized_hosts[h]
        return set(self._deprioritized_hosts)

    def release(self, key: str) -> bool:
        """Forget a job (finished / deleted / preempted). Returns True when
        it held quota — callers then kick the queue head."""
        self._draining.discard(key)
        self._regrow_holds.pop(key, None)
        self._regrow_hold_since.pop(key, None)
        self._queued.pop(key, None)
        self._reservations.pop(key, None)
        extra = self._overspec.pop(key, 0)  # loaned chips go back too
        info = self._admitted.pop(key, None)
        if info is None:
            return False
        u = self._usage.get((info.namespace, info.queue))
        if u is not None:
            u[0] = max(0, u[0] - info.demand - extra)
            u[1] = max(0, u[1] - 1)
        return True

    def next_queued(self, limit: int = 64) -> List[str]:
        """Top-of-queue job keys by precedence — the re-enqueue targets
        after quota or capacity was released."""
        order = sorted(self._queued.values(), key=lambda i: i.precedence())
        return [i.key for i in order[:limit]]

    def usage(self) -> Dict[Tuple[str, str], Tuple[int, int]]:
        """Snapshot of (namespace, queue) -> (chips, jobs) held by admitted
        jobs (metrics/CLI surface)."""
        return {k: (v[0], v[1]) for k, v in self._usage.items()}

    # ---- admission ------------------------------------------------------

    def admit(self, job: TPUJob) -> Decision:
        """Quota/priority gate, called before any placement. ADMIT means
        "may try to place now"; commit() only happens after placement
        succeeds, so a placement failure never leaks quota."""
        self.ensure_synced()
        key = job.key()
        if key in self._draining:
            # Preemption victim whose gang is still winding down: it must
            # not re-create (that would undo the eviction) and its quota
            # is not free yet. The post-drain release re-queues it.
            return Decision(
                WAIT, reason="preempted; re-queues once the drained gang exits"
            )
        if key in self._admitted:
            return Decision(ADMIT)
        info = self._info(job)
        q = self.queue_for(job)
        if q is None:
            return Decision(ADMIT)
        quota = max(q.spec.quota_chips, 0)
        max_jobs = max(q.spec.max_running_jobs, 0)
        if quota and info.demand > quota:
            # No amount of waiting or preemption can ever satisfy this.
            return Decision(
                FAIL,
                reason=(
                    f"demands {info.demand} chip(s) but queue {info.queue!r} "
                    f"quota is {quota} chip(s): unsatisfiable"
                ),
            )
        used, running = self._usage.get((info.namespace, info.queue), (0, 0))
        if (quota and used + info.demand > quota) or (
            max_jobs and running + 1 > max_jobs
        ):
            reclaims = self._overspec_reclaims(info, quota, max_jobs)
            if reclaims:
                self._queued[key] = info
                return Decision(
                    RECLAIM,
                    reason=(
                        f"over queue {info.queue!r} quota; reclaiming "
                        f"over-spec chips from {len(reclaims)} elastic "
                        "job(s)"
                    ),
                    victims=reclaims,
                )
            victims = self._quota_victims(info, quota, max_jobs)
            self._queued[key] = info
            if victims:
                return Decision(
                    PREEMPT,
                    reason=(
                        f"over queue {info.queue!r} quota; preempting "
                        f"{len(victims)} lower-priority job(s)"
                    ),
                    victims=victims,
                )
            return Decision(
                WAIT,
                reason=(
                    f"queue {info.queue!r} quota exhausted "
                    f"({used}/{quota or 'unlimited'} chips, "
                    f"{running} running job(s))"
                ),
            )
        blocker = self._head_blocker(info, quota, used)
        if blocker is not None:
            self._queued[key] = info
            return Decision(
                WAIT,
                reason=(
                    f"behind higher-precedence queued job {blocker} "
                    "(admitting now would delay its quota headroom)"
                ),
            )
        return Decision(ADMIT)

    def _overspec_reclaims(
        self, info: _JobInfo, quota: int, max_jobs: int
    ) -> List[str]:
        """Over-spec loans are the FIRST thing quota pressure reclaims
        (r19): before any preemption, ask same-queue elastic jobs that
        grew beyond spec to shrink back. Any-priority — a loaned chip is
        not an entitlement. Returned only when the reclaimed chips alone
        bring the queue under quota for ``info``; otherwise the caller
        falls through to preempt-by-priority (the next admit pass
        composes both once reclaims complete)."""
        used, running = self._usage.get((info.namespace, info.queue), (0, 0))
        if max_jobs and running + 1 > max_jobs:
            return []  # a reclaim frees chips, never a job slot
        if not quota:
            return []
        cands = [
            (k, extra)
            for k, extra in self._overspec.items()
            if extra > 0
            and k != info.key
            and k not in self._draining
            and k in self._admitted
            and self._admitted[k].namespace == info.namespace
            and self._admitted[k].queue == info.queue
        ]
        # Lowest-priority, newest first — the preemption order, applied
        # among the loans themselves.
        cands.sort(
            key=lambda kv: (
                self._admitted[kv[0]].priority,
                -self._admitted[kv[0]].ctime,
                kv[0],
            )
        )
        keys: List[str] = []
        for k, extra in cands:
            if used + info.demand <= quota:
                break
            keys.append(k)
            used -= extra
        return keys if keys and used + info.demand <= quota else []

    def _quota_victims(
        self, info: _JobInfo, quota: int, max_jobs: int
    ) -> List[str]:
        """Lowest-priority-NEWEST admitted jobs in the same queue whose
        eviction brings the queue under quota for ``info``. Empty when no
        strictly-lower-priority set suffices (equal priority never
        preempts — the job just waits)."""
        cands = [
            a
            for a in self._admitted.values()
            if a.namespace == info.namespace
            and a.queue == info.queue
            and a.priority < info.priority
            # A victim already draining is spoken for: its chips free up
            # when its drain completes, so evicting it "again" would
            # double-promise the same headroom (and churn events).
            and a.key not in self._draining
        ]
        cands.sort(key=lambda a: (a.priority, -a.ctime, a.key))
        used, running = self._usage.get((info.namespace, info.queue), (0, 0))

        def fits() -> bool:
            return (not quota or used + info.demand <= quota) and (
                not max_jobs or running + 1 <= max_jobs
            )

        victims: List[str] = []
        for a in cands:
            if fits():
                break
            victims.append(a.key)
            # Eviction releases the victim's spec demand AND any
            # over-spec loan it still holds (release() returns both).
            used -= a.demand + self._overspec.get(a.key, 0)
            running -= 1
        return victims if victims and fits() else []

    def _head_blocker(
        self, info: _JobInfo, quota: int, used: int
    ) -> Optional[str]:
        """First queued same-queue job with higher precedence that
        admitting ``info`` would delay. Backfill rule: ``info`` may jump
        the line only when the quota holds BOTH it and every job ahead of
        it — the blocker's headroom stays intact."""
        if not quota:
            return None  # no chip quota => admission can't delay anyone
        for w in sorted(self._queued.values(), key=lambda i: i.precedence()):
            if (
                w.key == info.key
                or w.namespace != info.namespace
                or w.queue != info.queue
            ):
                continue
            if w.precedence() < info.precedence():
                if used + info.demand + w.demand > quota:
                    return w.key
        return None

    # ---- capacity: reservations + fleet-wide preemption -----------------

    def on_unplaceable(self, job: TPUJob) -> Decision:
        """The gang cleared quota but had no atomic placement. Either
        preempt lower-priority placed jobs (their per-host chips become
        this job's reservation) or reserve the best candidate hosts and
        wait. Both park the job; a release or resync retries it."""
        self.ensure_synced()
        key = job.key()
        info = self._queued.get(key) or self._info(job)
        self._queued[key] = info
        victims = self._capacity_victims(info)
        if victims:
            reservation: Dict[str, int] = {}
            for _, hosts in victims:
                for host, chips in hosts.items():
                    reservation[host] = reservation.get(host, 0) + chips
            self._reservations[key] = reservation
            return Decision(
                PREEMPT,
                reason=(
                    f"no capacity; preempting {len(victims)} lower-priority "
                    "job(s) fleet-wide"
                ),
                victims=[vkey for vkey, _ in victims],
            )
        if key not in self._reservations:
            res = self._head_reservation(job, info)
            if res:
                self._reservations[key] = res
        return Decision(WAIT, reason="waiting for fleet capacity")

    def reserved_for_others(self, job: TPUJob) -> Dict[str, int]:
        """Chips on each host held for queued jobs with precedence over
        ``job`` — the placement subtracts them from free capacity, so a
        backfilling job fits only into holes the reserved gangs don't
        need (no starvation of the head of line). Elastic re-grow holds
        (r12) merge in unconditionally for every OTHER job, regardless of
        precedence: the shrunk job's quota is still charged for those
        chips, so letting anyone backfill them would double-book."""
        self.ensure_synced()
        self.expire_regrow_holds()
        mine = job.key()
        merged: Dict[str, int] = {}
        for key, hold in self._regrow_holds.items():
            if key == mine:
                continue
            for host, chips in hold.items():
                merged[host] = merged.get(host, 0) + chips
        if not self._reservations:
            return merged
        prec = (
            self._queued[mine].precedence()
            if mine in self._queued
            else self._info(job).precedence()
        )
        for key, res in self._reservations.items():
            w = self._queued.get(key)
            if key == mine or w is None or not (w.precedence() < prec):
                continue
            for host, chips in res.items():
                merged[host] = merged.get(host, 0) + chips
        return merged

    def _victim_hosts(self, info: _JobInfo) -> Dict[str, int]:
        """Per-host live chips of an admitted job (label-indexed list)."""
        ns, _, name = info.key.partition("/")
        hosts: Dict[str, int] = {}
        for p in self.store.list(
            KIND_PROCESS, namespace=ns, label_selector={LABEL_JOB_NAME: name}
        ):
            if p.spec.node_name and not p.is_finished():
                hosts[p.spec.node_name] = hosts.get(p.spec.node_name, 0) + max(
                    p.spec.chips, 0
                )
        return hosts

    def _capacity_victims(
        self, info: _JobInfo
    ) -> List[Tuple[str, Dict[str, int]]]:
        """Fleet-wide preempt-by-priority: lowest-priority-newest admitted
        jobs with live placements, accumulated until the chips they free
        cover the gang's demand. Approximate on purpose: placement
        re-verifies per-host fit after the drain, and the next pass picks
        more victims if fragmentation still blocks."""
        if info.priority <= 0 and not any(
            a.priority < info.priority for a in self._admitted.values()
        ):
            return []
        cands = [a for a in self._admitted.values() if a.priority < info.priority]
        cands.sort(key=lambda a: (a.priority, -a.ctime, a.key))
        # Chips held for another job's re-grow are NOT preemptable
        # headroom (r19): draining a victim on a held host hands the
        # freed chips straight to the hold, not to this gang. Discount
        # them so victims keep accumulating until genuinely-free chips
        # cover the demand (conservative: placement re-verifies anyway).
        self.expire_regrow_holds()
        held: Dict[str, int] = {}
        for hkey, hold in self._regrow_holds.items():
            if hkey == info.key:
                continue
            for host, chips in hold.items():
                held[host] = held.get(host, 0) + chips
        victims: List[Tuple[str, Dict[str, int]]] = []
        freed = 0
        need = max(info.demand, 1)
        for a in cands:
            if freed >= need:
                break
            hosts = self._victim_hosts(a)
            if not hosts:
                continue
            victims.append((a.key, hosts))
            for host, chips in hosts.items():
                absorbed = min(chips, held.get(host, 0))
                if absorbed:
                    held[host] -= absorbed
                freed += chips - absorbed
        return victims if victims and freed >= need else []

    def _head_reservation(self, job: TPUJob, info: _JobInfo) -> Dict[str, int]:
        """Hold the emptiest hosts this gang will need so smaller jobs
        backfill AROUND them — without this, a stream of small admits
        could consume every hole and starve the large gang forever."""
        want = max(1, job.spec.topology.num_hosts)
        if not info.demand:
            return {}
        per_host = -(-info.demand // want)  # ceil
        states = self.gang.host_states(job.spec.topology.slice_type)
        states.sort(key=lambda s: (-s.free_chips, s.host.metadata.name))
        return {s.host.metadata.name: per_host for s in states[:want]}


def _terminal(job: TPUJob) -> bool:
    for c in job.status.conditions:
        if c.status and c.type in (ConditionType.SUCCEEDED, ConditionType.FAILED):
            return True
    return False
