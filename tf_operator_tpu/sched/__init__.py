"""Fleet scheduler: multi-tenant priority/quota admission, preemption and
topology packing in front of the gang scheduler (see docs/design.md
§"Fleet scheduling")."""

from tf_operator_tpu.sched.objects import (  # noqa: F401
    PriorityClass,
    Queue,
    QueueSpec,
    job_demand,
)
from tf_operator_tpu.sched.fleet import (  # noqa: F401
    Decision,
    FleetScheduler,
)
