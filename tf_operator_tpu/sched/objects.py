"""Fleet-scheduler store objects: PriorityClass and Queue.

Reference parity: the reference operator punted multi-job scheduling to
kube-arbitrator behind a PodDisruptionBudget (pkg/trainer/
training.go:450-511) — there is no in-tree priority or quota object.
These two kinds are the kube-batch/Volcano-shaped replacement: a
cluster-level priority band and a per-namespace admission queue with a
chip/job quota. Both ride the generic store/API seam exactly like Spans
(runtime/serialize.py registers decoders; the dashboard serves CRUD at
/api/v1/{kind}).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tf_operator_tpu.api.types import (
    KIND_PRIORITY_CLASS,
    KIND_QUEUE,
    ObjectMeta,
    ReplicaType,
    TPUJob,
)


@dataclass
class PriorityClass:
    """Cluster-level priority band (k8s PriorityClass analogue).

    Stored in the "default" namespace by convention and resolved by NAME
    from any job's ``spec.scheduling.priority_class``. Higher ``value``
    schedules first and may preempt lower values; a job naming a missing
    class gets priority 0 (scheduling stays optional)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    description: str = ""
    kind: str = KIND_PRIORITY_CLASS

    def key(self) -> str:
        return self.metadata.key()


@dataclass
class QueueSpec:
    """Admission quota. 0 means unlimited on that dimension."""

    quota_chips: int = 0  # max chips admitted jobs in this queue may hold
    max_running_jobs: int = 0  # max concurrently admitted jobs


@dataclass
class Queue:
    """Per-namespace admission queue (kube-batch Queue analogue): jobs in
    the queue's namespace that name it in ``spec.scheduling.queue`` share
    its quota. A job naming a missing queue is unquota'd — quota is an
    opt-in contract, not a trap for unconfigured namespaces."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: QueueSpec = field(default_factory=QueueSpec)
    kind: str = KIND_QUEUE

    def key(self) -> str:
        return self.metadata.key()


def job_demand(job: TPUJob) -> int:
    """Chips the job occupies while admitted: the topology's slice size,
    falling back to the sum of per-process chip requests when the topology
    doesn't price itself (``chips_per_host`` unset). Evaluators are not
    gang members and don't count (they pack opportunistically)."""
    chips = job.spec.topology.total_chips()
    if chips > 0:
        return chips
    total = 0
    for rtype, rs in job.spec.replica_specs.items():
        if rtype is ReplicaType.EVALUATOR:
            continue
        total += (rs.replicas or 1) * max(rs.template.chips_per_process, 0)
    return total
