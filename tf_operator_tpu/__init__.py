"""tf_operator_tpu — a TPU-native distributed-training job framework.

A ground-up rebuild of the capabilities of kubeflow/tf-operator (reference at
/root/reference) designed for TPUs: a declarative ``TPUJob`` spec with typed
replica roles, an idempotent reconciling control plane with gang placement,
exit-code-driven restart policies, conditions-based status, events, and a
hermetic fake-backend test pyramid — with the parameter-server/gRPC data plane
replaced by SPMD JAX over a device mesh (pjit/shard_map, XLA collectives over
ICI/DCN, Pallas kernels for hot ops).

Layer map (mirrors SURVEY.md §1 of the reference):

- ``api``        — job spec/status types + defaulting + validation
                   (reference: pkg/apis/tensorflow/{v1alpha1,v1alpha2})
- ``runtime``    — object store with watches + process backends; the
                   "cluster" substrate (reference: k8s apiserver + kubelet)
- ``controller`` — workqueue, expectations, reconciler, status conditions,
                   events (reference: pkg/controller.v2)
- ``rendezvous`` — per-process jax.distributed coordinates
                   (reference: TF_CONFIG generator)
- ``parallel``   — mesh builder, DP/FSDP/TP/PP/CP/EP shardings, ring
                   attention, pipeline schedules (new surface; the reference
                   delegated all of this to user code)
- ``ops``        — Pallas/TPU kernels and reference implementations
- ``models``     — MNIST / ResNet / BERT / Llama model families
- ``train``      — pjit train loops, checkpointing, MFU telemetry
- ``utils``      — naming, logging, exit-code taxonomy
"""

__version__ = "0.1.0"
