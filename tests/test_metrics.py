"""MFU accounting (train.metrics).

The reference has no training telemetry; MFU is this framework's
north-star surface (BASELINE.md). These pin the FLOP-accounting math so
bench numbers stay comparable across rounds — especially the r3
attention-aware formula that fixed the long-context under-report.
"""

from tf_operator_tpu.train.metrics import (
    attention_train_flops,
    transformer_train_flops,
    transformer_train_flops_exact,
)


def test_6nd_rule():
    assert transformer_train_flops(100, 10) == 6000.0


def test_attention_term_palm_formula():
    # 12 * L * t * d per token, times tokens_per_step
    assert attention_train_flops(2, 8, 16, 4) == 12.0 * 2 * 16 * 8 * 4


def test_exact_is_sum_of_terms():
    n, d, L, t = 1_000_000, 64, 4, 128
    toks = 256
    assert transformer_train_flops_exact(n, toks, L, d, t) == (
        transformer_train_flops(n, toks) + attention_train_flops(L, d, t, toks)
    )


def test_long_context_correction_magnitude():
    """The bug the r3 fix closes: at t=8192 on gpt-small the attention term
    ~equals the 6ND term, so 6ND-only MFU halves the true number."""
    from tf_operator_tpu.models.transformer import PRESETS

    cfg = PRESETS["gpt-small"]
    t = 8192
    toks = 2 * t
    six_nd = transformer_train_flops(cfg.n_active_params(), toks)
    exact = transformer_train_flops_exact(
        cfg.n_active_params(), toks, cfg.n_layers, cfg.d_model, t
    )
    assert 1.9 < exact / six_nd < 2.1
    # and at short context the correction is small (<10%)
    t = 512
    toks = 32 * t
    six_nd = transformer_train_flops(cfg.n_active_params(), toks)
    exact = transformer_train_flops_exact(
        cfg.n_active_params(), toks, cfg.n_layers, cfg.d_model, t
    )
    assert exact / six_nd < 1.10
