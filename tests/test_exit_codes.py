"""Exit-code taxonomy tests (reference parity: train_util.go semantics +
TestIsRetryableTerminationState, pkg/trainer/training_test.go:33+)."""

import pytest

from tf_operator_tpu.utils import ExitClass, classify_exit_code, is_permanent, is_retryable


def test_success():
    assert classify_exit_code(0) is ExitClass.SUCCEEDED


@pytest.mark.parametrize("code", [1, 2, 126, 127, 128, 139])
def test_permanent_codes(code):
    assert is_permanent(code)


@pytest.mark.parametrize("code", [130, 137, 143])
def test_retryable_codes(code):
    assert is_retryable(code)


def test_user_defined_retryable_138():
    assert is_retryable(138)


def test_oom_always_permanent():
    # training.go:193-206: OOMKilled overrides even retryable codes. The
    # class is OOM (distinct from PERMANENT for cause accounting, r8) but
    # is_permanent — the restart decision — treats them identically.
    assert classify_exit_code(137, oom_killed=True) is ExitClass.OOM
    assert classify_exit_code(0, oom_killed=True) is ExitClass.OOM
    assert is_permanent(137, oom_killed=True)
    assert is_permanent(0, oom_killed=True)


def test_negative_signal_codes():
    # subprocess returncode -9 == killed by SIGKILL == 137 == retryable
    assert is_retryable(-9)
    assert is_retryable(-15)


def test_unknown_nonzero_permanent():
    assert is_permanent(3)
    assert is_permanent(42)
