"""Validation tests (reference parity: validation/validation_test.go)."""

import pytest

from tf_operator_tpu.api import (
    ObjectMeta,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    TopologySpec,
    TPUJob,
    TPUJobSpec,
    ValidationError,
    validate_job,
    validate_spec,
)


def good_spec():
    return TPUJobSpec(
        replica_specs={
            ReplicaType.COORDINATOR: ReplicaSpec(
                replicas=1, template=ProcessTemplate(entrypoint="m.mod:fn")
            ),
            ReplicaType.WORKER: ReplicaSpec(
                replicas=3, template=ProcessTemplate(entrypoint="m.mod:fn")
            ),
        },
        topology=TopologySpec(num_hosts=1, chips_per_host=8, mesh_axes={"dp": 2, "tp": 4}),
    )


def test_valid_spec_passes():
    validate_spec(good_spec())


def test_empty_replica_specs_rejected():
    with pytest.raises(ValidationError, match="must not be empty"):
        validate_spec(TPUJobSpec())


def test_missing_entrypoint_rejected():
    s = good_spec()
    s.replica_specs[ReplicaType.WORKER].template.entrypoint = ""
    with pytest.raises(ValidationError, match="entrypoint is required"):
        validate_spec(s)


def test_malformed_entrypoint_rejected():
    s = good_spec()
    s.replica_specs[ReplicaType.WORKER].template.entrypoint = "no_colon_here"
    with pytest.raises(ValidationError, match="pkg.module:fn"):
        validate_spec(s)


def test_multi_coordinator_rejected():
    s = good_spec()
    s.replica_specs[ReplicaType.COORDINATOR].replicas = 2
    with pytest.raises(ValidationError, match="Coordinator"):
        validate_spec(s)


def test_bad_port_rejected():
    s = good_spec()
    s.replica_specs[ReplicaType.WORKER].port = 70000
    with pytest.raises(ValidationError, match="valid port"):
        validate_spec(s)


def test_mesh_chip_mismatch_rejected():
    s = good_spec()
    s.topology.mesh_axes = {"dp": 3}  # 3 != 8 chips
    with pytest.raises(ValidationError, match="multiply"):
        validate_spec(s)


def test_job_requires_name():
    with pytest.raises(ValidationError, match="name"):
        validate_job(TPUJob(metadata=ObjectMeta(name=""), spec=good_spec()))


def test_negative_replicas_rejected():
    s = good_spec()
    s.replica_specs[ReplicaType.WORKER].replicas = 0
    with pytest.raises(ValidationError, match=">= 1"):
        validate_spec(s)


def test_dcn_mesh_axes_validated():
    s = good_spec()
    # ici 2x4 * dcn dp=2 = 16 != 8 chips
    s.topology.dcn_mesh_axes = {"dp": 2}
    with pytest.raises(ValidationError, match="multiply"):
        validate_spec(s)
    # consistent: 2 hosts of 8 chips, ici covers one slice, dcn spans hosts
    s.topology.num_hosts = 2
    validate_spec(s)


def test_dcn_mesh_axes_reject_ici_only_axes():
    s = good_spec()
    s.topology.num_hosts = 2
    s.topology.dcn_mesh_axes = {"tp": 2}
    with pytest.raises(ValidationError, match="must stay on ICI"):
        validate_spec(s)


def test_dcn_mesh_axes_reject_bad_size():
    s = good_spec()
    s.topology.dcn_mesh_axes = {"dp": 0}
    with pytest.raises(ValidationError, match="must be >= 1"):
        validate_spec(s)


def test_dcn_mesh_axes_require_explicit_mesh_axes():
    s = good_spec()
    s.topology.mesh_axes = {}
    s.topology.dcn_mesh_axes = {"dp": 2}
    with pytest.raises(ValidationError, match="requires explicit mesh_axes"):
        validate_spec(s)


def test_evaluator_only_job_rejected():
    s = TPUJobSpec(
        replica_specs={
            ReplicaType.EVALUATOR: ReplicaSpec(
                replicas=1, template=ProcessTemplate(entrypoint="m.mod:fn")
            )
        }
    )
    with pytest.raises(ValidationError, match="no chief"):
        validate_spec(s)


def test_every_example_spec_passes_admission():
    """examples/ are the user-facing contract: every shipped spec must
    parse (both API generations) and pass defaulting + validation."""
    import glob
    import json
    import os

    from tf_operator_tpu.api import set_defaults, validate_job
    from tf_operator_tpu.api.v1alpha1 import parse_job

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    specs = sorted(glob.glob(os.path.join(root, "examples", "*.json")))
    assert len(specs) >= 9
    for path in specs:
        with open(path) as f:
            job = parse_job(json.load(f))
        set_defaults(job)
        validate_job(job)
