"""Fleet compile-cache service tests (r11 TTFS tentpole).

The failure modes pinned here are the acceptance bar's "zero
cache-integrity failures surfaced as job failures": a corrupted entry, a
full service, and a dead service must all degrade a workload to the
PR 10 local-compile path — observable in stats/span attributes, never an
exception on the job's step path.
"""

import hashlib
import os
import threading
import time

import pytest

import tf_operator_tpu.train.compile_cache as cc
from tf_operator_tpu.cachesvc import CacheClient, CompileCacheService
from tf_operator_tpu.cachesvc.aot import AOTCompiler, aot_spec_of, modeled_payload


@pytest.fixture()
def svc():
    service = CompileCacheService(max_bytes=1 << 20)
    yield service
    service.stop()


@pytest.fixture(autouse=True)
def _isolate_compile_cache(monkeypatch):
    """Each test gets a disconnected remote tier and zeroed counters."""
    monkeypatch.delenv("TPUJOB_COMPILE_CACHE", raising=False)
    cc.configure_remote(None)
    for k in cc._stats:
        cc._stats[k] = 0
    yield
    cc.configure_remote(None)


def test_publish_fetch_round_trip(svc):
    client = CacheClient(svc.url)
    payload = b"serialized-executable" * 64
    assert client.publish("jit_step-abc123", payload)
    assert client.fetch("jit_step-abc123") == payload
    snap = svc.snapshot()
    assert snap["puts"] == 1 and snap["hits"] == 1 and snap["entries"] == 1
    assert not client.dead


def test_duplicate_publish_is_first_writer_wins(svc):
    client = CacheClient(svc.url)
    assert client.publish("k", b"first")
    assert client.publish("k", b"second")  # 200/409 either way: not a death
    assert client.fetch("k") == b"first"
    assert not client.dead


def test_key_sanitization_rejects_path_shapes(svc):
    client = CacheClient(svc.url)
    for bad in ("../../etc/passwd", "a/b", "a.b", "", "x" * 201, "kéy"):
        assert not client.publish(bad, b"data")
        assert client.fetch(bad) is None
        assert bad not in svc._entries
    # nothing escaped the root
    assert all(p.endswith((".bin",)) or p.startswith(".")
               for p in os.listdir(svc.root))


def test_transfer_digest_mismatch_rejected(svc):
    import urllib.request

    req = urllib.request.Request(
        f"{svc.url}/cachesvc/v1/entry?key=k", data=b"payload", method="PUT",
        headers={"X-Entry-SHA256": "0" * 64},
    )
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(req, timeout=5)
    assert err.value.code == 409
    assert svc.snapshot()["put_rejects"] == 1
    assert svc.snapshot()["entries"] == 0


def test_corrupted_entry_purged_and_workload_falls_back(svc, tmp_path):
    """Disk rot under a committed entry: the service must drop it (404),
    and a workload hitting that miss compiles locally — the integrity
    failure never reaches the job as anything but latency."""
    client = CacheClient(svc.url)
    key_material = "ns/job-fingerprint"
    key = hashlib.sha256(key_material.encode()).hexdigest()
    assert client.publish(key, modeled_payload(key_material))
    # rot the committed file behind the index's back
    with open(os.path.join(svc.root, f"{key}.bin"), "wb") as f:
        f.write(b"rotten")
    cc.configure_remote(svc.url)
    calls = []

    def compile_fn():
        calls.append(1)
        return modeled_payload(key_material)

    data, source = cc.cached_compile(
        key_material, compile_fn, cache_dir=str(tmp_path), wait_s=0.0
    )
    assert source == "compiled" and calls == [1]
    assert data == modeled_payload(key_material)
    # The rotten entry was purged; the async write-back of the fresh
    # compile may have re-published it. Both states are fine — what must
    # never happen is the rotten bytes being served as a hit.
    refetched = CacheClient(svc.url).fetch(key, wait_s=0.0)
    assert refetched in (None, modeled_payload(key_material))


def test_eviction_under_byte_cap():
    service = CompileCacheService(max_bytes=250)
    try:
        client = CacheClient(service.url)
        assert client.publish("old", b"a" * 100)
        assert client.publish("mid", b"b" * 100)
        client.fetch("old")  # refresh: now "mid" is the oldest-touched
        assert client.publish("new", b"c" * 100)
        snap = service.snapshot()
        assert snap["evictions"] == 1
        assert snap["bytes"] <= 250
        assert client.fetch("mid") is None  # the oldest-touched victim
        assert client.fetch("old") == b"a" * 100
        assert client.fetch("new") == b"c" * 100
    finally:
        service.stop()


def test_oversized_entry_rejected_not_fatal():
    service = CompileCacheService(max_bytes=64)
    try:
        client = CacheClient(service.url)
        assert not client.publish("big", b"x" * 100)
        assert not client.dead  # a policy reject is not a transport death
        assert service.snapshot()["entries"] == 0
    finally:
        service.stop()


def test_dead_cachesvc_degrades_to_local_with_span_attr(tmp_path, monkeypatch):
    """A dead service is a latency event: cached_compile() compiles
    locally, stats record the degradation, and mark_first_step carries it
    as a span attribute — never an exception on the step path."""
    cc.configure_remote("http://127.0.0.1:9")  # nothing listens there
    data, source = cc.cached_compile(
        "some/config", lambda: b"compiled-bytes",
        cache_dir=str(tmp_path), wait_s=0.0,
    )
    assert (data, source) == (b"compiled-bytes", "compiled")
    stats = cc.stats()
    assert stats["remote_dead"] is True and stats["misses"] == 1

    from tf_operator_tpu.rendezvous.context import JobContext

    captured = {}

    def fake_record(self, op, start, end, attrs=None, name=None):
        captured.update(attrs or {})
        return True

    monkeypatch.setattr(JobContext, "record_span", fake_record)
    assert JobContext(job_name="j", trace_id="t").mark_first_step(0)
    assert captured["cache_degraded"] == "1"
    assert captured["warm"] == "0"  # a degraded miss is a cold start


def test_intent_single_flight(svc):
    """A worker that reaches its miss while an admission-time compile is
    in flight waits it out (202 + Retry-After) and gets the publish —
    instead of duplicating the compile."""
    client = CacheClient(svc.url)
    client.announce("k")
    got = {}

    def waiter():
        got["data"] = client.fetch("k", wait_s=5.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.3)  # the modeled admission-time compile
    assert client.publish("k", b"aot-built")
    t.join(timeout=10)
    assert got["data"] == b"aot-built"
    assert svc.snapshot()["waits"] >= 1


def test_intent_ttl_expires_to_miss():
    service = CompileCacheService(intent_ttl=0.05)
    try:
        client = CacheClient(service.url)
        client.announce("k")
        time.sleep(0.1)
        assert client.fetch("k", wait_s=0.0) is None  # 404, not an endless 202
    finally:
        service.stop()


def test_remote_fill_lands_locally(svc, tmp_path):
    """A remote hit is written through to the local tier: the next lookup
    on this host never touches the network."""
    client = CacheClient(svc.url)
    key_material = "cfg"
    key = hashlib.sha256(key_material.encode()).hexdigest()
    assert client.publish(key, b"remote-built")
    cc.configure_remote(svc.url)
    data, source = cc.cached_compile(
        key_material, lambda: b"never", cache_dir=str(tmp_path), wait_s=0.0
    )
    assert (data, source) == (b"remote-built", "remote")
    data2, source2 = cc.cached_compile(
        key_material, lambda: b"never", cache_dir=str(tmp_path), wait_s=0.0
    )
    assert (data2, source2) == (b"remote-built", "local")


def test_cached_compile_configures_remote_from_env(svc, tmp_path, monkeypatch):
    """Workloads that call cached_compile() without enable() still reach
    the controller-stamped fleet tier."""
    key_material = "env-cfg"
    key = hashlib.sha256(key_material.encode()).hexdigest()
    CacheClient(svc.url).publish(key, b"fleet-built")
    monkeypatch.setenv("TPUJOB_COMPILE_CACHE", svc.url)
    data, source = cc.cached_compile(
        key_material, lambda: b"never", cache_dir=str(tmp_path), wait_s=0.0
    )
    assert (data, source) == (b"fleet-built", "remote")


# -- AOT-at-admission ---------------------------------------------------


def test_aot_spec_of_accepts_dict_and_json():
    assert aot_spec_of({"aot": {"key": "k"}}) == {"key": "k"}
    assert aot_spec_of('{"aot": {"topology": "v5e:2x4"}}') == {
        "topology": "v5e:2x4"
    }
    assert aot_spec_of({"dim": 16}) is None
    assert aot_spec_of("not json") is None
    assert aot_spec_of({"aot": "nope"}) is None


def test_aot_kick_publishes_and_dedupes(svc):
    done = threading.Event()
    spans = []

    def on_done(namespace, job_name, trace_id, key, mode, start, end, ok):
        spans.append((namespace, job_name, mode, ok))
        done.set()

    aot = AOTCompiler(svc.url, workers=1, on_done=on_done)
    try:
        workload = {"aot": {"key": "cfg", "compile_ms": 0}}
        assert aot.kick("ns", "job", "uid1", workload) is True
        assert aot.kick("ns", "job", "uid1", workload) is False  # dedup
        assert done.wait(timeout=10)
        assert spans == [("ns", "job", "modeled", True)]
        key = hashlib.sha256(b"cfg").hexdigest()
        assert CacheClient(svc.url).fetch(key) == modeled_payload("cfg")
        assert aot.stats["kicked"] == 1 and aot.stats["published"] == 1
    finally:
        aot.stop()


def test_aot_kick_nothing_declared(svc):
    aot = AOTCompiler(svc.url, workers=1)
    try:
        assert aot.kick("ns", "job", "uid", {"dim": 16}) is False
        assert aot.stats["kicked"] == 0
    finally:
        aot.stop()
